"""Shared finding/severity types for every static check in the repo.

One report format for the invariant analyzer (:mod:`p2pfl_tpu.analysis`),
the partition-rule lint (:mod:`p2pfl_tpu.parallel.sharding`) and anything
a later PR adds: a :class:`Finding` names the rule, the location, and a
human message; :attr:`Finding.fingerprint` is a line-number-independent
identity used by the baseline mechanism, so reformatting a file does not
resurrect accepted debt. Stdlib only — this module must stay importable
without jax (the analyzer parses code, it never executes it).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from enum import Enum
from typing import Iterable


class Severity(str, Enum):
    """How a finding gates: ``error`` fails the CLI, the rest inform."""

    ERROR = "error"
    WARNING = "warning"
    INFO = "info"


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``context`` is the enclosing ``Class.function`` qualname (or another
    stable anchor): it participates in the fingerprint instead of the
    line number, so accepted findings survive unrelated edits above them.
    """

    rule: str
    path: str
    line: int
    col: int
    message: str
    severity: Severity = Severity.ERROR
    context: str = ""

    def format(self) -> str:
        """``path:line:col: severity[rule-id] message`` — one line, grep-able."""
        return f"{self.path}:{self.line}:{self.col}: {self.severity.value}[{self.rule}] {self.message}"

    @property
    def fingerprint(self) -> str:
        """Stable identity for baselining: rule + tail of the path +
        enclosing context + message (never the line number)."""
        tail = "/".join(self.path.replace("\\", "/").split("/")[-2:])
        raw = "|".join((self.rule, tail, self.context, self.message))
        return hashlib.sha1(raw.encode()).hexdigest()[:16]


def format_findings(findings: Iterable[Finding]) -> str:
    """All findings, one line each, in deterministic order."""
    ordered = sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))
    return "\n".join(f.format() for f in ordered)
