"""Rule engine core: parse → visit → suppress → baseline.

The engine is deliberately dumb and lexical — it parses files with stdlib
``ast`` and hands each module to every registered rule, then the rule's
project-wide ``finalize`` pass (for cross-file contracts like the wire
header registry). No imports of analyzed code, no type inference: every
rule here is a pattern distilled from a real incident, tuned so the
historical bug shape flags and the shipped fix passes (the "teeth"
fixtures in ``tests/test_analysis.py`` pin both directions).

Escape hatches, in order of preference:

- ``# p2pfl: allow(rule-id) — justification`` on the finding's line (or
  the line directly above) suppresses that one finding, with the reason
  next to the code it excuses;
- a committed baseline file accepts a whole set of pre-existing findings
  by fingerprint, so the CLI can gate NEW violations on a tree with known
  debt (``--update-baseline`` refreshes it).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple, Type, Union

from p2pfl_tpu.analysis.findings import Finding

#: inline suppression: ``# p2pfl: allow(rule-id)``, ``allow(a, b)``, or
#: the every-rule wildcard ``allow(*)``
_SUPPRESS_RE = re.compile(r"#\s*p2pfl:\s*allow\(\s*([A-Za-z0-9_\-, *]+?)\s*\)")


@dataclass
class SourceModule:
    """One parsed file: path, source, AST, and its inline suppressions."""

    path: str
    source: str
    tree: ast.Module
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)

    @classmethod
    def parse(cls, path: str, source: Optional[str] = None) -> "SourceModule":
        if source is None:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        tree = ast.parse(source, filename=path)
        sup: Dict[int, Set[str]] = {}
        for lineno, line in enumerate(source.splitlines(), 1):
            m = _SUPPRESS_RE.search(line)
            if m:
                sup[lineno] = {tok.strip() for tok in m.group(1).split(",") if tok.strip()}
        return cls(path=path, source=source, tree=tree, suppressions=sup)

    @property
    def basename(self) -> str:
        return os.path.basename(self.path)

    def suppressed(self, line: int, rule_id: str) -> bool:
        """Pragma on the finding's line, or standalone on the line above."""
        for ln in (line, line - 1):
            ids = self.suppressions.get(ln)
            if ids and (rule_id in ids or "*" in ids):
                return True
        return False


class Rule:
    """Base rule: per-module check plus an optional project-wide pass.

    Rules are instantiated fresh per :func:`analyze` run, so a rule may
    accumulate cross-file state in ``check_module`` and cross-check it in
    ``finalize`` (the wire-header registry rule does exactly that).
    """

    id: str = ""
    summary: str = ""

    def check_module(self, mod: SourceModule) -> Iterable[Finding]:
        return ()

    def finalize(self) -> Iterable[Finding]:
        return ()


def iter_python_files(paths: Sequence[str]) -> List[str]:
    """Every ``.py`` under ``paths`` (files pass through), sorted, no dupes."""
    out: List[str] = []
    seen: Set[str] = set()
    for path in paths:
        if os.path.isfile(path):
            candidates = [path]
        else:
            candidates = []
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs if not d.startswith(".") and d != "__pycache__")
                candidates += [os.path.join(root, f) for f in sorted(files) if f.endswith(".py")]
        for c in candidates:
            norm = os.path.normpath(c)
            if norm not in seen:
                seen.add(norm)
                out.append(norm)
    return out


def analyze(
    paths: Sequence[str],
    rules: Optional[Sequence[Type[Rule]]] = None,
    *,
    sources: Optional[Dict[str, str]] = None,
) -> List[Finding]:
    """Run ``rules`` (default: the registry) over every file under ``paths``.

    ``sources`` maps path → source text for in-memory analysis (tests).
    Inline-suppressed findings are dropped here; baseline filtering is the
    caller's second stage (:func:`new_findings`) so the CLI can report
    "N findings, M baselined" honestly.
    """
    if rules is None:
        from p2pfl_tpu.analysis.rules import ALL_RULES

        rules = ALL_RULES
    instances = [r() for r in rules]
    mods: List[SourceModule] = []
    if sources is not None:
        mods = [SourceModule.parse(p, src) for p, src in sorted(sources.items())]
    else:
        for path in iter_python_files(paths):
            mods.append(SourceModule.parse(path))
    by_path = {m.path: m for m in mods}

    findings: List[Finding] = []
    for mod in mods:
        for rule in instances:
            findings += list(rule.check_module(mod))
    for rule in instances:
        findings += list(rule.finalize())

    kept = []
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None and mod.suppressed(f.line, f.rule):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return kept


# ---- baseline ----


def load_baseline(path: str) -> Dict[str, str]:
    """fingerprint → description; missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return dict(data.get("findings", {}))


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    data = {
        "comment": (
            "p2pfl-check baseline: accepted pre-existing findings by "
            "fingerprint. Prefer fixing, or an inline "
            "'# p2pfl: allow(rule-id)' with a justification, over adding here."
        ),
        "findings": {
            f.fingerprint: f"{f.path}: [{f.rule}] {f.message}"
            for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
        },
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")


def new_findings(findings: Iterable[Finding], baseline: Dict[str, str]) -> List[Finding]:
    return [f for f in findings if f.fingerprint not in baseline]


# ---- shared AST helpers (used by the rules) ----

FuncDef = Union[ast.FunctionDef, ast.AsyncFunctionDef]
FunctionNode = Tuple[str, FuncDef]  # (qualname, def node)

_FUNC_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef)
_SCOPE_TYPES = _FUNC_TYPES + (ast.ClassDef,)


def walk_functions(tree: ast.Module) -> Iterator[FunctionNode]:
    """Every function/method in the module with its dotted qualname.

    Nested functions are yielded separately from their parents, so rules
    that must not treat a deferred closure as part of the enclosing
    control flow (a ``def`` under a lock runs later, outside the lock)
    can simply skip nested defs in their own traversal.
    """

    def rec(node: ast.AST, prefix: str) -> Iterator[FunctionNode]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_TYPES):
                qual = f"{prefix}{child.name}"
                yield qual, child
                yield from rec(child, qual + ".")
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, f"{prefix}{child.name}.")
            else:
                yield from rec(child, prefix)

    yield from rec(tree, "")


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain of plain names, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_segment(node: ast.AST) -> Optional[str]:
    """Final attribute/name of a (possibly complex) dotted expression."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def node_pos(node: ast.AST) -> Tuple[int, int]:
    return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))


def node_end_pos(node: ast.AST) -> Tuple[int, int]:
    return (
        getattr(node, "end_lineno", getattr(node, "lineno", 0)),
        getattr(node, "end_col_offset", getattr(node, "col_offset", 0)),
    )


def iter_non_nested(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested defs/classes
    (their bodies execute later, under different locks and liveness)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, _SCOPE_TYPES) or isinstance(child, ast.Lambda):
            continue
        yield child
        yield from iter_non_nested(child)
