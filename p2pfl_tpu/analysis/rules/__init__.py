"""Built-in rule registry: one module per incident family.

Each rule is distilled from a real bug this repo shipped and fixed (or,
for ``no-host-gather``, a contract a new subsystem must keep rather than
a bug to remember); the rule docstrings name the incident, and
``tests/test_analysis.py`` pins both directions (the historical bug shape
flags, the shipped fix shape passes). Order here is the report order for
``--list-rules``.
"""

from p2pfl_tpu.analysis.rules.concurrency import SendUnderLockRule
from p2pfl_tpu.analysis.rules.donation import DonationReuseRule
from p2pfl_tpu.analysis.rules.hostgather import NoHostGatherRule
from p2pfl_tpu.analysis.rules.jit import JitStalenessRule
from p2pfl_tpu.analysis.rules.merge import MonotoneMergeRule
from p2pfl_tpu.analysis.rules.wire import WireHeaderCompatRule

ALL_RULES = (
    SendUnderLockRule,
    DonationReuseRule,
    MonotoneMergeRule,
    WireHeaderCompatRule,
    JitStalenessRule,
    NoHostGatherRule,
)

__all__ = [
    "ALL_RULES",
    "DonationReuseRule",
    "JitStalenessRule",
    "MonotoneMergeRule",
    "NoHostGatherRule",
    "SendUnderLockRule",
    "WireHeaderCompatRule",
]
