"""no-host-gather: the shard weights-plane modules never touch the host.

Incident class being prevented (rather than remembered): the shard-native
weights planes (``communication/ici.py`` + ``parallel/ici_plane.py``, and
their cross-process twins ``communication/dcn.py`` +
``parallel/dcn_plane.py``) exist for exactly one promise — model
diffusion with ZERO payload bytes crossing device→host. The promise is fragile in a way prose cannot
defend: one innocent ``np.asarray(leaf)`` for a shape check, one
``.tobytes()`` for a digest, one ``jax.device_get`` in a debug branch,
and the plane silently becomes a slower byte path while every counter
still reads "ici". PR 4 already paid this tuition on the encode side (the
host producer's full-model D2H pull hid on the critical path of every
gossip send for three PRs).

The rule is scope-targeted, not call-targeted: *inside the ICI modules*
(recognized by basename, like the wire codec set) any host
materialization is an error —

- ``jax.device_get`` / ``np.asarray`` / ``np.array`` /
  ``np.frombuffer`` (full-gather / host copies of device values),
- ``.item()`` (scalar host sync),
- ``.tobytes()`` (byte materialization — the exact call that would
  sneak the byte codec back into the plane).

Device-side mechanics stay allowed: ``make_array_from_single_device_arrays``,
``addressable_shards`` / per-shard ``reshape`` (zero-copy metadata
assembly), ``device_put`` (D2D), ``jnp.zeros`` filler uploads (H2D,
never payload D2H). Everywhere OUTSIDE these modules the rule is silent
— the byte transports legitimately materialize payloads.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from p2pfl_tpu.analysis.engine import Rule, SourceModule, dotted_name, node_pos
from p2pfl_tpu.analysis.findings import Finding

#: the weights-plane modules, recognized by basename (teeth fixtures can
#: replicate the shape in a scanned directory, like the wire codec set) —
#: the DCN plane carries the same zero-host-bytes contract across the
#: process boundary, so it lives in the same scope
ICI_BASENAMES = ("ici.py", "ici_plane.py", "dcn.py", "dcn_plane.py")

_HOST_CALLS = {
    "jax.device_get",
    "np.asarray",
    "np.array",
    "numpy.asarray",
    "numpy.array",
    "np.frombuffer",
    "numpy.frombuffer",
}
_HOST_ATTR_CALLS = {"item", "tobytes"}


class NoHostGatherRule(Rule):
    id = "no-host-gather"
    summary = "ICI weights-plane modules must not materialize bytes host-side"

    def check_module(self, mod: SourceModule) -> Iterable[Finding]:
        if mod.basename not in ICI_BASENAMES:
            return ()
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in _HOST_CALLS:
                out.append(
                    self._finding(
                        mod,
                        node,
                        f"{name}(…) inside the ICI weights plane — host "
                        "materialization of (potentially) device-resident "
                        "payload data breaks the zero-host-bytes contract; "
                        "keep the value a jax.Array or move the code out "
                        "of the plane modules",
                    )
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _HOST_ATTR_CALLS
                and not node.args
            ):
                out.append(
                    self._finding(
                        mod,
                        node,
                        f".{node.func.attr}() inside the ICI weights plane — "
                        "host sync/byte materialization breaks the "
                        "zero-host-bytes contract",
                    )
                )
        return out

    def _finding(self, mod: SourceModule, node: ast.AST, msg: str) -> Finding:
        line, col = node_pos(node)
        return Finding(
            rule=self.id, path=mod.path, line=line, col=col, message=msg
        )
