"""donation-reuse: a buffer donated to a jit call is dead — rebind or recover.

Incident: the fused-round programs donate params/opt-state
(``donate_argnums``/``donate_argnames``) so XLA reuses their buffers. A
dispatch that fails AFTER argument donation leaves the caller holding
deleted arrays; the next use explodes with "array has been deleted" deep
inside jit argument processing — the PR-4 encode-path poisoning, re-hit
by PR-6's fused round and fixed with the ``_recover_donated_state``
pattern (drop + rebuild on dispatch failure, rebind on success).

The rule works lexically within one module: it collects jitted functions
whose decorators declare donated parameters, then at every call site
checks that each donated argument (a plain ``name`` or dotted
``self.attr`` expression) is not READ again later in the same function
without an intervening rebind. Reads inside nested defs are exempt (they
run later, usually after the rebind); the historical fix shape —
``result = spmd_round(self.params, …)`` then
``self.params, … = result[:…]`` — passes because the store precedes any
read.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from p2pfl_tpu.analysis.engine import (
    Rule,
    SourceModule,
    dotted_name,
    iter_non_nested,
    node_end_pos,
    node_pos,
    walk_functions,
)
from p2pfl_tpu.analysis.findings import Finding

_JIT_NAMES = {"jit", "jax.jit", "pjit", "jax.pjit", "pjit.pjit"}
_PARTIAL_NAMES = {"partial", "functools.partial"}

Donation = Tuple[Set[int], Set[str]]  # (positional indices, kwarg names)


def _const_ints(node: ast.AST) -> Set[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for elt in node.elts:
            out |= _const_ints(elt)
        return out
    return set()


def _const_strs(node: ast.AST) -> Set[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for elt in node.elts:
            out |= _const_strs(elt)
        return out
    return set()


def _jit_donation(call: ast.AST, module_strs: Dict[str, Set[str]]) -> Optional[Donation]:
    """Donated (positions, names) declared by a jit/partial(jit, …) call."""
    if not isinstance(call, ast.Call):
        return None
    func = dotted_name(call.func)
    is_jit = func in _JIT_NAMES
    if func in _PARTIAL_NAMES and call.args:
        is_jit = dotted_name(call.args[0]) in _JIT_NAMES
    if not is_jit and isinstance(call.func, ast.Call):
        # partial(jax.jit, donate_argnums=…)(shard_map(body, …)): the
        # donation lives on the INNER partial call — the sharded-engine
        # wrapping shape
        return _jit_donation(call.func, module_strs)
    if not is_jit:
        return None
    positions: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            positions |= _const_ints(kw.value)
        elif kw.arg == "donate_argnames":
            strs = _const_strs(kw.value)
            if not strs:
                # a module-level constant tuple of names (the
                # _ROUND_DONATED_STATE idiom): resolve it lexically
                ref = dotted_name(kw.value)
                if ref in module_strs:
                    strs = module_strs[ref]
            names |= strs
    if positions or names:
        return positions, names
    return None


def _module_str_tuples(tree: ast.Module) -> Dict[str, Set[str]]:
    """Module-level ``NAME = ("a", "b", …)`` string-tuple constants."""
    out: Dict[str, Set[str]] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if isinstance(target, ast.Name):
                strs = _const_strs(node.value)
                if strs:
                    out[target.id] = strs
    return out


def _donated_functions(tree: ast.Module) -> Dict[str, Donation]:
    module_strs = _module_str_tuples(tree)
    out: Dict[str, Donation] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                don = _jit_donation(dec, module_strs)
                if don:
                    out[node.name] = don
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if isinstance(target, ast.Name):
                don = _jit_donation(node.value, module_strs)
                if don:
                    out[target.id] = don
    return out


def _store_paths(target: ast.AST) -> Iterable[str]:
    """Dotted paths a (possibly tuple) assignment target rebinds."""
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _store_paths(elt)
    elif isinstance(target, ast.Starred):
        yield from _store_paths(target.value)
    else:
        path = dotted_name(target)
        if path:
            yield path


class DonationReuseRule(Rule):
    id = "donation-reuse"
    summary = "donated jit arguments must be rebound before any later read"

    def check_module(self, mod: SourceModule) -> Iterable[Finding]:
        donated = _donated_functions(mod.tree)
        if not donated:
            return ()
        out: List[Finding] = []
        for qual, fn in walk_functions(mod.tree):
            out += self._check_function(mod, qual, fn, donated)
        return out

    def _check_function(
        self,
        mod: SourceModule,
        qual: str,
        fn: ast.AST,
        donated: Dict[str, Donation],
    ) -> List[Finding]:
        # one linear pass in source order: donate events, stores, loads
        donations: List[Tuple[Tuple[int, int], str, str]] = []  # (end_pos, path, callee)
        stores: List[Tuple[Tuple[int, int], str]] = []
        loads: List[Tuple[Tuple[int, int], str, ast.AST]] = []

        for node in iter_non_nested(fn):
            if isinstance(node, ast.Call):
                callee = dotted_name(node.func)
                callee_last = callee.rsplit(".", 1)[-1] if callee else None
                if callee_last in donated:
                    positions, names = donated[callee_last]
                    exprs = [
                        node.args[i]
                        for i in positions
                        if i < len(node.args) and not isinstance(node.args[i], ast.Starred)
                    ]
                    exprs += [kw.value for kw in node.keywords if kw.arg in names]
                    for expr in exprs:
                        path = dotted_name(expr)
                        if path:
                            donations.append((node_end_pos(node), path, callee_last))
            elif isinstance(node, ast.Assign):
                # a store lands AFTER its RHS evaluates: position it at the
                # statement's end so `x = donated_fn(x)` counts as a rebind
                for target in node.targets:
                    stores += [(node_end_pos(node), p) for p in _store_paths(target)]
            elif isinstance(node, ast.AugAssign):
                # read-modify-write: the read half counts as a load
                path = dotted_name(node.target)
                if path:
                    loads.append((node_pos(node), path, node))
            elif isinstance(node, (ast.Attribute, ast.Name)):
                path = dotted_name(node)
                if path:
                    if isinstance(node.ctx, ast.Load):
                        loads.append((node_pos(node), path, node))
                    elif isinstance(node.ctx, (ast.Store, ast.Del)):
                        # with-as / for-target / del rebinds too
                        stores.append((node_pos(node), path))

        out: List[Finding] = []
        for don_pos, path, callee in donations:
            next_load = min(
                (pos for pos, p, _ in loads if p == path and pos > don_pos),
                default=None,
            )
            if next_load is None:
                continue
            next_store = min(
                (pos for pos, p in stores if p == path and pos >= don_pos),
                default=None,
            )
            if next_store is not None and next_store <= next_load:
                continue  # rebound before the read — the shipped fix shape
            load_node = next(n for pos, p, n in loads if p == path and pos == next_load)
            out.append(
                Finding(
                    rule=self.id,
                    path=mod.path,
                    line=load_node.lineno,
                    col=load_node.col_offset,
                    message=(
                        f"'{path}' was donated to jitted '{callee}' and read "
                        "again without rebinding — a failed dispatch leaves "
                        "it deleted (rebind from the result, or recover via "
                        "the _recover_donated_state pattern)"
                    ),
                    context=qual,
                )
            )
        return out
