"""send-under-lock: no protocol send may run while a lock is held.

Incident: the async control plane's handlers run inline on the in-memory
transport — a send made while holding a context/state lock re-enters the
receiver's handler synchronously, which takes its own lock and may send
back, deadlocking two nodes on each other (the PR-9 deadlock contract:
"handlers compute under locks, collect Action tuples, and
execute_actions runs outside every lock"). On the gRPC transport the
same shape is a latency bomb instead: a send blocks up to
GOSSIP_SEND_TIMEOUT with the lock held, stalling every handler thread.

The rule flags any call whose final attribute is a known transport-send
entry point when it is lexically inside a ``with <…lock>:`` body.
Nested ``def``/``lambda`` bodies are exempt — a closure defined under a
lock runs later, outside it (the eviction-repair thread pattern in
``node.py``).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Sequence

from p2pfl_tpu.analysis.engine import (
    Rule,
    SourceModule,
    _SCOPE_TYPES,
    last_segment,
    walk_functions,
)
from p2pfl_tpu.analysis.findings import Finding

#: transport send entry points (communication/protocol.py + gossiper),
#: the async plane's action runner (which fans sends out), and the node
#: journal's snapshot commit (federation/durability.py) — blocking disk
#: I/O with the same stall shape as a send: fsync under a context lock
#: freezes every handler thread for the write's duration
SEND_CALLS = frozenset(
    {
        "send",
        "broadcast",
        "_do_send",
        "_send_to_neighbor",
        "_transport_send",
        "_dispatch_sends",
        "send_message",
        "send_weights",
        "gossip_weights",
        "execute_actions",
        "commit_snapshot",
    }
)


_LOCKISH = re.compile(r"(lock|mutex|cv|cond|condition)$", re.IGNORECASE)


def _lock_name(expr: ast.AST) -> Optional[str]:
    """Name of a with-item that acquires a lock or condition
    (``with self.lock:``, ``with st.status_merge_lock:``,
    ``with self._queue_cv:`` …)."""
    target = expr.func if isinstance(expr, ast.Call) else expr
    name = last_segment(target)
    if name and _LOCKISH.search(name):
        return name
    return None


class SendUnderLockRule(Rule):
    id = "send-under-lock"
    summary = "no transport send while holding a lock (async deadlock contract)"

    def check_module(self, mod: SourceModule) -> Iterable[Finding]:
        out: List[Finding] = []
        for qual, fn in walk_functions(mod.tree):
            self._visit(mod, qual, list(fn.body), [], out)
        return out

    def _visit(
        self,
        mod: SourceModule,
        qual: str,
        nodes: Sequence[ast.AST],
        locks: List[str],
        out: List[Finding],
    ) -> None:
        for node in nodes:
            if isinstance(node, _SCOPE_TYPES) or isinstance(node, ast.Lambda):
                continue  # deferred body: runs outside this lock scope
            held = locks
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired: List[str] = []
                for item in node.items:
                    name = _lock_name(item.context_expr)
                    if name is not None:
                        acquired.append(name)
                if acquired:
                    held = locks + acquired
                self._visit(mod, qual, list(node.body), held, out)
                continue
            if locks and isinstance(node, ast.Call):
                callee = last_segment(node.func)
                if callee in SEND_CALLS:
                    out.append(
                        Finding(
                            rule=self.id,
                            path=mod.path,
                            line=node.lineno,
                            col=node.col_offset,
                            message=(
                                f"'{callee}(…)' called while holding "
                                f"'{locks[-1]}' — no lock may be held across a "
                                "send (collect actions under the lock, send "
                                "outside it)"
                            ),
                            context=qual,
                        )
                    )
            self._visit(mod, qual, list(ast.iter_child_nodes(node)), held, out)
