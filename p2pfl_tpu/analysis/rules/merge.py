"""monotone-merge: NodeState coverage/status lattices mutate only under lock.

Incident: command handlers run on whatever thread delivers the message
(gossip workers, server executors, duplicate-delivery timers). The
control-plane views on ``NodeState`` — ``models_aggregated`` (coverage),
``nei_status`` (round progress), ``async_done_peers`` (drain release) —
are lattices whose merges must be monotone (union/max) AND atomic: two
unlocked read-merge-writes for the same source clobber each other,
losing a sender's FINAL announcement. That stale-overwrite is the root
cause of the PR-5 8-node round-0 wedge (one storm of stale redeliveries
held six nodes in TrainStage indefinitely); the fix serialized every
merge under ``status_merge_lock``.

The rule flags element-level mutations of the tracked dicts/sets —
subscript stores, ``.add/.update/.setdefault/…`` calls — outside a
``with …status_merge_lock:`` body. Whole-attribute REPLACEMENT
(``self.models_aggregated = {}``) is exempt: replace-don't-mutate is the
documented safe idiom (readers capture the old object; see
``NodeState.increase_round``'s ordering contract). Local aliases are
followed one hop (``coverage = st.models_aggregated`` — the shipped
merge captures the dict first).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence

from p2pfl_tpu.analysis.engine import (
    Rule,
    SourceModule,
    _SCOPE_TYPES,
    last_segment,
    node_pos,
    walk_functions,
)
from p2pfl_tpu.analysis.findings import Finding

TRACKED_ATTRS = frozenset({"models_aggregated", "nei_status", "async_done_peers"})
MUTATING_METHODS = frozenset(
    {"add", "update", "setdefault", "pop", "popitem", "remove", "discard", "clear"}
)
LOCK_ATTR = "status_merge_lock"


def _tracked(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """The tracked NodeState attribute ``node`` refers to, if any."""
    if isinstance(node, ast.Attribute) and node.attr in TRACKED_ATTRS:
        return node.attr
    if isinstance(node, ast.Name) and node.id in aliases:
        return aliases[node.id]
    return None


def _collect_aliases(fn: ast.AST) -> Dict[str, str]:
    """``coverage = st.models_aggregated`` → {"coverage": "models_aggregated"}."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if (
                isinstance(target, ast.Name)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr in TRACKED_ATTRS
            ):
                aliases[target.id] = node.value.attr
    return aliases


class MonotoneMergeRule(Rule):
    id = "monotone-merge"
    summary = "status-lattice mutations must hold status_merge_lock"

    def check_module(self, mod: SourceModule) -> Iterable[Finding]:
        out: List[Finding] = []
        for qual, fn in walk_functions(mod.tree):
            aliases = _collect_aliases(fn)
            self._visit(mod, qual, list(fn.body), False, aliases, out)
        return out

    def _visit(
        self,
        mod: SourceModule,
        qual: str,
        nodes: Sequence[ast.AST],
        locked: bool,
        aliases: Dict[str, str],
        out: List[Finding],
    ) -> None:
        for node in nodes:
            if isinstance(node, _SCOPE_TYPES) or isinstance(node, ast.Lambda):
                continue  # deferred body: must take the lock itself
            now_locked = locked
            if isinstance(node, (ast.With, ast.AsyncWith)):
                if any(last_segment(item.context_expr) == LOCK_ATTR for item in node.items):
                    now_locked = True
                self._visit(mod, qual, list(node.body), now_locked, aliases, out)
                continue
            if not locked:
                attr = self._mutation(node, aliases)
                if attr is not None:
                    line, col = node_pos(node)
                    out.append(
                        Finding(
                            rule=self.id,
                            path=mod.path,
                            line=line,
                            col=col,
                            message=(
                                f"'{attr}' mutated outside `with {LOCK_ATTR}` — "
                                "control-plane lattice merges must be atomic "
                                "monotone read-merge-writes under the lock "
                                "(or replace the whole attribute)"
                            ),
                            context=qual,
                        )
                    )
            self._visit(mod, qual, list(ast.iter_child_nodes(node)), now_locked, aliases, out)

    @staticmethod
    def _mutation(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
        """Tracked attr this node element-mutates, or None."""
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Subscript):
                    attr = _tracked(target.value, aliases)
                    if attr:
                        return attr
        elif isinstance(node, ast.AugAssign) and isinstance(node.target, ast.Subscript):
            return _tracked(node.target.value, aliases)
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                if isinstance(target, ast.Subscript):
                    attr = _tracked(target.value, aliases)
                    if attr:
                        return attr
        elif isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in MUTATING_METHODS:
                return _tracked(func.value, aliases)
        return None
