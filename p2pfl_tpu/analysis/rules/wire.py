"""wire-header-compat: optional envelope keys follow the tc/vv/xp pattern.

Contract (one registry:
:data:`p2pfl_tpu.communication.wire_headers.OPTIONAL_WIRE_HEADERS`): an
optional wire-header key must (a) decode unchanged when absent —
``d.get(key)``, never ``d[key]`` — in every native codec plane that
carries it, (b) be serialized only under a guard so ``None`` never hits
the wire (old receivers keep parsing new senders), (c) be copied by the
in-memory transport's byte-path re-wrap (or simulations diverge from the
network transports — the exact drift the MEMORY_WIRE_CODEC seam exists
to prevent), and (d) never appear in the protobuf interop codec, whose
schema must stay byte-compatible with real reference nodes.

This is a cross-file rule: it recognizes the three codec files by
basename (``grpc_transport.py``, ``memory.py``, ``proto_wire.py``),
records their ASTs during the per-module pass grouped by DIRECTORY
(each directory holding codec files is its own codec set — teeth
fixtures scanned alongside the real tree can never shadow the real
codec), and cross-checks every registered key in ``finalize``. A key declared in the registry but
missing any leg of the pattern — or a key string leaking into the
protobuf schema — is a finding; so is drift in the other direction
(a declared key the envelope codec never encodes at all).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence

from p2pfl_tpu.analysis.engine import FuncDef, Rule, SourceModule, last_segment
from p2pfl_tpu.analysis.findings import Finding

_ENCODERS = {"message": "encode_message", "weights": "encode_weights"}
_DECODERS = {"message": "decode_message", "weights": "decode_weights"}


def _functions(tree: ast.Module) -> Dict[str, FuncDef]:
    out: Dict[str, FuncDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def _with_local_callees(fn: ast.AST, fns: Dict[str, FuncDef]) -> List[ast.AST]:
    """The function plus module-local helpers it calls (one hop) — the
    ``_trace_ctx(d)`` indirection in the shipped decoder."""
    bodies = [fn]
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            helper = fns.get(node.func.id)
            if helper is not None and helper is not fn:
                bodies.append(helper)
    return bodies


def _get_calls(nodes: Sequence[ast.AST], key: str) -> bool:
    for fn in nodes:
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == key
            ):
                return True
    return False


def _subscript_loads(nodes: Sequence[ast.AST], key: str) -> Optional[ast.AST]:
    for fn in nodes:
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.slice, ast.Constant)
                and node.slice.value == key
            ):
                return node
    return None


def _key_stores(fn: ast.AST, key: str) -> List[ast.Subscript]:
    out = []
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Subscript)
            and isinstance(node.ctx, ast.Store)
            and isinstance(node.slice, ast.Constant)
            and node.slice.value == key
        ):
            out.append(node)
    return out


def _guarded(fn: ast.AST, target: ast.AST) -> bool:
    """Is ``target`` lexically inside an ``if`` within ``fn``?"""

    def rec(node: ast.AST, in_if: bool) -> Optional[bool]:
        if node is target:
            return in_if
        for child in ast.iter_child_nodes(node):
            nested = in_if or isinstance(node, ast.If) or isinstance(node, ast.IfExp)
            found = rec(child, nested)
            if found is not None:
                return found
        return None

    return bool(rec(fn, False))


class WireHeaderCompatRule(Rule):
    id = "wire-header-compat"
    summary = "optional wire keys: guarded encode, get() decode, memory copy, no protobuf leak"

    #: the codec files the contract lives in, recognized by basename so
    #: teeth fixtures can replicate the shape in a temp directory
    CODEC_BASENAMES = ("grpc_transport.py", "memory.py", "proto_wire.py")

    def __init__(self, headers: Optional[Sequence] = None) -> None:
        self._headers = headers
        # directory → {basename: module}: each directory holding codec
        # files is cross-checked as its own codec set, so teeth fixtures
        # in a scanned tree can never shadow the real codec (and vice
        # versa) — a basename collision across directories is two sets
        self._dirs: Dict[str, Dict[str, SourceModule]] = {}

    def check_module(self, mod: SourceModule) -> Iterable[Finding]:
        if mod.basename in self.CODEC_BASENAMES:
            directory = os.path.dirname(mod.path)
            self._dirs.setdefault(directory, {})[mod.basename] = mod
        return ()

    def finalize(self) -> Iterable[Finding]:
        headers = self._headers
        if headers is None:
            from p2pfl_tpu.communication.wire_headers import OPTIONAL_WIRE_HEADERS

            headers = OPTIONAL_WIRE_HEADERS
        out: List[Finding] = []
        for _directory, mods in sorted(self._dirs.items()):
            envelope = mods.get("grpc_transport.py")
            memory = mods.get("memory.py")
            proto = mods.get("proto_wire.py")
            for h in headers:
                if envelope is not None:
                    out += self._check_envelope(envelope, h)
                if memory is not None:
                    out += self._check_memory(memory, h)
                if proto is not None:
                    out += self._check_proto(proto, h)
        return out

    # ---- per-file checks ----

    def _finding(self, mod: SourceModule, node: Optional[ast.AST], msg: str, ctx: str) -> Finding:
        return Finding(
            rule=self.id,
            path=mod.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=msg,
            context=ctx,
        )

    def _check_envelope(self, mod: SourceModule, h) -> List[Finding]:
        fns = _functions(mod.tree)
        out: List[Finding] = []
        for plane in h.planes:
            enc = fns.get(_ENCODERS[plane])
            if enc is not None:
                stores = _key_stores(enc, h.key)
                if not stores:
                    out.append(
                        self._finding(
                            mod,
                            enc,
                            f"optional wire key '{h.key}' is registered for the "
                            f"{plane} plane but never written by "
                            f"{_ENCODERS[plane]} — registry/codec drift",
                            _ENCODERS[plane],
                        )
                    )
                for store in stores:
                    if not _guarded(enc, store):
                        out.append(
                            self._finding(
                                mod,
                                store,
                                f"optional wire key '{h.key}' is serialized "
                                "unconditionally — absent-frame compatibility "
                                "requires the is-not-None guard",
                                _ENCODERS[plane],
                            )
                        )
            dec = fns.get(_DECODERS[plane])
            if dec is not None:
                bodies = _with_local_callees(dec, fns)
                sub = _subscript_loads(bodies, h.key)
                if sub is not None:
                    out.append(
                        self._finding(
                            mod,
                            sub,
                            f"optional wire key '{h.key}' read with [] in the "
                            f"{plane} decoder — KeyError on absent frames; use "
                            ".get()",
                            _DECODERS[plane],
                        )
                    )
                elif not _get_calls(bodies, h.key):
                    out.append(
                        self._finding(
                            mod,
                            dec,
                            f"optional wire key '{h.key}' has no absent-frame "
                            f"decode path in {_DECODERS[plane]} (no "
                            f".get('{h.key}'))",
                            _DECODERS[plane],
                        )
                    )
        return out

    def _check_memory(self, mod: SourceModule, h) -> List[Finding]:
        out: List[Finding] = []
        for ctor, kwarg in h.memory_copies:
            calls = [
                node
                for node in ast.walk(mod.tree)
                if isinstance(node, ast.Call) and last_segment(node.func) == ctor
            ]
            if not calls:
                continue  # no byte-path re-wrap in this transport: pass-by-
                # reference carries every attribute automatically
            # EVERY re-wrap site must copy the kwarg — the unary path and the
            # streaming pump each rebuild the update, and a key dropped from
            # either one diverges simulations only on that path's sends
            for call in calls:
                if not any(kw.arg == kwarg for kw in call.keywords):
                    out.append(
                        self._finding(
                            mod,
                            call,
                            f"memory byte path rebuilds {ctor} without copying "
                            f"'{kwarg}' — the optional '{h.key}' header would "
                            "be dropped in simulation but kept on the network "
                            "transports",
                            ctor,
                        )
                    )
        return out

    def _check_proto(self, mod: SourceModule, h) -> List[Finding]:
        """Flag the key as a string constant, a schema keyword argument
        (``pb.Weights(vv=…)``), or a field access (``w.vv``)."""
        for node in ast.walk(mod.tree):
            leaked = (
                (isinstance(node, ast.Constant) and node.value == h.key)
                or (isinstance(node, ast.Attribute) and node.attr == h.key)
                or (
                    isinstance(node, ast.Call)
                    and any(kw.arg == h.key for kw in node.keywords)
                )
            )
            if leaked:
                return [
                    self._finding(
                        mod,
                        node,
                        f"optional wire key '{h.key}' appears in the protobuf "
                        "interop codec — the reference schema must never "
                        "carry optional envelope keys",
                        "protobuf-interop",
                    )
                ]
        return []
