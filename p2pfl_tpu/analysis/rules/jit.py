"""jit-staleness: jitted/pallas bodies must not read mutable host state.

Incident: the flash-attention backward once selected its algorithm from a
``BWD_MODE`` module global read at trace time. The global participated
in no jit cache key, so flipping it silently kept serving the OLD
compiled program — results changed or didn't depending on what had been
traced first (the PR-2 staleness class; the fix made every knob an
explicit ``KernelConfig`` argument that provably re-traces). The same
trap generalizes to ``Settings.*``: a read inside a jitted body bakes
the value of the FIRST trace into every later call.

The rule finds jitted functions (``@jax.jit``/``@partial(jax.jit, …)``
decorators, ``name = jax.jit(fn)`` bindings, kernels passed to
``pallas_call``/``shard_map``) and flags, anywhere in their bodies
(including nested defs — those trace inline):

- ``Settings.X`` attribute reads;
- reads of module globals that are REBOUND at runtime (named in a
  ``global`` statement, or assigned more than once at module level) —
  single-assignment module constants are static and fine;
- host syncs on traced values — ``.item()``, ``float(x)``,
  ``np.asarray``/``np.array``, ``jax.device_get`` — which either crash
  at trace time or silently pin a constant; inside the fused-round and
  submesh programs they also break the no-host-sync dispatch contract.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from p2pfl_tpu.analysis.engine import FuncDef, Rule, SourceModule, dotted_name, node_pos
from p2pfl_tpu.analysis.findings import Finding

_JIT_NAMES = {"jit", "jax.jit", "pjit", "jax.pjit", "pjit.pjit"}
_PARTIAL_NAMES = {"partial", "functools.partial"}
#: call wrappers whose first argument is traced as a device program —
#: pallas kernels and shard_map bodies (incl. the repo's compat shims)
_KERNEL_WRAPPER_LASTS = {
    "pallas_call",
    "shard_map",
    "shard_map_compat",
    "shard_map_unchecked",
}
_HOST_SYNC_CALLS = {
    "np.asarray",
    "np.array",
    "numpy.asarray",
    "numpy.array",
    "jax.device_get",
}


def _is_jit_decorator(dec: ast.AST) -> bool:
    name = dotted_name(dec)
    if name in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        func = dotted_name(dec.func)
        last = func.rsplit(".", 1)[-1] if func else None
        if func in _JIT_NAMES or last in _KERNEL_WRAPPER_LASTS:
            return True
        if func in _PARTIAL_NAMES and dec.args:
            inner = dotted_name(dec.args[0])
            if inner in _JIT_NAMES:
                return True
            # @partial(shard_map, mesh=…) / @partial(shard_map_compat, …):
            # the decorated def IS the per-shard device program
            inner_last = inner.rsplit(".", 1)[-1] if inner else None
            if inner_last in _KERNEL_WRAPPER_LASTS:
                return True
    return False


def _jitted_functions(tree: ast.Module) -> Dict[str, FuncDef]:
    """name → def for every function traced by jit/pallas/shard_map."""
    defs: Dict[str, List[FuncDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)

    # one-hop indirection: ``kernel = partial(_flash_kernel, …)`` aliases
    partial_aliases: Dict[str, Set[str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if isinstance(target, ast.Name):
                wrapped = _partial_target(node.value)
                if wrapped is not None:
                    partial_aliases.setdefault(target.id, set()).add(wrapped)

    def resolve(arg: ast.AST) -> List[str]:
        """Candidate function names a wrapper's first argument refers to."""
        wrapped = _partial_target(arg)
        if wrapped is not None:
            return [wrapped]
        name = dotted_name(arg)
        if name is None:
            return []
        last = name.rsplit(".", 1)[-1]
        return sorted(partial_aliases.get(last, set())) + [last]

    jitted: Dict[str, FuncDef] = {}
    for name, nodes in defs.items():
        for fn in nodes:
            if any(_is_jit_decorator(d) for d in fn.decorator_list):
                jitted[name] = fn
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        func = dotted_name(node.func)
        last = func.rsplit(".", 1)[-1] if func else None
        if func in _JIT_NAMES or last in _KERNEL_WRAPPER_LASTS:
            for kernel in resolve(node.args[0]):
                if kernel in defs:
                    jitted[kernel] = defs[kernel][0]
    return jitted


def _partial_target(node: ast.AST) -> Optional[str]:
    """``partial(fn, …)`` → ``fn``'s last name segment, else None."""
    if (
        isinstance(node, ast.Call)
        and dotted_name(node.func) in _PARTIAL_NAMES
        and node.args
    ):
        name = dotted_name(node.args[0])
        if name is not None:
            return name.rsplit(".", 1)[-1]
    return None


def _mutable_globals(tree: ast.Module) -> Set[str]:
    """Module names rebound at runtime: ``global`` targets + names with
    more than one module-level binding."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Global):
            out.update(node.names)
    counts: Dict[str, int] = {}
    for stmt in tree.body:
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)) and stmt.value is not None:
            targets = [stmt.target]
        for t in targets:
            if isinstance(t, ast.Name):
                counts[t.id] = counts.get(t.id, 0) + 1
    out |= {name for name, n in counts.items() if n > 1}
    return out


def _local_bindings(fn: FuncDef) -> Set[str]:
    """Names bound inside the function (params, assignments, comps)."""
    bound: Set[str] = set()
    args = fn.args
    for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
        bound.add(a.arg)
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Store, ast.Del)):
            bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
            bound.add(node.name)
            for a in list(node.args.posonlyargs) + list(node.args.args) + list(node.args.kwonlyargs):
                bound.add(a.arg)
    return bound


class JitStalenessRule(Rule):
    id = "jit-staleness"
    summary = "no Settings/mutable-global reads or host syncs inside jitted bodies"

    def check_module(self, mod: SourceModule) -> Iterable[Finding]:
        jitted = _jitted_functions(mod.tree)
        if not jitted:
            return ()
        mutable = _mutable_globals(mod.tree)
        out: List[Finding] = []
        for name, fn in jitted.items():
            local = _local_bindings(fn)
            for node in ast.walk(fn):
                f = self._check_node(mod, name, node, mutable, local)
                if f is not None:
                    out.append(f)
        return out

    def _check_node(
        self,
        mod: SourceModule,
        fn_name: str,
        node: ast.AST,
        mutable: Set[str],
        local: Set[str],
    ) -> Optional[Finding]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "Settings"
        ):
            return self._finding(
                mod,
                node,
                fn_name,
                f"Settings.{node.attr} read inside jitted '{fn_name}' — the "
                "value is baked at first trace and goes stale (pass it as an "
                "argument or static_argname)",
            )
        if (
            isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id in mutable
            and node.id not in local
        ):
            return self._finding(
                mod,
                node,
                fn_name,
                f"mutable module global '{node.id}' read inside jitted "
                f"'{fn_name}' — it participates in no jit cache key (the "
                "BWD_MODE class); pass it as an explicit argument",
            )
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr == "item" and not node.args:
                return self._finding(
                    mod,
                    node,
                    fn_name,
                    f".item() inside jitted '{fn_name}' — host sync on a "
                    "traced value",
                )
            name = dotted_name(func)
            if name in _HOST_SYNC_CALLS:
                return self._finding(
                    mod,
                    node,
                    fn_name,
                    f"{name}(…) inside jitted '{fn_name}' — host "
                    "materialization of a traced value",
                )
            if (
                isinstance(func, ast.Name)
                and func.id == "float"
                and node.args
                and not isinstance(node.args[0], ast.Constant)
            ):
                return self._finding(
                    mod,
                    node,
                    fn_name,
                    f"float(…) on a non-constant inside jitted '{fn_name}' — "
                    "host sync on a traced value (use jnp dtypes/astype)",
                )
        return None

    def _finding(self, mod: SourceModule, node: ast.AST, fn_name: str, msg: str) -> Finding:
        line, col = node_pos(node)
        return Finding(
            rule=self.id,
            path=mod.path,
            line=line,
            col=col,
            message=msg,
            context=fn_name,
        )
