"""CLI gate: ``python -m p2pfl_tpu.analysis [paths…]`` — nonzero on new findings.

Exit codes: 0 clean (or every error finding baselined / suppressed),
1 new error findings, 2 usage error. ``--update-baseline`` rewrites the
baseline to accept the current tree (review the diff — the baseline is
committed debt, and inline ``# p2pfl: allow(rule-id)`` pragmas with a
justification are preferred for deliberate exceptions).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Sequence

from p2pfl_tpu.analysis.engine import analyze, load_baseline, new_findings, write_baseline
from p2pfl_tpu.analysis.findings import Severity

DEFAULT_BASELINE = ".p2pfl-check-baseline.json"


def _rules():
    from p2pfl_tpu.analysis.rules import ALL_RULES

    return ALL_RULES


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m p2pfl_tpu.analysis",
        description="p2pfl-check: enforce the repo's concurrency, donation and wire contracts",
    )
    parser.add_argument("paths", nargs="*", default=["p2pfl_tpu"], help="files/dirs to analyze")
    parser.add_argument(
        "--baseline",
        default=None,
        help=f"baseline file of accepted findings (default: {DEFAULT_BASELINE} if present)",
    )
    parser.add_argument(
        "--update-baseline",
        action="store_true",
        help=(
            "REWRITE the baseline from this run's findings (run it over the "
            "full tree — a narrowed run would drop accepted entries; "
            "incompatible with --select for the same reason)"
        ),
    )
    parser.add_argument(
        "--select", default=None, help="comma-separated rule ids to run (default: all)"
    )
    parser.add_argument("--list-rules", action="store_true", help="print the rule registry and exit")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)

    rules = list(_rules())
    if args.list_rules:
        for rule in rules:
            print(f"{rule.id:20s} {rule.summary}")
        return 0
    if args.select and args.update_baseline:
        # a rule-filtered run sees a SUBSET of findings; rewriting the
        # baseline from it would silently drop every other rule's
        # accepted entries and re-gate them on the next full run
        print("--update-baseline requires a full-rule run (drop --select)", file=sys.stderr)
        return 2
    if args.select:
        wanted = {tok.strip() for tok in args.select.split(",") if tok.strip()}
        unknown = wanted - {r.id for r in rules}
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in wanted]

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"no such path: {', '.join(missing)}", file=sys.stderr)
        return 2

    findings = analyze(args.paths, rules)

    baseline_path = args.baseline
    if baseline_path is None and os.path.exists(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE
    if args.update_baseline:
        path = baseline_path or DEFAULT_BASELINE
        write_baseline(path, findings)
        print(f"p2pfl-check: wrote {len(findings)} finding(s) to {path}")
        return 0
    baseline = load_baseline(baseline_path) if baseline_path else {}
    fresh = new_findings(findings, baseline)
    gating: List = [f for f in fresh if f.severity is Severity.ERROR]

    if args.json:
        print(
            json.dumps(
                [
                    {
                        "rule": f.rule,
                        "path": f.path,
                        "line": f.line,
                        "col": f.col,
                        "severity": f.severity.value,
                        "message": f.message,
                        "fingerprint": f.fingerprint,
                        "baselined": f.fingerprint in baseline,
                    }
                    for f in findings
                ],
                indent=2,
            )
        )
    else:
        for f in fresh:
            print(f.format())
    baselined = len(findings) - len(fresh)
    print(
        f"p2pfl-check: {len(findings)} finding(s) "
        f"({baselined} baselined, {len(fresh)} new, {len(gating)} gating) "
        f"over {len(rules)} rule(s)",
        file=sys.stderr,
    )
    return 1 if gating else 0


if __name__ == "__main__":
    sys.exit(main())
