"""p2pfl-check: static enforcement of the repo's concurrency/donation/wire contracts.

The framework's hardest bugs have all been violations of invariants that
used to exist only as prose: "no lock held across a send" (the async
plane's deadlock contract), "donated buffers must not be reused after a
failed dispatch" (the deleted-array poisoning class), "control-plane
merges are monotone and serialized by ``status_merge_lock``" (the round-0
wedge), "optional wire-header keys decode unchanged when absent and never
reach the protobuf interop schema" (the ``tc``/``vv``/``xp`` pattern), and
"nothing inside a jitted program reads mutable host state" (the
``BWD_MODE`` staleness class). ``check_partition_rules`` proved that
turning one of these contracts into a construction-time lint converts
silent corruption into loud errors; this package generalizes the idea to
an AST-based rule engine over the whole codebase (stdlib ``ast`` only —
analyzed code is parsed, never imported or executed).

Usage::

    python -m p2pfl_tpu.analysis p2pfl_tpu/          # exit 1 on findings
    python -m p2pfl_tpu.analysis --list-rules

Findings are suppressed inline with ``# p2pfl: allow(rule-id)`` (same line
or the line above, with a justification after the pragma) or accepted
wholesale via a committed baseline file (``--baseline`` /
``--update-baseline``) so the gate can land on a tree with known debt and
still block NEW violations. The finding/severity types here are shared
with the sharding lint (:mod:`p2pfl_tpu.parallel.sharding`), so every
static check in the repo reports in one format.
"""

from p2pfl_tpu.analysis.engine import (
    Rule,
    SourceModule,
    analyze,
    load_baseline,
    new_findings,
    write_baseline,
)
from p2pfl_tpu.analysis.findings import Finding, Severity

__all__ = [
    "Finding",
    "Rule",
    "Severity",
    "SourceModule",
    "analyze",
    "load_baseline",
    "new_findings",
    "write_baseline",
]
