"""Two-mode gossiper: async message plane + synchronous model-gossip loop.

Reference semantics (``p2pfl/communication/gossiper.py:31-243``):

(a) *Message plane* — a daemon thread drains a queue of
    ``(message, pending_neighbors)`` pairs, at most
    ``GOSSIP_MESSAGES_PER_PERIOD`` sends per ``GOSSIP_PERIOD``; a bounded
    ring of seen message ids provides network-wide dedup.

(b) *Model plane* — ``gossip_weights`` runs a synchronous tick loop on the
    calling (stage) thread: each tick picks ``GOSSIP_MODELS_PER_ROUND``
    random candidates, builds a per-candidate payload, sends it, and exits
    when there are no candidates, the early-stop predicate fires, or the
    observed status is unchanged for ``GOSSIP_EXIT_ON_X_EQUAL_ROUNDS`` ticks
    (convergence detector, reference 209-226).

Concurrent fan-out (departure from the reference, which sends strictly
sequentially on both planes): sends are dispatched through a bounded
``ThreadPoolExecutor`` of ``Settings.GOSSIP_SEND_WORKERS`` threads with a
per-batch wall-clock budget of ``Settings.GOSSIP_SEND_TIMEOUT``. A stalled
peer therefore costs one worker slot, not the tick: the other candidates'
payloads are already on the wire while it hangs, and the tick moves on once
the budget expires. A send still in flight marks its neighbor busy — the
next tick skips that neighbor instead of stacking a second worker behind the
same stall — and results are collected in submission order so the caller's
convergence accounting is deterministic.

Control-plane reliability (departure from the reference, where a failed
send simply loses the message): a message-plane send that returns a
definitive False is retried with exponential backoff + jitter
(``communication/reliability.py``) up to ``Settings.MESSAGE_RETRY_MAX``
attempts before being dropped loudly (``msg_retry_exhausted`` metric);
``CommunicationProtocol.send`` routes its broadcast failures into the same
queue. Every definitive outcome also feeds the protocol's per-neighbor
circuit breaker via ``on_result``, which is what accelerates heartbeat
eviction of genuinely dead peers. Payload construction (``model_fn``)
stays on the calling thread — aggregator/learner state is never read
concurrently — but it is LAZY: the model plane passes payload builders, and
``_dispatch_sends`` resolves each one right before submitting its
neighbor's task, so candidate ``i+1``'s encode (a fused device dispatch
plus the compressed-bytes D2H under ``Settings.WIRE_COMPRESSION_DEVICE``,
or an encode-once cache hit — ``learning/weights.py``) overlaps candidate
``i``'s in-flight send. Send outcomes are counted into the logger's
communication metrics (``gossip_send_ok`` / ``_fail`` / ``_timeout`` /
``_inflight_skip``).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import OrderedDict, deque
from functools import partial
from concurrent.futures import CancelledError, Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout  # builtin alias only on 3.11+
from typing import Callable, Optional

from p2pfl_tpu.communication.heartbeater import BEAT_CMD
from p2pfl_tpu.communication.message import Message
from p2pfl_tpu.communication.reliability import retry_delay
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.settings import Settings


class Gossiper:
    def __init__(
        self,
        self_addr: str,
        send_fn: Callable[..., bool],
        on_result: Optional[Callable[[str, bool], None]] = None,
    ) -> None:
        self.self_addr = self_addr
        self._send = send_fn  # (nei, env, create_connection=False) -> bool
        # definitive per-neighbor send outcomes (True/False, never
        # timeouts — a stalled-but-running send is not evidence of death)
        # are reported here; the protocol feeds its circuit breaker
        self._on_result = on_result
        # message-plane queue entries: (message, pending_neighbors, attempt)
        self._queue: deque[tuple[Message, list[str], int]] = deque()
        self._queue_cv = threading.Condition()
        # failed control sends wait out their backoff here:
        # (due_monotonic, seq, attempt, neighbor, message) — guarded by
        # _queue_cv's lock; the gossip thread drains due entries each tick
        self._retries: list[tuple[float, int, int, str, Message]] = []
        self._retry_seq = itertools.count()
        self._processed: OrderedDict[str, None] = OrderedDict()
        self._processed_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pool: Optional[ThreadPoolExecutor] = None
        # neighbor -> the specific send task that outlived its budget and is
        # STILL running — guarded by _stalled_lock, cleared when THAT task
        # completes (a different plane's send to the same neighbor finishing
        # must not unmark a still-stuck one). Only marked neighbors are
        # skipped. NOTE: ordering is guaranteed per neighbor only WITHIN a
        # dispatch batch; cross-batch sends to one neighbor may interleave
        # (receivers' dedup/overlap rejection absorbs reordering).
        self._stalled: dict[str, Future] = {}
        self._stalled_lock = threading.Lock()

    # ---- lifecycle ----

    def start(self) -> None:
        self._stop.clear()
        with self._queue_cv:
            # backoff entries scheduled against the previous run's overlay
            # state must not fire into a fresh start
            self._retries.clear()
        with self._stalled_lock:
            # a send that hung past stop() never runs its done-callback
            # (shutdown can't cancel RUNNING tasks), so its _stalled entry
            # would outlive the old pool and silently exclude that neighbor
            # from every future tick; a fresh start gets a clean slate (the
            # orphaned callback's identity check no-ops against new entries)
            self._stalled.clear()
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, Settings.GOSSIP_SEND_WORKERS),
            thread_name_prefix=f"gossip-send-{self.self_addr}",
        )
        self._thread = threading.Thread(
            target=self._run, name=f"gossiper-{self.self_addr}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._queue_cv:
            self._queue_cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        if self._pool is not None:
            # don't wait: a stalled peer's send may never return
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None

    # ---- dedup ring ----

    def check_and_set_processed(self, msg_id: str) -> bool:
        """True if unseen (and marks it seen); False for duplicates."""
        with self._processed_lock:
            if msg_id in self._processed:
                return False
            self._processed[msg_id] = None
            while len(self._processed) > Settings.AMOUNT_LAST_MESSAGES_SAVED:
                self._processed.popitem(last=False)
            return True

    def _report(self, nei: str, ok: bool) -> None:
        if self._on_result is not None:
            try:
                self._on_result(nei, ok)
            except Exception:  # noqa: BLE001 — observers must not break sends
                pass

    # ---- concurrent send dispatch (both planes) ----

    def _dispatch_sends(
        self,
        sends: list[tuple[str, object]],
        create_connection: bool = False,
        on_late_failure: Optional[Callable[[str, object], None]] = None,
    ) -> tuple[list[Optional[bool]], list[tuple[str, object]]]:
        """Fan ``(neighbor, envelope)`` sends out across the worker pool.

        Sends are grouped per neighbor — one worker task per batch per
        neighbor runs that neighbor's envelopes in order (distinct
        neighbors proceed concurrently; ordering across batches is NOT
        guaranteed). An envelope may be a zero-arg CALLABLE: it is resolved
        on the calling thread immediately before its neighbor's task is
        submitted, so payload construction (device encode, cache lookup)
        for candidate ``i+1`` overlaps candidate ``i``'s in-flight send
        instead of serializing ahead of the whole batch — while aggregator
        and learner state are still only ever read from this one thread. A
        callable resolving to ``None`` declines the send (its slot stays
        ``None`` in the results). Returns ``(results, skipped)``: per-send
        outcomes in submission order — True/False from the transport, or
        None when the send outlived its ``GOSSIP_SEND_TIMEOUT`` budget (it
        keeps running on its worker; the neighbor is marked stalled until
        that exact task finishes) — plus the sends that were never
        submitted because their neighbor was already stalled (the message
        plane requeues those; the model plane rebuilds next tick anyway).

        A timed-out send's LATE outcome is not discarded: when the worker
        eventually finishes, the result still feeds metrics and the
        breaker, and each envelope that ultimately FAILED is handed to
        ``on_late_failure`` (the message plane schedules a retry there —
        without this, a send that hung past its budget and then failed
        would be silently lost, the exact hole the retry queue closes for
        prompt failures).
        """
        pool = self._pool
        if pool is None or Settings.GOSSIP_SEND_WORKERS <= 1:
            # not started (unit tests poking the loop directly), or
            # explicitly sequential: send inline on the calling thread —
            # the pre-overhaul behavior, each plane its own serial lane
            out: list[Optional[bool]] = []
            for nei, env in sends:
                if callable(env):
                    env = env()
                if env is None:
                    out.append(None)
                    continue
                ok = self._send(nei, env, create_connection=create_connection)
                logger.log_comm_metric(
                    self.self_addr, "gossip_send_ok" if ok else "gossip_send_fail"
                )
                self._report(nei, bool(ok))
                out.append(ok)
            return out, []
        timeout = Settings.GOSSIP_SEND_TIMEOUT
        workers = max(1, Settings.GOSSIP_SEND_WORKERS)
        results: list[Optional[bool]] = [None] * len(sends)
        grouped: "OrderedDict[str, list[tuple[int, object]]]" = OrderedDict()
        for i, (nei, env) in enumerate(sends):
            grouped.setdefault(nei, []).append((i, env))

        # per-task start times: the per-send budget counts from when the
        # task actually STARTS on a worker — a healthy send queued behind a
        # full pool is not "stalled", it just hasn't run yet
        starts: dict[str, float] = {}

        def send_all(nei: str, envs: list[object]) -> list[bool]:
            starts[nei] = time.monotonic()
            return [self._send(nei, env, create_connection=create_connection) for env in envs]

        skipped: list[tuple[str, object]] = []
        futures: list[tuple[str, list[int], list[object], Future]] = []
        for nei, items in grouped.items():
            with self._stalled_lock:
                if nei in self._stalled:
                    # a previous batch's send to this peer is stuck past its
                    # budget — submitting more would strand a second worker
                    # behind the same stall
                    logger.log_comm_metric(
                        self.self_addr, "gossip_send_inflight_skip", len(items)
                    )
                    for i, env in items:
                        results[i] = False
                        skipped.append((nei, env))
                    continue
            # resolve lazy payloads NOW, on the calling thread: the previous
            # neighbor's task is already running on a worker, so this
            # build (encode dispatch + D2H of the compressed buffers)
            # hides under that in-flight send
            resolved: list[tuple[int, object]] = []
            for i, env in items:
                if callable(env):
                    env = env()
                if env is None:
                    continue  # payload declined — not a send, not a failure
                resolved.append((i, env))
            if not resolved:
                continue
            try:
                fut = pool.submit(send_all, nei, [env for _i, env in resolved])
            except RuntimeError:  # stop() shut the pool down under us
                for i, _env in resolved:
                    results[i] = False
                continue

            def _done(_fut, nei=nei):
                with self._stalled_lock:
                    # only the task that set the mark may clear it — another
                    # plane's send to the same neighbor finishing must not
                    # unmark a still-stuck one
                    if self._stalled.get(nei) is _fut:
                        del self._stalled[nei]

            fut.add_done_callback(_done)
            futures.append(
                (nei, [i for i, _env in resolved], [env for _i, env in resolved], fut)
            )
        # everything-is-stuck backstop: enough budget for every task to get
        # a worker slot and its own timeout, then stop waiting regardless
        hard_deadline = time.monotonic() + timeout * (1 + len(futures) / workers)
        for nei, idxs, envs, fut in futures:
            timed_out = False
            while True:
                now = time.monotonic()
                started = starts.get(nei)
                if not fut.done():  # a finished task is never "timed out"
                    if started is not None and now - started >= timeout:
                        timed_out = True  # genuinely running too long
                        break
                    if now >= hard_deadline:
                        timed_out = True
                        break
                # queued tasks get short polls; running ones their remainder
                wait = 0.05 if started is None else max(0.0, started + timeout - now)
                try:
                    oks = fut.result(timeout=max(0.0, min(wait, hard_deadline - now)))
                except (FuturesTimeout, TimeoutError):
                    continue
                except CancelledError:  # stop() cancelled the queued send
                    oks = None
                except Exception as exc:  # noqa: BLE001 — transport raised on the worker
                    oks = None
                    logger.debug(self.self_addr, f"Send to {nei} raised {exc!r}")
                if oks is None:
                    for i in idxs:
                        results[i] = False
                    logger.log_comm_metric(self.self_addr, "gossip_send_fail", len(idxs))
                    self._report(nei, False)
                else:
                    for i, ok in zip(idxs, oks):
                        results[i] = bool(ok)
                        logger.log_comm_metric(
                            self.self_addr, "gossip_send_ok" if ok else "gossip_send_fail"
                        )
                        self._report(nei, bool(ok))
                break
            if timed_out:
                with self._stalled_lock:
                    # mark only tasks that actually STARTED and overran: a
                    # task still queued at the hard deadline is a healthy
                    # neighbor behind a congested pool, not a stall
                    if not fut.done() and starts.get(nei) is not None:
                        self._stalled[nei] = fut

                # the late outcome still matters: when the hung worker
                # finally finishes, feed metrics + breaker and hand each
                # envelope that FAILED to the caller (message plane retries
                # it) — otherwise a send that overran its budget and then
                # returned False would be silently lost
                def _late(f, nei=nei, envs=envs):
                    try:
                        oks = f.result()
                    except Exception:  # noqa: BLE001 — cancelled or transport raised
                        oks = None
                    if oks is None:
                        oks = [False] * len(envs)
                    for env, ok in zip(envs, oks):
                        logger.log_comm_metric(
                            self.self_addr,
                            "gossip_send_ok" if ok else "gossip_send_fail",
                        )
                        self._report(nei, bool(ok))
                        if not ok and on_late_failure is not None:
                            try:
                                on_late_failure(nei, env)
                            except Exception:  # noqa: BLE001 — observer must not kill the worker
                                pass

                fut.add_done_callback(_late)
                logger.log_comm_metric(self.self_addr, "gossip_send_timeout")
                logger.debug(
                    self.self_addr,
                    f"Send to {nei} exceeded GOSSIP_SEND_TIMEOUT "
                    f"({timeout}s) — continuing without it",
                )
        return results, skipped

    # ---- message plane ----

    def add_message(self, msg: Message, pending_neis: list[str], attempt: int = 0) -> None:
        if not pending_neis:
            return
        with self._queue_cv:
            self._queue.append((msg, list(pending_neis), attempt))
            self._queue_cv.notify()

    def schedule_retry(self, nei: str, msg: Message, attempt: int) -> None:
        """Queue retry ``attempt`` (1-based) of a failed control send.

        The entry waits out an exponential backoff (``reliability.
        retry_delay``) on the gossip thread, then rides a normal dispatch
        batch. Beyond ``Settings.MESSAGE_RETRY_MAX`` the message is
        dropped loudly (``msg_retry_exhausted``) — by then the breaker
        has marked the neighbor suspect and eviction owns the rest.

        Beats are exempt, HERE, for every path that funnels into the
        retry queue (direct sends, the queue's failure loop, late
        failures of budget-overrunning sends): a beat is superseded by
        the next one every HEARTBEAT_PERIOD, so a retry would only
        deliver stale liveness info while its backoff entries crowd the
        per-tick budget out from under genuine control messages during
        exactly the failure windows that matter (the failed send still
        fed the breaker).
        """
        from p2pfl_tpu.management.telemetry import telemetry

        if msg.cmd == BEAT_CMD:
            return
        if attempt > Settings.MESSAGE_RETRY_MAX:
            logger.log_comm_metric(self.self_addr, "msg_retry_exhausted")
            telemetry.event(
                self.self_addr,
                "retry_exhausted",
                kind="retry",
                attrs={"peer": nei, "cmd": msg.cmd},
            )
            logger.debug(
                self.self_addr,
                f"Dropping '{msg.cmd}' for {nei} after "
                f"{Settings.MESSAGE_RETRY_MAX} retries",
            )
            return
        delay = retry_delay(attempt)
        due = time.monotonic() + delay
        logger.log_comm_metric(self.self_addr, "msg_retry_scheduled")
        # retry-plane event: the RoundReport sums delay_s per peer into the
        # round's retry/backoff-wait attribution
        telemetry.event(
            self.self_addr,
            "retry_scheduled",
            kind="retry",
            attrs={"peer": nei, "cmd": msg.cmd, "attempt": attempt, "delay_s": round(delay, 4)},
        )
        with self._queue_cv:
            heapq.heappush(self._retries, (due, next(self._retry_seq), attempt, nei, msg))
            self._queue_cv.notify()

    def _pop_due_retries_locked(self) -> tuple[list[tuple[str, Message, int]], Optional[float]]:
        """(due retries as (nei, msg, attempt), next due time). Caller
        holds ``_queue_cv``."""
        now = time.monotonic()
        due: list[tuple[str, Message, int]] = []
        while self._retries and self._retries[0][0] <= now:
            _due, _seq, attempt, nei, msg = heapq.heappop(self._retries)
            due.append((nei, msg, attempt))
        return due, (self._retries[0][0] if self._retries else None)

    def _run(self) -> None:
        while not self._stop.is_set():
            with self._queue_cv:
                due, next_due = self._pop_due_retries_locked()
                if not self._queue and not due:
                    wait = Settings.GOSSIP_PERIOD
                    if next_due is not None:
                        wait = min(wait, max(next_due - time.monotonic(), 0.01))
                    self._queue_cv.wait(timeout=wait)
                    continue
                # (neighbor, message, attempt) — attempt 0 is a first
                # delivery, >= 1 a backoff retry re-entering the batch
                batch: list[tuple[str, Message, int]] = list(due)
                budget = Settings.GOSSIP_MESSAGES_PER_PERIOD - len(batch)
                while self._queue and budget > 0:
                    msg, neis, attempt = self._queue.popleft()
                    take, rest = neis[:budget], neis[budget:]
                    batch.extend((n, msg, attempt) for n in take)
                    budget -= len(take)
                    if rest:
                        self._queue.appendleft((msg, rest, attempt))
                        break
            if self._stop.is_set():
                return
            attempts = {(n, id(m)): a for n, m, a in batch}

            def _late_failure(nei: str, env: object, attempts=attempts) -> None:
                # a send that overran its budget and THEN failed on its
                # worker is still a definitive failure — retry it like a
                # prompt one (schedule_retry exempts beats)
                if isinstance(env, Message):
                    self.schedule_retry(nei, env, attempts.get((nei, id(env)), 0) + 1)

            results, skipped = self._dispatch_sends(
                [(n, m) for n, m, _a in batch], on_late_failure=_late_failure
            )
            # a send skipped for a stalled neighbor was never attempted —
            # requeued below at the same attempt, not counted as a failure
            skipset = {(nei, id(msg)) for nei, msg in skipped}
            for (nei, msg, attempt), ok in zip(batch, results):
                if (nei, id(msg)) in skipset:
                    continue
                if ok is False:
                    # definitive transport failure: back off and retry —
                    # a plain False must never silently lose a broadcast
                    # (relayed beats ride this queue too; schedule_retry
                    # exempts them)
                    self.schedule_retry(nei, msg, attempt + 1)
                elif ok and attempt > 0:
                    logger.log_comm_metric(self.self_addr, "msg_retry_ok")
                # ok is None: the send outlived its budget and is still
                # running on its worker — _dispatch_sends' late-result
                # callback will report it (and retry via _late_failure if
                # it ultimately fails)
            for nei, msg in skipped:
                # control messages must not be lost to a transient stall —
                # requeue for the stalled neighbor (the pre-overhaul serial
                # plane eventually delivered them); delivery resumes once
                # the stuck task completes or the neighbor is evicted
                self.add_message(msg, [nei], attempt=attempts.get((nei, id(msg)), 0))
            time.sleep(Settings.GOSSIP_PERIOD)

    # ---- model plane ----

    def gossip_weights(
        self,
        early_stopping_fn: Callable[[], bool],
        get_candidates_fn: Callable[[], list[str]],
        status_fn: Callable[[], object],
        model_fn: Callable[[str], Optional[object]],
        period: Optional[float] = None,
        create_connection: bool = False,
    ) -> None:
        from p2pfl_tpu.communication.protocol import random_subset

        period = Settings.GOSSIP_MODELS_PERIOD if period is None else period
        last_status: object = None
        equal_ticks = 0
        while True:
            if early_stopping_fn() or self._stop.is_set():
                return
            candidates = get_candidates_fn()
            if not candidates:
                return
            status = status_fn()
            if status == last_status:
                equal_ticks += 1
                if equal_ticks >= Settings.GOSSIP_EXIT_ON_X_EQUAL_ROUNDS:
                    logger.debug(
                        self.self_addr,
                        f"Gossip stalled for {equal_ticks} ticks — stopping (status={status})",
                    )
                    return
            else:
                equal_ticks = 0
                last_status = status
            # payloads stay lazily built ON the calling thread (learner /
            # aggregator state is never read concurrently), but resolution
            # happens per neighbor at submit time inside _dispatch_sends:
            # candidate i+1's encode (a device dispatch + compressed-bytes
            # D2H, or a payload-cache hit) overlaps candidate i's in-flight
            # send instead of running before any byte hits the wire —
            # compression hides under the fan-out
            sends: list[tuple[str, object]] = [
                (nei, partial(model_fn, nei))
                for nei in random_subset(candidates, Settings.GOSSIP_MODELS_PER_ROUND)
            ]
            if sends:
                self._dispatch_sends(sends, create_connection=create_connection)
            time.sleep(period)
