"""The one registry of OPTIONAL envelope header keys.

Every optional key the native wire envelope may carry (``tc``, ``vv``,
``xp``) follows the same backward-compat contract, established when the
flight recorder first put ``tc`` on the wire:

- **absent-frame decode**: a frame without the key decodes exactly as a
  pre-key frame did (``d.get(key)`` — never ``d[key]``);
- **guarded encode**: ``None`` is never serialized — the encoder writes
  the key only under an ``is not None`` guard, so old receivers keep
  parsing new senders and byte-for-byte golden frames stay stable;
- **memory byte path copies it**: the in-memory transport's
  ``MEMORY_WIRE_CODEC`` re-wrap (``communication/memory.py``) must carry
  the key's backing attributes onto the re-built envelope/update, or
  simulations silently diverge from the network transports;
- **never in the protobuf interop schema**: the reference's proto schema
  (``proto_wire.py``) predates these keys and must stay byte-compatible
  with real reference nodes — optional keys ride only the native JSON
  envelope;
- **streamed transfers inherit for free**: the streaming byte plane's
  first frame is a payload-free envelope built by the SAME
  ``encode_weights`` (``grpc_transport.py`` passes ``payload=b""``), so
  every key declared here rides a chunked ``send_weights_stream``
  transfer without any per-key plumbing — a new optional key needs no
  streaming-specific work.

Declaring a key here is what makes the contract enforceable: the
``wire-header-compat`` analyzer rule (:mod:`p2pfl_tpu.analysis`)
cross-checks every declared key against all three codec files and fails
CI when a new key skips any leg of the pattern. Adding an optional
header = add a :class:`WireHeader` entry + satisfy the rule.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple


@dataclass(frozen=True)
class WireHeader:
    """One optional envelope header key and where it must be handled.

    ``planes``: which native codecs carry it — ``"message"`` (control
    plane: ``encode_message``/``decode_message``) and/or ``"weights"``
    (data plane: ``encode_weights``/``decode_weights``).

    ``memory_copies``: ``(constructor, kwarg)`` pairs the in-memory byte
    path's re-wrap must pass — e.g. ``("ModelUpdate", "version")`` means
    the rebuilt wire update must copy ``version=``.
    """

    key: str
    planes: Tuple[str, ...]
    memory_copies: Tuple[Tuple[str, str], ...]
    doc: str


OPTIONAL_WIRE_HEADERS: Tuple[WireHeader, ...] = (
    WireHeader(
        key="tc",
        planes=("message", "weights"),
        memory_copies=(("WeightsEnvelope", "trace_ctx"),),
        doc=(
            "flight-recorder trace context (trace_id, parent_span_id) — "
            "management/telemetry.py; joins receiver spans to the "
            "sender's causal tree"
        ),
    ),
    WireHeader(
        key="vv",
        planes=("weights",),
        memory_copies=(("ModelUpdate", "version"),),
        doc=(
            "async-federation version triple (origin, seq, base_version) "
            "— federation/staleness.py; dedup + staleness weighting"
        ),
    ),
    WireHeader(
        key="xp",
        planes=("message", "weights"),
        memory_copies=(("ModelUpdate", "xp"), ("WeightsEnvelope", "xp")),
        doc=(
            "experiment identity minted by the start_learning initiator — "
            "receivers filter cross-experiment stragglers exactly"
        ),
    ),
    WireHeader(
        key="sp",
        planes=("weights",),
        memory_copies=(("ModelUpdate", "sp"),),
        doc=(
            "shard-plane handshake triple (slice_shape, slice_index, "
            "codec) — communication/ici.py; byte-path frames advertise "
            "the sender's slice topology so receivers can validate "
            "co-location for the ICI weights plane"
        ),
    ),
)
