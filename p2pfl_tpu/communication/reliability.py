"""Control-plane reliability: retry backoff policy + per-neighbor breaker.

Two pieces the overlay was missing (ISSUE 5 / the "silent message loss"
problem: a failed ``_send`` returned False and the broadcast was simply
gone):

- :func:`retry_delay` — bounded exponential backoff with jitter for
  message-plane retries (the :class:`~p2pfl_tpu.communication.gossiper.
  Gossiper` schedules failed control sends through it, up to
  ``Settings.MESSAGE_RETRY_MAX`` attempts).
- :class:`CircuitBreaker` — per-neighbor consecutive-failure tracking.
  After ``Settings.BREAKER_THRESHOLD`` consecutive send failures a
  neighbor becomes *suspect*; the heartbeater evicts suspect neighbors
  after ``Settings.BREAKER_SUSPECT_TIMEOUT`` seconds of beat silence
  instead of waiting out the full ``HEARTBEAT_TIMEOUT`` — an
  accrual-style failure detector in the spirit of Hayashibara et al.
  (*The φ Accrual Failure Detector*, SRDS 2004): send outcomes feed the
  suspicion level continuously rather than a single binary timeout. One
  success closes the breaker.

Every transition is counted into the logger's communication metrics
(``breaker_open`` / ``breaker_close``; the heartbeater adds
``breaker_suspect_evict``), so chaos tests can assert that retries stay
bounded and suspects actually accelerate eviction.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.settings import Settings


def retry_delay(attempt: int, rng: Optional[random.Random] = None) -> float:
    """Backoff before retry ``attempt`` (1-based): ``BASE * 2**(a-1)``
    capped at ``MESSAGE_RETRY_CAP``, times U(0.5, 1.0) jitter so a burst
    of failures against one neighbor does not retry in lockstep."""
    r = rng.random() if rng is not None else random.random()
    base = Settings.MESSAGE_RETRY_BASE * (2 ** max(attempt - 1, 0))
    return min(base, Settings.MESSAGE_RETRY_CAP) * (0.5 + r / 2)


class CircuitBreaker:
    """Consecutive-failure tracking per neighbor, thread-safe.

    ``record`` is called with every send outcome (all planes — beats,
    control gossip, model gossip all route through the protocol's send
    seam). State per neighbor: consecutive failure count, and — once the
    count crosses ``Settings.BREAKER_THRESHOLD`` — the monotonic time the
    breaker opened. Suspects are reported to the heartbeater's eviction
    sweep; :meth:`forget` drops all state when a neighbor is evicted or
    deliberately disconnected.
    """

    def __init__(self, self_addr: str) -> None:
        self.self_addr = self_addr
        self._lock = threading.Lock()
        self._failures: dict[str, int] = {}
        self._suspect_since: dict[str, float] = {}
        #: most recent failure per neighbor — the unreachable-despite-beats
        #: eviction requires the evidence to be ONGOING, not just old (see
        #: :meth:`suspects_older_than`)
        self._last_failure: dict[str, float] = {}

    def record(self, nei: str, ok: bool) -> None:
        with self._lock:
            if ok:
                self._failures.pop(nei, None)
                self._last_failure.pop(nei, None)
                if self._suspect_since.pop(nei, None) is not None:
                    logger.log_comm_metric(self.self_addr, "breaker_close")
                    from p2pfl_tpu.management.telemetry import telemetry

                    telemetry.event(
                        self.self_addr, "breaker_close", kind="fault", attrs={"peer": nei}
                    )
                    logger.info(
                        self.self_addr,
                        f"Breaker closed for {nei} — send succeeded again",
                    )
                return
            count = self._failures.get(nei, 0) + 1
            self._failures[nei] = count
            self._last_failure[nei] = time.monotonic()
            if count >= Settings.BREAKER_THRESHOLD and nei not in self._suspect_since:
                self._suspect_since[nei] = time.monotonic()
                logger.log_comm_metric(self.self_addr, "breaker_open")
                # flight-recorder event on the affected edge: inside a send
                # span when the failing send is what tripped the breaker
                from p2pfl_tpu.management.telemetry import telemetry

                telemetry.event(
                    self.self_addr,
                    "breaker_open",
                    kind="fault",
                    attrs={"peer": nei, "failures": count},
                )
                logger.info(
                    self.self_addr,
                    f"Breaker open for {nei}: {count} consecutive send "
                    "failures — suspect (early heartbeat eviction armed)",
                )

    def is_suspect(self, nei: str) -> bool:
        with self._lock:
            return nei in self._suspect_since

    def suspects(self) -> set[str]:
        with self._lock:
            return set(self._suspect_since)

    def suspects_older_than(self, age: float, fresh_within: Optional[float] = None) -> set[str]:
        """Neighbors whose breaker has been open for at least ``age``
        seconds — i.e. not one successful send in all that time. The
        heartbeater evicts these even if their beats still arrive (a
        one-way partition: the peer is alive but unreachable, so it is
        useless as a gossip target).

        ``fresh_within`` additionally requires the MOST RECENT failure to
        be at most that many seconds old: an open breaker whose evidence
        stopped accruing (the peer simply fell out of every send path —
        e.g. a non-direct gossip target the model plane converged away
        from) says nothing about the peer NOW, and evicting a live,
        beating neighbor on a stale burst of failures would be a false
        positive. Direct neighbors are beat targets every
        ``HEARTBEAT_PERIOD``, so a genuinely unreachable one keeps its
        evidence fresh for free."""
        now = time.monotonic()
        with self._lock:
            return {
                n
                for n, t0 in self._suspect_since.items()
                if now - t0 >= age
                and (
                    fresh_within is None
                    or now - self._last_failure.get(n, 0.0) <= fresh_within
                )
            }

    def forget(self, nei: str) -> None:
        with self._lock:
            self._failures.pop(nei, None)
            self._suspect_since.pop(nei, None)
            self._last_failure.pop(nei, None)

    def reset(self) -> None:
        with self._lock:
            self._failures.clear()
            self._suspect_since.clear()
            self._last_failure.clear()
