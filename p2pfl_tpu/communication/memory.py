"""In-process transport: simulation of N nodes without sockets.

Reference equivalent: ``p2pfl/communication/memory/`` (SURVEY §2.6) — a
process-global registry maps address → protocol instance and "sending" is a
direct method call on the receiver. Two deliberate upgrades over the
reference:

- weights are passed **by reference** as a live :class:`ModelUpdate`, so a
  simulated federation never serializes: pytrees stay device-resident
  (the reference memory transport still moves pickled bytes);
  ``Settings.MEMORY_WIRE_CODEC=True`` opts back into the byte path — the
  payload is encoded on send and materialized by the receiver's learner,
  exactly like a network transport — so the wire codec and the encode-once
  payload cache are testable and benchable without sockets
  (``bench_gossip.py``);
- delivery goes through the same :meth:`CommunicationProtocol.handle_message`
  / :meth:`handle_weights` dispatch as every other transport, so TTL, dedup
  and command semantics are tested identically.
"""

from __future__ import annotations

import itertools
import threading
from typing import Optional

from p2pfl_tpu.communication.message import Message, WeightsEnvelope
from p2pfl_tpu.communication.neighbors import Neighbors
from p2pfl_tpu.communication.protocol import CommunicationProtocol
from p2pfl_tpu.exceptions import NeighborNotConnectedError


class MemoryRegistry:
    """Process-global address → running protocol map (``server_singleton.py:22``)."""

    _lock = threading.Lock()
    _servers: dict[str, "InMemoryProtocol"] = {}
    _counter = itertools.count(1)

    @classmethod
    def register(cls, addr: str, proto: "InMemoryProtocol") -> None:
        with cls._lock:
            cls._servers[addr] = proto

    @classmethod
    def unregister(cls, addr: str) -> None:
        with cls._lock:
            cls._servers.pop(addr, None)

    @classmethod
    def get(cls, addr: str) -> Optional["InMemoryProtocol"]:
        with cls._lock:
            return cls._servers.get(addr)

    @classmethod
    def next_address(cls) -> str:
        with cls._lock:
            return f"node-{next(cls._counter)}"

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._servers.clear()
            cls._counter = itertools.count(1)


class InMemoryNeighbors(Neighbors):
    def _connect(self, addr: str, handshake: bool):
        peer = MemoryRegistry.get(addr)
        if peer is None:
            raise NeighborNotConnectedError(f"no in-memory server at {addr}")
        if handshake:
            peer.handshake(self.self_addr)
        return peer

    def _disconnect(self, addr: str, conn, notify: bool) -> None:
        peer = MemoryRegistry.get(addr)
        if peer is not None and notify:
            peer.peer_disconnected(self.self_addr)


class InMemoryProtocol(CommunicationProtocol):
    """N simulated nodes in one process; delivery is a direct method call."""

    def __init__(self, address: Optional[str] = None) -> None:
        super().__init__(address or MemoryRegistry.next_address())
        self._running = False

    # ---- transport pieces ----

    def _make_neighbors(self) -> Neighbors:
        return InMemoryNeighbors(self._address)

    def _server_start(self) -> None:
        MemoryRegistry.register(self._address, self)
        self._running = True

    def _server_stop(self) -> None:
        self._running = False
        MemoryRegistry.unregister(self._address)

    def crash(self) -> None:
        """Hard-crash simulation (``communication/faults.py:hard_crash``):
        vanish from the registry with NO disconnect notifications — unlike
        ``stop()``, peers only find out through send failures and
        heartbeat silence, which is what chaos tests exercise."""
        self._server_stop()

    def _send_to_neighbor(self, nei: str, env, create_connection: bool = False) -> bool:
        info = self.neighbors.get(nei)
        if info is None or not info.direct:
            if not create_connection:
                return False
        peer = MemoryRegistry.get(nei)
        if peer is None or not peer._running:
            return False
        try:
            if isinstance(env, WeightsEnvelope):
                from p2pfl_tpu.settings import Settings

                # shard-native weights plane (Settings.WEIGHTS_PLANE="ici"):
                # model payloads between co-located nodes move device-to-
                # device (communication/ici.py) — this sits INSIDE the
                # transport send, so the fault injector, send spans and
                # breaker feeds at the _do_send seam wrap it unchanged; an
                # ineligible peer falls through to the byte/reference path
                from p2pfl_tpu.communication.ici import try_shard_send

                handled = try_shard_send(self, nei, env)
                if handled is not None:
                    return handled

                if Settings.MEMORY_WIRE_CODEC and env.update.params is not None:
                    # byte-path simulation: ship encoded bytes (hitting the
                    # payload cache like a network transport would) and let
                    # the receiver materialize against its own learner.
                    # Every optional header in wire_headers.py must ride
                    # this re-wrap (enforced by wire-header-compat) or
                    # simulations diverge from the network transports.
                    # Large payloads take the streaming pipeline (bounded
                    # producer/consumer queue) exactly like the gRPC plane.
                    from p2pfl_tpu.learning.weights import (
                        ModelUpdate,
                        estimate_payload_bytes,
                    )

                    est = estimate_payload_bytes(env.update)
                    if (
                        Settings.WIRE_STREAM_ENABLED
                        and est is not None
                        and est >= Settings.WIRE_STREAM_THRESHOLD * 1024 * 1024
                    ):
                        return self._stream_to_peer(peer, env)
                    wire = ModelUpdate(
                        params=None,
                        contributors=list(env.update.contributors),
                        num_samples=env.update.num_samples,
                        encoded=env.update.encode(),
                        version=env.update.version,
                        xp=env.update.xp,
                        sp=env.update.sp,
                    )
                    env = WeightsEnvelope(
                        env.source, env.round, env.cmd, wire, env.msg_id,
                        trace_ctx=env.trace_ctx, xp=env.xp,
                    )
                return peer.handle_weights(env).ok
            if isinstance(env, Message):
                return peer.handle_message(env).ok
        except Exception:  # noqa: BLE001 — peer died mid-call
            return False
        return False

    def _stream_to_peer(self, peer: "InMemoryProtocol", env: WeightsEnvelope) -> bool:
        """Streaming byte path without sockets: a producer thread pumps the
        chunk list through a BOUNDED queue (``Settings.WIRE_STREAM_WINDOW``
        frames) while the receiver's incremental decoder drains it — at most
        window × chunk payload bytes are in flight, and the receiver decodes
        chunk i while the producer is queuing chunk i+1. Any receiver-side
        abort surfaces as this ONE send returning False, same as gRPC."""
        import queue

        from p2pfl_tpu.learning.weights import ModelUpdate
        from p2pfl_tpu.settings import Settings

        try:
            # lazy framing: the producer thread below pulls frames as the
            # queue drains, so at most window × chunk bytes are framed and
            # in flight at once (the encode/cache work happens here)
            chunks = env.update.iter_chunks()
        except Exception:  # noqa: BLE001 — encode trouble = failed send
            return False
        wire = ModelUpdate(
            params=None,
            contributors=list(env.update.contributors),
            num_samples=env.update.num_samples,
            encoded=None,
            version=env.update.version,
            xp=env.update.xp,
            sp=env.update.sp,
        )
        wire_env = WeightsEnvelope(
            env.source, env.round, env.cmd, wire, env.msg_id,
            trace_ctx=env.trace_ctx, xp=env.xp,
        )
        q: "queue.Queue" = queue.Queue(maxsize=max(1, Settings.WIRE_STREAM_WINDOW))
        abort = threading.Event()  # set when the receiver stops draining

        def _put(item) -> bool:
            while not abort.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        def _produce() -> None:
            for c in chunks:
                if not _put(c):
                    return
            _put(None)

        producer = threading.Thread(target=_produce, daemon=True, name="stream-pump")
        producer.start()

        def _drain():
            while True:
                c = q.get()
                if c is None:
                    return
                yield c

        try:
            return peer.handle_weights_stream(wire_env, _drain()).ok
        finally:
            abort.set()
            producer.join(timeout=5)

    # ---- server-side entry points (called by peers) ----

    def handshake(self, source: str) -> None:
        """Reverse direct edge, no handshake back (``grpc_server.py:102``)."""
        if self._running:
            self.neighbors.add(source, non_direct=False, handshake=False)

    def peer_disconnected(self, source: str) -> None:
        if self._running:
            self.neighbors.remove(source)
