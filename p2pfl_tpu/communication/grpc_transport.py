"""Real-network transport over gRPC.

Semantic spec is the reference's proto service
(``p2pfl/communication/grpc/proto/node.proto:26-57``): four unary RPCs —
``handshake``, ``disconnect``, ``send_message``, ``send_weights`` — over
insecure channels, control messages TTL-flooded with dedup, weight payloads
point-to-point. This environment ships grpcio but no stub generator, so the
service uses gRPC *generic handlers* over raw bytes with a compact envelope
codec (JSON header + the framework's own zero-pickle weights format from
``learning/weights.py``) — byte-layout documented in ``proto/node.proto``.

Interop: ``Settings.WIRE_FORMAT="protobuf"`` switches OUTGOING frames to
the reference's protobuf schema (``proto_wire.py``) AND dials the
reference's real gRPC method paths — its proto declares ``package node;``
(``node.proto:24``), so its generated stubs serve and call
``/node.NodeServices/{handshake,disconnect,send_message,send_weights}``
(``node_pb2_grpc.py:44``). The server registers BOTH that path and this
framework's native ``/p2pfl.NodeServices/`` prefix, and every entry point
sniffs the frame format — so mixed-format federations, including a real
reference node on the control plane, interoperate frame by frame. Replies
match the request's format (a no-error ``ResponseMessage`` serializes to
zero bytes, which also parses as the ``google.protobuf.Empty`` the
reference expects from ``disconnect``).

Weight payloads cross the wire as ``ModelUpdate.encoded`` bytes and are
materialized against the receiving learner's parameter structure
(name-aware, not positional — unlike the reference's zip-by-order decode,
``lightning_learner.py:126-138``).
"""

from __future__ import annotations

import json
import threading
from concurrent import futures
from typing import Optional

import grpc

from p2pfl_tpu.communication import proto_wire as pw
from p2pfl_tpu.communication.message import Message, WeightsEnvelope
from p2pfl_tpu.communication.neighbors import Neighbors
from p2pfl_tpu.communication.protocol import CommunicationProtocol
from p2pfl_tpu.exceptions import NeighborNotConnectedError
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.settings import Settings

_SERVICE = "/p2pfl.NodeServices/"
#: the reference's actual service path — its proto declares ``package node;``
#: so generated stubs use /node.NodeServices/* (reference node_pb2_grpc.py:44)
_SERVICE_REF = "/node.NodeServices/"
_METHODS = ("handshake", "disconnect", "send_message", "send_weights")
#: client-streaming RPCs (chunked weights transfers) — routed through
#: ``grpc.stream_unary_rpc_method_handler`` instead of unary_unary
_STREAM_METHODS = ("send_weights_stream",)


# ---- envelope codec ----

# Optional header keys ("tc"/"vv"/"xp") are declared in ONE registry —
# communication/wire_headers.py — and every leg of their compat contract
# (guarded encode, .get() decode, memory byte-path copy, no protobuf
# leak) is enforced against these functions by the wire-header-compat
# rule of `python -m p2pfl_tpu.analysis`. Add a key there first.


def encode_message(msg: Message) -> bytes:
    d = {
        "src": msg.source,
        "cmd": msg.cmd,
        "args": list(msg.args),
        "round": msg.round,
        "ttl": msg.ttl,
        "id": msg.msg_id,
    }
    if msg.trace_ctx is not None:
        # flight-recorder trace context (management/telemetry.py): optional
        # key — absent on old senders, ignored by old receivers, so both
        # wire directions stay compatible with pre-telemetry frames
        d["tc"] = list(msg.trace_ctx)
    if msg.xp is not None:
        # experiment identity (Node.set_start_learning) — optional like
        # "tc": old frames decode unchanged, receivers use it to filter
        # cross-experiment stragglers exactly
        d["xp"] = msg.xp
    return json.dumps(d).encode()


def _trace_ctx(d: dict):
    tc = d.get("tc")
    return (str(tc[0]), str(tc[1])) if tc else None


def decode_message(data: bytes) -> Message:
    d = json.loads(data.decode())
    return Message(
        d["src"], d["cmd"], tuple(d["args"]), d["round"], d["ttl"], d["id"],
        trace_ctx=_trace_ctx(d), xp=d.get("xp"),
    )


def encode_weights(env: WeightsEnvelope, payload: Optional[bytes] = None) -> bytes:
    # update.encode() is served by the encode-once payload cache while the
    # sender's model version is unchanged (learning/weights.py) — only this
    # small envelope header is built per send. ``payload`` overrides the
    # update's encoded bytes: the streaming path passes b"" to build the
    # payload-free header frame that precedes the P2TC chunks (the header
    # must carry every optional wire key, so it is built HERE — the one
    # function the wire-header-compat rule audits for guarded stores).
    d = {
        "src": env.source,
        "round": env.round,
        "cmd": env.cmd,
        "contributors": env.update.contributors,
        "num_samples": env.update.num_samples,
        "id": env.msg_id,
    }
    if env.trace_ctx is not None:
        d["tc"] = list(env.trace_ctx)  # optional — see encode_message
    if env.update.version is not None:
        # async-federation version triple (origin, seq, base_version) —
        # optional like "tc": absent on sync senders, ignored by old
        # receivers; the protobuf interop schema never carries it
        d["vv"] = list(env.update.version)
    xp = env.xp or env.update.xp
    if xp is not None:
        # experiment identity — optional like "tc"/"vv"; rides BOTH the
        # envelope and the decoded update so stash filters see it
        d["xp"] = xp
    if env.update.sp is not None:
        # shard-plane handshake triple (slice_shape, slice_index, codec)
        # — optional like "vv": a byte-path frame advertising the
        # sender's slice topology so receivers can validate co-location
        # for the ICI weights plane (communication/ici.py)
        d["sp"] = [list(env.update.sp[0]), env.update.sp[1], env.update.sp[2]]
    header = json.dumps(d).encode()
    body = env.update.encode() if payload is None else payload
    return b"".join((len(header).to_bytes(4, "little"), header, body))


def _sp_header(d: dict):
    sp = d.get("sp")
    return (tuple(sp[0]), int(sp[1]), str(sp[2])) if sp else None


def decode_weights(data: bytes) -> WeightsEnvelope:
    hlen = int.from_bytes(data[:4], "little")
    d = json.loads(data[4 : 4 + hlen].decode())
    vv = d.get("vv")
    update = ModelUpdate(
        params=None,
        contributors=list(d["contributors"]),
        num_samples=int(d["num_samples"]),
        encoded=data[4 + hlen :],
        version=(str(vv[0]), int(vv[1]), int(vv[2])) if vv else None,
        xp=d.get("xp"),
        sp=_sp_header(d),
    )
    return WeightsEnvelope(
        d["src"], d["round"], d["cmd"], update, d["id"], trace_ctx=_trace_ctx(d),
        xp=d.get("xp"),
    )


def _reply(ok: bool, error: str = "") -> bytes:
    return json.dumps({"ok": ok, "error": error}).encode()


def _reply_ok(data: bytes) -> bool:
    try:
        return bool(json.loads(data.decode()).get("ok"))
    except Exception:  # noqa: BLE001
        return False


def _reply_error(data: bytes) -> str:
    try:
        return str(json.loads(data.decode()).get("error") or "")
    except Exception:  # noqa: BLE001
        return ""


def _channel_options() -> list:
    """Message-size options for every channel AND the server: gRPC's 4 MB
    default silently caps unary weights payloads (RESOURCE_EXHAUSTED) far
    below real model sizes — raise both directions to
    ``Settings.GRPC_MAX_MESSAGE_MB``."""
    max_len = int(Settings.GRPC_MAX_MESSAGE_MB) * 1024 * 1024
    return [
        ("grpc.max_send_message_length", max_len),
        ("grpc.max_receive_message_length", max_len),
    ]


# ---- wire-format dispatch (envelope default; protobuf = reference interop) ----


def _pbuf() -> bool:
    return Settings.WIRE_FORMAT == "protobuf"


def _svc() -> str:
    """Dial path for outgoing RPCs: the reference's real /node.NodeServices/
    when speaking protobuf (so a reference server routes us), the native
    /p2pfl.NodeServices/ otherwise."""
    return _SERVICE_REF if _pbuf() else _SERVICE


def _enc_handshake(addr: str) -> bytes:
    return pw.encode_handshake_pb(addr) if _pbuf() else addr.encode()


def _enc_message(msg: Message) -> bytes:
    return pw.encode_message_pb(msg) if _pbuf() else encode_message(msg)


def _enc_weights(env: WeightsEnvelope) -> bytes:
    return pw.encode_weights_pb(env) if _pbuf() else encode_weights(env)


def _resp_ok(data: bytes) -> bool:
    return pw.decode_response_ok_pb(data) if _pbuf() else _reply_ok(data)


# ---- transport pieces ----


class GrpcNeighbors(Neighbors):
    def _connect(self, addr: str, handshake: bool):
        # encode before opening the channel: a misconfigured WIRE_FORMAT
        # (protobuf runtime absent) must raise without leaking a channel
        payload = _enc_handshake(self.self_addr) if handshake else b""
        channel = grpc.insecure_channel(addr, options=_channel_options())
        if handshake:
            try:
                caller = channel.unary_unary(_svc() + "handshake")
                resp = caller(payload, timeout=Settings.GRPC_TIMEOUT)
                if not _resp_ok(resp):
                    raise NeighborNotConnectedError(f"handshake rejected by {addr}")
            except grpc.RpcError as exc:
                channel.close()
                raise NeighborNotConnectedError(f"cannot reach {addr}: {exc.code()}") from exc
        return channel

    def _disconnect(self, addr: str, conn, notify: bool) -> None:
        if conn is None:
            return
        if notify:
            try:
                conn.unary_unary(_svc() + "disconnect")(
                    _enc_handshake(self.self_addr), timeout=Settings.GRPC_TIMEOUT
                )
            except (grpc.RpcError, RuntimeError):
                # RuntimeError: WIRE_FORMAT='protobuf' without the runtime —
                # best-effort notify must still close the channel below
                pass
        conn.close()


class GrpcProtocol(CommunicationProtocol):
    """gRPC transport: one server + heartbeat/gossip threads per node.

    Reference: ``grpc_communication_protocol.py:35`` + ``grpc_server.py`` +
    ``grpc_client.py``; server thread pool sizing mirrors
    ``grpc_server.py:62``.
    """

    def __init__(self, address: Optional[str] = None) -> None:
        from p2pfl_tpu.communication.address import parse_address

        super().__init__(parse_address(address).target)
        self._server: Optional[grpc.Server] = None
        self._lock = threading.Lock()
        # egress accounting (control vs weight plane) — the evidence base
        # for wire-compression claims (bench_suite config 8). Written from
        # the gossiper/heartbeater threads AND server-executor handlers, so
        # increments hold _lock; only successfully acknowledged sends count
        self.wire_stats: dict[str, int] = {
            "weights_bytes": 0, "weights_msgs": 0,
            "control_bytes": 0, "control_msgs": 0,
            # streaming byte plane: successful chunked transfers, chunks
            # shipped, and loud stream→unary fallbacks (peer rejected)
            "stream_sends": 0, "stream_chunks": 0, "stream_fallback_unary": 0,
        }
        #: peers that rejected streaming — the loud fallback logs ONCE per
        #: peer, then keeps falling back silently (PR-18 fallback taxonomy)
        self._stream_fallback_noted: set[str] = set()

    # ---- server ----

    def _make_neighbors(self) -> Neighbors:
        return GrpcNeighbors(self._address)

    def _server_start(self) -> None:
        # executor size is a knob (reference hardcodes 4, grpc_server.py:62):
        # a high-fan-in aggregator would serialize receives behind too few
        # handler threads, and a streamed transfer occupies one for its
        # whole duration
        server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=Settings.GRPC_SERVER_WORKERS),
            options=_channel_options(),
        )
        server.add_generic_rpc_handlers((_Handler(self),))
        bound = server.add_insecure_port(self._address)
        if bound == 0:
            raise NeighborNotConnectedError(f"cannot bind {self._address}")
        server.start()
        self._server = server

    def _server_stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=0.5)
            self._server = None

    # ---- client ----

    def _send_to_neighbor(self, nei: str, env, create_connection: bool = False) -> bool:
        info = self.neighbors.get(nei)
        channel = info.conn if info is not None and info.direct else None
        adhoc = None
        if channel is None:
            if not create_connection:
                return False
            # reference grpc_client.py:142-144
            adhoc = grpc.insecure_channel(nei, options=_channel_options())
            channel = adhoc
        try:
            kind = "weights" if isinstance(env, WeightsEnvelope) else "control"
            if kind == "weights":
                # shard-native weights plane: two gRPC nodes hosted in ONE
                # process on one fabric can move model payloads device-to-
                # device (communication/ici.py) while control keeps riding
                # the socket; sits inside the transport send so the fault
                # injector/spans at the _do_send seam wrap it unchanged.
                # Cross-process peers are never on the shard registry and
                # fall through to the DCN plane (same jax.distributed
                # world, different process: device arrays over the
                # cross-host collective — communication/dcn.py) and only
                # then to the wire below. Per-edge ladder: ICI → DCN →
                # bytes.
                from p2pfl_tpu.communication.dcn import try_dcn_send
                from p2pfl_tpu.communication.ici import try_shard_send

                handled = try_shard_send(self, nei, env)
                if handled is not None:
                    return handled
                handled = try_dcn_send(self, nei, env)
                if handled is not None:
                    return handled
                # streaming byte plane: large payloads go as a chunked
                # client stream (encode/wire/decode overlap, bounded
                # memory); None ⇒ ineligible or peer rejected → unary below
                handled = self._try_stream_send(channel, nei, env)
                if handled is not None:
                    return handled
                payload = _enc_weights(env)
                resp = channel.unary_unary(_svc() + "send_weights")(
                    payload, timeout=Settings.GRPC_TIMEOUT
                )
            else:
                payload = _enc_message(env)
                resp = channel.unary_unary(_svc() + "send_message")(
                    payload, timeout=Settings.GRPC_TIMEOUT
                )
            with self._lock:
                self.wire_stats[f"{kind}_bytes"] += len(payload)
                self.wire_stats[f"{kind}_msgs"] += 1
            return _resp_ok(resp)
        except grpc.RpcError:
            return False
        finally:
            if adhoc is not None:
                adhoc.close()

    def _try_stream_send(self, channel, nei: str, env) -> Optional[bool]:
        """Chunked weights send. Returns None when the transfer should fall
        through to the unary path (small payload, protobuf interop, peer
        rejects streaming) — a real mid-stream failure returns False: the
        whole stream is ONE failed send at the ``_do_send`` seam, so the
        breaker, retry scheduling and FaultPlan verdicts see it exactly
        like a failed unary transfer."""
        if _pbuf() or not Settings.WIRE_STREAM_ENABLED:
            return None  # the reference's protobuf schema has no stream RPC
        with self._lock:
            if nei in self._stream_fallback_noted:
                return None  # peer already said no — don't re-probe each send
        from p2pfl_tpu.learning.weights import estimate_payload_bytes

        est = estimate_payload_bytes(env.update)
        if est is None or est < Settings.WIRE_STREAM_THRESHOLD * 1024 * 1024:
            return None
        try:
            # lazy producer: the encode pipeline (or cache hit) runs here,
            # the per-chunk framing+CRC runs as gRPC's sender thread pulls
            # frames — overlapping with the wire and the receiver's decode
            chunk_iter = env.update.iter_chunks()
        except Exception as exc:  # noqa: BLE001 — encode trouble ⇒ let unary try
            logger.error(self._address, f"stream encode failed, trying unary: {exc!r}")
            return None
        sent = {"chunks": 0, "bytes": 0}

        def _frames():
            # payload-free header frame first: carries every optional wire
            # key (tc/vv/xp/sp) exactly like a unary envelope, then P2TC
            head = encode_weights(env, payload=b"")
            sent["bytes"] += len(head)
            yield head
            for c in chunk_iter:
                sent["chunks"] += 1
                sent["bytes"] += len(c)
                yield c

        try:
            resp = channel.stream_unary(_svc() + "send_weights_stream")(
                _frames(), timeout=Settings.GRPC_TIMEOUT
            )
        except grpc.RpcError as exc:
            if exc.code() == grpc.StatusCode.UNIMPLEMENTED:
                # pre-streaming peer: its generic handler has no such route
                self._note_stream_fallback(nei, "UNIMPLEMENTED")
                return None
            return False  # mid-stream death/timeout — one failed send
        if not _reply_ok(resp):
            if _reply_error(resp) == "stream-unsupported":
                # peer runs with WIRE_STREAM_ENABLED off — fall back loudly
                self._note_stream_fallback(nei, "stream-unsupported")
                return None
            return False  # receiver aborted (CRC, decode, dispatch error)
        with self._lock:
            self.wire_stats["weights_bytes"] += sent["bytes"]
            self.wire_stats["weights_msgs"] += 1
            self.wire_stats["stream_sends"] += 1
            self.wire_stats["stream_chunks"] += sent["chunks"]
        logger.log_comm_metric(self._address, "stream_send")
        logger.log_comm_metric(self._address, "stream_chunks_sent", sent["chunks"])
        return True

    def _note_stream_fallback(self, nei: str, why: str) -> None:
        with self._lock:
            self.wire_stats["stream_fallback_unary"] += 1
            first = nei not in self._stream_fallback_noted
            self._stream_fallback_noted.add(nei)
        logger.log_comm_metric(self._address, "stream_fallback_unary")
        if first:
            # loud once per peer, silent after — same taxonomy as the ICI/DCN
            # plane fallbacks: a fleet quietly degrading to unary is a
            # misconfiguration someone should see
            logger.info(
                self._address,
                f"Peer {nei} rejects streaming ({why}) — falling back to "
                "unary send_weights for this and future transfers",
            )

    # ---- server-side entry points ----

    # every entry point sniffs the frame format and replies in kind, so a
    # mixed-format federation (or a reference node) interoperates without
    # any receiver-side configuration

    @staticmethod
    def _reply_as(pbuf: bool, ok: bool, error: str = "") -> bytes:
        return pw.encode_response_pb(ok, error) if pbuf else _reply(ok, error)

    def _sniff(self, data: bytes, looks_protobuf: bool):
        """(is_protobuf, rejection_reply_or_None): a frame that LOOKS
        protobuf while the runtime is absent must be refused — decoding it
        as an envelope would silently accept garbage (e.g. a corrupt
        neighbor address)."""
        if not looks_protobuf:
            return False, None
        if not pw.HAVE_PROTOBUF:
            logger.error(
                self._address,
                "Received a protobuf frame but google.protobuf is not "
                "installed — rejecting (pip install protobuf for interop)",
            )
            return False, self._reply_as(False, False, "protobuf runtime unavailable")
        return True, None

    def rpc_handshake(self, data: bytes, context) -> bytes:
        pbuf, rejection = self._sniff(data, pw.is_protobuf_handshake(data))
        if rejection is not None:
            return rejection
        source = pw.decode_handshake_pb(data) if pbuf else data.decode()
        self.neighbors.add(source, non_direct=False, handshake=False)
        return self._reply_as(pbuf, True)

    def rpc_disconnect(self, data: bytes, context) -> bytes:
        pbuf, rejection = self._sniff(data, pw.is_protobuf_handshake(data))
        if rejection is not None:
            return rejection
        self.neighbors.remove(pw.decode_handshake_pb(data) if pbuf else data.decode())
        return self._reply_as(pbuf, True)

    def rpc_send_message(self, data: bytes, context) -> bytes:
        pbuf, rejection = self._sniff(data, pw.is_protobuf_message(data))
        if rejection is not None:
            return rejection
        msg = pw.decode_message_pb(data) if pbuf else decode_message(data)
        res = self.handle_message(msg)
        return self._reply_as(pbuf, res.ok, res.error or "")

    def rpc_send_weights(self, data: bytes, context) -> bytes:
        pbuf, rejection = self._sniff(data, pw.is_protobuf_weights(data))
        if rejection is not None:
            return rejection
        try:
            env = pw.decode_weights_pb(data) if pbuf else decode_weights(data)
        except Exception as exc:  # noqa: BLE001 — malformed payload
            logger.error(
                self._address,
                f"Malformed weights payload: {exc}"
                + (
                    ""
                    if pbuf
                    else " (if the sender speaks protobuf, note the sniff "
                    "requires a non-empty Weights.source — an empty source "
                    "frame is misrouted to the envelope decoder)"
                ),
            )
            return self._reply_as(pbuf, False, "malformed weights payload")
        res = self.handle_weights(env)
        return self._reply_as(pbuf, res.ok, res.error or "")

    def rpc_send_weights_stream(self, request_iterator, context) -> bytes:
        """Client-streaming weights receive: header frame, then P2TC chunks.

        The first message is a payload-free envelope (same codec as unary —
        every optional wire key rides it); the rest are self-delimiting
        chunks fed straight into the shared
        :meth:`CommunicationProtocol.handle_weights_stream`, which decodes
        leaves as their bytes complete. Only the native envelope format
        streams — protobuf interop peers never dial this method."""
        it = iter(request_iterator)
        try:
            first = next(it)
        except StopIteration:
            return _reply(False, "empty stream")
        try:
            env = decode_weights(first)
        except Exception as exc:  # noqa: BLE001 — malformed header frame
            logger.error(self._address, f"Malformed stream header frame: {exc}")
            return _reply(False, "malformed weights payload")
        res = self.handle_weights_stream(env, it)
        return _reply(res.ok, res.error or "")


class _Handler(grpc.GenericRpcHandler):
    def __init__(self, protocol: GrpcProtocol) -> None:
        # both prefixes route to the same sniffing handlers: the reference's
        # stubs call /node.NodeServices/* (its proto's `package node;`),
        # existing repo federations call /p2pfl.NodeServices/*
        self._routes = {
            svc + m: getattr(protocol, f"rpc_{m}")
            for svc in (_SERVICE, _SERVICE_REF)
            for m in _METHODS
        }
        self._stream_routes = {
            svc + m: getattr(protocol, f"rpc_{m}")
            for svc in (_SERVICE, _SERVICE_REF)
            for m in _STREAM_METHODS
        }

    def service(self, call_details):
        fn = self._stream_routes.get(call_details.method)
        if fn is not None:
            return grpc.stream_unary_rpc_method_handler(fn)
        fn = self._routes.get(call_details.method)
        if fn is None:
            return None
        return grpc.unary_unary_rpc_method_handler(fn)


