"""Protobuf wire interop: speak the reference's frames on the same RPCs.

Round 2's verdict listed "interop-grade protobuf wire" as the last
functional gap: the reference speaks generated-protobuf gRPC
(``p2pfl/communication/grpc/proto/node.proto`` in the upstream tree) while
this framework's default frames are a compact JSON-header envelope
(``grpc_transport.py``). Interop needs BOTH layers to line up:

- Frames: ``Settings.WIRE_FORMAT = "protobuf"`` makes every outgoing frame
  a reference-schema protobuf (``proto/interop.proto`` — field-for-field
  the reference's ``node.proto``); replies are ``ResponseMessage``.
- Routes: the reference's proto declares ``package node;``, so its stubs
  serve/call ``/node.NodeServices/*`` — NOT this framework's native
  ``/p2pfl.NodeServices/*``. ``grpc_transport.py`` registers both
  prefixes server-side and dials the reference path in protobuf mode
  (round 3 shipped matching frames on the wrong route; round 4 fixed it,
  proven in ``tests/test_proto_interop.py`` by driving a repo server with
  the reference's own generated stubs).
- Receivers never need the switch: every server entry point SNIFFS the
  frame. The two formats are structurally disjoint — JSON frames open
  with ``{`` (0x7B), envelope weights frames carry a little-endian header
  length whose high bytes are zero followed by ``{``, while a protobuf
  frame of these schemas always opens with the field-1 length-delimited
  tag 0x0A — so a mixed-format federation (some nodes on either setting)
  interoperates frame by frame.

Deliberate divergence, documented here and in ``interop.proto``: the
bytes inside ``Weights.weights``. The reference pickles a list of numpy
arrays — unpickling wire bytes is arbitrary code execution, which this
framework categorically refuses. Weight payloads must be the
self-describing P2TW codec (``learning/weights.py``); a frame whose
payload is not P2TW is rejected with a loud, specific error instead of
being unpickled. Control-plane interop is therefore complete; data-plane
interop requires the peer to emit P2TW payloads inside the same protobuf
frame.

The generated stub ``proto/interop_pb2.py`` is checked in (regenerate
with ``protoc --python_out=. interop.proto``); ``google.protobuf`` is an
optional dependency — without it, ``WIRE_FORMAT="protobuf"`` raises at
send time and sniffing falls through to the envelope path.
"""

from __future__ import annotations

import hashlib
from typing import Optional

from p2pfl_tpu.communication.message import Message, WeightsEnvelope
from p2pfl_tpu.learning.weights import ModelUpdate

try:
    from p2pfl_tpu.communication.proto import interop_pb2 as pb

    HAVE_PROTOBUF = True
except ImportError:  # pragma: no cover - protobuf is present in dev images
    pb = None
    HAVE_PROTOBUF = False

# NOTE: this codec is the reference-interop schema — the optional native
# envelope headers registered in communication/wire_headers.py must NEVER
# appear here (enforced by the wire-header-compat analyzer rule: any of
# those key strings, kwargs or field accesses in this file is a finding).

#: the P2TW magic (learning/weights.py) — the only weight payload accepted
_P2TW_MAGIC = b"P2TW"
#: protobuf field-1 length-delimited tag; both formats' first byte differs
_TAG_FIELD1 = 0x0A


def _require() -> None:
    if not HAVE_PROTOBUF:
        raise RuntimeError(
            "WIRE_FORMAT='protobuf' needs the google.protobuf runtime "
            "(pip install protobuf)"
        )


def _hash64(msg_id: str) -> int:
    """Map our string message ids onto the reference's int64 ``hash``.

    Ids that ARRIVED as a protobuf hash (decode sets ``msg_id=str(hash)``)
    must round-trip to the SAME integer when relayed — re-hashing would
    give every gossip hop a fresh dedup id and the flood would never be
    suppressed (each receiver dispatching the same command once per hop).
    Reference nodes derive the hash from Python's SIGNED hash, so negative
    values round-trip too.
    """
    digits = msg_id[1:] if msg_id.startswith("-") else msg_id
    # ascii-only: str.isdigit() accepts Unicode digits that int() rejects,
    # and a peer-controlled id must never crash the relaying gossiper
    if digits.isascii() and digits.isdigit():
        v = int(msg_id)
        if -(1 << 63) <= v < (1 << 63):  # the FULL signed-int64 range
            return v
    return int.from_bytes(hashlib.sha256(msg_id.encode()).digest()[:8], "big") >> 1


# ---- sniffing ----


def is_protobuf_message(data: bytes) -> bool:
    """True when a send_message frame is reference-schema protobuf.

    JSON envelope frames always start with ``{``; a protobuf ``Message``
    always starts with the field-1 tag. ASSUMPTION (documented limit of
    the sniff): ``source`` is non-empty. proto3 omits default-valued
    fields, so a Message with ``source=""`` would serialize starting at
    the ttl/hash tag (0x10/0x18) and be misrouted to the envelope decoder.
    Every sender in both implementations stamps its own address as the
    source (the gossip dedup and eviction logic require it), so an
    empty-source frame is malformed at the protocol level anyway — the
    envelope decoder's error message names this cause.
    """
    return bool(data) and data[0] == _TAG_FIELD1


def is_protobuf_weights(data: bytes) -> bool:
    """True when a send_weights frame is reference-schema protobuf.

    The envelope format opens with a 4-byte little-endian JSON-header
    length followed by ``{``; any header under 16 MB (the top byte of the
    length is zero — real headers are a few hundred bytes, and even a
    pathological many-thousand-contributor aggregate stays far below)
    matches ``data[3] == 0 and data[4] == '{'``. A protobuf ``Weights``
    opens with tag 0x0A + the length-prefixed source string, whose bytes
    land at data[2:] — an address never contains NUL, so ``data[3]`` is
    nonzero there and the two formats cannot collide. Same non-empty
    ``source`` assumption as :func:`is_protobuf_message` (an empty source
    would start the frame at the round/weights tag and misroute it).
    """
    if len(data) < 5:
        return False
    envelope = data[3] == 0 and data[4] == 0x7B
    return data[0] == _TAG_FIELD1 and not envelope


def is_protobuf_handshake(data: bytes) -> bool:
    """Addresses (host:port / unix paths) never start with 0x0A."""
    return bool(data) and data[0] == _TAG_FIELD1


# ---- control plane ----


def encode_message_pb(msg: Message) -> bytes:
    _require()
    out = pb.Message(
        source=msg.source,
        ttl=msg.ttl,
        hash=_hash64(msg.msg_id),
        cmd=msg.cmd,
        args=list(msg.args),
    )
    if msg.round >= 0:
        out.round = msg.round
    return out.SerializeToString()


def decode_message_pb(data: bytes) -> Message:
    _require()
    m = pb.Message.FromString(data)
    return Message(
        m.source,
        m.cmd,
        tuple(m.args),
        m.round if m.HasField("round") else -1,
        m.ttl,
        # keep the reference's dedup id stable across relays
        msg_id=str(m.hash),
    )


def encode_handshake_pb(addr: str) -> bytes:
    _require()
    return pb.HandShakeRequest(addr=addr).SerializeToString()


def decode_handshake_pb(data: bytes) -> str:
    _require()
    return pb.HandShakeRequest.FromString(data).addr


def encode_response_pb(ok: bool, error: str = "") -> bytes:
    _require()
    out = pb.ResponseMessage()
    if not ok:
        out.error = error or "error"
    return out.SerializeToString()


def decode_response_ok_pb(data: bytes) -> bool:
    _require()
    try:
        return not pb.ResponseMessage.FromString(data).HasField("error")
    except Exception:  # noqa: BLE001 — malformed reply = failure
        return False


# ---- data plane ----


def encode_weights_pb(env: WeightsEnvelope) -> bytes:
    _require()
    return pb.Weights(
        source=env.source,
        round=env.round,
        weights=env.update.encode(),
        contributors=list(env.update.contributors),
        weight=int(env.update.num_samples),
        cmd=env.cmd,
    ).SerializeToString()


def decode_weights_pb(data: bytes) -> WeightsEnvelope:
    _require()
    w = pb.Weights.FromString(data)
    if not w.weights.startswith(_P2TW_MAGIC):
        # almost certainly the reference's pickled-numpy payload —
        # unpickling wire bytes is arbitrary code execution; refuse loudly
        raise ValueError(
            "weights payload is not the P2TW codec (refusing to unpickle "
            "foreign bytes — see communication/proto_wire.py)"
        )
    update = ModelUpdate(
        params=None,
        contributors=list(w.contributors),
        num_samples=int(w.weight),
        encoded=bytes(w.weights),
    )
    return WeightsEnvelope(w.source, w.round, w.cmd, update)
