"""Transport-agnostic message model.

The reference defines these shapes in protobuf
(``p2pfl/communication/grpc/proto/node.proto:26-42``): a small control
``Message{source, ttl, hash, cmd, args[], round}`` that TTL-floods the
overlay, and a ``Weights{source, round, weights, contributors[], weight,
cmd}`` payload that moves point-to-point. Here they are plain dataclasses
that every transport shares; the gRPC transport maps them to/from protobuf,
the in-memory transport passes them by reference (weights stay
device-resident as a :class:`~p2pfl_tpu.learning.weights.ModelUpdate`).
"""

from __future__ import annotations

import hashlib
import itertools
import time
from dataclasses import dataclass, field
from typing import Optional, Union

from p2pfl_tpu.learning.weights import ModelUpdate

_seq = itertools.count()


def _message_id(source: str, cmd: str, args: tuple[str, ...]) -> str:
    """Unique-enough id for gossip dedup.

    The reference hashes cmd+args+now+random (``grpc_client.py:72``); a
    monotonic per-process sequence number removes the (tiny) collision
    probability while staying cheap.
    """
    raw = f"{source}|{cmd}|{'|'.join(args)}|{time.monotonic_ns()}|{next(_seq)}"
    return hashlib.blake2s(raw.encode(), digest_size=16).hexdigest()


@dataclass
class Message:
    """A small control-plane message (vote, beat, round status, ...).

    ``trace_ctx`` is the flight recorder's wire-propagated trace context
    (``(trace_id, parent_span_id)`` — ``management/telemetry.py``),
    stamped by ``protocol.build_msg`` from the sender's active span so the
    receiver's dispatch span joins the sender's causal tree. Optional end
    to end: ``None`` is never serialized, and a frame without the field
    decodes exactly as before.

    ``xp`` is the experiment identity (the fleet-wide id minted by the
    ``start_learning`` initiator, ``Node.set_start_learning``) — the same
    optional-key contract as ``trace_ctx``: absent frames decode
    unchanged, the protobuf interop schema never carries it. Receivers
    use it to filter cross-experiment stragglers EXACTLY instead of by
    TTL + epoch heuristics alone.

    Both ride the wire as optional header keys declared in
    :mod:`p2pfl_tpu.communication.wire_headers` — the registry the
    ``wire-header-compat`` analyzer rule enforces the compat contract
    against.
    """

    source: str
    cmd: str
    args: tuple[str, ...] = ()
    round: int = -1
    ttl: int = 1
    msg_id: str = ""
    trace_ctx: Optional[tuple[str, str]] = None
    xp: Optional[str] = None

    def __post_init__(self) -> None:
        self.args = tuple(str(a) for a in self.args)
        if not self.msg_id:
            self.msg_id = _message_id(self.source, self.cmd, self.args)


@dataclass
class WeightsEnvelope:
    """A model payload moving between nodes (data plane).

    ``update`` may hold a live pytree (in-process transports — zero copy,
    device-resident) or only ``update.encoded`` bytes (network transports).
    ``trace_ctx`` carries the sender's trace context exactly like
    :class:`Message` (stamped by ``protocol.build_weights``); ``xp`` the
    experiment identity (same optional-key contract — it also rides
    ``update.xp`` so stash filters see it after decode).
    """

    source: str
    round: int
    cmd: str  # "init_model" | "add_model"
    update: ModelUpdate
    msg_id: str = field(default="")
    trace_ctx: Optional[tuple[str, str]] = None
    xp: Optional[str] = None

    def __post_init__(self) -> None:
        if not self.msg_id:
            self.msg_id = _message_id(self.source, self.cmd, ())


Envelope = Union[Message, WeightsEnvelope]


@dataclass
class CommandResult:
    """Outcome of dispatching a message to a command handler."""

    ok: bool = True
    error: Optional[str] = None
