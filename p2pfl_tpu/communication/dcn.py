"""The DCN weights plane: cross-process model diffusion, device-native.

``Settings.WEIGHTS_PLANE = "dcn"`` completes the transport hierarchy —
intra-slice ICI (:mod:`p2pfl_tpu.communication.ici`, co-resident nodes in
one process) → cross-host DCN (this module, nodes in *different*
processes of one ``jax.distributed`` world) → WAN gRPC bytes (everything
else). Model payloads between same-world cross-process peers move as
device arrays over an XLA cross-host collective
(:mod:`p2pfl_tpu.parallel.dcn_plane` — the ici_plane pair-mesh exchange
generalized to process-spanning pairs), composed with the shard-resident
top-k/int8 codec: encode on the sender's devices, transfer over the
interconnect, decode against the receiver's anchor. Pickled numpy never
rides gRPC between these peers.

What deliberately does NOT change (the ici.py contract, verbatim):

- **The control plane.** Votes, beats, TTL floods, membership keep riding
  the byte transport — including this plane's OWN rendezvous verbs
  (``dcn_offer``/``dcn_accept``/``dcn_nack``/``dcn_ready``/``dcn_done``/
  ``dcn_abort``): small direct ``ttl=1`` control messages that carry only
  JSON metadata, never weights.
- **The ``_do_send`` seam.** ``try_dcn_send`` runs INSIDE the transport's
  ``_send_to_neighbor`` (after the ICI attempt), and every rendezvous
  verb goes out through ``proto._do_send`` — so FaultPlan verdicts,
  breaker feeds, retries and telemetry spans wrap a DCN transfer exactly
  as they wrap a byte send. A dropped verb surfaces as a rendezvous
  timeout and a loud per-edge byte fallback, never a hang.
- **Failure semantics.** Ineligible peers (not in the world directory,
  same process, mismatched topology, anchor from another round) fall back
  LOUDLY to the byte path for that edge only (``dcn_fallback_bytes``
  metric, one log line per (peer, reason)); a dead peer fails the send so
  breakers/eviction see their usual signals.

Rendezvous & ordering — why there is a protocol at all: a cross-process
collective must be co-dispatched by BOTH processes, in the SAME order on
each (multi-controller SPMD). Discovery rides the distributed runtime's
KV store (``dcn/nodes/<addr>`` → process placement, published on
``Node.start``); per transfer, the sender offers (leaf metadata, mesh
ids, codec specs), the receiver accepts (its mesh ids + a pair-monotone
``seq`` assigned by the pair's master — the lower ``process_index``), and
per-pair executor threads on both sides run transfers in ``seq`` order
behind one process-global dispatch lock, with a ready handshake before
each dispatch. Any disorder (an abort racing a queue, cross-pair lock
inversion at ≥3 processes) degrades to a ready-timeout → abort → byte
fallback, counted and logged — never a deadlock, never silent.

This module is inside the ``no-host-gather`` analyzer scope
(:mod:`p2pfl_tpu.analysis`): no ``np.asarray``/``jax.device_get``/
``.tobytes()`` may appear here — weights stay device-resident; only JSON
scalars ride the control verbs.
"""

from __future__ import annotations

import heapq
import itertools
import json
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from p2pfl_tpu.communication.ici import (
    ShardPlaneRegistry,
    _named_dict,
    _restore_named,
)
from p2pfl_tpu.communication.message import Message, WeightsEnvelope
from p2pfl_tpu.learning.weights import ModelUpdate, named_leaves
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.management.telemetry import telemetry
from p2pfl_tpu.parallel.dcn_plane import (
    dcn_transfer,
    mesh_from_ids,
    mesh_wire_meta,
    process_local,
    spec_from_wire,
    spec_to_wire,
)
from p2pfl_tpu.parallel.distributed import kv_client, world_active
from p2pfl_tpu.parallel.ici_plane import (
    SliceInfo,
    replicate_on_slice,
    slice_info_of,
    tree_device_bytes,
)

Pytree = Any

#: KV-store key prefix of the world directory
_DIR_PREFIX = "dcn/nodes/"

#: the six rendezvous verbs (control-plane commands, commands/dcn.py)
DCN_VERBS = (
    "dcn_offer", "dcn_accept", "dcn_nack", "dcn_ready", "dcn_done", "dcn_abort",
)

# ---- process-wide accounting (bench/tests read these) ----

_stats_lock = threading.Lock()
_stats = {
    "dcn_sends": 0,       # payloads delivered over the DCN plane (sender side)
    "dcn_recvs": 0,       # payloads delivered over the DCN plane (receiver side)
    "bytes_moved": 0,     # device bytes that crossed the interconnect
    "fallback_bytes": 0,  # sends that fell back to the byte path
    "nacks": 0,           # offers this process refused (receiver side)
    "aborts": 0,          # rendezvous aborted after an accept (either side)
    #: receiver-side re-layouts (device_put within the receiver's slice)
    #: after a transfer — sender layout differed from the receiver's
    #: placement; still device-to-device, never host
    "conform_copies": 0,
}


def dcn_stats() -> dict:
    with _stats_lock:
        return dict(_stats)


def reset_dcn_stats() -> None:
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0


def _count(key: str, n: int = 1) -> None:
    with _stats_lock:
        _stats[key] += n


def _fallback(src: str, nei: str, reason: str) -> None:
    """Per-edge loud degradation to the byte path (never aborts)."""
    _count("fallback_bytes")
    logger.log_comm_metric(src, "dcn_fallback_bytes")
    if ShardPlaneRegistry.warn_once(src, nei, "dcn:" + reason):
        logger.info(
            src,
            f"DCN weights plane ineligible for {nei} ({reason}) — "
            "falling back to the byte path for this peer",
        )
    telemetry.event(
        src, "dcn_fallback", kind="gossip", attrs={"peer": nei, "reason": reason}
    )


# ---- world directory (KV-store backed peer discovery) ----


class WorldDirectory:
    """``node address → process placement`` via the runtime's KV store.

    Nodes publish themselves on ``Node.start`` (withdraw on stop); lookups
    read the whole ``dcn/nodes/`` directory once per
    ``Settings.DCN_DIR_TTL_S`` and serve from the snapshot in between —
    the directory is membership metadata, not a hot path, and
    ``key_value_dir_get`` is the only non-blocking read this jax exposes.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cache: dict[str, dict] = {}
        self._stamp: Optional[float] = None

    def publish(self, addr: str) -> None:
        client = kv_client()
        if client is None or not world_active():
            return
        info = {"pi": int(jax.process_index())}
        try:
            # set is not an upsert on every jaxlib: clear any stale entry
            # from a restarted node first (delete of a missing key raises
            # — ignored)
            try:
                client.key_value_delete(_DIR_PREFIX + addr)
            except Exception:  # noqa: BLE001
                pass
            client.key_value_set(_DIR_PREFIX + addr, json.dumps(info))
        except Exception as exc:  # noqa: BLE001 — directory is best-effort
            logger.debug(addr, f"DCN directory publish failed: {exc!r}")
        self.invalidate()

    def withdraw(self, addr: str) -> None:
        client = kv_client()
        if client is None:
            return
        try:
            client.key_value_delete(_DIR_PREFIX + addr)
        except Exception:  # noqa: BLE001 — already absent
            pass
        self.invalidate()

    def invalidate(self) -> None:
        with self._lock:
            self._stamp = None

    def lookup(self, addr: str) -> Optional[dict]:
        from p2pfl_tpu.settings import Settings

        now = time.monotonic()
        with self._lock:
            if self._stamp is not None and now - self._stamp <= Settings.DCN_DIR_TTL_S:
                return self._cache.get(addr)
        client = kv_client()
        if client is None:
            return None
        cache: dict[str, dict] = {}
        try:
            for key, val in client.key_value_dir_get(_DIR_PREFIX):
                name = key[len(_DIR_PREFIX):] if key.startswith(_DIR_PREFIX) else key
                try:
                    cache[name] = json.loads(val)
                except (ValueError, TypeError):
                    continue
        except Exception as exc:  # noqa: BLE001 — coordinator mid-teardown
            logger.debug("dcn", f"DCN directory read failed: {exc!r}")
            return None
        with self._lock:
            self._cache = cache
            self._stamp = now
            return self._cache.get(addr)


# ---- transfer state ----


class _Transfer:
    """One in-flight cross-process transfer (either side)."""

    def __init__(self, tid: str, role: str, peer_pi: int) -> None:
        self.tid = tid
        self.role = role  # "send" | "recv"
        self.peer_pi = peer_pi
        self.seq: Optional[int] = None
        self.proto = None           # the local node's protocol (verb channel)
        self.peer_addr: str = ""
        self.meta: dict = {}        # the offer (both sides)
        self.accept_meta: dict = {}
        self.enqueued = False
        # sender side
        self.env = None
        self.src_info: Optional[SliceInfo] = None
        self.transfer_tree: Optional[dict] = None
        self.specs: tuple = ()
        self.dst_mesh = None
        self.moved_bytes = 0
        self.mode = "none"
        # receiver side
        self.node = None
        self.template = None
        self.src_mesh = None
        self.dst_info: Optional[SliceInfo] = None
        self.filler: Optional[dict] = None
        # rendezvous events
        self.accepted = threading.Event()
        self.peer_ready = threading.Event()
        self.finished = threading.Event()
        self.outcome: Optional[str] = None  # "ok" | "failed" | "fallback"
        self.reason = ""


# ---- the plane ----


class DcnPlane:
    """Process-global DCN rendezvous state: transfers, per-peer-process
    executors, the pair-monotone sequence counters and the dispatch-order
    lock. One instance per process (all local nodes share it — collective
    dispatch order is a PROCESS property, not a node property)."""

    _instance: Optional["DcnPlane"] = None
    _ilock = threading.Lock()

    @classmethod
    def instance(cls) -> "DcnPlane":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        """Tests: drop all state and stop executor threads."""
        with cls._ilock:
            inst = cls._instance
            cls._instance = None
        if inst is not None:
            with inst._lock:
                inst._stop = True
                for cv in inst._cvs.values():
                    cv.notify_all()
                transfers = list(inst._transfers.values())
            for t in transfers:
                inst._finish(t, "fallback", "plane_reset")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._transfers: dict[str, _Transfer] = {}
        self._heaps: dict[int, list] = {}
        self._cvs: dict[int, threading.Condition] = {}
        self._seqs: dict[int, int] = {}
        self._tids = itertools.count(1)
        self._stop = False
        #: collective dispatch order is process-global: ONE cross-process
        #: exchange in flight per process at a time
        self._dispatch_lock = threading.Lock()
        self._filler_lock = threading.Lock()
        self._fillers: dict = {}
        self.directory = WorldDirectory()

    # ---- verb plumbing ----

    @staticmethod
    def _verb_msg(proto, cmd: str, payload: dict, round: int = -1) -> Message:
        return Message(
            proto.get_address(),
            cmd,
            (json.dumps(payload),),
            round,
            ttl=1,  # direct rendezvous, never flooded
            trace_ctx=telemetry.current_ctx(),
            xp=getattr(proto, "experiment_xid", None),
        )

    def _send_verb(self, proto, nei: str, cmd: str, payload: dict, round: int = -1) -> bool:
        """One rendezvous verb through the ``_do_send`` seam (spans +
        fault injector apply; no gossip retry — the rendezvous has its own
        timeout/abort machinery)."""
        try:
            return bool(
                proto._do_send(
                    nei, self._verb_msg(proto, cmd, payload, round), create_connection=True
                )
            )
        except Exception as exc:  # noqa: BLE001 — a failed verb is a failed verb
            logger.debug(proto.get_address(), f"DCN verb {cmd} to {nei} failed: {exc!r}")
            return False

    # ---- sequencing / executors ----

    def _next_seq_locked(self, peer_pi: int) -> int:
        self._seqs[peer_pi] = self._seqs.get(peer_pi, 0) + 1
        return self._seqs[peer_pi]

    def _get(self, tid: str) -> Optional[_Transfer]:
        with self._lock:
            return self._transfers.get(tid)

    def _enqueue(self, t: _Transfer) -> None:
        with self._lock:
            if self._stop or t.enqueued or t.seq is None:
                return
            t.enqueued = True
            if t.peer_pi not in self._heaps:
                self._heaps[t.peer_pi] = []
                self._cvs[t.peer_pi] = threading.Condition(self._lock)
                threading.Thread(
                    target=self._run_executor,
                    args=(t.peer_pi,),
                    name=f"dcn-exec-p{t.peer_pi}",
                    daemon=True,
                ).start()
            heapq.heappush(self._heaps[t.peer_pi], (t.seq, t.tid))
            self._cvs[t.peer_pi].notify_all()

    def _run_executor(self, peer_pi: int) -> None:
        while True:
            with self._lock:
                cv = self._cvs[peer_pi]
                while not self._stop and not self._heaps[peer_pi]:
                    cv.wait(timeout=0.5)
                if self._stop:
                    return
                _seq, tid = heapq.heappop(self._heaps[peer_pi])
                t = self._transfers.get(tid)
            if t is None:
                continue  # finished/aborted while queued
            try:
                self._execute(t)
            except Exception as exc:  # noqa: BLE001 — executor must survive
                logger.error("dcn", f"DCN executor error on {tid}: {exc!r}")
                self._abort(t, f"executor_error:{exc!r}", outcome="failed", notify=True)

    # ---- lifecycle hooks (Node.start/stop) ----

    def publish_node(self, addr: str) -> None:
        self.directory.publish(addr)

    def withdraw_node(self, addr: str) -> None:
        self.directory.withdraw(addr)

    # ---- finish / abort ----

    def _finish(self, t: _Transfer, outcome: str, reason: str = "") -> bool:
        with self._lock:
            first = t.outcome is None
            if first:
                t.outcome = outcome
                t.reason = reason
            self._transfers.pop(t.tid, None)
        t.accepted.set()
        t.finished.set()
        return first

    def _abort(
        self, t: _Transfer, reason: str, outcome: str = "fallback", notify: bool = False
    ) -> None:
        if self._finish(t, outcome, reason):
            _count("aborts")
            if notify and t.proto is not None and t.peer_addr:
                self._send_verb(
                    t.proto, t.peer_addr, "dcn_abort", {"tid": t.tid, "reason": reason}
                )

    # ---- sender side ----

    def begin_send(
        self, proto, nei: str, env, built: dict, src_info: SliceInfo, src_ep, peer_pi: int
    ) -> Optional[_Transfer]:
        update = env.update
        my_pi = int(jax.process_index())
        tid = f"{proto.get_address()}#{next(self._tids)}"
        t = _Transfer(tid, "send", peer_pi)
        t.proto = proto
        t.peer_addr = nei
        t.env = env
        t.src_info = src_info
        t.transfer_tree = built["transfer"]
        t.specs = built["specs"]
        t.moved_bytes = built["moved"]
        t.mode = built["mode"]
        with self._lock:
            if my_pi < peer_pi:
                t.seq = self._next_seq_locked(peer_pi)
            self._transfers[tid] = t
        sp = src_ep.handshake(t.mode)
        offer = {
            "tid": tid,
            "seq": t.seq,
            "pi": my_pi,
            "src": proto.get_address(),
            "dst": nei,
            "cmd": env.cmd,
            "round": env.round,
            "msg_id": env.msg_id,
            "tc": list(env.trace_ctx) if env.trace_ctx else None,
            "xp": env.xp or update.xp,
            "contributors": list(update.contributors),
            "num_samples": update.num_samples,
            "vv": list(update.version) if update.version else None,
            "sp": [list(sp[0]), sp[1], sp[2]] if sp else None,
            "anchor_tag": update.anchor_tag,
            "mode": t.mode,
            "mesh": mesh_wire_meta(src_info),
            "model": built["model_meta"],
            "leaves": built["leaves_meta"],
            "tk_spec": [list(e) for e in built["tk_spec"]],
            "dense_spec": [list(e) for e in built["dense_spec"]],
        }
        t.meta = offer
        if not self._send_verb(proto, nei, "dcn_offer", offer, round=env.round):
            # peer unreachable: clean up and let the byte path fail the
            # send, so breakers/eviction see their usual signals
            with self._lock:
                self._transfers.pop(tid, None)
            return None
        return t

    def await_send(self, t: _Transfer, proto, nei: str) -> Optional[bool]:
        from p2pfl_tpu.settings import Settings

        src = proto.get_address()
        if not t.accepted.wait(Settings.DCN_ACCEPT_TIMEOUT_S):
            self._abort(t, "accept_timeout", notify=True)
        if not t.finished.wait(Settings.DCN_DONE_TIMEOUT_S):
            # the collective may already have fired: falling back to bytes
            # here could double-deliver, so a done-timeout is a FAILED
            # send (the gossiper's normal retry machinery takes over)
            self._abort(t, "done_timeout", outcome="failed", notify=True)
        if t.outcome == "ok":
            _count("dcn_sends")
            _count("bytes_moved", t.moved_bytes)
            logger.log_comm_metric(src, "dcn_send_shard")
            logger.log_comm_metric(src, "dcn_bytes_moved", t.moved_bytes)
            telemetry.event(
                src,
                "dcn_transfer",
                kind="gossip",
                attrs={
                    "peer": nei, "codec": t.mode, "bytes": t.moved_bytes, "seq": t.seq,
                },
            )
            return True
        if t.outcome == "failed":
            logger.error(src, f"DCN transfer to {nei} failed ({t.reason})")
            return False
        _fallback(src, nei, t.reason or "aborted")
        return None

    def on_accept(self, node, source: str, meta: dict) -> None:
        t = self._get(str(meta.get("tid")))
        if t is None or t.role != "send":
            # stale accept (we already aborted): tell the peer to unqueue
            self._send_verb(
                node.protocol, source, "dcn_abort",
                {"tid": str(meta.get("tid")), "reason": "unknown_tid"},
            )
            return
        with self._lock:
            if t.enqueued:
                return  # duplicate accept
            if t.seq is None:
                try:
                    t.seq = int(meta["seq"])
                except (KeyError, TypeError, ValueError):
                    pass
            t.accept_meta = meta
        if t.seq is None:
            self._abort(t, "accept_without_seq", notify=True)
            return
        t.accepted.set()
        self._enqueue(t)

    def on_nack(self, node, source: str, meta: dict) -> None:
        t = self._get(str(meta.get("tid")))
        if t is not None:
            self._finish(t, "fallback", str(meta.get("reason", "nacked")))

    def on_done(self, node, source: str, meta: dict) -> None:
        t = self._get(str(meta.get("tid")))
        if t is not None:
            ok = bool(meta.get("ok"))
            self._finish(t, "ok" if ok else "failed", "" if ok else "peer_deliver_failed")

    def on_ready(self, node, source: str, meta: dict) -> None:
        t = self._get(str(meta.get("tid")))
        if t is not None:
            t.peer_ready.set()

    def on_abort(self, node, source: str, meta: dict) -> None:
        t = self._get(str(meta.get("tid")))
        if t is not None:
            reason = str(meta.get("reason", "peer_abort"))
            self._finish(t, "fallback", f"peer_abort:{reason}")

    # ---- receiver side ----

    def on_offer(self, node, source: str, meta: dict) -> None:
        from p2pfl_tpu.settings import Settings

        proto = node.protocol
        tid = str(meta.get("tid"))

        def nack(reason: str) -> None:
            _count("nacks")
            logger.log_comm_metric(proto.get_address(), "dcn_nack")
            self._send_verb(proto, source, "dcn_nack", {"tid": tid, "reason": reason})

        if Settings.WEIGHTS_PLANE != "dcn":
            nack("plane_off")
            return
        if not world_active():
            nack("no_distributed_world")
            return
        if not getattr(node, "_running", False) or node.learner is None:
            nack("peer_not_ready")
            return
        try:
            template = node.learner.get_parameters()
        except Exception:  # noqa: BLE001 — learner mid-teardown
            nack("peer_not_ready")
            return
        tmpl_named = _named_dict(template)
        model_meta = {
            str(k): (tuple(shape), str(dt)) for k, shape, dt in meta.get("model", [])
        }
        mine = {
            k: (tuple(leaf.shape), str(leaf.dtype)) for k, leaf in tmpl_named.items()
        }
        if model_meta != mine:
            nack("architecture_mismatch")
            return
        dst_info = slice_info_of(template)
        if dst_info is None:
            nack("params_not_device_resident")
            return
        if not process_local(dst_info):
            nack("slice_spans_processes")
            return
        mesh_meta = meta.get("mesh") or {}
        if (
            list(dst_info.mesh.devices.shape) != list(mesh_meta.get("shape", []))
            or list(dst_info.mesh.axis_names) != list(mesh_meta.get("axes", []))
        ):
            nack("slice_topology_mismatch")
            return
        src_mesh = mesh_from_ids(
            mesh_meta["ids"], mesh_meta["shape"], mesh_meta["axes"]
        )
        if src_mesh is None:
            nack("unknown_devices")
            return
        my_pi = int(jax.process_index())
        if any(d.process_index == my_pi for d in src_mesh.devices.flat):
            nack("same_process")
            return
        mode = str(meta.get("mode", "none"))
        if mode in ("int8", "topk8") and meta.get("tk_spec"):
            dst_anchor = getattr(node.learner, "_wire_anchor", None)
            dst_tag = getattr(node.learner, "_wire_anchor_tag", None)
            if dst_anchor is None or dst_tag != meta.get("anchor_tag"):
                nack("anchor_round_mismatch")
                return
        peer_pi = int(meta.get("pi", -1))
        t = _Transfer(tid, "recv", peer_pi)
        t.proto = proto
        t.peer_addr = source
        t.meta = meta
        t.mode = mode
        t.node = node
        t.template = template
        t.src_mesh = src_mesh
        t.dst_info = dst_info
        with self._lock:
            if tid in self._transfers:
                return  # duplicate offer
            if my_pi < peer_pi:
                t.seq = self._next_seq_locked(peer_pi)
            else:
                try:
                    t.seq = int(meta["seq"])
                except (KeyError, TypeError, ValueError):
                    t.seq = None
            if t.seq is not None:
                self._transfers[tid] = t
        if t.seq is None:
            nack("offer_without_seq")
            return
        accept = {"tid": tid, "seq": t.seq, "mesh": mesh_wire_meta(dst_info)}
        if not self._send_verb(proto, source, "dcn_accept", accept):
            self._finish(t, "fallback", "accept_send_failed")
            return
        self._enqueue(t)

    def _filler_buf(self, key: str, shape: tuple, dtype: str, mesh, spec):
        """Zero filler resident on this side's slice under the transfer
        spec — cached per (name, shape, dtype, devices, spec)."""
        ck = (
            key, shape, dtype, tuple(d.id for d in mesh.devices.flat),
            tuple(spec_to_wire_key(spec)),
        )
        with self._filler_lock:
            buf = self._fillers.get(ck)
        if buf is not None:
            return buf
        buf = jax.device_put(jnp.zeros(shape, dtype), NamedSharding(mesh, spec))
        with self._filler_lock:
            self._fillers[ck] = buf
        return buf

    # ---- the executor body (both roles) ----

    def _prepare(self, t: _Transfer) -> None:
        if t.role == "send":
            mesh_meta = t.accept_meta.get("mesh") or {}
            dst_mesh = mesh_from_ids(
                mesh_meta.get("ids", []),
                mesh_meta.get("shape", []),
                mesh_meta.get("axes", []),
            )
            if dst_mesh is None:
                raise RuntimeError("peer devices unknown to this world")
            if (
                dst_mesh.devices.shape != t.src_info.mesh.devices.shape
                or dst_mesh.axis_names != t.src_info.mesh.axis_names
            ):
                raise RuntimeError("peer slice topology mismatch")
            t.dst_mesh = dst_mesh
        else:
            filler = {}
            for key, shape, dtype, specw in t.meta["leaves"]:
                spec = spec_from_wire(specw)
                filler[str(key)] = self._filler_buf(
                    str(key), tuple(shape), str(dtype), t.dst_info.mesh, spec
                )
            t.filler = filler
            t.specs = tuple(
                spec_from_wire(specw)
                for _key, _shape, _dt, specw in sorted(
                    t.meta["leaves"], key=lambda e: str(e[0])
                )
            )

    def _execute(self, t: _Transfer) -> None:
        from p2pfl_tpu.settings import Settings

        if t.finished.is_set():
            return
        try:
            self._prepare(t)
        except Exception as exc:  # noqa: BLE001 — bad metadata, not a bug
            self._abort(t, f"prepare_failed:{exc!r}", notify=True)
            return
        if not self._dispatch_lock.acquire(timeout=Settings.DCN_READY_TIMEOUT_S):
            self._abort(t, "dispatch_lock_timeout", notify=True)
            return
        landed = None
        err: Optional[Exception] = None
        try:
            if t.finished.is_set():
                return
            self._send_verb(t.proto, t.peer_addr, "dcn_ready", {"tid": t.tid})
            if not t.peer_ready.wait(Settings.DCN_READY_TIMEOUT_S):
                self._abort(t, "ready_timeout", notify=True)
                return
            if t.finished.is_set():
                return  # aborted during the handshake
            if t.role == "send":
                dcn_transfer(
                    t.transfer_tree, t.src_info.mesh, t.dst_mesh, t.specs, "send"
                )
            else:
                landed = dcn_transfer(
                    t.filler, t.src_mesh, t.dst_info.mesh, t.specs, "recv"
                )
        except Exception as exc:  # noqa: BLE001 — a failed exchange is a failed send
            err = exc
        finally:
            self._dispatch_lock.release()
        if err is not None:
            logger.error(
                t.proto.get_address(), f"DCN exchange {t.tid} failed: {err!r}"
            )
            self._abort(t, f"exchange_failed:{err!r}", outcome="failed", notify=True)
            return
        if t.role == "send":
            return  # completion arrives as dcn_done
        # decode + delivery run OFF the executor thread: a command handler
        # reached through handle_weights may itself start a DCN send to the
        # same peer, and THAT dispatch needs this executor free (only
        # collective DISPATCH is order-constrained, not delivery)
        threading.Thread(
            target=self._deliver_and_done,
            args=(t, landed),
            name=f"dcn-deliver-{t.tid}",
            daemon=True,
        ).start()

    def _deliver_and_done(self, t: _Transfer, landed: dict) -> None:
        ok = self._deliver(t, landed)
        self._send_verb(
            t.proto, t.peer_addr, "dcn_done", {"tid": t.tid, "ok": bool(ok)}
        )
        self._finish(t, "ok" if ok else "failed", "" if ok else "deliver_failed")

    def _deliver(self, t: _Transfer, landed: dict) -> bool:
        node = t.node
        meta = t.meta
        try:
            tmpl_named = _named_dict(t.template)
            tk_spec = tuple(tuple(e) for e in meta.get("tk_spec", []))
            dense_spec = tuple(tuple(e) for e in meta.get("dense_spec", []))
            if tk_spec or dense_spec:
                from p2pfl_tpu.ops.compression import decode_shard_device

                payload = {k[2:]: v for k, v in landed.items() if k.startswith("c/")}
                anchor_named = None
                if tk_spec:
                    anchor_named = _named_dict(
                        getattr(node.learner, "_wire_anchor", None)
                    )
                out_named = decode_shard_device(
                    payload, tk_spec, dense_spec, anchor_named, tmpl_named
                )
                for k, v in landed.items():
                    if k.startswith("r/"):
                        out_named[k[2:]] = v
            else:
                out_named = {k[2:]: v for k, v in landed.items()}
            restored = _restore_named(t.template, out_named)
            # decoded/landed layouts are the SENDER's: normalize onto the
            # receiver's own placement (device_put within the receiver's
            # slice, counted as conform, never host)
            from p2pfl_tpu.ops.tree import tree_align_copy_count, tree_align_devices

            before = tree_align_copy_count()
            restored = tree_align_devices(restored, t.template)
            moved_leaves = tree_align_copy_count() - before
            if moved_leaves:
                _count("conform_copies", moved_leaves)
            sp = meta.get("sp")
            delivered = ModelUpdate(
                restored,
                [str(c) for c in meta.get("contributors", [])],
                int(meta.get("num_samples", 1)),
                version=tuple(meta["vv"]) if meta.get("vv") else None,
                xp=meta.get("xp"),
                sp=(tuple(sp[0]), sp[1], sp[2]) if sp else None,
            )
            # the receiver re-encodes relays/diffusions against ITS OWN
            # anchor, exactly like the byte path's materialize()
            delivered.anchor = getattr(node.learner, "_wire_anchor", None)
            delivered.anchor_tag = getattr(node.learner, "_wire_anchor_tag", None)
            tc = meta.get("tc")
            denv = WeightsEnvelope(
                str(meta.get("src", t.peer_addr)),
                int(meta.get("round", -1)),
                str(meta.get("cmd", "add_model")),
                delivered,
                str(meta.get("msg_id", "")),
                trace_ctx=(tc[0], tc[1]) if tc else None,
                xp=meta.get("xp"),
            )
            result = node.protocol.handle_weights(denv)
        except Exception as exc:  # noqa: BLE001 — delivery must not kill the executor
            logger.error(
                t.proto.get_address(),
                f"DCN delivery from {t.peer_addr} failed: {exc!r}",
            )
            return False
        _count("dcn_recvs")
        logger.log_comm_metric(node.addr, "dcn_recv_shard")
        telemetry.event(
            node.addr,
            "dcn_transfer_recv",
            kind="gossip",
            attrs={"peer": t.peer_addr, "codec": t.mode, "seq": t.seq},
        )
        return bool(result.ok)


def spec_to_wire_key(spec) -> tuple:
    """Hashable form of a PartitionSpec for cache keys."""
    return tuple(tuple(e) if isinstance(e, (list, tuple)) else e for e in spec)


# ---- sender-side payload build (the codec leg) ----


def _build_payload(update: ModelUpdate, src_info: SliceInfo, mode: str) -> dict:
    """Encode-once: the transfer tree (codec buffers + raw passthrough),
    per-key specs and all wire metadata. Mirrors ``ici._move_codec``'s
    encode half, caching under a ``"dcn"``-prefixed key and claiming the
    cross-plane error-feedback fold through the SAME
    ``PayloadCache.ef_fold_once`` ownership protocol."""
    from p2pfl_tpu.settings import Settings

    src_params = update.params
    named = _named_dict(src_params)
    spec_keys = [k for k, _leaf in named_leaves(src_params)[1]]
    spec_by_key = dict(zip(spec_keys, src_info.specs))
    model_meta = [
        [k, list(named[k].shape), str(named[k].dtype)] for k in sorted(named)
    ]
    tk_spec: tuple = ()
    dense_spec: tuple = ()
    if mode in ("int8", "topk8"):
        from p2pfl_tpu.ops.compression import build_topk_plan, encode_shard_device

        anchor_named = (
            _named_dict(update.anchor) if update.anchor is not None else None
        )
        topk_frac = Settings.TOPK_FRACTION if mode == "topk8" else 0.0
        topk_plan = build_topk_plan(named, anchor_named, topk_frac)
        with update._encode_lock:
            cache = update.payload_cache
            use_cache = Settings.GOSSIP_PAYLOAD_CACHE
            key = None
            cached = None
            if use_cache and cache is not None and update.cache_version is not None:
                key = (
                    "dcn",
                    update.cache_version,
                    update.cache_round,
                    mode,
                    update.anchor_tag,
                    update.ef_residual is not None,
                )
                cached = cache.get(key)
            elif use_cache:
                cached = getattr(update, "_dcn_payload", None)
            if cached is not None:
                tk_spec, dense_spec, payload = cached
            else:
                residual = update.ef_residual
                if (
                    residual is not None
                    and cache is not None
                    and update.cache_version is not None
                ):
                    # cross-plane fold ownership — ONE key builder, shared
                    # with the byte and ICI encoders (ModelUpdate.ef_fold_key)
                    if not cache.ef_fold_once(update.ef_fold_key(mode)):
                        residual = None
                tk_spec, dense_spec, payload = encode_shard_device(
                    named,
                    anchor_named,
                    topk_plan,
                    residual,
                    # optimization_barrier under the SPMD partitioner is a
                    # single-device-only workaround (see _encode_jit)
                    barrier=len(src_info.device_ids) == 1,
                )
                payload = replicate_on_slice(payload, src_info)
                if key is not None:
                    cache.put(key, (tk_spec, dense_spec, payload))
                elif use_cache:
                    update._dcn_payload = (tk_spec, dense_spec, payload)
        coded = {k for k, _s, _b in tk_spec} | {k for k, _s in dense_spec}
        raw_keys = [k for k in sorted(named) if k not in coded]
        transfer = {f"c/{k}": v for k, v in payload.items()}
        spec_of = {f"c/{k}": P() for k in payload}
        for k in raw_keys:
            transfer[f"r/{k}"] = named[k]
            spec_of[f"r/{k}"] = spec_by_key[k]
    else:
        transfer = {f"r/{k}": named[k] for k in named}
        spec_of = {f"r/{k}": spec_by_key[k] for k in named}
    ordered = sorted(transfer)
    specs = tuple(spec_of[k] for k in ordered)
    leaves_meta = [
        [k, list(transfer[k].shape), str(transfer[k].dtype), spec_to_wire(spec_of[k])]
        for k in ordered
    ]
    return {
        "mode": mode,
        "transfer": transfer,
        "specs": specs,
        "moved": tree_device_bytes(transfer),
        "model_meta": model_meta,
        "leaves_meta": leaves_meta,
        "tk_spec": tk_spec,
        "dense_spec": dense_spec,
    }


# ---- the transport hook ----


def try_dcn_send(proto, nei: str, env) -> Optional[bool]:
    """Attempt a DCN cross-process delivery for one outgoing envelope.

    Returns ``True``/``False`` when the plane handled the send (the byte
    path must NOT run), or ``None`` when this edge is not DCN-eligible
    and the caller proceeds down its byte path. Called from inside
    ``_send_to_neighbor`` AFTER the ICI attempt, so the per-edge ladder
    is: ICI (co-resident) → DCN (same world, different process) → bytes.
    """
    from p2pfl_tpu.settings import Settings

    if Settings.WEIGHTS_PLANE != "dcn" or not isinstance(env, WeightsEnvelope):
        return None
    update = env.update
    if update.params is None:
        return None  # pre-encoded frame (relay) — bytes it is
    src = proto.get_address()
    if not world_active():
        _fallback(src, nei, "no_distributed_world")
        return None
    plane = DcnPlane.instance()
    peer = plane.directory.lookup(nei)
    if peer is None:
        _fallback(src, nei, "peer_not_in_world_directory")
        return None
    peer_pi = int(peer.get("pi", -1))
    if peer_pi == int(jax.process_index()):
        # same process: the ICI plane's territory — it already ran (and
        # counted any fallback of its own); stay silent here
        return None
    src_ep = ShardPlaneRegistry.get(src)
    if src_ep is None:
        _fallback(src, nei, "sender_not_on_shard_plane")
        return None
    src_info = slice_info_of(update.params)
    if src_info is None:
        _fallback(src, nei, "params_not_device_resident")
        return None
    if not process_local(src_info):
        _fallback(src, nei, "slice_spans_processes")
        return None
    try:
        built = _build_payload(update, src_info, Settings.WIRE_COMPRESSION)
    except Exception as exc:  # noqa: BLE001 — a failed encode is a failed send
        logger.error(src, f"DCN encode for {nei} failed: {exc!r}")
        return False
    t = plane.begin_send(proto, nei, env, built, src_info, src_ep, peer_pi)
    if t is None:
        return None  # offer undeliverable — byte path fails the send
    return plane.await_send(t, proto, nei)
