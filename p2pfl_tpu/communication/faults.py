"""Seeded, deterministic fault injection for the overlay.

Production FL systems treat device dropout and stragglers as the common
case, not the exception (Bonawitz et al., *Towards Federated Learning at
Scale*, MLSys 2019) — but until this module the only way to exercise this
repo's safety nets (vote timeout, ``AGGREGATION_TIMEOUT``, stalled-peer
skip, secagg dropout recovery, the retry/breaker control plane) was ad-hoc
monkeypatching per test. A :class:`FaultPlan` describes the chaos to
inject declaratively:

- per-edge **drop** / **delay** / **duplicate** probabilities
  (:class:`EdgeFault`, directed ``src -> dst``),
- **one-way partitions** (``src`` cannot reach ``dst``; the reverse
  direction is untouched unless also listed),
- **slow peers** (every inbound weights delivery to that node pays a fixed
  latency — the straggler the gossip send budget exists for),
- **crash-at-stage** hooks (:class:`CrashSpec`): a node hard-crashes — no
  goodbye messages, exactly like a killed process — when its learning
  thread enters a named stage at a given round,
- **Byzantine attackers** (:class:`ByzantineSpec`): a node's model
  payloads are corrupted at the same send seam — sign-flip, scale-by-λ,
  Gaussian noise, stale replay, per-edge equivocation — while its
  control plane stays perfectly healthy: the node lies, it does not
  stop, so only semantic defenses (robust merge kernels + the admission
  screen, ``federation/defense.py``) can catch it.

Determinism: every directed edge draws from its own
``random.Random(f"{seed}:{src}->{dst}")`` stream, so the k-th send on an
edge sees the same drop/duplicate verdict on every run regardless of how
the OS interleaves the other edges' threads. Replaying a chaos run is
``FaultPlan(seed=...)`` with the same topology and workload.

The plan wraps a transport at the :class:`CommunicationProtocol` send seam
(``protocol.fault_injector``) — every plane (heartbeat, control gossip,
model gossip) passes through it, and any transport (in-memory, gRPC) can
be wrapped. ``install_fault_plan(nodes, plan)`` wires a whole in-process
federation; chaos federations then run under plain pytest
(``tests/test_chaos.py``).

Duplicate semantics: a duplicated *control* message is re-delivered after
``EdgeFault.duplicate_delay`` seconds **with a fresh message id** — it
models a relayed copy that comes back after the receiver's bounded dedup
ring (``Settings.AMOUNT_LAST_MESSAGES_SAVED``) has forgotten the
original, which is exactly the stale-redelivery storm behind the round-0
wedge this layer was built to reproduce (the redelivered copy carries
``ttl=1`` so it cannot re-amplify through relays). Duplicated weights
envelopes are re-sent as-is — the data plane has no dedup and must
tolerate replays through the aggregator's contributor checks.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable, Iterable, Optional

import numpy as np

from p2pfl_tpu.communication.message import Message, WeightsEnvelope
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.management.telemetry import telemetry

if TYPE_CHECKING:
    from p2pfl_tpu.node import Node


@dataclass(frozen=True)
class EdgeFault:
    """Faults applied to one directed edge (or as the plan-wide default).

    ``scope`` limits which plane the fault touches: ``"both"`` (default),
    ``"weights"`` (model payloads only — a fat-pipe straggler whose
    control plane is healthy), or ``"control"`` (lossy signaling over a
    healthy bulk path).
    """

    drop: float = 0.0            # P(send fails; transport reports False)
    delay: float = 0.0           # fixed seconds added before delivery
    jitter: float = 0.0          # + U(0, jitter) drawn from the edge RNG
    duplicate: float = 0.0       # P(a second copy is delivered later)
    duplicate_delay: float = 0.2  # how much later the copy lands
    scope: str = "both"          # "both" | "weights" | "control"

    def applies_to(self, env: object) -> bool:
        if self.scope == "both":
            return True
        is_weights = isinstance(env, WeightsEnvelope)
        return is_weights if self.scope == "weights" else not is_weights


@dataclass(frozen=True)
class CrashSpec:
    """Hard-crash a node when its learning thread enters ``stage``.

    ``round_no=None`` matches any round. ``after_s > 0`` arms a timer at
    stage entry instead of crashing synchronously — the node dies
    mid-stage (mid-fit, mid-gossip), which is the interesting case for
    train-set repair.
    """

    stage: str
    round_no: Optional[int] = 0
    after_s: float = 0.0


@dataclass(frozen=True)
class RestartSpec:
    """Kill a node like a :class:`CrashSpec`, then RESURRECT it.

    The crash half is identical (hard crash at ``stage``/``round_no``,
    optionally ``after_s`` into the stage — no goodbyes); ``resume_after_s``
    later the driver brings the node back **from its journal**
    (:mod:`~p2pfl_tpu.federation.durability`): the live fleet's
    ``resurrect_fn`` calls ``Node.resume(journal_dir)`` (or re-spawns the
    process for a real-SIGKILL drill), the simulator schedules a
    ``resurrect`` event on its virtual clock. Either way the node
    re-enters through the EXISTING elastic join machinery with its
    journaled identity — sequence counters resumed strictly past the
    high-water, pending buffers re-armed — so kill-and-resurrect is a
    first-class replayable chaos verdict, not a new node wearing an old
    address.
    """

    stage: str = "AsyncTrainStage"
    round_no: Optional[int] = 0
    after_s: float = 0.0
    resume_after_s: float = 1.0


@dataclass(frozen=True)
class ByzantineSpec:
    """A node that keeps talking and LIES: every model payload it sends is
    corrupted at the ``_do_send`` seam before it reaches the wire.

    The chaos taxonomy's other specs model nodes that *stop* (crash, drop,
    delay); this one models the production failure Bonawitz et al. rank
    worst — a semantically wrong participant that no liveness machinery
    ever notices. Attack kinds:

    - ``"sign_flip"`` — sends ``−params`` (gradient-ascent poisoning);
    - ``"scale"`` — sends ``lam × params`` (model-boost / scaling attack);
    - ``"noise"`` — sends ``params + N(0, noise_std)`` (fresh per send);
    - ``"stale_replay"`` — re-sends its FIRST payload forever, stamped
      with the CURRENT version triple (a semantic lie the version-vector
      dedup and staleness bound cannot catch — the triple is fresh);
    - ``"equivocate"`` — sends a DIFFERENT corruption to each peer
      (per-edge scale drawn from the edge's own stream), the classic
      split-view attack against aggregators that compare contributions.

    Determinism: corruption draws ride dedicated per-edge streams
    (``FaultPlan.byz_rng`` — ``f"{seed}:byz:{src}->{dst}"``), separate
    from the drop/duplicate verdict streams so arming an attack never
    shifts any existing fault verdict; the k-th payload on an edge is
    corrupted identically on every run. ``cmds`` bounds the blast radius
    to contribution payloads (an attacker's ``init_model`` or global
    pushes would model a hostile *initiator/root*, a different threat).

    The ORIGINAL update is never mutated — in-process transports pass
    payloads by reference, and the attacker's own learner (and other
    edges' deliveries) must keep the honest object.
    """

    kind: str = "sign_flip"
    lam: float = 10.0           # scale factor for "scale" (and the
    noise_std: float = 1.0      # equivocate magnitude bound) / noise σ
    cmds: tuple = ("async_update", "add_model")


@dataclass(frozen=True)
class JoinSpec:
    """A member JOINS the running experiment at ``at_s`` (seconds — the
    virtual clock in :mod:`~p2pfl_tpu.federation.simfleet`, wall clock
    after :func:`schedule_churn` on a live fleet).

    The same conceptual seam as :class:`CrashSpec`: a churn plan keyed by
    address, replayed bit-exact by the simulator and driven by timers on
    the live fleet. The joiner bootstraps by pulling its aggregator's
    current global (``async_pull``) before contributing — see
    ``federation/workflow.py``.
    """

    at_s: float


@dataclass(frozen=True)
class LeaveSpec:
    """A member LEAVES the running experiment at ``at_s``.

    ``graceful=True`` is an announced departure (``async_leave``): an
    aggregator forwards its partial buffer to the successor tier before
    exiting, so no buffered contribution is lost. ``graceful=False`` is
    an abrupt exit discovered like a crash — through heartbeat silence
    (live fleet) or the simulator's ``evict_delay``.
    """

    at_s: float
    graceful: bool = True


class FaultCrash(Exception):
    """Raised on the learning thread of a node crashed by a CrashSpec —
    unwinds the stage workflow the way a killed process stops executing."""


class FaultPlan:
    """A replayable description of everything that goes wrong.

    ``edges`` maps directed ``(src, dst)`` pairs to :class:`EdgeFault`
    overrides; ``default`` applies to every other edge. ``partitions`` is
    an iterable of one-way ``(src, dst)`` blocks. ``slow_nodes`` maps a
    receiver address to the latency (seconds) every inbound *weights*
    delivery pays. ``crashes`` maps a node address to a
    :class:`CrashSpec`.
    """

    def __init__(
        self,
        seed: int,
        default: EdgeFault = EdgeFault(),
        edges: Optional[dict[tuple[str, str], EdgeFault]] = None,
        partitions: Iterable[tuple[str, str]] = (),
        slow_nodes: Optional[dict[str, float]] = None,
        crashes: Optional[dict[str, CrashSpec]] = None,
        joins: Optional[dict[str, "JoinSpec"]] = None,
        leaves: Optional[dict[str, "LeaveSpec"]] = None,
        byzantine: Optional[dict[str, "ByzantineSpec"]] = None,
        restarts: Optional[dict[str, "RestartSpec"]] = None,
    ) -> None:
        self.seed = seed
        self.default = default
        self.edges = dict(edges or {})
        self.partitions = set(partitions)
        self.slow_nodes = dict(slow_nodes or {})
        self.crashes = dict(crashes or {})
        #: kill-and-resurrect events: addr -> RestartSpec
        self.restarts = dict(restarts or {})
        #: churn events (elastic membership): addr -> JoinSpec / LeaveSpec
        self.joins = dict(joins or {})
        self.leaves = dict(leaves or {})
        #: adversaries: attacker addr -> ByzantineSpec
        self.byzantine = dict(byzantine or {})
        self._rngs: dict[tuple[str, str], random.Random] = {}
        self._byz_rngs: dict[tuple[str, str], random.Random] = {}
        self._rng_lock = threading.Lock()
        #: crash specs already fired (addr) — a spec fires exactly once
        self._crashed: set[str] = set()
        #: stale_replay capture: attacker addr -> its first payload's
        #: params (host numpy copy), taken at its first corrupted send
        self._byz_stale: dict[str, object] = {}

    # ---- per-edge state ----

    def rng(self, src: str, dst: str) -> random.Random:
        """The directed edge's own deterministic stream."""
        key = (src, dst)
        with self._rng_lock:
            r = self._rngs.get(key)
            if r is None:
                r = self._rngs[key] = random.Random(f"{self.seed}:{src}->{dst}")
            return r

    def byz_rng(self, src: str, dst: str) -> random.Random:
        """The directed edge's Byzantine-corruption stream — separate from
        :meth:`rng` so arming an attack never shifts drop/dup verdicts."""
        key = (src, dst)
        with self._rng_lock:
            r = self._byz_rngs.get(key)
            if r is None:
                r = self._byz_rngs[key] = random.Random(f"{self.seed}:byz:{src}->{dst}")
            return r

    def edge_fault(self, src: str, dst: str) -> EdgeFault:
        return self.edges.get((src, dst), self.default)

    def partitioned(self, src: str, dst: str) -> bool:
        return (src, dst) in self.partitions


class FaultInjector:
    """Wraps one protocol's transport send with a plan's edge faults.

    Installed as ``protocol.fault_injector``; the protocol routes every
    send through :meth:`__call__` with the real transport send as the
    continuation.
    """

    def __init__(self, plan: FaultPlan, src: str) -> None:
        self.plan = plan
        self.src = src

    def __call__(
        self,
        nei: str,
        env: object,
        create_connection: bool,
        transport_send: Callable[..., bool],
    ) -> bool:
        plan = self.plan
        # every verdict is also a flight-recorder event: the injector runs
        # INSIDE the protocol's send span, so each event lands on the
        # affected edge's timeline and chaos runs are self-explaining
        cmd = getattr(env, "cmd", "?")
        if plan.partitioned(self.src, nei):
            logger.log_comm_metric(self.src, "fault_partition_drop")
            telemetry.event(
                self.src, "fault_partition_drop", attrs={"peer": nei, "cmd": cmd}
            )
            return False
        # straggler latency: every inbound WEIGHTS delivery to a slow node
        # pays it (its control plane stays healthy — that asymmetry, a fat
        # pipe stalling while signaling flows, is the hard case the gossip
        # send budget and the stall machinery exist for)
        slow = plan.slow_nodes.get(nei, 0.0)
        if slow and isinstance(env, WeightsEnvelope):
            telemetry.event(
                self.src, "fault_slow", attrs={"peer": nei, "delay_s": slow}
            )
            time.sleep(slow)
        # corruption runs BEFORE the edge fault's scope gate: a Byzantine
        # attacker and (say) a control-scoped drop fault are independent
        # dimensions of one plan, and an applies_to short-circuit must not
        # silently disarm the attack (the simulator corrupts before its
        # edge verdict for the same reason — one seam, one behavior)
        if plan.byzantine and isinstance(env, WeightsEnvelope):
            bad = byz_corrupt_update(plan, self.src, nei, env.update, env.cmd)
            if bad is not None:
                logger.log_comm_metric(self.src, "fault_byzantine")
                telemetry.event(
                    self.src,
                    "fault_byzantine",
                    attrs={
                        "peer": nei,
                        "cmd": cmd,
                        "kind": plan.byzantine[self.src].kind,
                    },
                )
                env = WeightsEnvelope(
                    env.source, env.round, env.cmd, bad,
                    trace_ctx=env.trace_ctx, xp=env.xp,
                )
        fault = plan.edge_fault(self.src, nei)
        if not fault.applies_to(env):
            return transport_send(nei, env, create_connection=create_connection)
        rng = plan.rng(self.src, nei)
        # draw the full verdict tuple up front so the edge's stream
        # advances identically whether or not earlier faults short-circuit
        # (keeps the k-th send's verdict stable across fault combinations)
        drop_u, dup_u, jitter_u = rng.random(), rng.random(), rng.random()
        if fault.drop and drop_u < fault.drop:
            logger.log_comm_metric(self.src, "fault_drop")
            telemetry.event(self.src, "fault_drop", attrs={"peer": nei, "cmd": cmd})
            return False
        d = fault.delay + jitter_u * fault.jitter
        if d > 0:
            telemetry.event(
                self.src, "fault_delay", attrs={"peer": nei, "delay_s": round(d, 4)}
            )
            time.sleep(d)
        ok = transport_send(nei, env, create_connection=create_connection)
        if ok and fault.duplicate and dup_u < fault.duplicate:
            logger.log_comm_metric(self.src, "fault_duplicate")
            telemetry.event(self.src, "fault_duplicate", attrs={"peer": nei, "cmd": cmd})
            copy = _stale_copy(env)
            t = threading.Timer(
                max(fault.duplicate_delay, 0.001),
                _deliver_copy,
                args=(transport_send, nei, copy, create_connection),
            )
            t.daemon = True
            t.start()
        return ok


def _stale_copy(env: object) -> object:
    """A re-delivery of ``env`` as the overlay would actually produce it.

    Control messages come back with a fresh id and ttl=1 — a relay the
    dedup ring has already forgotten (the id rotation models ring
    overflow, Settings.AMOUNT_LAST_MESSAGES_SAVED being finite), which
    must not re-amplify. Weights envelopes replay verbatim.
    """
    if isinstance(env, Message):
        return Message(
            env.source, env.cmd, env.args, env.round, ttl=1,
            trace_ctx=env.trace_ctx, xp=env.xp,
        )
    return env


def _deliver_copy(transport_send, nei, env, create_connection) -> None:
    try:
        transport_send(nei, env, create_connection=create_connection)
    except Exception:  # noqa: BLE001 — the node may have stopped meanwhile
        pass


# ---- Byzantine corruption ----


def _tree_map_np(params: object, fn: Callable) -> object:
    """Apply ``fn`` to every floating leaf as a host fp32 numpy array,
    casting back to the leaf dtype; non-float leaves pass through. Always
    returns NEW arrays — corruption must never alias the honest pytree."""
    import jax

    def one(x):
        arr = np.asarray(x)
        if not (np.issubdtype(arr.dtype, np.floating) or arr.dtype.kind == "V"):
            # "V" covers ml_dtypes (bfloat16) which numpy reports as void-kind
            # on some versions; astype below validates either way
            return np.array(arr, copy=True)
        try:
            f32 = arr.astype(np.float32)
        except (TypeError, ValueError):
            return np.array(arr, copy=True)
        return fn(f32).astype(arr.dtype)

    return jax.tree.map(one, params)


def byz_corrupt_update(plan: FaultPlan, src: str, dst: str, update, cmd: str):
    """The corrupted COPY of ``update`` an attacker ``src`` ships to
    ``dst``, or None when no corruption applies (no spec, wrong command,
    or a byte-only payload with no materialized params to lie about).

    Shared by both drivers — the live :class:`FaultInjector` and the
    simulator's virtual wire call exactly this, so a plan's attack
    replays identically at whichever seam delivers it. Deterministic:
    draws ride :meth:`FaultPlan.byz_rng`'s per-edge stream, advanced once
    per corrupted payload.
    """
    spec = plan.byzantine.get(src)
    if spec is None or cmd not in spec.cmds:
        return None
    params = getattr(update, "params", None)
    if params is None:
        return None
    rng = plan.byz_rng(src, dst)
    kind = spec.kind
    if kind == "sign_flip":
        corrupted = _tree_map_np(params, lambda a: -a)
    elif kind == "scale":
        lam = float(spec.lam)
        corrupted = _tree_map_np(params, lambda a: lam * a)
    elif kind == "noise":
        g = np.random.default_rng(rng.getrandbits(32))
        std = float(spec.noise_std)
        corrupted = _tree_map_np(
            params, lambda a: a + g.normal(0.0, std, a.shape).astype(np.float32)
        )
    elif kind == "stale_replay":
        with plan._rng_lock:
            stale = plan._byz_stale.get(src)
            if stale is None:
                stale = plan._byz_stale[src] = _tree_map_np(params, lambda a: a)
        # fresh copies per send: receivers must never share the capture
        corrupted = _tree_map_np(stale, lambda a: a)
    elif kind == "equivocate":
        # a DIFFERENT lie per edge per send: sign and magnitude from the
        # edge's own stream, so no two peers (and no two sends) agree
        s = (-1.0 if rng.random() < 0.5 else 1.0) * rng.uniform(1.0, max(spec.lam, 1.0))
        corrupted = _tree_map_np(params, lambda a: np.float32(s) * a)
    else:
        raise ValueError(f"unknown ByzantineSpec kind {kind!r}")
    from p2pfl_tpu.learning.weights import ModelUpdate

    bad = ModelUpdate(corrupted, list(update.contributors), update.num_samples)
    bad.version = update.version
    bad.xp = update.xp
    # topk8 delta coding needs the round anchor to re-encode the lie
    bad.anchor = update.anchor
    bad.anchor_tag = update.anchor_tag
    return bad


#: ByzantineSpec kinds with a vectorized payload-transform twin — the
#: corruptions expressible as one elementwise op per payload, which is
#: what lets the megafleet engine apply them as masked array transforms
#: inside its scan. stale_replay and equivocate are *stateful per edge*
#: (a capture, a per-peer split view) and stay heap-only.
BYZ_VECTOR_KINDS = ("sign_flip", "scale", "noise")
_BYZ_KIND_CODE = {"sign_flip": 1, "scale": 2, "noise": 3}


def byz_payload_grid(plan: FaultPlan, addrs: list) -> tuple:
    """Dense per-node corruption codes for a plan's Byzantine specs —
    ``(kind_code [N] int32, lam [N] f32, std [N] f32)`` over ``addrs`` in
    index order, code 0 = honest. The array-engine twin of
    :func:`byz_corrupt_update`'s kind dispatch: ``1`` → ``−a``, ``2`` →
    ``lam·a``, ``3`` → ``a + N(0, std)`` (noise rows drawn by the caller
    from its own counter-based stream — the per-edge ``byz_rng`` streams
    have no vectorized form, so cross-driver noise parity is
    statistical). A spec whose ``cmds`` excludes ``"async_update"`` never
    touches the async contribution seam and maps to code 0; a kind
    outside :data:`BYZ_VECTOR_KINDS` raises — those attacks need the
    heap driver.
    """
    n = len(addrs)
    code = np.zeros(n, np.int32)
    lam = np.ones(n, np.float32)
    std = np.zeros(n, np.float32)
    idx = {a: j for j, a in enumerate(addrs)}
    for addr, spec in plan.byzantine.items():
        j = idx.get(addr)
        if j is None:
            continue
        if spec.kind not in BYZ_VECTOR_KINDS:
            raise ValueError(
                f"ByzantineSpec kind {spec.kind!r} is stateful per edge "
                "and needs the heap driver; vectorized kinds: "
                f"{'/'.join(BYZ_VECTOR_KINDS)}"
            )
        if "async_update" not in spec.cmds:
            continue
        code[j] = _BYZ_KIND_CODE[spec.kind]
        lam[j] = np.float32(spec.lam)
        std[j] = np.float32(spec.noise_std)
    return code, lam, std


# ---- crash machinery ----


def hard_crash(node: "Node") -> None:
    """Kill a node the way a dead process dies: no goodbyes.

    The server unregisters (subsequent sends to it fail), heartbeats and
    gossip stop, the learner is interrupted — but neighbors are NOT
    notified and no disconnect messages go out. Peers find out through
    heartbeat silence / send failures, which is the code path chaos tests
    exist to exercise.
    """
    logger.warning(node.addr, "FAULT: hard crash injected")
    logger.log_comm_metric(node.addr, "fault_crash")
    telemetry.event(
        node.addr,
        "fault_crash",
        attrs={"stage": getattr(node.state, "current_stage", None),
               "round": getattr(node.state, "round", None)},
    )
    node._interrupt.set()
    if node.learner is not None:
        try:
            node.learner.interrupt_fit()
        except Exception:  # noqa: BLE001 — learner may not be fitted yet
            pass
    proto = node.protocol
    try:
        crash = getattr(proto, "crash", proto._server_stop)
        crash()  # unregister only — no peer notifications
    except Exception:  # noqa: BLE001
        pass
    proto.heartbeater.stop()
    proto.gossiper.stop()
    node._running = False
    # take the corpse out of the StallWatchdog's scope: its state stays
    # registered (the harness holds the Node) with status "Learning" and a
    # frozen last_transition, so any chaos run outlasting STALL_WATCHDOG_S
    # would count a phantom stall_detected for a node that is *dead*, not
    # stalled — a real killed process takes its watchdog down with it
    node.state.status = "Idle"


def make_stage_hook(
    plan: FaultPlan,
    resurrect_fn: Optional[Callable[[str], None]] = None,
) -> Callable[["Node", str], None]:
    """A ``Node.stage_hooks`` entry firing the plan's crash AND restart
    specs. ``resurrect_fn(addr)`` is the live half of the restart seam —
    called ``resume_after_s`` after the kill, on a daemon timer; only
    the harness knows how to rebuild models/datasets and call
    ``Node.resume``, exactly like :func:`schedule_churn`'s ``join_fn``.
    A RestartSpec with no ``resurrect_fn`` degrades to its crash half
    (the kill still fires; nobody comes back).
    """

    def kill(node: "Node", spec, stage_name: str, sync: bool) -> None:
        hard_crash(node)
        delay = getattr(spec, "resume_after_s", None)
        if delay is not None and resurrect_fn is not None:
            t = threading.Timer(max(delay, 0.001), _resurrect, args=(node.addr,))
            t.daemon = True
            t.start()
        if sync:
            raise FaultCrash(f"{node.addr} crashed entering {stage_name}")

    def _resurrect(addr: str) -> None:
        try:
            resurrect_fn(addr)
        except Exception as exc:  # noqa: BLE001 — a failed resurrection is a dead node, not a harness crash
            logger.error(addr, f"FAULT: resurrection failed: {exc!r}")

    def hook(node: "Node", stage_name: str) -> None:
        spec = plan.crashes.get(node.addr) or plan.restarts.get(node.addr)
        if spec is None or node.addr in plan._crashed:
            return
        if spec.stage != stage_name:
            return
        if spec.round_no is not None and node.state.round != spec.round_no:
            return
        plan._crashed.add(node.addr)
        if spec.after_s > 0:
            t = threading.Timer(spec.after_s, kill, args=(node, spec, stage_name, False))
            t.daemon = True
            t.start()
            return
        kill(node, spec, stage_name, sync=True)

    return hook


def install_fault_plan(
    nodes: Iterable["Node"],
    plan: FaultPlan,
    resurrect_fn: Optional[Callable[[str], None]] = None,
) -> None:
    """Wire a plan into an in-process federation (or any node set)."""
    hook = (
        make_stage_hook(plan, resurrect_fn)
        if (plan.crashes or plan.restarts)
        else None
    )
    for node in nodes:
        node.protocol.fault_injector = FaultInjector(plan, node.addr)
        if hook is not None:
            node.stage_hooks.append(hook)


def remove_fault_plan(nodes: Iterable["Node"]) -> None:
    for node in nodes:
        node.protocol.fault_injector = None
        node.stage_hooks.clear()


def schedule_churn(plan: FaultPlan, join_fn, leave_fn) -> list:
    """Arm a plan's churn events on a LIVE fleet (wall-clock timers).

    The live half of the seam :class:`JoinSpec`/:class:`LeaveSpec` share
    with the simulator: ``join_fn(addr)`` is called at each join's
    ``at_s`` (the caller constructs/connects the joining node — only it
    knows models and datasets), ``leave_fn(addr, graceful)`` at each
    leave's. Returns the started timers so a test can cancel them on
    teardown. Crash specs stay on the stage-hook seam
    (:func:`install_fault_plan`) — they are driven by the victim's own
    learning thread, not the clock.
    """
    timers = []
    for addr in sorted(plan.joins):
        t = threading.Timer(plan.joins[addr].at_s, join_fn, args=(addr,))
        t.daemon = True
        t.start()
        timers.append(t)
    for addr in sorted(plan.leaves):
        spec = plan.leaves[addr]
        t = threading.Timer(spec.at_s, leave_fn, args=(addr, spec.graceful))
        t.daemon = True
        t.start()
        timers.append(t)
    return timers
