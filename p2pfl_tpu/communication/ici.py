"""The shard-native ICI weights plane: model diffusion never touches the host.

``Settings.WEIGHTS_PLANE = "ici"`` re-routes MODEL payloads between
co-located nodes (nodes in one process whose learners live on slices of
one accelerator fabric) through a device-to-device shard transfer
(:mod:`p2pfl_tpu.parallel.ici_plane`) instead of the byte codec: each
device copies its parameter block directly to the matching device of the
peer's slice — a ``lax.ppermute`` collective everywhere, a Pallas remote
DMA on TPU — composing with the shard-resident top-k/int8 codec
(:mod:`p2pfl_tpu.ops.compression`) so the encode→transfer→decode→merge
chain is end to end on device and ZERO model-plane bytes cross D2H.

What deliberately does NOT change:

- **The control plane.** Votes, coverage announcements, beats, TTL floods
  keep riding the existing transport untouched — the ICI plane carries
  only :class:`~p2pfl_tpu.communication.message.WeightsEnvelope` payloads.
- **The ``_do_send`` seam.** The plane plugs in INSIDE the transport's
  ``_send_to_neighbor``, i.e. *behind* the protocol's send span and the
  fault-injection continuation — FaultPlan drop/delay/duplicate/partition
  verdicts, circuit-breaker feeds, retries and telemetry spans wrap an
  ICI transfer exactly as they wrap a byte send. A chaos plan cannot tell
  the difference; that is the point.
- **Failure semantics.** A peer that is not eligible — unregistered,
  another process, mismatched architecture or slice topology, anchor from
  a different round — falls back LOUDLY to the byte path *for that peer
  only* (``ici_fallback_bytes`` metric, one log line per (peer, reason)),
  never aborting the round. A dead peer fails the send exactly like the
  byte path would, so eviction and repair machinery see the same signals.

Delivery places the payload under the RECEIVER's own shardings before
handing it to ``handle_weights``, so
:func:`~p2pfl_tpu.ops.tree.tree_align_devices` is an asserted no-op
downstream: the plane checks the align copy counter after every transfer
and self-heals (with a loud ``ici_align_violation`` metric) if a leaf
ever lands misplaced.

This module is inside the ``no-host-gather`` analyzer scope
(:mod:`p2pfl_tpu.analysis`): no ``np.asarray``/``jax.device_get``/
``.tobytes()`` may appear here — the zero-host-bytes contract is
statically enforced.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from p2pfl_tpu.communication.message import WeightsEnvelope
from p2pfl_tpu.learning.weights import ModelUpdate, named_leaves
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.management.telemetry import telemetry
from p2pfl_tpu.parallel.ici_plane import (
    SliceInfo,
    conform_specs,
    replicate_on_slice,
    same_devices,
    shard_transfer,
    slice_info_of,
    tree_device_bytes,
)

Pytree = Any

# ---- process-wide accounting (bench/tests read these) ----

_stats_lock = threading.Lock()
_stats = {
    "shard_sends": 0,       # payloads delivered over the ICI plane
    "bytes_moved": 0,       # device bytes that crossed the interconnect
                            # (co-resident zero-copy handoffs count 0)
    "fallback_bytes": 0,    # sends that fell back to the byte path
    "align_violations": 0,  # delivered leaves that needed re-placement
    #: source-side re-layouts (device_put within the sender's slice)
    #: before a transfer — a producing program (aggregation fold) left a
    #: leaf in a different layout than the receiver's placement; still
    #: all device-to-device, never host
    "conform_copies": 0,
}


def ici_stats() -> dict:
    with _stats_lock:
        return dict(_stats)


def reset_ici_stats() -> None:
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0


def _count(key: str, n: int = 1) -> None:
    with _stats_lock:
        _stats[key] += n


class IciEndpoint:
    """One node's presence on the shard plane.

    Holds a weak reference to the node (the registry must never keep a
    stopped node alive), the node's slot on the global mesh's nodes axis
    when known (``slice_index`` — rides the ``sp`` handshake), and a
    cache of receiver-side zero filler buffers for codec payloads (the
    pair-transfer needs structurally-matching blocks on the destination
    slice; zeros are uploaded once per payload shape, then reused every
    round).
    """

    def __init__(self, node, slice_index: int = -1) -> None:
        self._node_ref = weakref.ref(node)
        self.slice_index = slice_index
        self._filler_lock = threading.Lock()
        self._fillers: dict = {}

    def node(self):
        return self._node_ref()

    @property
    def learner(self):
        node = self.node()
        return None if node is None else node.learner

    def slice_info(self) -> Optional[SliceInfo]:
        learner = self.learner
        if learner is None:
            return None
        try:
            return slice_info_of(learner.get_parameters())
        except Exception:  # noqa: BLE001 — learner mid-teardown
            return None

    def handshake(self, codec: str) -> Optional[Tuple]:
        """The ``sp`` wire-header triple (slice_shape, slice_index, codec)."""
        info = self.slice_info()
        if info is None:
            return None
        return (info.shape, self.slice_index, codec)

    def filler(self, name: str, leaf, info: SliceInfo):
        """A zero buffer shaped like ``leaf``, resident replicated on this
        endpoint's slice — cached per (name, shape, dtype, slice)."""
        key = (
            name,
            tuple(leaf.shape),
            str(leaf.dtype),
            tuple(sorted(info.device_ids)),
        )
        with self._filler_lock:
            buf = self._fillers.get(key)
        if buf is not None:
            return buf
        buf = jax.device_put(
            jnp.zeros(tuple(leaf.shape), leaf.dtype), NamedSharding(info.mesh, P())
        )
        with self._filler_lock:
            self._fillers[key] = buf
        return buf


class ShardPlaneRegistry:
    """Process-global address → :class:`IciEndpoint` map.

    The shard plane is in-process by construction (live ``jax.Array``
    shards cannot cross process boundaries); a peer absent from this
    registry is simply not co-located and its sends ride the byte path.
    """

    _lock = threading.Lock()
    _endpoints: dict[str, IciEndpoint] = {}
    #: (src, dst, reason) triples already logged — fallback is per-send
    #: metric-counted but only narrated once per edge per reason
    _warned: set = set()

    @classmethod
    def register(cls, addr: str, endpoint: IciEndpoint) -> None:
        with cls._lock:
            cls._endpoints[addr] = endpoint

    @classmethod
    def unregister(cls, addr: str) -> None:
        with cls._lock:
            cls._endpoints.pop(addr, None)

    @classmethod
    def get(cls, addr: str) -> Optional[IciEndpoint]:
        with cls._lock:
            return cls._endpoints.get(addr)

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._endpoints.clear()
            cls._warned.clear()

    @classmethod
    def warn_once(cls, src: str, dst: str, reason: str) -> bool:
        key = (src, dst, reason)
        with cls._lock:
            if key in cls._warned:
                return False
            cls._warned.add(key)
            return True


def stamp_handshake(addr: str, update: ModelUpdate) -> None:
    """Stamp the optional ``sp`` wire header on an outgoing update.

    Called by ``protocol.build_weights`` when the ICI plane is on: even
    frames that end up on the BYTE path (non-colocated peers) advertise
    the sender's slice topology, which is what lets a mixed fleet
    diagnose per-peer plane selection from the wire alone.
    """
    from p2pfl_tpu.settings import Settings

    if Settings.WEIGHTS_PLANE not in ("ici", "dcn") or update.sp is not None:
        return
    ep = ShardPlaneRegistry.get(addr)
    if ep is None:
        return
    update.sp = ep.handshake(Settings.WIRE_COMPRESSION)


def _fallback(src: str, nei: str, reason: str) -> None:
    """Per-peer loud degradation to the byte path (never aborts)."""
    _count("fallback_bytes")
    logger.log_comm_metric(src, "ici_fallback_bytes")
    if ShardPlaneRegistry.warn_once(src, nei, reason):
        logger.info(
            src,
            f"ICI weights plane ineligible for {nei} ({reason}) — "
            "falling back to the byte path for this peer",
        )
    telemetry.event(
        src, "ici_fallback", kind="gossip", attrs={"peer": nei, "reason": reason}
    )


def _leaf_meta_matches(a: Pytree, b: Pytree) -> bool:
    return all(
        tuple(x.shape) == tuple(y.shape) and x.dtype == y.dtype
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def _named_dict(tree: Pytree) -> dict:
    """Canonical path → leaf, leaves kept device-resident."""
    return dict(named_leaves(tree)[1])


def _restore_named(template: Pytree, flat: dict) -> Pytree:
    """Rebuild ``template``'s structure from a path → device-leaf dict
    (the shard plane's host-free twin of ``weights.restore_like`` — no
    casts, no host materialization; shapes/dtypes were checked upfront)."""
    from p2pfl_tpu.learning.weights import _SEP, _path_part

    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, _leaf in leaves_with_path:
        key = _SEP.join(_path_part(p) for p in path)
        new_leaves.append(flat[key])
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _move_codec(
    update: ModelUpdate,
    src_params: Pytree,
    template: Pytree,
    src_info: SliceInfo,
    dst_info: SliceInfo,
    dst_ep: IciEndpoint,
    dst_learner,
    mode: str,
    backend: str,
) -> Optional[Tuple[Pytree, int]]:
    """topk8/int8 composition: device encode → shard transfer → device
    decode against the receiver's anchor. Returns ``(params, bytes)`` or
    ``None`` when this peer must fall back (anchor round mismatch — the
    byte path then reproduces the exact AnchorMismatch skip semantics)."""
    from p2pfl_tpu.ops.compression import (
        build_topk_plan,
        decode_shard_device,
        encode_shard_device,
    )
    from p2pfl_tpu.settings import Settings

    named = _named_dict(src_params)
    anchor_named = _named_dict(update.anchor) if update.anchor is not None else None
    topk_frac = Settings.TOPK_FRACTION if mode == "topk8" else 0.0
    topk_plan = build_topk_plan(named, anchor_named, topk_frac)
    if topk_plan:
        # delta segments reconstruct against the RECEIVER's anchor — both
        # ends must hold the same round's (anchor divergence is part of
        # the codec's loss budget, exactly like the byte decoder)
        dst_anchor = getattr(dst_learner, "_wire_anchor", None)
        dst_tag = getattr(dst_learner, "_wire_anchor_tag", None)
        if dst_anchor is None or dst_tag != update.anchor_tag:
            return None
        dst_anchor_named = _named_dict(dst_anchor)
    else:
        dst_anchor_named = None

    # encode ONCE per payload content: repeat sends of the same update
    # (many candidates, many ticks) reuse the device buffers, and the
    # error-feedback residual folds exactly once PER CONTENT ACROSS
    # PLANES — the ICI and byte encodes cache under different keys, so
    # fold ownership is coordinated through PayloadCache.ef_fold_once
    # (whichever plane encodes first folds; the other goes residual-free
    # instead of re-applying the just-written carry)
    with update._encode_lock:
        cache = update.payload_cache
        # same knob as the byte path: GOSSIP_PAYLOAD_CACHE=False means
        # every send re-encodes (the benchable baseline), on BOTH planes
        use_cache = Settings.GOSSIP_PAYLOAD_CACHE
        key = None
        cached = None
        if use_cache and cache is not None and update.cache_version is not None:
            key = (
                "ici",
                update.cache_version,
                update.cache_round,
                mode,
                update.anchor_tag,
                update.ef_residual is not None,
            )
            cached = cache.get(key)
        elif use_cache:
            cached = getattr(update, "_ici_payload", None)
        if cached is not None:
            tk_spec, dense_spec, payload = cached
        else:
            residual = update.ef_residual
            if residual is not None and cache is not None and update.cache_version is not None:
                # cross-plane fold ownership — ONE key builder, shared
                # with the byte encoder (ModelUpdate.ef_fold_key)
                if not cache.ef_fold_once(update.ef_fold_key(mode)):
                    residual = None
            tk_spec, dense_spec, payload = encode_shard_device(
                named,
                anchor_named,
                topk_plan,
                residual,
                # optimization_barrier under the SPMD partitioner is a
                # single-device-only workaround (see _encode_jit)
                barrier=len(src_info.device_ids) == 1,
            )
            # deterministic transfer layout: buffers replicated over the
            # sender's slice (D2D within the slice, nothing host-side)
            payload = replicate_on_slice(payload, src_info)
            if key is not None:
                cache.put(key, (tk_spec, dense_spec, payload))
            elif use_cache:
                update._ici_payload = (tk_spec, dense_spec, payload)

    spec_keys = [k for k, _leaf in named_leaves(src_params)[1]]
    src_spec_by_key = dict(zip(spec_keys, src_info.specs))
    coded = {k for k, _s, _b in tk_spec} | {k for k, _s in dense_spec}
    raw_keys = [k for k in sorted(named) if k not in coded]
    template_named = _named_dict(template)

    # one combined transfer tree: codec buffers (replicated) + raw
    # passthrough leaves (their own specs) move in ONE dispatch
    transfer_tree: dict = {f"c/{k}": v for k, v in payload.items()}
    filler: dict = {
        f"c/{k}": dst_ep.filler(k, v, dst_info) for k, v in payload.items()
    }
    for k in raw_keys:
        transfer_tree[f"r/{k}"] = named[k]
        filler[f"r/{k}"] = template_named[k]
    spec_of = {
        **{f"c/{k}": P() for k in payload},
        **{f"r/{k}": src_spec_by_key[k] for k in raw_keys},
    }
    ordered_specs = tuple(spec_of[k] for k in sorted(transfer_tree))
    if same_devices(src_info, dst_info):
        # co-resident: the buffers are already on the receiver's devices
        # — zero interconnect bytes, honestly counted as such
        moved = 0
        landed = transfer_tree
    else:
        moved = tree_device_bytes(transfer_tree)
        landed = shard_transfer(
            transfer_tree,
            filler,
            SliceInfo(src_info.mesh, ordered_specs),
            SliceInfo(dst_info.mesh, ordered_specs),
            backend,
        )
    payload_dst = {k[2:]: v for k, v in landed.items() if k.startswith("c/")}
    out_named = decode_shard_device(
        payload_dst, tk_spec, dense_spec, dst_anchor_named, template_named
    )
    for k in raw_keys:
        out_named[k] = landed[f"r/{k}"]
    restored = _restore_named(template, out_named)
    # the decode jit's output layout is XLA-chosen: on a multi-device
    # slice it can differ from the receiver's placement — normalize HERE
    # (device_put within the receiver's slice, counted as conform, never
    # host) so delivery always lands receiver-ready
    from p2pfl_tpu.ops.tree import tree_align_copy_count, tree_align_devices

    before = tree_align_copy_count()
    restored = tree_align_devices(restored, template)
    moved_leaves = tree_align_copy_count() - before
    if moved_leaves:
        _count("conform_copies", moved_leaves)
    return restored, moved


def try_shard_send(proto, nei: str, env) -> Optional[bool]:
    """Attempt an ICI shard delivery for one outgoing envelope.

    Returns ``True``/``False`` when the plane handled the send (the
    transport's byte path must NOT run), or ``None`` when this envelope/
    peer is not eligible and the caller should proceed down its normal
    byte path. Called from inside ``_send_to_neighbor`` so every wrapper
    at the ``_do_send`` seam (fault injector, send spans, breaker feeds,
    retries) applies unchanged.
    """
    from p2pfl_tpu.settings import Settings, ici_backend

    if Settings.WEIGHTS_PLANE not in ("ici", "dcn") or not isinstance(
        env, WeightsEnvelope
    ):
        return None
    update = env.update
    if update.params is None:
        return None  # pre-encoded frame (relay) — bytes it is
    src = proto.get_address()
    src_ep = ShardPlaneRegistry.get(src)
    dst_ep = ShardPlaneRegistry.get(nei)
    if src_ep is None or dst_ep is None:
        if Settings.WEIGHTS_PLANE == "dcn":
            # cross-process peers are never on this process's registry —
            # the DCN plane runs next in the ladder and does its own
            # (loud) eligibility accounting; stay silent here
            return None
        _fallback(src, nei, "peer_not_on_shard_plane")
        return None
    dst_node = dst_ep.node()
    if dst_node is None or not getattr(dst_node, "_running", False):
        # dead peer: let the byte path fail the send so breakers/eviction
        # see exactly the signals they are built for
        return None
    dst_learner = dst_ep.learner
    if dst_learner is None:
        _fallback(src, nei, "peer_has_no_learner")
        return None
    try:
        template = dst_learner.get_parameters()
    except Exception:  # noqa: BLE001 — learner mid-teardown
        return None
    if jax.tree.structure(template) != jax.tree.structure(update.params):
        _fallback(src, nei, "architecture_mismatch")
        return None
    if not _leaf_meta_matches(update.params, template):
        _fallback(src, nei, "shape_dtype_mismatch")
        return None
    src_info = slice_info_of(update.params)
    dst_info = slice_info_of(template)
    if src_info is None or dst_info is None:
        _fallback(src, nei, "params_not_device_resident")
        return None
    if (
        src_info.shape != dst_info.shape
        or src_info.mesh.axis_names != dst_info.mesh.axis_names
    ):
        _fallback(src, nei, "slice_topology_mismatch")
        return None
    co_resident = src_info.device_ids == dst_info.device_ids
    if not co_resident and (src_info.device_ids & dst_info.device_ids):
        _fallback(src, nei, "slices_overlap")
        return None

    src_params = update.params
    if src_info.specs != dst_info.specs:
        # the producing program (an aggregation fold's XLA-chosen output
        # layout) left leaves laid out differently than the receiver's
        # placement: conform at the SOURCE — device_put within the
        # sender's own devices, still zero host — so the transfer lands
        # every block exactly where the receiver's jits expect it.
        # Cached per update instance: repeat sends of one payload (many
        # candidates, many ticks) re-lay out once.
        with update._encode_lock:
            cached = getattr(update, "_ici_conformed", None)
            if cached is not None and cached[0] == dst_info.specs:
                src_params = cached[1]
            else:
                target_mesh = dst_info.mesh if co_resident else src_info.mesh
                src_params, n_moved = conform_specs(
                    update.params, target_mesh, dst_info.specs
                )
                update._ici_conformed = (dst_info.specs, src_params)
                if n_moved:
                    _count("conform_copies", n_moved)
                    logger.log_comm_metric(src, "ici_conform_copies", n_moved)
        src_info = SliceInfo(
            dst_info.mesh if co_resident else src_info.mesh, dst_info.specs
        )

    mode = Settings.WIRE_COMPRESSION
    backend = ici_backend()
    try:
        if mode in ("int8", "topk8"):
            out = _move_codec(
                update, src_params, template, src_info, dst_info, dst_ep,
                dst_learner, mode, backend,
            )
            if out is None:
                _fallback(src, nei, "anchor_round_mismatch")
                return None
            params, moved = out
        else:
            if co_resident:
                # co-resident slices: the shards are already exactly where
                # the receiver wants them — a zero-copy handoff (the same
                # read-only contract as the in-memory reference path), so
                # zero interconnect bytes are counted
                moved = 0
                params = src_params
            else:
                moved = tree_device_bytes(src_params)
                params = shard_transfer(
                    src_params, template, src_info, dst_info, backend
                )
    except Exception as exc:  # noqa: BLE001 — a failed transfer is a failed send
        logger.error(src, f"ICI shard transfer to {nei} failed: {exc!r}")
        return False

    delivered = ModelUpdate(
        params,
        list(update.contributors),
        update.num_samples,
        version=update.version,
        xp=update.xp or env.xp,
        sp=src_ep.handshake(mode),
    )
    # the receiver re-encodes relays/diffusions against ITS OWN anchor,
    # exactly like the byte path's materialize()
    delivered.anchor = getattr(dst_learner, "_wire_anchor", None)
    delivered.anchor_tag = getattr(dst_learner, "_wire_anchor_tag", None)

    # the no-fix-up contract, asserted: delivery already matches the
    # receiver's placement, so aligning against it must copy NOTHING
    from p2pfl_tpu.ops.tree import tree_align_copy_count, tree_align_devices

    before = tree_align_copy_count()
    delivered.params = tree_align_devices(delivered.params, template)
    misplaced = tree_align_copy_count() - before
    if misplaced:
        _count("align_violations", misplaced)
        logger.log_comm_metric(src, "ici_align_violation", misplaced)
        logger.error(
            src,
            f"ICI delivery to {nei} needed {misplaced} device fix-up "
            "copies — the shard plane mis-placed a leaf (self-healed)",
        )

    denv = WeightsEnvelope(
        env.source, env.round, env.cmd, delivered, env.msg_id,
        trace_ctx=env.trace_ctx, xp=env.xp,
    )
    try:
        result = dst_node.protocol.handle_weights(denv)
    except Exception:  # noqa: BLE001 — peer died mid-delivery
        return False
    _count("shard_sends")
    _count("bytes_moved", moved)
    logger.log_comm_metric(src, "ici_send_shard")
    logger.log_comm_metric(src, "ici_bytes_moved", moved)
    telemetry.event(
        src,
        "ici_transfer",
        kind="gossip",
        attrs={"peer": nei, "backend": backend, "codec": mode, "bytes": moved},
    )
    return bool(result.ok)
