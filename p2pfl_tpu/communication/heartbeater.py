"""Heartbeat membership / failure detection.

Reference semantics (``p2pfl/communication/heartbeater.py:33-111``): a daemon
thread broadcasts a ``beat`` control message every ``HEARTBEAT_PERIOD``
seconds; every second tick it evicts neighbors whose last beat is older than
``HEARTBEAT_TIMEOUT``. Because ``beat`` TTL-floods the overlay, every node
discovers every other node as a *non-direct* neighbor within roughly one
heartbeat period (reference ``grpc_neighbors.py:34-55``).

Three hardenings over the reference:

- **Origin-time validation**: beats carry the origin's wall clock, and a
  beat whose origin stamp is older than ``HEARTBEAT_TIMEOUT`` is rejected
  instead of refreshing ``last_beat`` with *local* time — a TTL-flooded
  beat relayed (or fault-injected) after its origin died must not keep a
  dead node "live" indefinitely.
- **Suspect fast path**: every tick, neighbors the protocol's circuit
  breaker marks suspect (consecutive send failures) are evicted after
  only ``Settings.BREAKER_SUSPECT_TIMEOUT`` of beat silence — send-path
  evidence accelerates detection instead of waiting out the full binary
  timeout.
- **One-way-partition eviction**: a neighbor whose breaker has been open
  for a full ``HEARTBEAT_TIMEOUT`` — not one successful send in all that
  time — is evicted even though its beats still arrive. Liveness without
  reachability is useless to the overlay, and inbound beats would
  otherwise keep the unreachable peer a member forever. (The reference
  evicted on the FIRST failed send, losing the message with it.)
"""

from __future__ import annotations

import threading
import time
from typing import TYPE_CHECKING

from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.settings import Settings

if TYPE_CHECKING:
    from p2pfl_tpu.communication.protocol import CommunicationProtocol

BEAT_CMD = "beat"


class Heartbeater:
    def __init__(self, self_addr: str, protocol: "CommunicationProtocol") -> None:
        self.self_addr = self_addr
        self._protocol = protocol
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"heartbeater-{self.self_addr}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def beat(self, source: str, t: float) -> None:
        """Record an incoming beat (called by the ``beat`` command handler).

        ``t`` is the ORIGIN's wall clock (``time.time()`` stamped into the
        beat args by the sender). A beat relayed long after its origin
        stamped it says nothing about the origin being alive NOW — without
        this check a TTL-flooded beat redelivered after the origin died
        still refreshed ``last_beat`` with local monotonic time and kept a
        dead node in the membership forever. ``t <= 0`` means "no origin
        info" (older senders / tests) and is accepted for compatibility.

        Cross-host caveat: the check compares wall clocks, so peers whose
        clocks disagree by more than ``HEARTBEAT_TIMEOUT`` would reject
        each other's beats; keep clocks within a few seconds (NTP) or
        raise the timeout on skew-prone deployments.
        """
        if t > 0 and time.time() - t > Settings.HEARTBEAT_TIMEOUT:
            logger.log_comm_metric(self.self_addr, "stale_beat_rejected")
            logger.debug(
                self.self_addr,
                f"Rejecting stale beat from {source}: origin stamp "
                f"{time.time() - t:.1f}s old (> HEARTBEAT_TIMEOUT)",
            )
            return
        self._protocol.neighbors.heartbeat(source, t=None)

    def _run(self) -> None:
        tick = 0
        while not self._stop.is_set():
            msg = self._protocol.build_msg(BEAT_CMD, [str(time.time())])
            self._protocol.broadcast(msg)
            tick += 1
            if tick % 2 == 0:
                self._protocol.neighbors.evict_stale(Settings.HEARTBEAT_TIMEOUT)
            # breaker fast path: suspects go on a shorter silence clock
            suspects = self._protocol.breaker.suspects()
            if suspects:
                evicted = self._protocol.neighbors.evict_stale(
                    Settings.BREAKER_SUSPECT_TIMEOUT, only=suspects
                )
                if evicted:
                    logger.log_comm_metric(
                        self.self_addr, "breaker_suspect_evict", len(evicted)
                    )
                # one-way partition: a neighbor we have not managed ONE
                # successful send to for a full HEARTBEAT_TIMEOUT is
                # evicted even though its beats still arrive — it is alive
                # but unreachable, useless as a gossip target (and its
                # inbound beats would otherwise keep it "live" forever).
                # The freshness bound demands the failures be ONGOING:
                # a breaker left open because the peer fell out of every
                # send path (stale evidence, no attempts at all) must not
                # evict a live neighbor — beats to direct neighbors go out
                # every HEARTBEAT_PERIOD, so a real partition keeps its
                # evidence fresher than two periods for free
                for addr in self._protocol.breaker.suspects_older_than(
                    Settings.HEARTBEAT_TIMEOUT,
                    fresh_within=2 * Settings.HEARTBEAT_PERIOD,
                ):
                    if self._protocol.neighbors.get(addr) is None:
                        continue
                    logger.info(
                        self.self_addr,
                        f"Evicting {addr}: breaker open for a full "
                        "HEARTBEAT_TIMEOUT (unreachable despite beats)",
                    )
                    logger.log_comm_metric(self.self_addr, "breaker_unreachable_evict")
                    self._protocol.neighbors.evict(addr, quarantine=True)
            if self._stop.wait(timeout=Settings.HEARTBEAT_PERIOD):
                return
