"""The transport seam: ``CommunicationProtocol``.

Same 12-operation surface as the reference ABC
(``p2pfl/communication/communication_protocol.py:27-190``), so transports are
interchangeable per node. Unlike the reference — where the gRPC and memory
protocol classes duplicate their wiring byte-for-byte
(``memory_communication_protocol.py:47-66``) — the shared wiring (gossiper,
heartbeater, command registry, dispatch with TTL re-gossip and dedup) lives
here once, and concrete transports only provide a server, a client and a
neighbors manager.
"""

from __future__ import annotations

import contextlib
import random
import threading
from abc import ABC, abstractmethod
from typing import Callable, Optional

from p2pfl_tpu.communication.gossiper import Gossiper
from p2pfl_tpu.communication.heartbeater import Heartbeater
from p2pfl_tpu.communication.message import CommandResult, Message, WeightsEnvelope
from p2pfl_tpu.communication.neighbors import Neighbors
from p2pfl_tpu.communication.reliability import CircuitBreaker
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.management.telemetry import telemetry


class CommunicationProtocol(ABC):
    """Base for all transports. Owns gossip, heartbeat, membership, dispatch."""

    def __init__(self, address: str) -> None:
        self._address = address
        self._commands: dict[str, "Command"] = {}  # noqa: F821 — commands registered by Node
        self._terminated = threading.Event()
        #: per-neighbor consecutive-failure detector; every plane's send
        #: outcome feeds it, suspects are evicted early by the heartbeater
        self.breaker = CircuitBreaker(address)
        #: optional chaos seam (communication/faults.py FaultInjector):
        #: when set, every outgoing send routes through it with the real
        #: transport send as the continuation
        self.fault_injector: Optional[Callable] = None
        #: callbacks fired with the address of every heartbeat-evicted
        #: neighbor (Node hooks mid-round train-set repair here)
        self._evict_listeners: list[Callable[[str], None]] = []
        #: current experiment identity (set by the workflows from
        #: NodeState.experiment_xid): stamped as the optional "xp" header
        #: on every outgoing envelope so receivers can filter
        #: cross-experiment stragglers exactly. Deliberately NOT cleared
        #: at experiment end — a tail frame between experiments carrying
        #: the OLD id is precisely what the filter exists to reject.
        self.experiment_xid: Optional[str] = None
        self.neighbors: Neighbors = self._make_neighbors()
        self.neighbors.on_evict = self._neighbor_evicted
        self.gossiper = Gossiper(
            address, send_fn=self._do_send, on_result=self._record_send_outcome
        )
        self.heartbeater = Heartbeater(address, self)

    # ---- transport-specific pieces ----

    @abstractmethod
    def _make_neighbors(self) -> Neighbors:
        ...

    @abstractmethod
    def _server_start(self) -> None:
        ...

    @abstractmethod
    def _server_stop(self) -> None:
        ...

    @abstractmethod
    def _send_to_neighbor(self, nei: str, env, create_connection: bool = False) -> bool:
        """Deliver one envelope to one peer. Returns False on failure."""

    # ---- lifecycle ----

    def start(self) -> None:
        self._terminated.clear()
        self._server_start()
        self.heartbeater.start()
        self.gossiper.start()

    def stop(self) -> None:
        self.heartbeater.stop()
        self.gossiper.stop()
        self._server_stop()
        self.neighbors.clear(disconnect=True)
        self.breaker.reset()
        self._terminated.set()

    def wait_for_termination(self) -> None:
        self._terminated.wait()

    # ---- command registry ----

    def add_command(self, cmd) -> None:
        self._commands[cmd.get_name()] = cmd

    # ---- message construction ----

    def build_msg(self, cmd: str, args: Optional[list[str]] = None, round: int = -1) -> Message:
        from p2pfl_tpu.settings import Settings

        # flight recorder: outgoing envelopes are stamped with the BUILDING
        # thread's trace context (usually a stage span on the learning
        # thread) — the seam where causality is still known; the worker
        # threads that later transmit the envelope have no context of
        # their own, and the same Message object is shared across a whole
        # broadcast, so per-send mutation would race
        return Message(
            self._address,
            cmd,
            tuple(args or ()),
            round,
            ttl=Settings.TTL,
            trace_ctx=telemetry.current_ctx(),
            xp=self.experiment_xid,
        )

    def build_weights(
        self, cmd: str, round: int, update: ModelUpdate
    ) -> WeightsEnvelope:
        # the round completes the payload-cache key (learning/weights.py):
        # byte transports then reuse the encode across candidates and ticks
        # for as long as the learner's model version is unchanged
        update.cache_round = round
        # experiment identity rides both the envelope and the update (the
        # update is what stash filters hold after decode); one update may
        # be shared across a broadcast — identical stamp, benign
        if update.xp is None and self.experiment_xid is not None:
            update.xp = self.experiment_xid
        # shard-plane handshake: when the ICI weights plane is on, every
        # weights frame — including byte-path fallbacks to non-colocated
        # peers — advertises this node's slice topology via the optional
        # "sp" header (communication/ici.py)
        from p2pfl_tpu.communication.ici import stamp_handshake

        stamp_handshake(self._address, update)
        return WeightsEnvelope(
            self._address, round, cmd, update, trace_ctx=telemetry.current_ctx(),
            xp=update.xp or self.experiment_xid,
        )

    # ---- sending ----

    def _do_send(self, nei: str, env, create_connection: bool = False) -> bool:
        """Transport send behind the fault-injection seam — EVERY outgoing
        envelope (both gossip planes, direct sends, broadcasts) passes
        through here, so a chaos plan sees all of them — and behind the
        flight recorder's send span: one ``send:<cmd>`` span per attempt,
        parented to the envelope's wire trace context, with the outcome
        and peer in its attrs (the RoundReport's edge attribution reads
        exactly these). Beats are span-exempt by default
        (``Settings.TELEMETRY_BEAT_SPANS``) — they flood at
        1/HEARTBEAT_PERIOD per neighbor and would drown the ring."""
        from p2pfl_tpu.settings import Settings

        cmd = getattr(env, "cmd", "?")
        if not telemetry.enabled() or (
            cmd == "beat" and not Settings.TELEMETRY_BEAT_SPANS
        ):
            return self._transport_send(nei, env, create_connection)
        is_weights = isinstance(env, WeightsEnvelope)
        with telemetry.span(
            self._address,
            f"send:{cmd}",
            kind="heartbeat" if cmd == "beat" else "gossip",
            parent=getattr(env, "trace_ctx", None),
            attrs={"peer": nei, "plane": "weights" if is_weights else "control"},
        ) as sp:
            ok = self._transport_send(nei, env, create_connection)
            if sp is not None:
                sp.attrs["ok"] = bool(ok)
        return ok

    def _transport_send(self, nei: str, env, create_connection: bool) -> bool:
        fi = self.fault_injector
        if fi is not None:
            return fi(nei, env, create_connection, self._send_to_neighbor)
        return self._send_to_neighbor(nei, env, create_connection=create_connection)

    def send(self, nei: str, env, create_connection: bool = False) -> bool:
        ok = self._do_send(nei, env, create_connection=create_connection)
        if not create_connection:
            self._record_send_outcome(nei, ok)
            if not ok and isinstance(env, Message):
                # counted separately from the gossiper's gossip_send_fail:
                # direct sends (command broadcasts, coverage re-announcements)
                # fail outside the dispatch path — without this metric a
                # retry scheduled here has no matching failure counter and
                # the chaos suite's "retries are 1:1-backed by failures"
                # budget would be unsound (e.g. sends to a crashed peer in
                # the window before its eviction)
                logger.log_comm_metric(self._address, "send_fail_direct")
                # The reference evicts a neighbor on ANY send failure
                # (grpc_client.py:173-179) — and the message is simply gone.
                # One transient failure is not death: the message is retried
                # with backoff on the gossip thread (schedule_retry exempts
                # beats), while the breaker's consecutive-failure count
                # decides suspicion and the heartbeater owns the
                # (accelerated) eviction.
                self.gossiper.schedule_retry(nei, env, attempt=1)
        return ok

    def broadcast(self, env, exclude: tuple[str, ...] = ()) -> None:
        for nei in self.neighbors.get_all(only_direct=True):
            if nei not in exclude:
                self.send(nei, env)

    def _record_send_outcome(self, nei: str, ok: bool) -> None:
        """Feed the breaker — but never for failures to NON-members: an
        in-flight backoff retry to an already-evicted neighbor would
        otherwise repopulate the state ``forget()`` just cleared, leaving a
        permanent suspect entry no eviction sweep ever forgets (the sweeps
        only touch current members)."""
        if ok or self.neighbors.get(nei) is not None:
            self.breaker.record(nei, ok)

    # ---- eviction notifications ----

    def add_evict_listener(self, fn: Callable[[str], None]) -> None:
        self._evict_listeners.append(fn)

    def _neighbor_evicted(self, addr: str) -> None:
        logger.log_comm_metric(self._address, "neighbor_evicted")
        # eviction transition on the flight-recorder timeline: every
        # eviction path (stale beats, breaker suspect fast path, one-way
        # partition) funnels through here
        telemetry.event(
            self._address, "neighbor_evicted", kind="fault", attrs={"peer": addr}
        )
        self.breaker.forget(addr)
        for fn in self._evict_listeners:
            try:
                fn(addr)
            except Exception as exc:  # noqa: BLE001 — listeners must not kill the heartbeater
                logger.error(self._address, f"Evict listener failed for {addr}: {exc!r}")

    # ---- membership ----

    def connect(self, addr: str, non_direct: bool = False) -> bool:
        return self.neighbors.add(addr, non_direct=non_direct)

    def disconnect(self, addr: str, disconnect_msg: bool = True) -> None:
        self.breaker.forget(addr)  # deliberate disconnect is not a failure
        self.neighbors.remove(addr, disconnect_msg=disconnect_msg)

    def get_neighbors(self, only_direct: bool = False) -> dict:
        return self.neighbors.get_all(only_direct)

    def get_address(self) -> str:
        return self._address

    # ---- model-plane gossip (synchronous loop used by stages) ----

    def gossip_weights(
        self,
        early_stopping_fn: Callable[[], bool],
        get_candidates_fn: Callable[[], list[str]],
        status_fn: Callable[[], object],
        model_fn: Callable[[str], Optional[tuple]],
        period: Optional[float] = None,
        create_connection: bool = False,
    ) -> None:
        self.gossiper.gossip_weights(
            early_stopping_fn,
            get_candidates_fn,
            status_fn,
            model_fn,
            period=period,
            create_connection=create_connection,
        )

    # ---- receive path (called by transport servers) ----

    def handle_message(self, msg: Message) -> CommandResult:
        """Control-plane receive: dedup → TTL re-gossip → dispatch.

        Mirrors ``grpc_server.py:130-166``.
        """
        if not self.gossiper.check_and_set_processed(msg.msg_id):
            return CommandResult(ok=True)  # duplicate — already handled
        if msg.ttl > 1:
            # the relay keeps the ORIGIN's trace context: every hop of a
            # TTL flood stays one causal tree rooted at the first sender
            relay = Message(
                msg.source, msg.cmd, msg.args, msg.round, msg.ttl - 1, msg.msg_id,
                trace_ctx=msg.trace_ctx, xp=msg.xp,
            )
            pending = [n for n in self.neighbors.get_all(only_direct=True) if n != msg.source]
            self.gossiper.add_message(relay, pending)
        return self._dispatch(
            msg.cmd, msg.source, msg.round, list(msg.args), None,
            trace_ctx=msg.trace_ctx, xp=msg.xp,
        )

    def handle_weights(self, env: WeightsEnvelope) -> CommandResult:
        """Data-plane receive: direct dispatch, no TTL/dedup (``grpc_server.py:168-197``)."""
        return self._dispatch(
            env.cmd, env.source, env.round, [], env.update,
            trace_ctx=env.trace_ctx, xp=env.xp or env.update.xp,
        )

    def handle_weights_stream(self, env: WeightsEnvelope, chunks) -> CommandResult:
        """Streaming data-plane receive: feed ``P2TC`` chunks into an
        incremental decoder, then dispatch exactly like :meth:`handle_weights`.

        ``env`` is the stream's header envelope (metadata only, payload-free);
        ``chunks`` iterates framed chunk bytes as they arrive off the wire (or
        out of the memory transport's bounded queue). Dense leaves are decoded
        — and ``device_put`` when a non-CPU backend is present — the moment
        each one's bytes complete, so the unary frame never materializes on
        this side and peak payload memory stays O(chunk window). Any mid-
        stream violation (per-chunk CRC, ordering, truncation, total CRC)
        drops the WHOLE transfer as one failed receive — the sender's
        ``_do_send`` sees one failed send, so breakers, retries, FaultPlan
        verdicts and spans attribute a streamed edge exactly like a unary one.
        """
        from p2pfl_tpu.settings import Settings

        if not Settings.WIRE_STREAM_ENABLED:
            # structured rejection: the sender's fallback taxonomy matches
            # this exact error string and retries the transfer as unary
            return CommandResult(ok=False, error="stream-unsupported")
        import jax

        from p2pfl_tpu.learning.weights import StreamDecoder

        dec = StreamDecoder(device_put=jax.default_backend() != "cpu")
        try:
            for frame in chunks:
                dec.feed(frame)
            if not dec.complete:
                raise ValueError("stream ended before its end chunk")
            if dec.reassembled:
                # delta-coded (tk8) stream: the byte-identical unary frame,
                # decoded later by materialize against the learner's anchor
                env.update.encoded = dec.result_payload()
            else:
                env.update.decoded_flat = dec.result_flat()
                env.update.encoded = None
        except Exception as exc:  # noqa: BLE001 — one bad chunk = one failed transfer
            logger.log_comm_metric(self._address, "stream_recv_drop")
            logger.error(
                self._address, f"Dropping weights stream from {env.source}: {exc}"
            )
            return CommandResult(ok=False, error=f"stream aborted: {exc}")
        logger.log_comm_metric(self._address, "stream_recv")
        logger.log_comm_metric(self._address, "stream_recv_chunks", dec.chunks)
        return self.handle_weights(env)

    def _dispatch(
        self,
        cmd: str,
        source: str,
        round: int,
        args: list[str],
        update: Optional[ModelUpdate],
        trace_ctx: Optional[tuple[str, str]] = None,
        xp: Optional[str] = None,
    ) -> CommandResult:
        from p2pfl_tpu.settings import Settings

        if cmd != "beat" or not Settings.EXCLUDE_BEAT_LOGS:
            # beat floods at 1/HEARTBEAT_PERIOD per neighbor — excluded from
            # logs by default, same knob as the reference
            logger.debug(self._address, f"Received '{cmd}' from {source}")
        handler = self._commands.get(cmd)
        if handler is None:
            logger.error(self._address, f"Unknown command '{cmd}' from {source}")
            return CommandResult(ok=False, error=f"unknown command {cmd}")
        # the receiver's half of the wire-propagated causal edge: a
        # recv:<cmd> span parented to the SENDER's span via trace_ctx, so
        # the round's tree crosses nodes; beats span-exempt as on send
        if cmd != "beat" or Settings.TELEMETRY_BEAT_SPANS:
            span_cm = telemetry.span(
                self._address,
                f"recv:{cmd}",
                kind="heartbeat" if cmd == "beat" else "gossip",
                parent=trace_ctx,
                attrs={"src": source, "round": round},
            )
        else:
            span_cm = contextlib.nullcontext()
        try:
            with span_cm:
                # xp: the frame's experiment identity (optional — None on
                # old/sync frames); commands that gate on experiment
                # boundaries read it from kwargs
                if update is not None:
                    handler.execute(source, round, update=update, xp=xp)
                else:
                    handler.execute(source, round, *args, xp=xp)
            return CommandResult(ok=True)
        except Exception as exc:  # noqa: BLE001 — commands must not kill the server thread
            logger.error(self._address, f"Error executing {cmd} from {source}: {exc!r}")
            return CommandResult(ok=False, error=str(exc))


def random_subset(items: list[str], k: int) -> list[str]:
    """k random picks without replacement (gossip target selection)."""
    if len(items) <= k:
        return list(items)
    return random.sample(items, k)
