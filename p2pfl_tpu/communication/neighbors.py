"""Thread-safe neighbor registry.

Semantics from the reference's ``p2pfl/communication/neighbors.py:27-170``:
a map addr → :class:`NeighborInfo`; *direct* neighbors were connected
explicitly (transport connection + handshake), *non-direct* neighbors are
learned from TTL-flooded heartbeats and can only be reached by creating an
ad-hoc connection.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.settings import Settings


@dataclass
class NeighborInfo:
    direct: bool
    conn: Any = None  # transport-specific handle (channel/stub/server ref)
    last_beat: float = field(default_factory=time.monotonic)


class Neighbors:
    """Base neighbors manager. Transports override the connect/disconnect hooks."""

    def __init__(self, self_addr: str) -> None:
        self.self_addr = self_addr
        self._lock = threading.Lock()
        self._neis: dict[str, NeighborInfo] = {}
        #: addr → monotonic deadline: peers evicted DESPITE arriving beats
        #: (one-way partition: alive but unreachable) are quarantined for a
        #: HEARTBEAT_TIMEOUT so the very next beat cannot immediately
        #: re-add them — without this, evict/re-add flaps once per beat
        #: period and the unreachable-eviction guarantee is hollow.
        #: Silence-based evictions do NOT quarantine: a briefly-paused node
        #: that resumes beating should rejoin on its next beat, not sit out
        #: an extra timeout. A deliberate direct connect overrides the
        #: quarantine.
        self._quarantine: dict[str, float] = {}
        #: fired with each heartbeat-evicted address (NOT on deliberate
        #: removes) — the protocol fans it out to its evict listeners
        #: (mid-round train-set repair, breaker cleanup)
        self.on_evict: Optional[Any] = None

    # ---- transport hooks ----

    def _connect(self, addr: str, handshake: bool) -> Optional[Any]:
        """Open a transport connection; return the handle or raise. Base: none."""
        return None

    def _disconnect(self, addr: str, conn: Any, notify: bool) -> None:
        """Close a transport connection (best-effort)."""

    # ---- registry ----

    def add(self, addr: str, non_direct: bool = False, handshake: bool = True) -> bool:
        """Register a neighbor. Direct adds open a connection + handshake.

        Re-adding an already-direct neighbor is a no-op; a heartbeat from a
        direct neighbor must NOT demote it to non-direct
        (reference ``neighbors.py:73-110``).
        """
        if addr == self.self_addr:
            return False
        with self._lock:
            if non_direct:
                if self._quarantined_locked(addr):
                    return False
            # a DELIBERATE direct connect overrides quarantine — but only
            # once it SUCCEEDS (pop below, after _connect): popping here
            # would let a failed connect attempt clear the entry, and the
            # unreachable peer's very next beat would re-admit it — the
            # exact evict/re-add flap quarantine exists to prevent
            existing = self._neis.get(addr)
            if existing is not None:
                if non_direct:
                    existing.last_beat = time.monotonic()
                    return True
                if existing.direct:
                    logger.debug(self.self_addr, f"Already connected to {addr}")
                    return False
                # upgrade non-direct → direct below (outside dict mutation)
        if non_direct:
            with self._lock:
                if addr not in self._neis:
                    self._neis[addr] = NeighborInfo(direct=False)
            return True
        try:
            conn = self._connect(addr, handshake)
        except Exception as exc:  # noqa: BLE001 — connection errors are expected
            logger.info(self.self_addr, f"Cannot connect to {addr}: {exc}")
            return False
        with self._lock:
            self._quarantine.pop(addr, None)
            self._neis[addr] = NeighborInfo(direct=True, conn=conn)
        return True

    def remove(self, addr: str, disconnect_msg: bool = False) -> None:
        with self._lock:
            info = self._neis.pop(addr, None)
        if info is not None and info.direct:
            try:
                self._disconnect(addr, info.conn, notify=disconnect_msg)
            except Exception:  # noqa: BLE001
                pass

    def _quarantined_locked(self, addr: str) -> bool:
        """Caller holds ``_lock``. Expired entries are dropped lazily."""
        until = self._quarantine.get(addr)
        if until is None:
            return False
        if time.monotonic() >= until:
            del self._quarantine[addr]
            return False
        return True

    def heartbeat(self, addr: str, t: Optional[float] = None) -> None:
        """Record a beat; unknown senders become non-direct neighbors —
        unless quarantined (recently evicted: beats alone must not re-admit
        a peer the overlay just decided it cannot reach)."""
        with self._lock:
            info = self._neis.get(addr)
            if info is None:
                if addr != self.self_addr and not self._quarantined_locked(addr):
                    self._neis[addr] = NeighborInfo(direct=False)
                return
            info.last_beat = time.monotonic() if t is None else t

    def evict_stale(self, timeout: float, only: Optional[set] = None) -> list[str]:
        """Drop neighbors whose last beat is older than ``timeout`` seconds.

        ``only`` restricts the sweep to a subset — the heartbeater uses it
        to evict breaker-suspect neighbors on a shorter clock than the
        full ``HEARTBEAT_TIMEOUT``. Each eviction fires ``on_evict``.
        """
        now = time.monotonic()
        with self._lock:
            stale = [
                a
                for a, i in self._neis.items()
                if now - i.last_beat > timeout and (only is None or a in only)
            ]
        for addr in stale:
            logger.info(self.self_addr, f"Heartbeat timeout — evicting {addr}")
            self.evict(addr)
        return stale

    def evict(self, addr: str, quarantine: bool = False) -> None:
        """Remove ``addr`` and fire ``on_evict`` regardless of last_beat.

        ``quarantine=True`` is the heartbeater's unreachable-despite-beats
        (one-way partition) path: the peer's beats keep arriving, so without
        a quarantine window the next one would re-add it immediately.
        Silence-based evictions leave it False — no beats are arriving, and
        a node that resumes beating should rejoin right away.
        """
        with self._lock:
            if addr not in self._neis:
                return
            if quarantine:
                self._quarantine[addr] = time.monotonic() + Settings.HEARTBEAT_TIMEOUT
        self.remove(addr)
        if self.on_evict is not None:
            try:
                self.on_evict(addr)
            except Exception:  # noqa: BLE001 — observers must not break the sweep
                pass

    def get(self, addr: str) -> Optional[NeighborInfo]:
        with self._lock:
            return self._neis.get(addr)

    def get_all(self, only_direct: bool = False) -> dict[str, NeighborInfo]:
        with self._lock:
            if only_direct:
                return {a: i for a, i in self._neis.items() if i.direct}
            return dict(self._neis)

    def clear(self, disconnect: bool = False) -> None:
        for addr in list(self.get_all(only_direct=True)):
            self.remove(addr, disconnect_msg=disconnect)
        with self._lock:
            self._neis.clear()
            self._quarantine.clear()
