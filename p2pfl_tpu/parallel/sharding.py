"""Partition-rule engine: regex rules over named pytrees → shardings.

Model (and optimizer) state placement is driven by a *rule set*: an
ordered list of ``(path regex, axis spec)`` pairs matched against each
leaf's ``/``-joined tree path, **first match wins** (the fmengine /
EasyLM ``match_partition_rules`` idiom). Axis specs name LOGICAL axes
(``"model"``, ``"data"``, ``"nodes"``) which resolve to the mesh axis
names in :class:`~p2pfl_tpu.settings.Settings` at spec-build time, so a
rule set is mesh-layout-independent.

Contract (enforced by :func:`check_partition_rules` at federation
construction — a typo'd regex fails loudly at startup instead of
silently replicating a 1B-param tensor):

- every non-scalar leaf path is matched by some rule (scalars always
  replicate — there is nothing to shard);
- no dead rules: every rule is the *winning* (first) match for at least
  one path — a rule that never wins is a shadowed or typo'd pattern;
- every named axis in a winning spec exists in the target mesh.

Placement itself stays forgiving on one point only: an axis whose mesh
size does not divide the leaf dimension is dropped (replicated) for that
leaf, because tiny test configs legitimately under-fill big meshes. The
lint reports these as ``indivisible`` so real deployments can treat them
as errors.

The default transformer rule set follows the Megatron pattern:

- attention q/k/v projections: column-parallel (shard the head/output dim),
- attention output projection: row-parallel (shard the input dim),
- MLP gate/up (w1/w3): column-parallel; down (w2): row-parallel,
- embeddings: shard the vocab dim; norms and LoRA adapters replicate
  (adapters are tiny and are the federated payload — keeping them
  replicated makes the FedAvg collective mesh-local),
- MoE expert stacks ``[E, ...]`` shard the expert axis.

XLA inserts the matching all-reduces at the row-parallel boundaries; with
sequence sharded on the same axis (ring attention) activations stay
distributed end to end.

Optimizer state needs no separate rule set: optax state paths embed the
param path (``0/mu/layer_0/attn/wq/kernel``), and rules use ``re.search``,
so the same rules place both — Adam moments shard exactly like the params
they mirror and the step counter replicates as a scalar.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2pfl_tpu.analysis.findings import Finding, Severity
from p2pfl_tpu.settings import Settings

Pytree = Any

# One axis entry: None (replicate this dim), a logical axis name, or a
# tuple of logical axis names (shard one dim over several mesh axes).
AxisSpec = Optional[Any]
PartitionRules = Sequence[tuple[str, Sequence[AxisSpec]]]

# (path regex, spec) — first match wins; paths look like
# "layer_0/attn/wq/kernel". LoRA params replicate (they're the federated
# unit). The trailing catch-all replicates everything else (norm scales,
# biases) — kept explicit so the rule set itself satisfies the "every
# path matched" contract.
DEFAULT_TRANSFORMER_RULES: PartitionRules = (
    (r"lora_", ()),  # replicated
    (r"attn/(wq|wk|wv)/kernel", (None, "model")),  # column-parallel
    (r"attn/wo/kernel", ("model", None)),  # row-parallel
    (r"mlp/(w1|w3)/kernel", (None, "model")),  # column-parallel
    (r"mlp/w2/kernel", ("model", None)),  # row-parallel
    # expert parallelism: MoE expert stacks [E, ...] shard the expert axis;
    # XLA turns the dispatch/combine einsums into token all-to-alls.
    # Router replicates (every chip routes its own tokens).
    (r"mlp/router$", ()),
    (r"mlp/w[123]$", ("model", None, None)),
    (r"embed", ("model", None)),  # vocab-sharded embeddings
    (r".*", ()),  # everything else replicates
)

# Logical axis tokens → Settings attribute carrying the mesh axis name.
_LOGICAL_AXES = {
    "model": "MESH_MODEL_AXIS",
    "data": "MESH_DATA_AXIS",
    "nodes": "MESH_NODES_AXIS",
}


def resolve_axis(token: AxisSpec) -> AxisSpec:
    """Logical axis token → concrete mesh axis name (tuples element-wise)."""
    if token is None:
        return None
    if isinstance(token, (tuple, list)):
        return tuple(resolve_axis(t) for t in token)
    return getattr(Settings, _LOGICAL_AXES.get(token, ""), token)


def _path_str(key_path) -> str:
    parts = []
    for p in key_path:
        parts.append(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))))
    return "/".join(parts)


def named_paths(tree: Pytree) -> list[tuple[str, Any]]:
    """``[(slash/joined/path, leaf), ...]`` for every leaf of ``tree``."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(_path_str(kp), leaf) for kp, leaf in flat]


def _is_scalar(leaf) -> bool:
    shape = getattr(leaf, "shape", ())
    ndim = len(shape)
    size = 1
    for s in shape:
        size *= s
    return ndim == 0 or size == 1


def _match_one(rules: PartitionRules, path: str) -> Optional[int]:
    """Index of the first rule matching ``path`` (None = unmatched)."""
    for i, (pattern, _) in enumerate(rules):
        if re.search(pattern, path):
            return i
    return None


def match_partition_rules(
    rules: PartitionRules,
    tree: Pytree,
    *,
    on_unmatched: str = "error",
) -> Pytree:
    """PartitionSpec pytree for ``tree`` under ``rules`` (first match wins).

    Scalar leaves (0-d or single-element) always get ``P()`` — there is
    nothing to shard and optimizer step counters must never trip the
    unmatched check. ``on_unmatched``: ``"error"`` raises naming every
    unmatched path (the loud default); ``"replicate"`` maps them to
    ``P()`` (useful for exploratory trees).

    Axis tokens in the winning spec resolve through
    :data:`Settings.MESH_MODEL_AXIS` / ``MESH_DATA_AXIS`` /
    ``MESH_NODES_AXIS`` at call time, so the same rule set follows a
    renamed mesh.
    """
    if on_unmatched not in ("error", "replicate"):
        raise ValueError(f"on_unmatched must be 'error'|'replicate', got {on_unmatched!r}")
    unmatched: list[str] = []

    def one(key_path, leaf):
        path = _path_str(key_path)
        if _is_scalar(leaf):
            return P()
        idx = _match_one(rules, path)
        if idx is None:
            unmatched.append(path)
            return P()
        _, axes = rules[idx]
        return P(*(resolve_axis(a) for a in axes))

    specs = jax.tree_util.tree_map_with_path(one, tree)
    if unmatched and on_unmatched == "error":
        raise ValueError(
            "no partition rule matches "
            f"{len(unmatched)} path(s): {unmatched[:8]}"
            + (" …" if len(unmatched) > 8 else "")
            + " — add a rule (a trailing ('.*', ()) replicates the rest)"
        )
    return specs


@dataclass
class RuleLintReport:
    """Outcome of :func:`lint_partition_rules` — empty lists mean clean.

    ``unmatched``: non-scalar paths no rule matches. ``dead_rules``: rule
    patterns that are never the *winning* (first) match for any path —
    shadowed or typo'd. ``unknown_axes``: ``(pattern, axis)`` pairs whose
    resolved axis is absent from the mesh. ``indivisible``: ``(path, axis)``
    pairs where the axis exists but its size does not divide the leaf dim
    (placement replicates these — legitimate for tiny test models, an
    error for a 1B deployment).

    Reporting rides the shared static-check types
    (:mod:`p2pfl_tpu.analysis.findings`): :meth:`findings` renders the
    same facts as :class:`~p2pfl_tpu.analysis.findings.Finding` objects
    — error severity for the three construction-time failures, info for
    ``indivisible`` — so sharding lint output and the analyzer CLI speak
    one format.
    """

    unmatched: list[str] = field(default_factory=list)
    dead_rules: list[str] = field(default_factory=list)
    unknown_axes: list[tuple[str, str]] = field(default_factory=list)
    indivisible: list[tuple[str, str]] = field(default_factory=list)

    def findings(self, path: str = "partition-rules") -> list[Finding]:
        """The report as shared :class:`Finding` objects (one per fact).

        ``path`` labels the source of the rule set (there is no file to
        point at — rules are data); line/col are 0 by construction.
        """

        def make(rule: str, message: str, severity: Severity = Severity.ERROR) -> Finding:
            return Finding(
                rule=rule, path=path, line=0, col=0, message=message, severity=severity
            )

        out = [make("partition-unmatched", f"unmatched path: {p}") for p in self.unmatched]
        out += [
            make("partition-dead-rule", f"dead rule (never first match): {r!r}")
            for r in self.dead_rules
        ]
        out += [
            make("partition-unknown-axis", f"rule {r!r} names axis {a!r} not in the mesh")
            for r, a in self.unknown_axes
        ]
        out += [
            make(
                "partition-indivisible",
                f"axis {a!r} does not divide {p!r} — leaf replicates",
                Severity.INFO,
            )
            for p, a in self.indivisible
        ]
        return out

    @property
    def errors(self) -> list[str]:
        return [f.message for f in self.findings() if f.severity is Severity.ERROR]

    def ok(self) -> bool:
        return not self.errors


def lint_partition_rules(
    rules: PartitionRules,
    tree: Pytree,
    mesh: Optional[Mesh] = None,
) -> RuleLintReport:
    """Pure check of ``rules`` against ``tree``'s named paths (and ``mesh``).

    Flags the three ways a rule set silently goes wrong — an unmatched
    path (would replicate a tensor meant to shard), a dead rule (a typo'd
    regex that never wins, its tensors falling through to a later rule),
    and a spec naming an axis the mesh doesn't have. ``indivisible``
    entries are informational: placement replicates those leaves.
    """
    report = RuleLintReport()
    wins: set[int] = set()
    for path, leaf in named_paths(tree):
        if _is_scalar(leaf):
            # scalars always place as P() and never count as unmatched —
            # but a rule whose only matches are size-1 leaves is still a
            # LIVE rule, not a dead one (e.g. an explicit rule for a
            # (1,)-shaped logit scale must not fail the dead-rule check)
            idx = _match_one(rules, path)
            if idx is not None:
                wins.add(idx)
            continue
        idx = _match_one(rules, path)
        if idx is None:
            report.unmatched.append(path)
            continue
        wins.add(idx)
        _, axes = rules[idx]
        if mesh is not None:
            shape = getattr(leaf, "shape", ())
            for dim, token in enumerate(axes):
                axis = resolve_axis(token)
                if axis is None:
                    continue
                group = axis if isinstance(axis, tuple) else (axis,)
                known = [ax for ax in group if ax in mesh.shape]
                for ax in group:
                    if ax not in mesh.shape:
                        report.unknown_axes.append((rules[idx][0], ax))
                # divisibility is against the PRODUCT of the dim's mesh
                # axes — exactly what placement (tree_shardings) divides
                # by, so a product-indivisible tuple spec cannot lint
                # clean while silently replicating
                size = 1
                for ax in known:
                    size *= mesh.shape[ax]
                if known and (dim >= len(shape) or shape[dim] % size != 0):
                    report.indivisible.append((path, ax if len(group) == 1 else "+".join(group)))
    report.dead_rules = [pat for i, (pat, _) in enumerate(rules) if i not in wins]
    # dedupe, preserving order
    report.unknown_axes = list(dict.fromkeys(report.unknown_axes))
    return report


def check_partition_rules(
    rules: PartitionRules,
    tree: Pytree,
    mesh: Optional[Mesh] = None,
    *,
    allow_dead: bool = False,
) -> RuleLintReport:
    """:func:`lint_partition_rules`, raising ``ValueError`` on any error.

    Run at federation construction. ``allow_dead=True`` skips the
    dead-rule check — the built-in :data:`DEFAULT_TRANSFORMER_RULES` are
    deliberately broader than any one model, so applying them to an MLP
    leaves transformer rules unmatched by design.
    """
    report = lint_partition_rules(rules, tree, mesh)
    errors = report.errors
    if allow_dead:
        errors = [e for e in errors if not e.startswith("dead rule")]
    if errors:
        raise ValueError(
            "partition rule set fails lint:\n  " + "\n  ".join(errors[:16])
            + ("\n  …" if len(errors) > 16 else "")
        )
    return report


def tree_shardings(
    mesh: Mesh,
    tree: Pytree,
    rules: PartitionRules = DEFAULT_TRANSFORMER_RULES,
    *,
    on_unmatched: str = "error",
) -> Pytree:
    """NamedSharding pytree placing ``tree`` on ``mesh`` per ``rules``.

    The one forgiving step: an axis whose mesh size does not divide the
    leaf dim is dropped (that dim replicates) — tiny configs on big
    meshes. :func:`lint_partition_rules` reports exactly which leaves
    this touched.
    """
    specs = match_partition_rules(rules, tree, on_unmatched=on_unmatched)

    def one(spec, leaf):
        shape = getattr(leaf, "shape", ())
        fixed = []
        for i, axis in enumerate(spec):
            if axis is None:
                fixed.append(None)
                continue
            axes = axis if isinstance(axis, tuple) else (axis,)
            size = 1
            for ax in axes:
                if ax not in mesh.shape:
                    # a spec naming an axis the mesh doesn't carry must
                    # fail HERE, not silently replicate — this is the
                    # direct-call twin of the lint's unknown-axis error
                    # (the pre-engine transformer_shardings raised too)
                    raise ValueError(
                        f"partition spec names axis {ax!r} not in the mesh "
                        f"(axes: {tuple(mesh.shape)})"
                    )
                size *= mesh.shape[ax]
            if i < len(shape) and shape[i] % size == 0:
                fixed.append(axis)
            else:
                fixed.append(None)
        return NamedSharding(mesh, P(*fixed))

    return jax.tree.map(one, specs, tree)


def shard_tree(
    mesh: Mesh,
    tree: Pytree,
    rules: PartitionRules = DEFAULT_TRANSFORMER_RULES,
    *,
    on_unmatched: str = "error",
) -> Pytree:
    """Place ``tree`` onto ``mesh`` per ``rules`` (``jax.device_put``)."""
    return jax.device_put(tree, tree_shardings(mesh, tree, rules, on_unmatched=on_unmatched))


# ---- transformer-rule conveniences (the pre-engine public API) ----


def partition_spec_for(path: str) -> P:
    """Spec for one path under :data:`DEFAULT_TRANSFORMER_RULES`."""
    idx = _match_one(DEFAULT_TRANSFORMER_RULES, path)
    _, axes = DEFAULT_TRANSFORMER_RULES[idx]  # catch-all: never None
    return P(*(resolve_axis(a) for a in axes))


def transformer_shardings(mesh: Mesh, params: Pytree) -> Pytree:
    """NamedSharding pytree for a transformer param tree on ``mesh``."""
    return tree_shardings(mesh, params, DEFAULT_TRANSFORMER_RULES)


def shard_transformer(mesh: Mesh, params: Pytree) -> Pytree:
    """Place a transformer param tree onto the mesh per the TP rules."""
    return jax.device_put(params, transformer_shardings(mesh, params))
