"""Pipeline parallelism: GPipe-style microbatched stage pipeline over a mesh axis.

A stack of identical layers (e.g. transformer blocks) is split into
``P = mesh.shape[axis]`` contiguous stages; layer params stack on a leading
``[L, ...]`` axis that shards over the pipeline axis, so each device holds
``L/P`` layers. Microbatches stream through the stages: device ``s``
processes microbatch ``m`` at step ``s + m`` and hands its activation to
stage ``s+1`` via ``lax.ppermute`` — the classic fill/steady/drain schedule
with ``P - 1`` bubble steps on each side.

Everything is a single SPMD program under ``shard_map``: one ``lax.scan``
over ``M + P - 1`` steps, one ``ppermute`` per step riding ICI. Autodiff
goes straight through (``ppermute``'s transpose is the reverse permute), so
``jax.grad`` of a pipelined loss just works — the backward pass replays the
schedule in reverse.

The reference has no pipeline (or any tensor) parallelism anywhere
(SURVEY §2.9); this provides the PP axis of the multi-chip design, composing
with the ``nodes`` (federated DP), ``model`` (TP/SP) and expert (EP) axes.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from p2pfl_tpu.parallel.compat import device_varying, shard_map_compat

Pytree = Any


def pipeline_mesh(n_stages: int, devices=None, axis: str = "pipe") -> Mesh:
    """A 1-axis mesh of ``n_stages`` devices for pipeline tests/dryruns."""
    devices = list(devices if devices is not None else jax.devices())[:n_stages]
    if len(devices) < n_stages:
        raise ValueError(f"need {n_stages} devices, have {len(devices)}")
    return Mesh(np.array(devices), (axis,))


def stack_layers(per_layer_params: list[Pytree]) -> Pytree:
    """Stack per-layer param pytrees into one ``[L, ...]`` pytree."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer_params)


# jax>=0.8 shard_map typing: scan carries must be device-varying to match
# values produced by axis_index/ppermute; identity on older jax (compat.py)
_varying = device_varying


def _pipeline_body(stage_params, xs, apply_layer: Callable, axis: str, n_stages: int):
    """Per-device body. stage_params: ``[L/P, ...]``; xs: ``[M, mb, ...]``
    (replicated). ``apply_layer(p_layer, act) -> (act, aux_scalar)``.
    Returns ``([M, mb, ...], aux)`` replicated (psum off the last stage);
    aux = sum over layers, mean over microbatches."""
    sid = lax.axis_index(axis)
    m_micro = xs.shape[0]
    total = m_micro + n_stages - 1
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    def apply_stage(p_stage, act):
        def one(act, p_layer):
            act, aux = apply_layer(p_layer, act)
            return act, aux

        act, auxs = lax.scan(one, act, p_stage)
        return act, jnp.sum(auxs)

    def step_fn(carry, step):
        act_in, ys, aux_acc = carry
        # stage 0 consumes the next microbatch; everyone else consumes the
        # activation handed over by the previous stage last step
        feed = xs[jnp.clip(step, 0, m_micro - 1)]
        inp = jnp.where(sid == 0, _varying(feed, axis), act_in)
        out, aux = apply_stage(stage_params, inp)
        # stage s holds real data only during steps [s, s + M): outside that
        # window it is chewing on fill/drain garbage whose aux must not count
        valid = jnp.logical_and(step >= sid, step < sid + m_micro)
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        # the last stage emits microbatch step-(P-1) during drain
        oidx = jnp.clip(step - (n_stages - 1), 0, m_micro - 1)
        collect = jnp.logical_and(sid == n_stages - 1, step >= n_stages - 1)
        ys = ys.at[oidx].set(jnp.where(collect, out, ys[oidx]))
        act_next = lax.ppermute(out, axis, perm)
        return (act_next, ys, aux_acc), None

    act0 = _varying(jnp.zeros_like(xs[0]), axis)
    ys0 = _varying(jnp.zeros_like(xs), axis)
    aux0 = _varying(jnp.zeros((), jnp.float32), axis)
    (_, ys, aux_acc), _ = lax.scan(step_fn, (act0, ys0, aux0), jnp.arange(total))
    # only the last stage holds real outputs; psum replicates them to all.
    # aux: every stage contributes its layers' sum; normalize microbatches.
    ys = lax.psum(jnp.where(sid == n_stages - 1, ys, jnp.zeros_like(ys)), axis)
    aux = lax.psum(aux_acc, axis) / m_micro
    return ys, aux


def pipeline_apply(
    stacked_params: Pytree,
    x_microbatches: jax.Array,
    apply_layer: Callable[[Pytree, jax.Array], jax.Array],
    mesh: Mesh,
    axis: str = "pipe",
    with_aux: bool = False,
) -> jax.Array:
    """Run ``[M, mb, ...]`` microbatches through pipelined stacked layers.

    ``stacked_params``: pytree with leading layer axis ``[L, ...]``,
    ``L`` divisible by ``mesh.shape[axis]``; sharded over ``axis`` (each
    device keeps its own stage's slice — pass it pre-sharded or let
    ``shard_map`` split it). ``apply_layer(p_layer, act) -> act`` applies a
    single layer — or, with ``with_aux=True``, returns ``(act, aux_scalar)``
    and the call returns ``(out, aux)`` where aux is summed over layers and
    averaged over microbatches (how MoE balance losses ride the pipeline).
    Differentiable end to end.
    """
    n_stages = mesh.shape[axis]
    n_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_layers % n_stages != 0:
        raise ValueError(f"{n_layers} layers not divisible by {n_stages} stages")
    if with_aux:
        layer_fn = apply_layer
    else:
        def layer_fn(p_layer, act):
            return apply_layer(p_layer, act), jnp.zeros((), jnp.float32)

    fn = shard_map_compat(
        partial(_pipeline_body, apply_layer=layer_fn, axis=axis, n_stages=n_stages),
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=(P(), P()),
    )
    out, aux = fn(stacked_params, x_microbatches)
    return (out, aux) if with_aux else out


def pipelined_lm_apply(
    params: Pytree,
    tokens: jax.Array,
    cfg,
    mesh: Mesh,
    axis: str = "pipe",
    n_micro: int = 0,
    attn_fn: Callable | None = None,
    return_aux: bool = False,
) -> jax.Array:
    """Forward a :class:`~p2pfl_tpu.models.transformer.CausalLM` param tree
    with its block stack pipelined over ``mesh[axis]``.

    Embedding, final norm and the tied head are cheap and stay replicated;
    only the ``layer_i`` blocks stream through stages. ``n_micro`` defaults
    to the stage count (the minimum that fills the pipeline). The batch must
    divide into ``n_micro`` microbatches. Same modules and params as
    ``CausalLM.apply`` — forward ``attn_fn`` if the model was built with a
    non-default attention backend. A ``cfg.flash_config`` kernel schedule
    needs NO threading: the stages build their Blocks from ``cfg``, so the
    statically-keyed Pallas flash schedule rides into every stage program
    (and into any enclosing jit's cache key) through the config itself.

    MoE blocks (``cfg.n_experts > 0``): sown router losses are collected
    per stage and returned when ``return_aux=True`` (sum over layers, mean
    over microbatches — per-microbatch balance fractions, vs the monolithic
    model's full-batch fractions). Training an MoE pipeline MUST use
    ``return_aux=True`` and add the aux term, or routers never learn to
    balance.
    """
    from p2pfl_tpu.models.transformer import Block, RMSNorm

    if cfg.n_experts > 0 and not return_aux:
        raise ValueError(
            "MoE pipeline: pass return_aux=True and add the aux loss "
            "(silently dropping router balance losses breaks routing)"
        )
    n_stages = mesh.shape[axis]
    n_micro = n_micro or n_stages
    b = tokens.shape[0]
    if b % n_micro != 0:
        raise ValueError(f"batch {b} not divisible into {n_micro} microbatches")

    emb = params["embed"]
    x = emb[tokens].astype(cfg.dtype)  # [B, T, D]
    xm = x.reshape(n_micro, b // n_micro, *x.shape[1:])
    stacked = stack_layers([params[f"layer_{i}"] for i in range(cfg.n_layers)])
    block = Block(cfg, attn_fn)

    def apply_layer(p_layer, act):
        out, mut = block.apply({"params": p_layer}, act, mutable=["moe_losses"])
        leaves = jax.tree.leaves(mut)
        return out, (sum(leaves) if leaves else jnp.zeros((), jnp.float32))

    y, aux = pipeline_apply(stacked, xm, apply_layer, mesh, axis, with_aux=True)
    y = y.reshape(b, *x.shape[1:])
    y = RMSNorm(cfg.dtype).apply({"params": params["final_norm"]}, y)
    logits = jnp.dot(y, emb.T.astype(cfg.dtype)).astype(jnp.float32)
    return (logits, aux) if return_aux else logits
