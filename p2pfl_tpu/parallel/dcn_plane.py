"""Cross-process shard transfer glue for the DCN weights plane.

The process-spanning generalization of :mod:`p2pfl_tpu.parallel.ici_plane`:
move a pytree that lives on one node's device slice in THIS process onto
the matching devices of a peer node's slice in ANOTHER process of the same
``jax.distributed`` world — device ``p`` of the source slice copies its
block to device ``p`` of the destination slice over the cross-host
interconnect (DCN on a pod; gloo on the CPU world CI runs), never through
host pickling.

Mechanics — the ici_plane pair-mesh idiom under multi-controller SPMD:

1. Both processes independently build the SAME ``(2, *slice_shape)`` pair
   mesh from the global device list (``jax.devices()`` spans the world;
   the rendezvous protocol in ``communication/dcn.py`` carried the peer's
   device ids). Row 0 is the sender's slice, row 1 the receiver's.
2. Each process wraps its OWN row's shards into the pair-global arrays —
   ``make_array_from_single_device_arrays`` accepts exactly the
   addressable shards, which per process is one row: the sender
   contributes the payload blocks, the receiver zero filler blocks.
3. Both processes co-dispatch ONE jitted ``shard_map`` exchange program
   (the ici_plane 2-cycle ``ppermute`` — same program cache, same
   backends) over the pair mesh. XLA runs it as a cross-process
   computation; the blocks swap rows over the wire.
4. The receiver re-wraps its row of the output under its own shardings
   (metadata assembly) — the delivered tree is already placed where its
   jits expect it. The sender's row holds the discarded filler.

Each side must dispatch transfers in the SAME order — that sequencing
(per-pair monotone seq + ready handshake) is ``communication/dcn.py``'s
job; this module is the pure device-plane primitive.

This module is inside the ``no-host-gather`` analyzer scope
(:mod:`p2pfl_tpu.analysis`): no ``np.asarray``/``jax.device_get``/
``.tobytes()`` may appear here — the zero-host-bytes contract is enforced
statically, not by prose.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2pfl_tpu.parallel.ici_plane import PAIR_AXIS, SliceInfo, _exchange_program

Pytree = Any


def devices_by_id() -> dict:
    """Global (world-spanning) device id → device object map."""
    return {d.id: d for d in jax.devices()}


def mesh_from_ids(
    ids: Sequence[int], shape: Sequence[int], axis_names: Sequence[str]
) -> Optional[Mesh]:
    """Rebuild a peer slice's mesh from wire metadata (flat C-order ids).

    Returns ``None`` when an id is not in this world's device list — the
    caller then nacks the transfer instead of crashing.
    """
    by_id = devices_by_id()
    flat = np.empty((len(ids),), dtype=object)
    for i, did in enumerate(ids):
        dev = by_id.get(int(did))
        if dev is None:
            return None
        flat[i] = dev
    return Mesh(flat.reshape(tuple(shape)), tuple(axis_names))


def mesh_wire_meta(info: SliceInfo) -> dict:
    """A slice mesh as JSON-ready metadata: flat C-order ids + shape +
    axis names (the offer/accept's topology fields)."""
    return {
        "ids": [int(d.id) for d in info.mesh.devices.flat],
        "shape": list(info.mesh.devices.shape),
        "axes": list(info.mesh.axis_names),
    }


def process_local(info: SliceInfo) -> bool:
    """True when every device of the slice belongs to THIS process — the
    DCN plane's precondition on both endpoints (each side contributes
    exactly one row of the pair mesh)."""
    pi = jax.process_index()
    return all(d.process_index == pi for d in info.mesh.devices.flat)


def spec_to_wire(spec) -> list:
    """A ``PartitionSpec`` as JSON (tuples become lists)."""
    return [list(e) if isinstance(e, tuple) else e for e in spec]


def spec_from_wire(wire) -> P:
    """Inverse of :func:`spec_to_wire`."""
    return P(*[tuple(e) if isinstance(e, list) else e for e in wire])


def _pair_global_local(leaf_local, gsharding: NamedSharding, gshape: tuple):
    """Wrap ONE process's row of a pair-global from its local shards.

    The cross-process variant of ici_plane's ``_pair_global``: here only
    this side's row is addressable, and
    ``addressable_devices_indices_map`` lists exactly those devices —
    metadata assembly, no transfer, no host.
    """
    dmap = {
        s.device: s.data.reshape((1,) + s.data.shape)
        for s in leaf_local.addressable_shards
    }
    arrs = [dmap[d] for d in gsharding.addressable_devices_indices_map(gshape)]
    return jax.make_array_from_single_device_arrays(gshape, gsharding, arrs)


def _dst_view_local(out_leaf, dst_sharding: NamedSharding, shape: tuple):
    """The receiver's row of an exchanged pair-global re-wrapped under its
    own sharding (this process only addresses its own row, so no device
    filter is needed)."""
    omap = {
        s.device: s.data.reshape(s.data.shape[1:])
        for s in out_leaf.addressable_shards
    }
    arrs = [omap[d] for d in dst_sharding.addressable_devices_indices_map(shape)]
    return jax.make_array_from_single_device_arrays(shape, dst_sharding, arrs)


def dcn_transfer(
    local_tree: Pytree,
    src_mesh: Mesh,
    dst_mesh: Mesh,
    specs: tuple,
    role: str,
    backend: str = "ppermute",
) -> Optional[Pytree]:
    """Run one side of a cross-process pair exchange.

    ``local_tree`` is this process's contribution: the payload (sender) or
    structurally-identical zero filler already resident on the destination
    slice (receiver). ``src_mesh``/``dst_mesh`` are the two slices' meshes
    — one local, one rebuilt from wire ids by :func:`mesh_from_ids` — and
    MUST be identical on both processes (same device order), as must
    ``specs`` (one per leaf, sorted-key order fixed by the offer). Both
    processes co-dispatch the same cached exchange program; the call
    blocks until the collective completes (the caller holds the process's
    dispatch-order lock across it). Returns the received tree placed
    under ``dst_mesh`` shardings for ``role="recv"``, ``None`` for
    ``role="send"``.
    """
    leaves = jax.tree.leaves(local_tree)
    treedef = jax.tree.structure(local_tree)
    pair_devices = np.stack([src_mesh.devices, dst_mesh.devices])
    pair_mesh = Mesh(pair_devices, (PAIR_AXIS, *src_mesh.axis_names))
    gspecs = tuple(P(PAIR_AXIS, *spec) for spec in specs)
    pair_globals = tuple(
        _pair_global_local(
            leaf, NamedSharding(pair_mesh, gs), (2,) + tuple(leaf.shape)
        )
        for leaf, gs in zip(leaves, gspecs)
    )
    prog = _exchange_program(pair_mesh, gspecs, backend)
    outs = prog(*pair_globals)
    # dispatch-order safety: the next collective on this process must not
    # start until this one has completed on the wire (readiness only —
    # no values cross to the host)
    jax.block_until_ready(outs)
    if role == "send":
        return None
    new_leaves = [
        _dst_view_local(o, NamedSharding(dst_mesh, spec), tuple(x.shape))
        for o, spec, x in zip(outs, specs, leaves)
    ]
    return jax.tree.unflatten(treedef, new_leaves)
