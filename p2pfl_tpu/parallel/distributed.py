"""Multi-host bootstrap: the DCN side of the communication backend.

One process per host, ``jax.distributed.initialize`` to form the global
runtime; after that every mesh built from ``jax.devices()`` spans the whole
slice/pod and the SPMD federations in this package scale transparently —
collectives ride ICI within a slice and DCN across slices, with XLA picking
the routing. This is the rebuild's counterpart to the reference's "start a
gRPC server per node" bring-up (``grpc_server.py:74-88``): here the hosts
form one SPMD world instead of a socket overlay.

Single-host (or already-initialized) calls are no-ops, so the same script
runs on a laptop and on a pod.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from p2pfl_tpu.management.logger import logger

_initialized = False


def _enable_cpu_collectives() -> None:
    """Opt the CPU backend into cross-process collectives (gloo).

    jaxlib's CPU client defaults its collectives implementation to
    ``"none"`` — any cross-process computation then dies with
    "Multiprocess computations aren't implemented on the CPU backend".
    Flipping the config to gloo BEFORE the backend is created makes the
    same shard_map/ppermute programs the TPU DCN path runs work across
    real CPU processes (the tier the multi-process tests and the DCN
    weights plane exercise in CI). The env-var spelling
    (``JAX_CPU_COLLECTIVES_IMPLEMENTATION``) is NOT honored by this
    jaxlib — only the config update is, which is why this lives in code.
    Harmless on TPU (it only configures the auxiliary CPU client), and a
    jaxlib built without gloo simply keeps its default.
    """
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as exc:  # noqa: BLE001 — absent option/implementation
        logger.debug("distributed", f"cpu collectives stay default: {exc!r}")


def init_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> dict:
    """Join (or skip joining) the multi-host JAX runtime.

    With no arguments, reads the standard env vars
    (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``
    — also set automatically on TPU pods) and no-ops when absent.
    Returns a summary dict for logging/tests.
    """
    global _initialized
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    num_processes = num_processes or _env_int("JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None else _env_int("JAX_PROCESS_ID")

    if not _initialized and (coordinator_address or _on_tpu_pod()):
        _enable_cpu_collectives()
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True

    info = {
        "initialized": _initialized,
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
    logger.info("distributed", f"runtime: {info}")
    return info


def _env_int(name: str) -> Optional[int]:
    val = os.environ.get(name)
    return int(val) if val else None


def _on_tpu_pod() -> bool:
    """True when TPU pod metadata is present (initialize() self-configures)."""
    return bool(os.environ.get("TPU_WORKER_HOSTNAMES")) and bool(
        os.environ.get("TPU_WORKER_ID")
    )


# ---- world introspection (the DCN weights plane's eligibility seam) ----


def kv_client():
    """The distributed runtime's key-value store client, or ``None``.

    The coordinator-backed KV store (``DistributedRuntimeClient``) is how
    same-world processes publish/discover each other without any extra
    service: ``key_value_set`` / ``key_value_dir_get`` / ``key_value_delete``
    are the surface the DCN world directory (``communication/dcn.py``)
    uses. ``None`` outside a ``jax.distributed`` world.
    """
    try:
        from jax._src.distributed import global_state

        return getattr(global_state, "client", None)
    except Exception:  # noqa: BLE001 — private seam moved; treat as no world
        return None


def world_active() -> bool:
    """True inside a formed multi-process ``jax.distributed`` world.

    Checks the runtime client rather than this module's ``_initialized``
    flag so a world formed by a direct ``jax.distributed.initialize`` call
    (not through :func:`init_multihost`) still counts. A single-process
    "world" returns False — there is no cross-process edge to serve.
    """
    if kv_client() is None:
        return False
    try:
        return jax.process_count() > 1
    except Exception:  # noqa: BLE001 — backend mid-teardown
        return False
