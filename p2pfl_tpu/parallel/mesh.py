"""Mesh construction helpers.

Axis convention (Settings.MESH_NODES_AXIS / MESH_DATA_AXIS /
MESH_MODEL_AXIS):
- ``nodes``: one federated node per slot — data-parallel across the
  federation; collectives over this axis ride ICI within a slice.
- ``data``: intra-node batch parallelism (submesh federations only).
- ``model``: intra-node model sharding (tensor/sequence parallel) for
  models too big for one chip (BASELINE config 5). Size 1 by default.

Two layouts ship:

- :func:`federation_mesh` — the SPMD layout ``(nodes, model)``: logical
  nodes fold onto slots, one jit program spans the whole mesh.
- :func:`submesh_federation_mesh` — the sharded-node layout
  ``(nodes, data, model)``: each node OWNS a ``(data, model)`` slice
  (:func:`node_slices`) and runs its round as its own dispatch;
  cross-slice aggregation is a collective over ``nodes``
  (``parallel/submesh.py``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from p2pfl_tpu.settings import Settings


def federation_mesh(
    n_nodes: Optional[int] = None,
    model_parallel: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``(nodes, model)`` mesh from the available devices.

    ``n_nodes`` is the number of mesh slots along the nodes axis — logical
    federated nodes are folded onto slots (multiple nodes per slot when the
    federation is larger than the device count). Defaults to
    ``len(devices) // model_parallel``.

    Every passed device must land in the mesh: a slot count that would
    strand trailing devices raises instead of silently shrinking the mesh
    (the pre-fix behavior quietly built a 2-device mesh out of 8 when
    ``n_nodes=3`` — six chips idle with no indication). Callers that WANT
    a subset pass ``devices=jax.devices()[:k]`` explicitly.
    """
    devices = list(devices if devices is not None else jax.devices())
    if model_parallel < 1 or len(devices) % model_parallel != 0:
        raise ValueError(f"model_parallel={model_parallel} does not divide {len(devices)} devices")
    slots = len(devices) // model_parallel
    if n_nodes is not None and n_nodes < slots:
        raise ValueError(
            f"n_nodes={n_nodes} mesh slots would strand "
            f"{len(devices) - n_nodes * model_parallel} of {len(devices)} devices "
            f"(model_parallel={model_parallel}). Pass "
            f"devices=devices[:{n_nodes * model_parallel}] to use a subset "
            "deliberately, or let n_nodes default so logical nodes fold onto "
            "all slots."
        )
    arr = np.array(devices).reshape(slots, model_parallel)
    return Mesh(arr, (Settings.MESH_NODES_AXIS, Settings.MESH_MODEL_AXIS))


def submesh_federation_mesh(
    n_nodes: int,
    model_parallel: int = 1,
    data_parallel: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the ``(nodes, data, model)`` global mesh for sharded nodes.

    Exactly ``n_nodes * data_parallel * model_parallel`` devices are
    required — every federated node owns a ``(data_parallel,
    model_parallel)`` slice. With ``devices=None`` the first ``needed``
    of ``jax.devices()`` are taken (and any surplus is reported loudly in
    the error when the counts cannot work out). Device order decides
    which node owns which chips: consecutive runs of ``data_parallel *
    model_parallel`` devices form one node's slice, so multi-host
    layouts can interleave processes by ordering the list.
    """
    if n_nodes < 1 or model_parallel < 1 or data_parallel < 1:
        raise ValueError(
            f"n_nodes={n_nodes}, data_parallel={data_parallel}, "
            f"model_parallel={model_parallel} must all be >= 1"
        )
    needed = n_nodes * data_parallel * model_parallel
    explicit = devices is not None
    devices = list(devices if explicit else jax.devices())
    if (explicit and len(devices) != needed) or len(devices) < needed:
        raise ValueError(
            f"submesh federation needs exactly {needed} devices "
            f"({n_nodes} nodes x {data_parallel} data x {model_parallel} "
            f"model), got {len(devices)}"
        )
    arr = np.array(devices[:needed]).reshape(n_nodes, data_parallel, model_parallel)
    return Mesh(
        arr,
        (Settings.MESH_NODES_AXIS, Settings.MESH_DATA_AXIS, Settings.MESH_MODEL_AXIS),
    )


def node_slices(mesh: Mesh) -> list[Mesh]:
    """Per-node ``(data, model)`` submeshes of a ``(nodes, data, model)`` mesh.

    Slice ``i`` holds node ``i``'s devices; each node's training dispatch
    targets its own slice, so slices run concurrently and independently.
    """
    nodes_axis = Settings.MESH_NODES_AXIS
    if nodes_axis not in mesh.shape:
        raise ValueError(f"mesh has no {nodes_axis!r} axis: {dict(mesh.shape)}")
    axis_names = tuple(a for a in mesh.axis_names if a != nodes_axis)
    node_dim = mesh.axis_names.index(nodes_axis)
    return [
        Mesh(np.take(mesh.devices, i, axis=node_dim), axis_names)
        for i in range(mesh.shape[nodes_axis])
    ]
