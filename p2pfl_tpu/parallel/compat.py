"""JAX version compatibility for the parallel runtime (and SPMD ops).

Two moving targets pinned here once, so every ``parallel/`` module (and
``ops/attention.py``) imports from one place instead of hard-coding a JAX
release's layout:

- ``shard_map`` graduated from ``jax.experimental.shard_map`` to a
  top-level ``jax.shard_map`` export (jax >= 0.6). Importing it from
  ``jax`` directly breaks every module in the package on older installs —
  at *collection* time, before a single test runs.
- the device-varying type system (``lax.pvary``, later ``lax.pcast``)
  only exists on newer releases; on older JAX, shard_map has no varying
  types and the identity is the correct (and only) lowering.
"""

from __future__ import annotations

try:  # jax >= 0.6: top-level export
    from jax import shard_map
except ImportError:  # older jax: experimental namespace
    from jax.experimental.shard_map import shard_map  # type: ignore[no-redef]


def device_varying(x, axis: str):
    """Mark ``x`` (a pytree of arrays) device-varying over mesh axis ``axis``.

    Scan carries under ``shard_map`` must match the varying type of values
    produced by ``lax.axis_index`` / ``lax.ppermute`` on jax >= 0.8; older
    releases have no varying-type checker, so the value passes through.
    """
    from jax import lax

    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis,), to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, (axis,))
    return x


def shard_map_compat(f, **kwargs):
    """``shard_map`` tolerant of older JAX's replication checker.

    Old releases (``check_rep`` era) have no replication rules for
    collectives like ``ppermute`` inside ``lax.scan`` bodies, so the check
    must be off there; newer releases' vma checker handles them and stays
    ON (the ``device_varying`` marks exist to satisfy it).
    """
    import inspect

    params = inspect.signature(shard_map).parameters
    if "check_rep" in params and "check_vma" not in params:
        kwargs["check_rep"] = False
    return shard_map(f, **kwargs)


def shard_map_unchecked(f, **kwargs):
    """``shard_map`` with the replication/varying checker disabled.

    The kwarg spelling moved across releases (``check_rep`` →
    ``check_vma``); bodies whose out_shape carries no vma typing (Pallas
    calls) need it off whichever JAX is installed.
    """
    import inspect

    params = inspect.signature(shard_map).parameters
    for kw in ("check_vma", "check_rep"):
        if kw in params:
            kwargs[kw] = False
            break
    return shard_map(f, **kwargs)


__all__ = ["shard_map", "device_varying", "shard_map_compat", "shard_map_unchecked"]
