"""Shard-to-shard transfer glue for the ICI weights plane.

The primitive behind ``communication/ici.py``: move a pytree that lives on
one node's device slice onto the matching devices of a peer's slice —
device ``p`` of the source slice copies its block directly to device ``p``
of the destination slice — without the data ever visiting the host.

Mechanics (the zero-copy "pair mesh" idiom):

1. Source and destination slices are described by :class:`SliceInfo`
   (slice mesh + per-leaf partition specs), derived from the live arrays
   by :func:`slice_info_of`. A single-chip node is the degenerate
   one-device slice.
2. For a transfer, the two slices' device arrays stack into one
   ``(2, *slice_shape)`` **pair mesh**. Each leaf is wrapped into a
   ``(2, *leaf.shape)`` pair-global array sharded ``P('ici_pair', *spec)``
   — pure metadata assembly (``make_array_from_single_device_arrays``
   over the *existing* shards plus the receiver-side filler blocks; the
   only per-shard work is a device-local leading-axis reshape).
3. One jitted ``shard_map`` program over the pair mesh exchanges the two
   blocks along ``ici_pair``: the pure-XLA backend is a
   ``lax.ppermute`` collective (CPU-runnable — the bit-parity fallback
   tier-1 and the chaos suite exercise on the virtual device mesh); the
   TPU backend is a Pallas remote-DMA kernel
   (``pltpu.make_async_remote_copy`` — each device RDMAs its block
   straight into the partner chip's HBM, the SNIPPETS right-permute
   shape specialized to a pair). Both backends move the same shards, so
   backend choice can never change what the receiver decodes.
4. The output's destination-side blocks re-wrap under the receiver's own
   shardings — metadata assembly again — so the delivered tree is
   *already placed* exactly where the receiver's jits expect it and
   ``ops/tree.tree_align_devices`` is an asserted no-op downstream.

This module is inside the ``no-host-gather`` analyzer scope
(:mod:`p2pfl_tpu.analysis`): no ``np.asarray``/``jax.device_get``/
``.tobytes()`` may appear here — the zero-host-bytes contract is enforced
statically, not by prose.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2pfl_tpu.parallel.compat import shard_map_compat, shard_map_unchecked

Pytree = Any

#: leading axis of the transfer pair mesh (block 0 = sender's slice,
#: block 1 = receiver's)
PAIR_AXIS = "ici_pair"
#: synthesized sub-axis name for the degenerate single-device slice
_SUB_AXIS = "ici_sub"

#: compiled exchange programs, keyed on (pair device ids, gspecs, backend)
#: — jax.jit handles per-shape caching under each entry
_programs: dict = {}


@dataclass(frozen=True)
class SliceInfo:
    """Where a pytree lives: the slice mesh + per-leaf partition specs.

    ``mesh`` is the node's ``(data, model)`` submesh for placed learners,
    or a synthesized one-device mesh for single-chip nodes; ``specs`` is
    one :class:`~jax.sharding.PartitionSpec` per leaf in
    ``jax.tree.leaves`` order. Equality of ``specs`` + mesh layout is
    what makes two slices shard-compatible.
    """

    mesh: Mesh
    specs: tuple

    @property
    def device_ids(self) -> frozenset:
        return frozenset(d.id for d in self.mesh.devices.flat)

    @property
    def shape(self) -> tuple:
        """The slice's devices-array shape — the wire ``sp`` handshake's
        first element."""
        return tuple(self.mesh.devices.shape)


def _single_device_mesh(device) -> Mesh:
    arr = np.empty((1,), dtype=object)
    arr[0] = device
    return Mesh(arr, (_SUB_AXIS,))


def slice_info_of(tree: Pytree) -> Optional[SliceInfo]:
    """Derive the :class:`SliceInfo` of a live pytree, or ``None``.

    Eligible trees: every leaf a committed ``jax.Array``, either all on
    ONE device (single-chip node — synthesized one-device mesh, all
    specs replicated) or all ``NamedSharding`` over one common mesh
    (submesh-placed learner). Anything mixed — host numpy leaves, leaves
    scattered across meshes — returns ``None`` and the caller falls back
    to the byte path.
    """
    leaves = jax.tree.leaves(tree)
    if not leaves or not all(isinstance(x, jax.Array) for x in leaves):
        return None
    shardings = [x.sharding for x in leaves]
    if all(isinstance(s, NamedSharding) for s in shardings):
        mesh = shardings[0].mesh
        if any(s.mesh is not mesh and s.mesh != mesh for s in shardings[1:]):
            return None
        return SliceInfo(mesh=mesh, specs=tuple(s.spec for s in shardings))
    device_sets = [s.device_set for s in shardings]
    first = device_sets[0]
    if len(first) == 1 and all(ds == first for ds in device_sets[1:]):
        (dev,) = first
        return SliceInfo(
            mesh=_single_device_mesh(dev), specs=tuple(P() for _ in leaves)
        )
    return None


def same_devices(src: SliceInfo, dst: SliceInfo) -> bool:
    """True when the two slices are the SAME devices with the SAME
    per-leaf layout — the degenerate co-residency case where a transfer
    is a zero-copy handoff (the shards are already where the receiver
    wants them)."""
    return (
        src.device_ids == dst.device_ids
        and src.shape == dst.shape
        and src.specs == dst.specs
    )


def transfer_compatible(src: SliceInfo, dst: SliceInfo) -> bool:
    """True when a shard-to-shard pair transfer between the slices is
    well-defined: same slice topology (devices-array shape + axis
    names), identical per-leaf specs (device ``p`` holds the same block
    on both sides), and disjoint device sets (each chip belongs to one
    side of the pair)."""
    return (
        src.shape == dst.shape
        and src.mesh.axis_names == dst.mesh.axis_names
        and src.specs == dst.specs
        and not (src.device_ids & dst.device_ids)
    )


def tree_device_bytes(tree: Pytree) -> int:
    """Payload size moved over the interconnect (metadata only — reads
    shapes/dtypes, never the buffers)."""
    return sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(tree)
        if isinstance(x, jax.Array)
    )


# ---- the exchange program ----


def _pallas_exchange(v, sub_axes: tuple):
    """Pair exchange of one leaf block as a Pallas TPU remote DMA.

    Each device RDMAs its local block straight into the HBM of the
    partner device — same sub-axis coordinates, opposite side of the
    pair (the SNIPPETS [2] ``right_permute`` shape specialized to a
    2-cycle). Refs live in ``ANY`` memory space so arbitrarily large
    parameter blocks stream HBM→HBM without a VMEM bound; the DMA
    semaphore pair is scratch. Only lowers on real TPU hardware — the
    ``ppermute`` backend is the everywhere-else fallback.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(x_ref, o_ref, send_sem, recv_sem):
        my_pair = jax.lax.axis_index(PAIR_AXIS)
        partner = (1 - my_pair, *(jax.lax.axis_index(a) for a in sub_axes))
        rdma = pltpu.make_async_remote_copy(
            src_ref=x_ref,
            dst_ref=o_ref,
            send_sem=send_sem,
            recv_sem=recv_sem,
            device_id=partner,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()

    params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(v.shape, v.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
        compiler_params=params_cls(has_side_effects=True, collective_id=0),
    )(v)


def _exchange_program(pair_mesh: Mesh, gspecs: tuple, backend: str):
    key = (
        tuple(d.id for d in pair_mesh.devices.flat),
        pair_mesh.axis_names,
        gspecs,
        backend,
    )
    prog = _programs.get(key)
    if prog is not None:
        return prog
    sub_axes = pair_mesh.axis_names[1:]

    if backend == "pallas":

        def body(*leaves):
            return tuple(_pallas_exchange(v, sub_axes) for v in leaves)

    else:

        def body(*leaves):
            # a 2-cycle: both blocks swap sides in one collective, so the
            # kernel stays uniform across the pair (no masked sender) and
            # the discarded source-side block costs nothing extra
            return tuple(
                jax.lax.ppermute(v, PAIR_AXIS, perm=((0, 1), (1, 0)))
                for v in leaves
            )

    wrap = shard_map_unchecked if backend == "pallas" else shard_map_compat
    prog = jax.jit(
        wrap(body, mesh=pair_mesh, in_specs=gspecs, out_specs=gspecs)
    )
    _programs[key] = prog
    return prog


def _pair_global(leaf_src, leaf_fill, gsharding: NamedSharding):
    """Wrap the two slices' existing shards into one pair-global array.

    Metadata assembly: the only per-shard work is the device-local
    leading-axis reshape (no transfer, no host)."""
    gshape = (2,) + tuple(leaf_src.shape)
    dmap = {}
    for s in leaf_src.addressable_shards:
        dmap[s.device] = s.data.reshape((1,) + s.data.shape)
    for s in leaf_fill.addressable_shards:
        dmap[s.device] = s.data.reshape((1,) + s.data.shape)
    arrs = [dmap[d] for d in gsharding.addressable_devices_indices_map(gshape)]
    return jax.make_array_from_single_device_arrays(gshape, gsharding, arrs)


def _dst_view(out_leaf, dst_sharding: NamedSharding, shape: tuple, dst_devs: set):
    """The receiver-side block of an exchanged pair-global, re-wrapped
    under the receiver's own sharding (metadata assembly again)."""
    omap = {
        s.device: s.data.reshape(s.data.shape[1:])
        for s in out_leaf.addressable_shards
        if s.device in dst_devs
    }
    arrs = [omap[d] for d in dst_sharding.addressable_devices_indices_map(shape)]
    return jax.make_array_from_single_device_arrays(shape, dst_sharding, arrs)


def shard_transfer(
    tree: Pytree,
    filler: Pytree,
    src: SliceInfo,
    dst: SliceInfo,
    backend: str = "ppermute",
) -> Pytree:
    """Move ``tree`` from slice ``src`` onto slice ``dst``, shard to shard.

    ``filler`` is a structurally-identical pytree already resident on
    ``dst`` (the receiver's current parameters, or cached zero buffers
    for codec payloads) — its shards complete the pair-global's
    receiver-side blocks; its VALUES are discarded by the exchange.
    Returns the tree placed under ``dst``'s shardings. One jitted
    dispatch for the whole tree; everything else is metadata.
    """
    leaves = jax.tree.leaves(tree)
    fillers = jax.tree.leaves(filler)
    treedef = jax.tree.structure(tree)
    pair_devices = np.stack([src.mesh.devices, dst.mesh.devices])
    pair_mesh = Mesh(pair_devices, (PAIR_AXIS, *src.mesh.axis_names))
    gspecs = tuple(P(PAIR_AXIS, *spec) for spec in src.specs)
    gshardings = [NamedSharding(pair_mesh, gs) for gs in gspecs]
    pair_globals = tuple(
        _pair_global(a, b, gs) for a, b, gs in zip(leaves, fillers, gshardings)
    )
    prog = _exchange_program(pair_mesh, gspecs, backend)
    outs = prog(*pair_globals)
    dst_devs = set(dst.mesh.devices.flat)
    new_leaves = [
        _dst_view(
            o,
            NamedSharding(dst.mesh, spec),
            tuple(x.shape),
            dst_devs,
        )
        for o, spec, x in zip(outs, dst.specs, leaves)
    ]
    return jax.tree.unflatten(treedef, new_leaves)


def conform_specs(tree: Pytree, mesh: Mesh, specs: tuple) -> tuple[Pytree, int]:
    """Re-lay out a pytree to ``specs`` on ``mesh``, counting moved leaves.

    A payload's producing program (an aggregation fold, an XLA-chosen
    output layout) may leave leaves on the sender's slice in a DIFFERENT
    per-leaf layout than the receiver's placement expects. Conforming at
    the SOURCE — one ``device_put`` per differing leaf, device-to-device
    within the slice — is what lets the subsequent pair transfer land
    every block exactly where the receiver's jits want it, keeping
    ``tree_align_devices`` a no-op downstream. Returns
    ``(conformed_tree, moved_leaf_count)``.
    """
    leaves = jax.tree.leaves(tree)
    treedef = jax.tree.structure(tree)
    moved = 0
    out = []
    for leaf, spec in zip(leaves, specs):
        target = NamedSharding(mesh, spec)
        if isinstance(leaf, jax.Array) and leaf.sharding == target:
            out.append(leaf)
        else:
            moved += 1
            out.append(jax.device_put(leaf, target))
    return jax.tree.unflatten(treedef, out), moved


def replicate_on_slice(tree: Pytree, info: SliceInfo) -> Pytree:
    """Re-place a pytree replicated over a slice's devices (D2D within
    the slice — used to give codec buffers a deterministic layout before
    a pair transfer). No-op for the single-device slice when the leaves
    already live there."""
    sharding = NamedSharding(info.mesh, P())
    slice_devices = set(info.mesh.devices.flat)

    def one(x):
        if isinstance(x, jax.Array):
            if x.sharding == sharding:
                return x
            # a one-device slice: anything already committed to that
            # device IS "replicated over the slice" — skip the copy
            if len(slice_devices) == 1 and x.sharding.device_set == slice_devices:
                return x
        return jax.device_put(x, sharding)

    return jax.tree.map(one, tree)
