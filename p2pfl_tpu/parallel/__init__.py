"""TPU-native parallel runtime.

This is where the rebuild departs most from the reference: instead of N
OS processes exchanging pickled weights over gRPC
(``p2pfl/communication/grpc/``), an entire federation runs as **one SPMD
program** over a ``jax.sharding.Mesh`` — one logical node per mesh slot,
local training as per-slot batched compute, FedAvg as a masked weighted
reduction that XLA lowers to an all-reduce over ICI. Control decisions
(election, round count) stay on host; nothing crosses the host↔device
boundary inside a round.
"""

from p2pfl_tpu.parallel.fleet_mesh import fleet_clients_mesh, shard_capacity
from p2pfl_tpu.parallel.mesh import (
    federation_mesh,
    node_slices,
    submesh_federation_mesh,
)
from p2pfl_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_mesh,
    pipelined_lm_apply,
    stack_layers,
)
from p2pfl_tpu.parallel.spmd import SpmdFederation

__all__ = [
    "ChunkedFederation",
    "PipelineFederation",
    "ShardedNodeFederation",
    "SpmdFederation",
    "SpmdLmFederation",
    "SpmdLoraFederation",
    "federation_mesh",
    "fleet_clients_mesh",
    "node_slices",
    "shard_capacity",
    "pipeline_apply",
    "pipeline_mesh",
    "pipelined_lm_apply",
    "stack_layers",
    "submesh_federation_mesh",
]

_LAZY = {
    "ChunkedFederation": "p2pfl_tpu.parallel.chunked",
    "ShardedNodeFederation": "p2pfl_tpu.parallel.submesh",
    "SpmdLoraFederation": "p2pfl_tpu.parallel.spmd_lora",
    "SpmdLmFederation": "p2pfl_tpu.parallel.spmd_lm",
    "PipelineFederation": "p2pfl_tpu.parallel.spmd_lm",
}


def __getattr__(name):
    if name in _LAZY:  # lazy: avoid importing optax paths eagerly
        import importlib

        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(name)
