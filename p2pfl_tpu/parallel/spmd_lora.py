"""SPMD federated LoRA: BASELINE config 5 at mesh scale.

Node-stacked state is ONLY the adapter subtree ``[N, ...]``; the frozen base
model is stored once and replicated (or tensor-parallel over the ``model``
axis via ``parallel/sharding.py``) — N nodes' federation state costs
``N × adapter_size + 1 × model_size`` instead of ``N × model_size``, which is
what makes 32-node TinyLlama-scale federations fit a slice. The FedAvg
all-reduce moves only adapters.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import adam, ce_eval
from p2pfl_tpu.learning.lora import lora_train_epoch as _node_lora_epoch  # noqa: F401 (shared math)
from p2pfl_tpu.learning.lora import _lm_loss, merge_params, split_lora
from p2pfl_tpu.models.base import FlaxModel
from p2pfl_tpu.parallel.spmd import SpmdFederation, _aggregate

Pytree = Any


def _lora_round_core(
    stacked_lora,  # [N, ...] adapters
    opt_states,  # [N, ...]
    base,  # shared frozen params (no node axis)
    x_all,  # [N, S, T] int tokens
    y_all,  # [N, S, T]
    perm,  # [N, epochs, nb, bs]
    mask,  # [N]
    weights,  # [N]
    sel_idx,  # [K] int32 indices of mask==1 rows
    *,
    module,
    tx,
    agg: str = "fedavg",
    trim: int = 0,
    out_sharding=None,
    keep_opt_state: bool = False,
    remat: bool = False,
    node_chunk: int = 0,
):
    """Trace-time body shared by the one-round and fused-round programs.

    ``node_chunk``: train the N nodes ``node_chunk`` at a time via a
    ``lax.scan`` of vmapped chunks instead of one N-wide vmap. Activation
    memory scales with nodes-in-flight, so chunking buys HBM headroom for
    a richer selective-remat policy (``TransformerConfig.remat_policy``) —
    the 0.98B bench row trades 4× fewer nodes in flight for skipping the
    FFN recompute entirely, a net model-MFU win. 0 = single vmap.
    """
    n = mask.shape[0]

    def node_fn(lora, opt_state, x, y, idx):
        def epoch_body(carry, ep_idx):
            lo, o = carry
            xs = jnp.take(x, ep_idx, axis=0)
            ys = jnp.take(y, ep_idx, axis=0)

            def step(c, batch):
                lo_, o_ = c
                bx, by = batch

                def loss_of(lo__, bx_, by_):
                    return _lm_loss(lo__, base, module, bx_, by_)

                if remat:
                    # recompute transformer activations in the backward
                    # instead of the scan storing every batch's (HBM↔FLOPs)
                    loss_of = jax.checkpoint(loss_of)
                (loss, _), grads = jax.value_and_grad(loss_of, has_aux=True)(
                    lo_, bx, by
                )
                updates, o_ = tx.update(grads, o_, lo_)
                lo_ = optax.apply_updates(lo_, updates)
                return (lo_, o_), loss

            (lo, o), losses = jax.lax.scan(step, (lo, o), (xs, ys))
            return (lo, o), jnp.mean(losses)

        (lora, opt_state), losses = jax.lax.scan(epoch_body, (lora, opt_state), idx)
        return lora, opt_state, jnp.mean(losses)

    vmapped = jax.vmap(node_fn, in_axes=(0, 0, 0, 0, 0))
    if node_chunk and node_chunk < n:
        if n % node_chunk:
            raise ValueError(f"node_chunk {node_chunk} must divide n_nodes {n}")
        nc = n // node_chunk

        def chunked(tree):
            return jax.tree.map(
                lambda a: a.reshape(nc, node_chunk, *a.shape[1:]), tree
            )

        def chunk_body(_, args):
            return None, vmapped(*args)

        _, (trained, trained_opt, losses) = jax.lax.scan(
            chunk_body,
            None,
            (
                chunked(stacked_lora), chunked(opt_states),
                chunked(x_all), chunked(y_all), chunked(perm),
            ),
        )
        trained, trained_opt = jax.tree.map(
            lambda a: a.reshape(n, *a.shape[2:]), (trained, trained_opt)
        )
        losses = losses.reshape(n)
    else:
        trained, trained_opt, losses = vmapped(
            stacked_lora, opt_states, x_all, y_all, perm
        )

    def sel(new, old):
        m = mask.reshape((n,) + (1,) * (new.ndim - 1)).astype(new.dtype)
        return new * m + old * (1 - m)

    used = jax.tree.map(sel, trained, stacked_lora)
    agg_lora = _aggregate(used, mask, weights, sel_idx, agg, trim)
    out = jax.tree.map(lambda a: jnp.broadcast_to(a[None], (n, *a.shape)), agg_lora)
    if out_sharding is not None:
        out = jax.tree.map(lambda a: jax.lax.with_sharding_constraint(a, out_sharding), out)
    out_opt = trained_opt if keep_opt_state else jax.vmap(tx.init)(out)
    if out_sharding is not None:
        out_opt = jax.tree.map(
            lambda a: jax.lax.with_sharding_constraint(a, out_sharding), out_opt
        )
    return out, out_opt, jnp.mean(losses, where=mask.astype(bool))


_LORA_STATICS = (
    "module", "tx", "agg", "trim", "out_sharding", "keep_opt_state", "remat",
    "node_chunk",
)


@partial(jax.jit, static_argnames=_LORA_STATICS, donate_argnums=(0, 1))
def spmd_lora_round(
    stacked_lora, opt_states, base, x_all, y_all, perm, mask, weights, sel_idx, **kw
):
    return _lora_round_core(
        stacked_lora, opt_states, base, x_all, y_all, perm, mask, weights, sel_idx, **kw
    )


@partial(jax.jit, static_argnames=_LORA_STATICS, donate_argnums=(0, 1))
def spmd_lora_rounds_fused(
    stacked_lora, opt_states, base, x_all, y_all, perms, mask, weights, sel_idx, **kw
):
    """R LoRA federated rounds as ONE device dispatch (``lax.scan``).

    ``perms``: [R, N, epochs, nb, bs]. Adapters are tiny (config 5:
    57 k params/node), so a round is dispatch-dominated — fusing amortizes
    the host↔device round-trip R×, same as :func:`spmd_rounds_fused`.
    Returns (adapters', opt', losses [R]).
    """

    def body(carry, perm):
        p, o = carry
        out_p, out_o, loss = _lora_round_core(
            p, o, base, x_all, y_all, perm, mask, weights, sel_idx, **kw
        )
        return (out_p, out_o), loss

    (p, o), losses = jax.lax.scan(body, (stacked_lora, opt_states), perms)
    return p, o, losses


@partial(jax.jit, static_argnames=("module",))
def spmd_lora_eval(stacked_lora, base, x_test, y_test, *, module):
    def node_eval(lora, x, y):
        loss, logits = ce_eval(merge_params(base, lora), module, x, y)
        acc = jnp.mean((jnp.argmax(logits, axis=-1) == y).astype(jnp.float32))
        return loss, acc

    return jax.vmap(node_eval, in_axes=(0, 0, 0))(stacked_lora, x_test, y_test)


class SpmdLoraFederation(SpmdFederation):
    """SPMD federation over adapter subtrees; frozen base stored once."""

    def __init__(
        self,
        model: FlaxModel,
        datasets: list[FederatedDataset],
        mesh: Optional[Mesh] = None,
        model_parallel_base: bool = False,
        node_chunk: int = 0,
        **kwargs,
    ) -> None:
        lora0, base0 = split_lora(model.params)
        if not jax.tree.leaves(lora0):
            raise ValueError("model has no lora_* params")
        self._lora_template = lora0
        self._base_template = base0
        self._mp_base = model_parallel_base
        self.node_chunk = node_chunk
        super().__init__(model, datasets, mesh=mesh, **kwargs)

    # node-stacked state = adapters only; base placed separately
    def _stage_state(self) -> None:
        n = self.n

        @partial(jax.jit, out_shardings=(self._shard, self._shard))
        def stage(tree):
            stacked = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (n, *x.shape)), tree)
            return stacked, jax.vmap(self.tx.init)(stacked)

        self.params, self.opt_state = stage(self._lora_template)
        if self._mp_base:
            from p2pfl_tpu.parallel.sharding import shard_transformer

            self.base = shard_transformer(self.mesh, self._base_template)
        else:
            self.base = jax.device_put(self._base_template, self._repl)

    def run_round(self, epochs: int = 1) -> dict:
        from p2pfl_tpu.settings import Settings

        if self._vote and (self.round == 0 or Settings.VOTE_EVERY_ROUND):
            self.train_mask = self.elect_train_set()
        perm = self._make_perm(epochs)
        eff = self._effective_mask()
        mask = jax.device_put(jnp.asarray(eff), self._shard)
        sel_idx = jax.device_put(np.flatnonzero(eff).astype(np.int32), self._repl)
        self.params, self.opt_state, loss = spmd_lora_round(
            self.params,
            self.opt_state,
            self.base,
            self.x_all,
            self.y_all,
            perm,
            mask,
            self._samples,
            sel_idx,
            module=self.module,
            tx=self.tx,
            agg=self.aggregator,
            trim=self.trim,
            out_sharding=self._shard,
            keep_opt_state=self.keep_opt_state,
            remat=self.remat,
            node_chunk=self.node_chunk,
        )
        self.round += 1
        entry = {"round": self.round, "train_loss": loss}
        self.history.append(entry)
        return entry

    def run_fused(self, rounds: int, epochs: int = 1, eval: bool = False) -> list[dict]:  # noqa: A002
        """R adapter-federation rounds as ONE device dispatch.

        Same contract as :meth:`SpmdFederation.run_fused` (fixed train set
        for the span; no per-round voting). ``eval`` is not fused here —
        adapters are tiny, call :meth:`evaluate` where a curve is needed.
        """
        if eval:
            raise ValueError("SpmdLoraFederation.run_fused has no fused eval; call evaluate()")
        perms, mask, sel_idx = self._fused_inputs(rounds, epochs)
        self.params, self.opt_state, losses = spmd_lora_rounds_fused(
            self.params, self.opt_state, self.base, self.x_all, self.y_all,
            perms, mask, self._samples, sel_idx,
            module=self.module, tx=self.tx, agg=self.aggregator, trim=self.trim,
            out_sharding=self._shard, keep_opt_state=self.keep_opt_state,
            remat=self.remat, node_chunk=self.node_chunk,
        )
        entries = []
        for r in range(rounds):
            self.round += 1
            entry = {"round": self.round, "train_loss": losses[r]}
            self.history.append(entry)
            entries.append(entry)
        return entries

    def evaluate(self) -> dict:
        loss, acc = spmd_lora_eval(
            self.params, self.base, self.x_test, self.y_test, module=self.module
        )
        return {
            "test_loss": float(jnp.mean(loss)),
            "test_acc": float(jnp.mean(acc)),
            "per_node_acc": np.asarray(acc).tolist(),
        }

    def round_flops(self, epochs: int = 1) -> Optional[float]:
        """FLOPs of one LoRA round (scan-trip-count aware, VERDICT r2 #2).

        The base class's version lowers the FULL-model ``spmd_round``
        program, which is not what this federation runs. A LoRA round is
        step-dominated (the adapter aggregation is tiny next to the
        transformer fwd/bwd through the frozen base), so: one node's ONE
        SGD step from the shared scan-free probe × every step the round
        executes.
        """

        def loss_fn(lo, bx, by):
            return _lm_loss(lo, self.base, self.module, bx, by)[0]

        step = self._probe_step_flops(loss_fn)
        if step is None:
            return None
        return self.n * epochs * self._nb * step
