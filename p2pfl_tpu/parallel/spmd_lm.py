"""Full-parameter causal-LM federations: MoE expert parallelism and GPipe.

Round 2 left the MoE FFN and the GPipe pipeline as compile-tested islands —
unit tests and dryrun grad steps, but no federation actually *training*
through them (VERDICT r2 weak #3). This module closes that:

- :class:`SpmdLmFederation` — N federated nodes training a full-parameter
  transformer LM as ONE jitted round program on a 2-D ``(nodes, model)``
  mesh. Node-stacked state ``[N, ...]`` shards over ``nodes`` (federated
  data parallelism); MoE expert stacks ``[N, E, ...]`` additionally shard
  the expert axis over ``model`` (expert parallelism — XLA lowers the
  router's dispatch/combine einsums to token all-to-alls on ICI, same
  rules as ``parallel/sharding.py``). FedAvg is the usual masked weighted
  reduction over the ``nodes`` axis. dp × ep in one dispatch.

- :class:`PipelineFederation` — federated nodes whose local training runs
  a GPipe-pipelined model (``parallel/pipeline.py``: microbatches stream
  through layer stages via ``ppermute``). In a real deployment each node
  IS its own slice — the pipeline rides ICI inside the slice and the
  federation exchanges weights across slices over DCN. A single-process
  simulation has one mesh, so nodes time-share it: each runs its jitted
  pipelined epoch in turn, then a host-side sample-weighted FedAvg (the
  stand-in for the DCN exchange) closes the round. Same per-node program,
  same collectives as the real topology.

The reference has no notion of either axis (SURVEY §2.9: federated data
parallelism only); these compose the reference's round semantics with the
TPU parallelism the rebuild is for.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import adam, ce_eval
from p2pfl_tpu.models.base import FlaxModel, apply_with_aux
from p2pfl_tpu.ops.aggregation import fedavg
from p2pfl_tpu.ops.tree import tree_stack
from p2pfl_tpu.parallel.mesh import federation_mesh
from p2pfl_tpu.parallel.pipeline import pipeline_mesh, pipelined_lm_apply
from p2pfl_tpu.parallel.spmd import SpmdFederation, _aggregate
from p2pfl_tpu.settings import Settings

Pytree = Any


def _lm_round_core(
    stacked,  # [N, ...] full params
    opt_states,  # [N, ...]
    x_all,  # [N, S, T] int tokens
    y_all,  # [N, S, T] next-token targets
    perm,  # [N, epochs, nb, bs]
    mask,  # [N]
    weights,  # [N]
    sel_idx,  # [K]
    *,
    module,
    tx,
    agg: str = "fedavg",
    trim: int = 0,
    out_sharding=None,
    keep_opt_state: bool = False,
    remat: bool = False,
):
    """Trace-time body: local scan-epochs per node, then masked aggregation.

    Mirrors ``spmd_lora._lora_round_core`` with the base/adapter split
    removed — the whole parameter tree trains and federates. The LM loss
    includes the sown MoE auxiliary losses (router balance + z-loss), so
    MoE routers learn *through the federation*.
    """
    n = mask.shape[0]

    def node_fn(p, o, x, y, idx):
        def epoch_body(carry, ep_idx):
            p_, o_ = carry
            xs = jnp.take(x, ep_idx, axis=0)
            ys = jnp.take(y, ep_idx, axis=0)

            def step(c, batch):
                p__, o__ = c
                bx, by = batch

                def loss_of(pp, bx_, by_):
                    logits, aux = apply_with_aux(module, pp, bx_)
                    ce = optax.softmax_cross_entropy_with_integer_labels(
                        logits, by_
                    ).mean()
                    return ce + aux

                if remat:
                    loss_of = jax.checkpoint(loss_of)
                loss, grads = jax.value_and_grad(loss_of)(p__, bx, by)
                updates, o__ = tx.update(grads, o__, p__)
                return (optax.apply_updates(p__, updates), o__), loss

            (p_, o_), losses = lax.scan(step, (p_, o_), (xs, ys))
            return (p_, o_), jnp.mean(losses)

        (p, o), losses = lax.scan(epoch_body, (p, o), idx)
        return p, o, jnp.mean(losses)

    trained, trained_opt, losses = jax.vmap(node_fn, in_axes=(0, 0, 0, 0, 0))(
        stacked, opt_states, x_all, y_all, perm
    )

    def sel(new, old):
        m = mask.reshape((n,) + (1,) * (new.ndim - 1)).astype(new.dtype)
        return new * m + old * (1 - m)

    used = jax.tree.map(sel, trained, stacked)
    agg_params = _aggregate(used, mask, weights, sel_idx, agg, trim)
    out = jax.tree.map(lambda a: jnp.broadcast_to(a[None], (n, *a.shape)), agg_params)
    if out_sharding is not None:
        shard_tree = out_sharding.tree()  # _ShardTree static arg
        out = jax.tree.map(
            lambda a, s: jax.lax.with_sharding_constraint(a, s), out, shard_tree
        )
    out_opt = trained_opt if keep_opt_state else jax.vmap(tx.init)(out)
    return out, out_opt, jnp.mean(losses, where=mask.astype(bool))


_LM_STATICS = ("module", "tx", "agg", "trim", "out_sharding", "keep_opt_state", "remat")


@partial(jax.jit, static_argnames=_LM_STATICS, donate_argnums=(0, 1))
def spmd_lm_round(stacked, opt_states, x_all, y_all, perm, mask, weights, sel_idx, **kw):
    return _lm_round_core(
        stacked, opt_states, x_all, y_all, perm, mask, weights, sel_idx, **kw
    )


@partial(jax.jit, static_argnames=_LM_STATICS, donate_argnums=(0, 1))
def spmd_lm_rounds_fused(
    stacked, opt_states, x_all, y_all, perms, mask, weights, sel_idx, **kw
):
    """R LM-federation rounds as ONE device dispatch (``lax.scan``).

    ``perms``: [R, N, epochs, nb, bs]. Fixed train set for the span (no
    per-round voting). Returns (params', opt', losses [R]).

    When fusing pays, measured: it amortizes the host↔device round trip,
    which only matters when rounds are DISPATCH-dominated — tiny federated
    state like config 5's LoRA adapters (0.40 → 0.15 s/round). For
    compute-bound full-parameter federations the fused scan's whole-state
    carry makes XLA's scheduling WORSE, not better: config 10's MoE
    federation measured 0.78 s/round unfused vs 3.4 s/round fused on the
    chip. Default to :meth:`SpmdLmFederation.run_round`; reach for fused
    only after measuring.
    """

    def body(carry, perm):
        p, o = carry
        out_p, out_o, loss = _lm_round_core(
            p, o, x_all, y_all, perm, mask, weights, sel_idx, **kw
        )
        return (out_p, out_o), loss

    (p, o), losses = jax.lax.scan(body, (stacked, opt_states), perms)
    return p, o, losses


@partial(jax.jit, static_argnames=("module",))
def spmd_lm_eval(stacked, x_test, y_test, *, module):
    def node_eval(p, x, y):
        loss, logits = ce_eval(p, module, x, y)
        acc = jnp.mean((jnp.argmax(logits, axis=-1) == y).astype(jnp.float32))
        return loss, acc

    return jax.vmap(node_eval, in_axes=(0, 0, 0))(stacked, x_test, y_test)


class SpmdLmFederation(SpmdFederation):
    """Full-parameter LM federation on a ``(nodes, model)`` mesh.

    dp × tp × ep in one program: ``expert_parallel`` sets the
    ``model``-axis size of the default mesh; MoE expert stacks shard
    their expert axis over it per the rules in ``parallel/sharding.py``
    (``mlp/w[123]``, router replicated) — and the SAME rules
    column/row-shard the dense attention and MLP projections
    (Megatron-style tensor parallelism), so dense transformers use the
    ``model`` axis too. The point of this class is federations whose
    per-node model exceeds one chip's appetite along either axis.
    """

    def __init__(
        self,
        model: FlaxModel,
        datasets: list[FederatedDataset],
        mesh: Optional[Mesh] = None,
        expert_parallel: int = 1,
        **kwargs,
    ) -> None:
        for unsupported in ("scaffold", "server_opt", "dp_clip", "dp_noise", "prox_mu"):
            if kwargs.get(unsupported):
                raise ValueError(f"SpmdLmFederation does not support {unsupported}")
        if mesh is None:
            # mirror SpmdFederation._default_mesh: pick the largest slot
            # count the logical nodes fold onto evenly, and pass the exact
            # device subset — federation_mesh refuses to strand devices
            # silently (ISSUE 10 satellite), so the subset is explicit here
            devices = jax.devices()
            n = len(datasets)
            slots = min(n, len(devices) // expert_parallel)
            if slots < 1:
                # expert_parallel wider than the device count: the old
                # direct federation_mesh call raised here too — keep the
                # failure at construction, not as a 0-slot mesh downstream
                raise ValueError(
                    f"expert_parallel={expert_parallel} needs at least "
                    f"{expert_parallel} devices, have {len(devices)}"
                )
            while slots > 1 and n % slots != 0:
                slots -= 1
            mesh = federation_mesh(
                n_nodes=slots,
                model_parallel=expert_parallel,
                devices=devices[: slots * expert_parallel],
            )
        super().__init__(model, datasets, mesh=mesh, **kwargs)

    def _node_stacked_shardings(self, params: Pytree) -> Pytree:
        """P(nodes, *tp_spec) per leaf — the tp/ep rules shifted one axis
        right to make room for the node-stacking axis."""
        from p2pfl_tpu.parallel.sharding import _path_str, partition_spec_for

        nodes = Settings.MESH_NODES_AXIS

        def one(key_path, leaf):
            spec = partition_spec_for(_path_str(key_path))
            fixed: list = [nodes]
            for i, axis in enumerate(spec):
                if axis is None:
                    fixed.append(None)
                    continue
                size = self.mesh.shape[axis]
                if i < leaf.ndim and leaf.shape[i] % size == 0:
                    fixed.append(axis)
                else:
                    fixed.append(None)
            return NamedSharding(self.mesh, P(*fixed))

        return jax.tree_util.tree_map_with_path(one, params)

    def _stage_state(self) -> None:
        n = self.n
        self._param_shard = self._node_stacked_shardings(self.model.params)

        @partial(jax.jit, out_shardings=self._param_shard)
        def stage(tree):
            return jax.tree.map(lambda x: jnp.broadcast_to(x[None], (n, *x.shape)), tree)

        self.params = stage(self.model.params)
        # opt-state moments inherit the param shardings through GSPMD
        # propagation (explicit out_shardings would need an optax-state
        # pytree of specs for no benefit)
        self.opt_state = jax.jit(jax.vmap(self.tx.init))(self.params)
        self._server_t = 0

    # hashability for jit static args: tuple-ize the sharding pytree
    def _out_sharding_static(self):
        leaves, treedef = jax.tree_util.tree_flatten(self._param_shard)
        return _ShardTree(tuple(leaves), treedef)

    def run_round(self, epochs: int = 1) -> dict:
        if self._vote and (self.round == 0 or Settings.VOTE_EVERY_ROUND):
            self.train_mask = self.elect_train_set()
        perm = self._make_perm(epochs)
        eff = self._effective_mask()
        mask = jax.device_put(jnp.asarray(eff), self._shard)
        sel_idx = jax.device_put(np.flatnonzero(eff).astype(np.int32), self._repl)
        self.params, self.opt_state, loss = spmd_lm_round(
            self.params,
            self.opt_state,
            self.x_all,
            self.y_all,
            perm,
            mask,
            self._samples,
            sel_idx,
            module=self.module,
            tx=self.tx,
            agg=self.aggregator,
            trim=self.trim,
            out_sharding=self._out_sharding_static(),
            keep_opt_state=self.keep_opt_state,
            remat=self.remat,
        )
        self.round += 1
        entry = {"round": self.round, "train_loss": loss}
        self.history.append(entry)
        return entry

    def run_fused(self, rounds: int, epochs: int = 1) -> list[dict]:
        """R rounds in ONE dispatch (fixed train set for the span)."""
        perms, mask, sel_idx = self._fused_inputs(rounds, epochs)
        self.params, self.opt_state, losses = spmd_lm_rounds_fused(
            self.params, self.opt_state, self.x_all, self.y_all,
            perms, mask, self._samples, sel_idx,
            module=self.module, tx=self.tx, agg=self.aggregator, trim=self.trim,
            out_sharding=self._out_sharding_static(),
            keep_opt_state=self.keep_opt_state, remat=self.remat,
        )
        entries = []
        for r in range(rounds):
            self.round += 1
            entry = {"round": self.round, "train_loss": losses[r]}
            self.history.append(entry)
            entries.append(entry)
        return entries

    def evaluate(self) -> dict:
        loss, acc = spmd_lm_eval(self.params, self.x_test, self.y_test, module=self.module)
        return {
            "test_loss": float(jnp.mean(loss)),
            "test_acc": float(jnp.mean(acc)),
            "per_node_acc": np.asarray(acc).tolist(),
        }

    def round_flops(self, epochs: int = 1) -> Optional[float]:
        """Scan-aware FLOPs of one LM-federation round: the shared scan-free
        probe of one node's one SGD step × every step the round runs (the
        FedAvg reduction is negligible next to the transformer fwd/bwd)."""

        def loss_fn(p, bx, by):
            logits, aux = apply_with_aux(self.module, p, bx)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, by).mean()
            return ce + aux

        step = self._probe_step_flops(loss_fn)
        if step is None:
            return None
        return self.n * epochs * self._nb * step


class _ShardTree:
    """Hashable wrapper so a sharding pytree can ride a jit static arg."""

    def __init__(self, leaves: tuple, treedef) -> None:
        self.leaves = leaves
        self.treedef = treedef

    def tree(self):
        return jax.tree_util.tree_unflatten(self.treedef, list(self.leaves))

    def __hash__(self) -> int:
        return hash((self.leaves, self.treedef))

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, _ShardTree)
            and self.leaves == other.leaves
            and self.treedef == other.treedef
        )


class PipelineFederation:
    """Federated nodes whose local training is GPipe-pipelined.

    Each round: every node starts from the global model, runs ``epochs``
    jitted pipelined epochs on the pipe mesh (one node at a time — the
    single-process stand-in for per-node slices), then the round closes
    with a sample-weighted FedAvg on host (the DCN weight exchange).
    Matches the reference's round semantics (all nodes train, FedAvg,
    fresh optimizer per round unless ``keep_opt_state``).
    """

    def __init__(
        self,
        model: FlaxModel,
        datasets: list[FederatedDataset],
        mesh: Optional[Mesh] = None,
        n_stages: int = 0,
        batch_size: int = 8,
        learning_rate: float = 1e-3,
        n_micro: int = 0,
        keep_opt_state: bool = False,
        seed: int = 0,
    ) -> None:
        cfg = model.extra.get("config")
        if cfg is None:
            raise ValueError("model must be a tiny_transformer-built CausalLM")
        if n_stages == 0 and mesh is None:
            n_stages = max(
                s for s in range(1, len(jax.devices()) + 1) if cfg.n_layers % s == 0
            )
        self.mesh = mesh if mesh is not None else pipeline_mesh(n_stages)
        self.axis = self.mesh.axis_names[0]
        if cfg.n_layers % self.mesh.shape[self.axis] != 0:
            raise ValueError(
                f"{cfg.n_layers} layers not divisible into {self.mesh.shape[self.axis]} stages"
            )
        self.cfg = cfg
        self.model = model
        self.params = model.params
        self.n = len(datasets)
        self.batch_size = batch_size
        self.n_micro = n_micro or self.mesh.shape[self.axis]
        if batch_size % self.n_micro != 0:
            raise ValueError(f"batch {batch_size} not divisible into {self.n_micro} microbatches")
        self.tx = adam(learning_rate)
        self.keep_opt_state = keep_opt_state
        self._opts = [self.tx.init(self.params) for _ in range(self.n)] if keep_opt_state else None
        self._rng = np.random.default_rng(seed)
        self.datasets = datasets
        smallest = min(d.num_samples for d in datasets)
        if smallest < batch_size:
            # an undersized shard would yield ZERO scan steps and a NaN
            # round loss with params silently unchanged
            raise ValueError(f"smallest shard ({smallest}) < batch size ({batch_size})")
        self._samples = np.asarray([d.num_samples for d in datasets], np.float32)
        self.round = 0
        self.history: list[dict] = []
        self.last_profile: Optional[dict] = None

        mesh_, axis_, n_micro_, cfg_ = self.mesh, self.axis, self.n_micro, cfg
        # thread the model's attention backend into the pipeline stages: a
        # model built with attn="flash" (or a cfg-pinned FlashConfig) keeps
        # its statically-keyed kernel schedule inside the pipelined jits —
        # the closure captures cfg_ and attn_fn_, so a federation rebuilt
        # with a different schedule compiles a different program
        attn_fn_ = getattr(model.module, "attn_fn", None)

        def epoch(params, opt_state, xs, ys):
            """One pipelined epoch: scan of GPipe train steps over batches."""

            def step(carry, batch):
                p, o = carry
                bx, by = batch

                def loss_of(pp):
                    logits, aux = pipelined_lm_apply(
                        pp, bx, cfg_, mesh_, axis_, n_micro=n_micro_,
                        attn_fn=attn_fn_, return_aux=True
                    )
                    ce = optax.softmax_cross_entropy_with_integer_labels(
                        logits, by
                    ).mean()
                    return ce + aux

                loss, grads = jax.value_and_grad(loss_of)(p)
                updates, o = self.tx.update(grads, o, p)
                return (optax.apply_updates(p, updates), o), loss

            (params, opt_state), losses = lax.scan(step, (params, opt_state), (xs, ys))
            return params, opt_state, jnp.mean(losses)

        self._epoch = jax.jit(epoch)

        def eval_acc(params, x, y):
            logits, _aux = pipelined_lm_apply(
                params, x, cfg_, mesh_, axis_, n_micro=n_micro_,
                attn_fn=attn_fn_, return_aux=True
            )
            return jnp.mean((jnp.argmax(logits, axis=-1) == y).astype(jnp.float32))

        self._eval = jax.jit(eval_acc)

    def _node_batches(self, i: int, epochs: int):
        d = self.datasets[i]
        nb = d.num_samples // self.batch_size
        for _ in range(epochs):
            idx = self._rng.permutation(d.num_samples)[: nb * self.batch_size]
            idx = idx.reshape(nb, self.batch_size)
            yield jnp.asarray(d.x_train[idx]), jnp.asarray(d.y_train[idx])

    def run_round(self, epochs: int = 1, profile: bool = False) -> dict:
        """One federated round; ``profile=True`` adds per-node host syncs.

        The default keeps dispatch fully async (node i+1's epochs enqueue
        while node i computes); profiling inserts a ``block_until_ready``
        per node to attribute wall time, which serializes the round.
        """
        import time

        prof = {"node_epoch_s": [0.0] * self.n, "fedavg_s": 0.0}
        trained, losses = [], []
        for i in range(self.n):
            p = self.params
            o = self._opts[i] if self.keep_opt_state else self.tx.init(p)
            t0 = time.monotonic()
            loss = None
            for xs, ys in self._node_batches(i, epochs):
                p, o, loss = self._epoch(p, o, xs, ys)
            if loss is None:
                # zero batches for this node (epochs=0, or a shard shrunk
                # under batch_size after construction): params are the
                # untouched global — keep them in the FedAvg stack (its
                # weights are positional) but contribute no loss term
                from p2pfl_tpu.management.logger import logger

                logger.warning(
                    "pipeline-fed",
                    f"node {i} produced zero batches this round — skipping its loss",
                )
                trained.append(p)
                continue
            if profile:
                jax.block_until_ready(loss)
                prof["node_epoch_s"][i] = round(time.monotonic() - t0, 3)
            if self.keep_opt_state:
                self._opts[i] = o
            trained.append(p)
            losses.append(loss)
        # host-side FedAvg — the DCN weight exchange between slices
        t0 = time.monotonic()
        stacked = tree_stack(trained)
        self.params = fedavg(stacked, jnp.asarray(self._samples))
        if profile:
            jax.block_until_ready(self.params)
            prof["fedavg_s"] = round(time.monotonic() - t0, 3)
        # stale profiles must not be attributed to an unprofiled round
        self.last_profile = prof if profile else None
        self.round += 1
        entry = {
            "round": self.round,
            "train_loss": float(np.mean([float(x) for x in losses])) if losses else float("nan"),
        }
        self.history.append(entry)
        return entry

    def evaluate(self) -> dict:
        accs = []
        for d in self.datasets:
            n = (len(d.y_test) // self.batch_size) * self.batch_size
            if n == 0:
                raise ValueError(f"test split smaller than one batch ({len(d.y_test)})")
            acc = []
            for s in range(0, n, self.batch_size):
                acc.append(
                    float(
                        self._eval(
                            self.params,
                            jnp.asarray(d.x_test[s : s + self.batch_size]),
                            jnp.asarray(d.y_test[s : s + self.batch_size]),
                        )
                    )
                )
            accs.append(float(np.mean(acc)))
        return {"test_acc": float(np.mean(accs)), "per_node_acc": accs}
