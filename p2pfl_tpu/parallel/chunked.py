"""Time-shared federation: N logical nodes streamed through one chip in chunks.

BASELINE config 3's nameplate is 64 ResNet-50 nodes — 64 × (params + 2
Adam moments) ≈ 19.6 GB of node-stacked state, over a single v5e's HBM.
:class:`SpmdFederation` holds all N nodes resident, so it can only fold the
node count down (round 3 measured a 16-node proxy). This module runs the
STATED node count by time-sharing the chip instead — the same pattern
:class:`~p2pfl_tpu.parallel.spmd_lm.PipelineFederation` uses for stages,
applied to the federated-node axis:

- nodes process in chunks of ``chunk_size``; each chunk's jitted program
  broadcasts the round-start aggregate to its C slots, runs the vmapped
  local epochs, and reduces the trained models to a weighted partial sum
  ON DEVICE;
- FedAvg becomes a running (partial-sum, weight) accumulation across
  chunks, so the resident set is one aggregate + one chunk's workspace —
  nothing per-node ever leaves the device or lands in host RAM;
- the running sums ride DONATED accumulator arguments through the chunk
  program (``Settings.CHUNK_FUSED_REDUCE`` / ``CHUNK_DONATE_BUFFERS``):
  chunk k's partial sum updates in place inside the same dispatch that
  trains the chunk, instead of the host issuing 2×leaf-count eager adds
  between chunks — the serialization the round-5 ``gap_attribution``
  measured behind "broadcast + fp32 reduce";
- chunk inputs are staged ``Settings.CHUNK_STAGING_DEPTH`` chunks ahead
  (double buffering at the default 2): chunk k+1's host→device copies
  (perm indices, and x/y when ``resident=False`` streams the dataset
  from host RAM) overlap chunk k's compute instead of following it;
- optimizer moments are AGGREGATED with the same weighted mean as the
  params ("federated moment averaging"). Per-node moments would need
  N × 2 × params of storage — exactly the state that doesn't fit — and
  host-swapping them through the axon tunnel costs more than the round's
  compute. Every node therefore starts a round from (aggregate params,
  aggregate moments); step counts (integer optax leaves) pass through
  unchanged so warmup-cosine schedules keep ticking across rounds.
  This is a documented DIVERGENCE from :class:`SpmdFederation`'s
  per-node ``keep_opt_state``; config 3's convergence curve is the
  evidence it trains (the round-2 lesson — fresh moments every round —
  flatlined; averaged moments preserve the schedule and the moment
  scale).

FedAvg only: one streaming pass cannot compute coordinate-wise medians or
Krum distances, which need all K models simultaneously (use
:class:`SpmdFederation` at a node count that fits for those).

The reference has no analogue (its scale ceiling is one process per node,
SURVEY §2.9); this exists so the v4-128-sized configs EXECUTE on one chip,
slower, instead of shrinking to a proxy (VERDICT r3 #3).
"""

from __future__ import annotations

import random
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import _loss, adam
from p2pfl_tpu.models.base import FlaxModel
from p2pfl_tpu.parallel.spmd import _local_epoch, elect_train_set_mask
from p2pfl_tpu.settings import Settings

Pytree = Any


def _is_inexact(x) -> bool:
    return jnp.issubdtype(x.dtype, jnp.inexact)


def _chunk_contrib(agg_params, agg_opt, x, y, perm, mask, weights, module, tx, remat):
    """One chunk's round contribution (trace-time body).

    Broadcast the aggregate to C slots, run each slot's scan-epochs, and
    reduce to (weighted param sum, weighted opt sum, total weight, loss).
    Masked slots train but contribute zero weight (static shapes; the
    host skips fully-masked chunks entirely).
    """
    c = mask.shape[0]
    stacked = jax.tree.map(lambda a: jnp.broadcast_to(a[None], (c, *a.shape)), agg_params)
    opts = jax.tree.map(lambda a: jnp.broadcast_to(a[None], (c, *a.shape)), agg_opt)

    def node_fn(p, o, x_, y_, idx):
        def epoch(carry, ep_idx):
            p_, o_ = carry
            xs = jnp.take(x_, ep_idx, axis=0)
            ys = jnp.take(y_, ep_idx, axis=0)
            p_, o_, loss = _local_epoch(p_, o_, xs, ys, module, tx, remat)
            return (p_, o_), loss

        (p, o), losses = lax.scan(epoch, (p, o), idx)
        return p, o, jnp.mean(losses)

    trained, t_opt, losses = jax.vmap(node_fn)(stacked, opts, x, y, perm)
    w = (mask * weights).astype(jnp.float32)
    psum = jax.tree.map(
        lambda t: jnp.tensordot(w, t.astype(jnp.float32), axes=(0, 0)), trained
    )
    osum = jax.tree.map(
        lambda t: jnp.tensordot(w, t.astype(jnp.float32), axes=(0, 0))
        if _is_inexact(t)
        else t[0],
        t_opt,
    )
    denom = jnp.maximum(jnp.sum(w), 1e-9)
    loss = jnp.sum(losses * w) / denom
    return psum, osum, jnp.sum(w), loss


@partial(jax.jit, static_argnames=("module", "tx", "remat"))
def _chunk_round(agg_params, agg_opt, x, y, perm, mask, weights, *, module, tx, remat):
    """Serial-path chunk program: contribution only, reduce on host.

    Kept verbatim as the reference semantics — the overlapped path's
    bit-parity test (tests/test_chunked.py) compares against it.
    """
    return _chunk_contrib(agg_params, agg_opt, x, y, perm, mask, weights, module, tx, remat)


def _chunk_round_acc_impl(
    psum, osum, wsum, loss_sum, agg_params, agg_opt, x, y, perm, mask, weights,
    *, module, tx, remat,
):
    """Fused-reduce chunk program: train the chunk AND fold its weighted
    contribution into the running accumulators in the same dispatch.

    fp32 zero-init + in-program adds keep the accumulation order identical
    to the host-side serial reduce (0 + x ≡ x in fp32), so the overlapped
    path stays numerically exact against it. Integer opt leaves (schedule
    step counts) are identical across chunks; the chunk's own value passes
    through.
    """
    p_c, o_c, w_c, l_c = _chunk_contrib(
        agg_params, agg_opt, x, y, perm, mask, weights, module, tx, remat
    )
    psum = jax.tree.map(jnp.add, psum, p_c)
    osum = jax.tree.map(
        lambda a, b: jnp.add(a, b) if _is_inexact(b) else b, osum, o_c
    )
    return psum, osum, wsum + w_c, loss_sum + l_c * w_c


# donated variant: XLA writes each chunk's updated sums into the same HBM
# buffers (no fresh full-model allocation per chunk); the plain variant is
# the CHUNK_DONATE_BUFFERS=False debugging path
_chunk_round_acc_donated = partial(
    jax.jit, static_argnames=("module", "tx", "remat"), donate_argnums=(0, 1, 2, 3)
)(_chunk_round_acc_impl)
_chunk_round_acc_plain = partial(
    jax.jit, static_argnames=("module", "tx", "remat")
)(_chunk_round_acc_impl)


@jax.jit
def _zero_acc(params, opt_state):
    """Fresh on-device accumulators (fp32 sums, zero weight/loss)."""
    psum = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32), params)
    osum = jax.tree.map(
        lambda a: jnp.zeros(a.shape, jnp.float32 if _is_inexact(a) else a.dtype),
        opt_state,
    )
    return psum, osum, jnp.float32(0.0), jnp.float32(0.0)


def _finalize_impl(psum, osum, wsum, params_ref, opt_ref, *, tx, keep_opt):
    """Divide the accumulated sums into the new aggregate (one dispatch)."""
    params = jax.tree.map(lambda s, ref: (s / wsum).astype(ref.dtype), psum, params_ref)
    if keep_opt:
        opt = jax.tree.map(
            lambda s, ref: (s / wsum).astype(ref.dtype) if _is_inexact(ref) else s,
            osum,
            opt_ref,
        )
    else:
        opt = tx.init(params)
    return params, opt


# keep_opt reads osum (donate both sums); the fresh-opt variant leaves osum
# untouched, so donating it would only emit an unused-donation warning
_finalize_keep = partial(
    jax.jit, static_argnames=("tx", "keep_opt"), donate_argnums=(0, 1)
)(_finalize_impl)
_finalize_fresh = partial(
    jax.jit, static_argnames=("tx", "keep_opt"), donate_argnums=(0,)
)(_finalize_impl)


@partial(jax.jit, static_argnames=("module",))
def _chunk_eval(agg_params, x_t, y_t, *, module):
    def one(x, y):
        loss, logits = _loss(agg_params, module, x, y)
        acc = jnp.mean((jnp.argmax(logits, axis=-1) == y).astype(jnp.float32))
        return loss, acc

    return jax.vmap(one)(x_t, y_t)


class ChunkedFederation:
    """N-node FedAvg federation streamed through the chip ``chunk_size``
    nodes at a time. Same round semantics as :class:`SpmdFederation`
    (reference round loop, §3.3) except the moment-averaging divergence
    documented in the module docstring."""

    def __init__(
        self,
        model: FlaxModel,
        datasets: list[FederatedDataset],
        chunk_size: int,
        batch_size: int = 128,
        learning_rate: float = 1e-3,
        keep_opt_state: bool = False,
        remat: bool = False,
        vote: bool = False,
        seed: int = 0,
        tx: Optional[optax.GradientTransformation] = None,
        resident: bool = True,
    ) -> None:
        self.model = model
        self.module = model.module
        self._resident = resident
        self.n = len(datasets)
        if self.n % chunk_size != 0:
            raise ValueError(f"{self.n} nodes not divisible into chunks of {chunk_size}")
        self._chunk_size = chunk_size
        self.datasets = datasets
        self.batch_size = batch_size
        self.tx = tx if tx is not None else adam(learning_rate)
        self.keep_opt_state = keep_opt_state
        self.remat = remat
        self._vote = vote
        self._rng = np.random.default_rng(seed)
        self._py_rng = random.Random(seed)

        sizes = [d.num_samples for d in datasets]
        tr_min, tr_max = min(sizes), max(sizes)
        if tr_min < batch_size:
            raise ValueError(f"smallest shard ({tr_min}) < batch size ({batch_size})")
        te_min = min(len(d.y_test) for d in datasets)

        # whole-federation data stays on device (config 3: ~200 MB — it's
        # the PER-NODE STATE that doesn't fit, not the data), PRE-SPLIT
        # into per-chunk arrays: slicing a device array per round per chunk
        # materializes a fresh copy every time (measured as pure HBM-copy
        # overhead on the round path); staging the slices once removes it
        self._tr_max = tr_max
        self._stage_chunks()
        self.x_test = jax.device_put(np.stack([d.x_test[:te_min] for d in datasets]))
        self.y_test = jax.device_put(np.stack([d.y_test[:te_min] for d in datasets]))
        self._sizes = sizes
        self._samples = np.asarray(sizes, np.float32)
        self._nb = tr_min // batch_size

        self.train_mask = np.ones(self.n, dtype=np.float32)
        self.active_mask = np.ones(self.n, dtype=np.float32)
        self.round = 0
        self.history: list[dict] = []
        self._stage_state()

    def _stage_chunks(self) -> None:
        # resident: rebuilt from the datasets each time (only at init and on
        # a chunk_size change) so no whole-federation numpy copy lives in
        # host RAM for the object's lifetime. resident=False keeps the
        # per-chunk numpy stacks IN host RAM instead — the mode for datasets
        # that don't fit HBM next to the model workspace; the round loop
        # streams them chunk-by-chunk, CHUNK_STAGING_DEPTH ahead of compute.
        c = self._chunk_size

        def wrap(a: np.ndarray) -> np.ndarray:
            if len(a) == self._tr_max:
                return a
            reps = -(-self._tr_max // len(a))
            return np.concatenate([a] * reps, axis=0)[: self._tr_max]

        xs = [
            np.stack([wrap(d.x_train) for d in self.datasets[c0 : c0 + c]])
            for c0 in range(0, self.n, c)
        ]
        ys = [
            np.stack([wrap(d.y_train) for d in self.datasets[c0 : c0 + c]])
            for c0 in range(0, self.n, c)
        ]
        if self._resident:
            self.x_chunks = [jax.device_put(x) for x in xs]
            self.y_chunks = [jax.device_put(y) for y in ys]
            self._x_np = self._y_np = None
        else:
            self._x_np, self._y_np = xs, ys
            self.x_chunks = self.y_chunks = None

    @property
    def chunk_size(self) -> int:
        return self._chunk_size

    @chunk_size.setter
    def chunk_size(self, value: int) -> None:
        # re-splitting the pre-staged per-chunk data keeps the round path
        # copy-free while letting callers retune the chunk size
        if self.n % value != 0:
            raise ValueError(f"{self.n} nodes not divisible into chunks of {value}")
        if value != self._chunk_size:
            self._chunk_size = value
            self._stage_chunks()

    def _stage_state(self) -> None:
        self.params = jax.device_put(self.model.params)
        self.opt_state = jax.jit(self.tx.init)(self.params)

    def reset(self, seed: int = 0) -> None:
        self._rng = np.random.default_rng(seed)
        self._py_rng = random.Random(seed)
        self.train_mask = np.ones(self.n, dtype=np.float32)
        self.active_mask = np.ones(self.n, dtype=np.float32)
        self.round = 0
        self.history = []
        self._stage_state()

    def drop_node(self, i: int) -> None:
        self.active_mask[i] = 0.0

    def restore_node(self, i: int) -> None:
        self.active_mask[i] = 1.0

    def elect_train_set(self) -> np.ndarray:
        """Reference vote semantics — delegates to
        :func:`~p2pfl_tpu.parallel.spmd.elect_train_set_mask`."""
        return elect_train_set_mask(self.n, self._py_rng)

    def _make_perm_np(self, epochs: int) -> np.ndarray:
        take = self._nb * self.batch_size
        return np.stack(
            [
                np.stack(
                    [
                        self._rng.permutation(self._sizes[i])[:take].reshape(
                            self._nb, self.batch_size
                        )
                        for _ in range(epochs)
                    ]
                )
                for i in range(self.n)
            ]
        ).astype(np.int32)

    def _stage_chunk_inputs(self, ci: int, perm_np: np.ndarray):
        """Start chunk ``ci``'s host→device transfers (async device_put)."""
        c, c0 = self._chunk_size, ci * self._chunk_size
        perm_d = jax.device_put(perm_np[c0 : c0 + c])
        if self._resident:
            return perm_d, self.x_chunks[ci], self.y_chunks[ci]
        return perm_d, jax.device_put(self._x_np[ci]), jax.device_put(self._y_np[ci])

    def run_round(self, epochs: int = 1, eval: bool = False) -> dict:  # noqa: A002
        if self._vote and (self.round == 0 or Settings.VOTE_EVERY_ROUND):
            self.train_mask = self.elect_train_set()
        perm_np = self._make_perm_np(epochs)
        eff = self.train_mask * self.active_mask
        if eff.sum() == 0:
            raise RuntimeError("no active train-set nodes left")

        c = self.chunk_size
        # fully-masked chunks contribute nothing: never staged, never dispatched
        live = [ci for ci in range(self.n // c) if eff[ci * c : ci * c + c].sum() > 0]
        # overlapped staging: keep DEPTH chunks' inputs in flight so chunk
        # k+1's host→device copies (perm indices; x/y when streaming
        # non-resident data) run while chunk k's program computes. Depth 1
        # reproduces the serial order (stage → dispatch → stage → ...).
        depth = max(1, int(Settings.CHUNK_STAGING_DEPTH))
        staged = {ci: self._stage_chunk_inputs(ci, perm_np) for ci in live[:depth]}

        def chunk_args(ci):
            c0 = ci * c
            perm_d, x_d, y_d = staged.pop(ci)
            return (
                x_d, y_d, perm_d,
                jnp.asarray(eff[c0 : c0 + c]),
                jnp.asarray(self._samples[c0 : c0 + c]),
            )

        # loss/weight accumulate ON DEVICE: a float() per chunk would block
        # the host until that chunk's whole jitted program finishes,
        # serializing chunk k+1's staging behind chunk k's compute and
        # defeating the async dispatch pipeline this class exists for
        if Settings.CHUNK_FUSED_REDUCE:
            # overlapped path: partial sums ride donated accumulator args
            # through the chunk program — one dispatch per chunk, no
            # host-side per-leaf adds between chunks
            step = (
                _chunk_round_acc_donated
                if Settings.CHUNK_DONATE_BUFFERS
                else _chunk_round_acc_plain
            )
            acc = _zero_acc(self.params, self.opt_state)
            for i, ci in enumerate(live):
                acc = step(
                    *acc, self.params, self.opt_state, *chunk_args(ci),
                    module=self.module, tx=self.tx, remat=self.remat,
                )
                if i + depth < len(live):
                    staged[live[i + depth]] = self._stage_chunk_inputs(
                        live[i + depth], perm_np
                    )
            psum, osum, wsum, loss_acc = acc
            fin = _finalize_keep if self.keep_opt_state else _finalize_fresh
            self.params, self.opt_state = fin(
                psum, osum, wsum, self.params, self.opt_state,
                tx=self.tx, keep_opt=self.keep_opt_state,
            )
        else:
            # serial reference path (CHUNK_FUSED_REDUCE=False): host-side
            # tree adds after every chunk — the bit-parity baseline
            psum = osum = None
            wsum = jnp.float32(0.0)
            loss_acc = jnp.float32(0.0)
            for i, ci in enumerate(live):
                p_c, o_c, w_c, l_c = _chunk_round(
                    self.params, self.opt_state, *chunk_args(ci),
                    module=self.module, tx=self.tx, remat=self.remat,
                )
                if i + depth < len(live):
                    staged[live[i + depth]] = self._stage_chunk_inputs(
                        live[i + depth], perm_np
                    )
                if psum is None:
                    psum, osum = p_c, o_c
                else:
                    psum = jax.tree.map(jnp.add, psum, p_c)
                    osum = jax.tree.map(
                        lambda a, b: jnp.add(a, b) if _is_inexact(a) else a, osum, o_c
                    )
                wsum = wsum + w_c
                loss_acc = loss_acc + l_c * w_c

            self.params = jax.tree.map(
                lambda s, ref: (s / wsum).astype(ref.dtype), psum, self.params
            )
            if self.keep_opt_state:
                self.opt_state = jax.tree.map(
                    lambda s, ref: (s / wsum).astype(ref.dtype) if _is_inexact(ref) else s,
                    osum,
                    self.opt_state,
                )
            else:
                self.opt_state = jax.jit(self.tx.init)(self.params)
        self.round += 1
        entry: dict = {"round": self.round, "train_loss": float(loss_acc / wsum)}
        if eval:
            entry.update(self.evaluate())
        self.history.append(entry)
        return entry

    def evaluate(self) -> dict:
        losses, accs = [], []
        for c0 in range(0, self.n, self.chunk_size):
            loss, acc = _chunk_eval(
                self.params,
                self.x_test[c0 : c0 + self.chunk_size],
                self.y_test[c0 : c0 + self.chunk_size],
                module=self.module,
            )
            losses.append(np.asarray(loss))
            accs.append(np.asarray(acc))
        return {
            "test_loss": float(np.mean(np.concatenate(losses))),
            "test_acc": float(np.mean(np.concatenate(accs))),
        }

    def round_flops(self, epochs: int = 1, hw: bool = False) -> Optional[float]:
        """Scan-aware FLOPs of one full round (all N nodes).

        ``hw=False``: model FLOPs (no remat recompute) — the useful-work
        numerator. ``hw=True``: the step probed WITH the round's actual
        ``jax.checkpoint``, so XLA's count includes the recompute — the
        executed-work numerator the resident SpmdFederation probes report
        (config 3's chunked-vs-resident MFU is only comparable on this one).
        """
        from p2pfl_tpu.management.profiling import compiled_flops

        def one_step(p, o, bx, by):
            def loss_fn(p_):
                return _loss(p_, self.module, bx, by)[0]

            if hw and self.remat:
                loss_fn = jax.checkpoint(loss_fn)
            loss, grads = jax.value_and_grad(loss_fn)(p)
            updates, o = self.tx.update(grads, o, p)
            return optax.apply_updates(p, updates), o, loss

        x0 = self.x_chunks[0] if self._resident else self._x_np[0]
        y0 = self.y_chunks[0] if self._resident else self._y_np[0]
        bx = jnp.asarray(x0[0, : self.batch_size])
        by = jnp.asarray(y0[0, : self.batch_size])
        step = compiled_flops(jax.jit(one_step), self.params, self.opt_state, bx, by)
        if step is None:
            return None
        return self.n * epochs * self._nb * step

    @classmethod
    def from_dataset(
        cls,
        model: FlaxModel,
        dataset: FederatedDataset,
        n_nodes: int,
        chunk_size: int,
        strategy: str = "iid",
        alpha: float = 0.5,
        **kwargs,
    ) -> "ChunkedFederation":
        shards = [dataset.partition(i, n_nodes, strategy, alpha) for i in range(n_nodes)]
        return cls(model, shards, chunk_size, **kwargs)
