"""Clients-axis mesh for the sharded megafleet engine.

The megafleet engine's only fleet-scale state is the per-client
parameter matrix ``w [N, dim+1]`` — everything else (global model
history, windows, counters) is version-count-sized. The sharded engine
(:func:`p2pfl_tpu.ops.fleet_kernels.run_fleet_program_sharded`)
therefore uses the simplest possible layout: a 1-D mesh over
``Settings.MESH_CLIENTS_AXIS`` with ``w`` block-sharded by client id
and the small state replicated on every device.

Ownership is the static block rule shared by the host layout code and
the device program:

- ``shard_capacity(n, p)`` → ``ncap = ceil(n / p)`` rows per shard;
- client ``i`` lives on shard ``i // ncap`` at local row ``i % ncap``;
- each shard carries ONE extra local row (``ncap``, the trash row) that
  masked scatters route dead lanes to, mirroring the chunked engine's
  global trash row.

Like :func:`~p2pfl_tpu.parallel.mesh.federation_mesh`, a request that
cannot be satisfied raises loudly instead of silently shrinking.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from p2pfl_tpu.settings import Settings


def shard_capacity(n_clients: int, n_shards: int) -> int:
    """Client rows OWNED per shard (excluding its trash row):
    ``ceil(n_clients / n_shards)``. The last shard may own fewer real
    clients; its surplus rows are padding that no event ever addresses.
    """
    if n_clients < 1 or n_shards < 1:
        raise ValueError(
            f"n_clients={n_clients}, n_shards={n_shards} must both be >= 1"
        )
    return -(-n_clients // n_shards)


def fleet_clients_mesh(
    n_shards: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the 1-D ``(clients,)`` mesh of the sharded fleet engine.

    ``n_shards`` defaults to every available device. Asking for more
    shards than devices raises (the engine cannot oversubscribe — each
    shard is one device's program); asking for fewer takes the FIRST
    ``n_shards`` devices, which is deliberate and loud in the docstring
    rather than an error: the bench sweeps 1/2/4/8 shards on one host.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices) if n_shards is None else int(n_shards)
    if n < 1:
        raise ValueError(f"n_shards={n} must be >= 1")
    if n > len(devices):
        raise ValueError(
            f"n_shards={n} exceeds the {len(devices)} available devices; "
            "on CPU hosts set XLA_FLAGS=--xla_force_host_platform_device_count"
            f"={n} before importing jax to split the host"
        )
    return Mesh(np.array(devices[:n]), (Settings.MESH_CLIENTS_AXIS,))
