"""One-program SPMD federation: the ICI "transport".

The reference moves weights between nodes as pickled gRPC payloads and
aggregates them in Python (``p2pfl/learning/aggregators/fedavg.py:43-60``,
``grpc_client.py:142-179``). Here an entire federated round is ONE jitted
SPMD program over a ``(nodes, model)`` mesh:

- node-stacked params/opt-state/data arrays ``[N, ...]`` are sharded over
  the ``nodes`` axis — each chip owns its nodes' replicas;
- local training is a per-node ``lax.scan`` epoch, vectorized over the node
  axis (XLA partitions it across the mesh — zero communication);
- FedAvg is a masked, sample-weighted reduction over the node axis that XLA
  lowers to a single fp32 all-reduce over ICI, and the broadcast back is the
  reference's "diffusion" stage;
- election (the reference's vote protocol, ``vote_train_set_stage.py``) runs
  on host — it's a few hundred bytes — and enters the program as a ``[N]``
  mask.

Nothing touches the host inside a round: data lives device-resident across
rounds, per-round shuffles enter as ``[N, take]`` int32 index arrays.

Semantics preserved from the reference round (SURVEY §3.3): train-set
election in round 0 only, sample-weighted FedAvg over the train set,
aggregated model diffused to every node, optimizer state reset on
aggregation (the reference's ``set_parameters`` builds a fresh ``Trainer``
each round, ``lightning_learner.py:180-198``). Trades the reference's
asynchronous gossip for bulk-synchronous collectives — same round outcome,
orders of magnitude less overhead (SURVEY §7 "gossip semantics on
collectives").
"""

from __future__ import annotations

import math
import random
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import _loss, _prox_term, adam, ce_eval, sgd
from p2pfl_tpu.models.base import FlaxModel
from p2pfl_tpu.settings import Settings

Pytree = Any


# ---- pure round program (module-level => one jit cache for all federations) ----


def _local_epoch(
    params, opt_state, xs, ys, module, tx, remat: bool = False,
    prox_mu: float = 0.0, anchor=None, corr=None,
    dp_clip: float = 0.0, dp_noise: float = 0.0, key=None,
    accumulate_grads: bool = False,
):
    """One node's epoch: scan of SGD steps (identical math to JaxLearner).

    ``remat=True`` wraps the loss in :func:`jax.checkpoint`: the backward
    pass recomputes activations instead of the scan storing every batch's —
    the HBM↔FLOPs trade that lets big models (ResNet-50 × many nodes) train
    on one chip.

    ``prox_mu``/``anchor``: FedProx proximal pull toward the round's global
    model. ``corr``: SCAFFOLD control-variate correction ``c − c_i`` added
    to every step's gradient (pre-cast to the param dtype by the caller —
    the per-step ``astype`` is a no-op then). ``dp_clip > 0``: DP-SGD —
    per-example clipped grads + Gaussian noise (multiplier ``dp_noise``,
    rng ``key``). ``accumulate_grads=True`` additionally carries the fp32
    sum of RAW step gradients (pre-correction) through the scan and returns
    it as a fourth output — the SCAFFOLD fused-ci path derives each node's
    new control variate from it without retaining the round-start params.
    """
    import optax

    gsum0 = (
        jax.tree.map(lambda p_: jnp.zeros(p_.shape, jnp.float32), params)
        if accumulate_grads
        else None
    )

    if dp_clip > 0.0:
        from p2pfl_tpu.learning.privacy import dp_grads

        def loss_one(p_, xi, yi):
            loss = _loss(p_, module, xi[None], yi[None])[0]
            if prox_mu > 0.0:
                loss = loss + _prox_term(p_, anchor, prox_mu)
            return loss

        def dp_step(carry, batch):
            p, o, k, gs = carry
            x, y = batch
            k, sub = jax.random.split(k)
            grads, loss = dp_grads(loss_one, p, x, y, dp_clip, dp_noise, sub, remat=remat)
            if accumulate_grads:
                gs = jax.tree.map(lambda s, g: s + g.astype(jnp.float32), gs, grads)
            if corr is not None:
                grads = jax.tree.map(lambda g, c: g + c.astype(g.dtype), grads, corr)
            updates, o = tx.update(grads, o, p)
            p = optax.apply_updates(p, updates)
            return (p, o, k, gs), loss

        (params, opt_state, _, gsum), losses = jax.lax.scan(
            dp_step, (params, opt_state, key, gsum0), (xs, ys)
        )
        if accumulate_grads:
            return params, opt_state, jnp.mean(losses), gsum
        return params, opt_state, jnp.mean(losses)

    def step(carry, batch):
        p, o, gs = carry
        x, y = batch

        def loss_fn(p_):
            loss = _loss(p_, module, x, y)[0]  # CE + sown aux (canonical definition)
            if prox_mu > 0.0:
                loss = loss + _prox_term(p_, anchor, prox_mu)
            return loss

        if remat:
            loss_fn = jax.checkpoint(loss_fn)
        loss, grads = jax.value_and_grad(loss_fn)(p)
        if accumulate_grads:
            gs = jax.tree.map(lambda s, g: s + g.astype(jnp.float32), gs, grads)
        if corr is not None:
            grads = jax.tree.map(lambda g, c: g + c.astype(g.dtype), grads, corr)
        updates, o = tx.update(grads, o, p)
        p = optax.apply_updates(p, updates)
        return (p, o, gs), loss

    (params, opt_state, gsum), losses = jax.lax.scan(
        step, (params, opt_state, gsum0), (xs, ys)
    )
    if accumulate_grads:
        return params, opt_state, jnp.mean(losses), gsum
    return params, opt_state, jnp.mean(losses)


def _node_round_core(
    params,
    opt_state,
    xs,  # [E, nb, bs, ...] all local epochs' batches
    ys,  # [E, nb, bs]
    weight,  # fp32 scalar sample count (traced: reweighting never retraces)
    x_test=None,
    y_test=None,
    *,
    module,
    tx,
    prox_mu: float = 0.0,
    with_acc: bool = True,
    agg_dtype: str = "float32",
):
    """Trace-time body of :func:`fused_node_round` — one node's round.

    Shared by the overlay fused round (single-chip dispatch) and the
    submesh federation's per-slice dispatch
    (``parallel/submesh.py submesh_node_round``), so the two paths cannot
    drift: at ``model_parallel=1`` the sharded program IS this program,
    which is the bit-parity contract.
    """
    out = {}
    if x_test is not None:
        e_loss, logits = ce_eval(params, module, x_test, y_test)
        out["eval_loss"] = e_loss
        out["eval_acc"] = jnp.mean(
            (jnp.argmax(logits, axis=-1) == y_test).astype(jnp.float32)
        )
    anchor = params if prox_mu > 0.0 else None

    def epoch(carry, batch):
        p, o = carry
        exs, eys = batch
        p, o, loss = _local_epoch(
            p, o, exs, eys, module, tx, False, prox_mu=prox_mu, anchor=anchor
        )
        return (p, o), loss

    (params, opt_state), losses = jax.lax.scan(epoch, (params, opt_state), (xs, ys))
    out["params"] = params
    out["opt_state"] = opt_state
    # [E] per-epoch mean losses — the caller logs the same per-epoch
    # series the staged fit() produces (one metric point per epoch)
    out["train_losses"] = losses
    if with_acc:
        # weighted fold in Settings.AGG_DTYPE (the same accumulate dtype
        # the staged fedavg kernel uses), zero-init order identical to the
        # staged aggregate's ``w·p`` term (0 + w·p ≡ w·p) — the bit-parity
        # anchor for tests/test_fused_round.py
        out["psum"] = jax.tree.map(
            lambda p: p.astype(agg_dtype) * weight.astype(agg_dtype), params
        )
        out["wsum"] = weight.astype(agg_dtype)
    return out


@partial(
    jax.jit,
    static_argnames=("module", "tx", "prox_mu", "with_acc", "agg_dtype"),
    donate_argnums=(1,),
)
def fused_node_round(
    params,
    opt_state,
    xs,  # [E, nb, bs, ...] all local epochs' batches
    ys,  # [E, nb, bs]
    weight,  # fp32 scalar sample count (traced: reweighting never retraces)
    x_test=None,
    y_test=None,
    *,
    module,
    tx,
    prox_mu: float = 0.0,
    with_acc: bool = True,
    agg_dtype: str = "float32",
):
    """ONE overlay node's whole round compute as ONE donated dispatch.

    The overlay (gossip Node) round used to cross the host at every stage
    boundary: an eval dispatch, one ``train_epoch`` dispatch per epoch with
    a blocking ``float(loss)`` between each, then host-side re-weighting at
    aggregation time. This program fuses all of it — the eval forward of
    the INCOMING params (TrainStage evaluates before training, pure-CE
    :func:`~p2pfl_tpu.learning.learner.ce_eval` so the metric stays
    comparable with the staged path), the epoch ``lax.scan`` (shared
    :func:`_local_epoch` math — bit-comparable to ``train_epoch``), and the
    node's own partial-aggregation fold ``psum = weight × params'`` in fp32
    (the chunked-federation accumulator algebra from ``parallel/chunked.py``
    applied at the Train→Aggregate seam) — so ``TrainStage`` issues exactly
    one device dispatch and nothing on the model plane syncs to host.

    ``opt_state`` is donated (round-carried state, exactly like
    ``train_epoch``); ``params`` is NOT — with the zero-copy in-memory
    transport other nodes' aggregators may hold references to these exact
    buffers. Returns a dict of device values: ``params``, ``opt_state``,
    ``train_losses`` (the [E] per-epoch mean-loss vector — the same series
    the staged path logs point by point), ``psum``/``wsum`` when
    ``with_acc`` (the :class:`~p2pfl_tpu.learning.weights.ModelUpdate.
    partial_acc` payload, accumulated in ``agg_dtype`` exactly like the
    staged fedavg kernel), ``eval_loss``/``eval_acc`` when test data was
    passed. All metrics stay device values — the caller batches their D2H
    into one flush per round instead of one sync per step.
    """
    return _node_round_core(
        params, opt_state, xs, ys, weight, x_test, y_test,
        module=module, tx=tx, prox_mu=prox_mu, with_acc=with_acc,
        agg_dtype=agg_dtype,
    )


def _aggregate(p_used, mask, weights, sel_idx, agg: str, trim: int, center=None, clip_tau: float = 1.0):
    """Combine node-stacked params [N, ...] into one model (fp32 accumulate).

    ``sel_idx`` is the [K] array of train-set ∩ active node indices
    (host-computed, K static per trace). The robust aggregators operate on
    the gathered [K, ...] stack only — non-elected / dropped slots hold
    stale copies of the previous aggregate and would otherwise dominate the
    coordinate-wise median and win Krum's distance score, silently freezing
    training (mirrors host Node mode, where robust aggregators only ever
    see train-set models).
    """
    from p2pfl_tpu.ops import aggregation as ops

    if agg == "fedavg":
        w = (mask * weights).astype(jnp.float32)
        wn = w / jnp.sum(w)
        return jax.tree.map(
            lambda x: jnp.tensordot(wn, x.astype(jnp.float32), axes=(0, 0)).astype(x.dtype),
            p_used,
        )
    k = sel_idx.shape[0]
    p_sel = jax.tree.map(lambda x: jnp.take(x, sel_idx, axis=0), p_used)
    if agg == "median":
        return jax.tree.map(
            lambda x: jnp.median(x.astype(jnp.float32), axis=0).astype(x.dtype), p_sel
        )
    if agg == "trimmed_mean":
        # clamp like the host-side TrimmedMean class: 2*trim must leave >=1 row
        t = min(trim, (k - 1) // 2)

        def tm(x):
            xs = jnp.sort(x.astype(jnp.float32), axis=0)
            kept = jax.lax.slice_in_dim(xs, t, k - t, axis=0)
            return jnp.mean(kept, axis=0).astype(x.dtype)

        return jax.tree.map(tm, p_sel)
    if agg == "krum":
        idx = ops.krum_select(p_sel, n_byzantine=trim, multi=1)

        def pick(x):
            return jnp.take(x, idx, axis=0).astype(jnp.float32).mean(axis=0).astype(x.dtype)

        return jax.tree.map(pick, p_sel)
    if agg == "bulyan":
        # iterated Krum selection (θ = K − 2f picks, re-scored each pick)
        # then β = f trimmed mean — all static shapes: the removal keeps
        # K−i−1 rows via an index shift around the traced Krum pick
        f = trim
        if k < 4 * f + 3:
            raise ValueError(f"Bulyan needs K >= 4f + 3 (K={k}, f={f})")
        theta = k - 2 * f
        cur = p_sel
        orig = jnp.arange(k, dtype=jnp.int32)
        chosen = []
        for i in range(theta):
            m = k - i
            idx = ops.krum_select(cur, n_byzantine=f, multi=1)[0]
            chosen.append(orig[idx])
            pos = jnp.arange(m - 1, dtype=jnp.int32)
            keep = jnp.where(pos < idx, pos, pos + 1)  # skip the pick
            cur = jax.tree.map(lambda x: jnp.take(x, keep, axis=0), cur)
            orig = jnp.take(orig, keep)
        sel = jnp.stack(chosen)
        sel_tree = jax.tree.map(lambda x: jnp.take(x, sel, axis=0), p_sel)
        return ops.trimmed_mean(sel_tree, trim=f)
    if agg == "clip":
        # centered clipping (Karimireddy et al. 2021): center = previous
        # round's global (every slot held it identically pre-training)
        return ops.centered_clip(p_sel, center, clip_tau)
    raise ValueError(f"unknown aggregator {agg}")


def _round_core(
    stacked_params,  # [N, ...] pytree
    opt_states,  # [N, ...] pytree
    x_all,  # [N, S, ...] node-resident datasets
    y_all,  # [N, S]
    perm,  # [N, epochs, nb, bs] int32 shuffle indices (host-generated)
    mask,  # [N] 1.0 = in train set
    weights,  # [N] sample counts
    sel_idx,  # [K] int32 indices of mask==1 rows (robust aggregation support)
    *,
    module,
    tx,
    agg: str = "fedavg",
    trim: int = 0,
    clip_tau: float = 1.0,
    out_sharding=None,
    keep_opt_state: bool = False,
    remat: bool = False,
    prox_mu: float = 0.0,
    scaffold: bool = False,
    scaffold_fused_ci: bool = True,  # ci⁺ from the scan's grad mean (fast path)
    local_lr: float = 1e-3,
    c_global=None,  # SCAFFOLD server control variate (replicated pytree)
    c_local=None,  # SCAFFOLD per-node control variates [N, ...]
    server_opt: str = "",  # FedOpt: "adam" | "yogi" | "adagrad" ("" = plain)
    server_lr: float = 0.1,
    opt_m=None,  # FedOpt server first/second moments (replicated pytrees)
    opt_v=None,
    opt_t=None,  # FedOpt server step count (scalar, 1-based)
    dp_clip: float = 0.0,  # DP-SGD clip norm (0 = off)
    dp_noise: float = 0.0,  # DP-SGD noise multiplier
    dp_keys=None,  # [N, 2] uint32 per-node rng keys (required when dp_clip > 0)
):
    """One federated round's device program (train → aggregate → diffuse).

    Pure trace-time function shared by :func:`spmd_round` (one jitted round)
    and :func:`spmd_rounds_fused` (many rounds in one dispatch). Returns
    ``(out_params, out_opt, mean_loss, scaffold_state, fedopt_state,
    agg_params)`` where the two state tuples are ``()`` when the feature is
    off. ``prox_mu`` enables FedProx; ``scaffold`` threads SCAFFOLD control
    variates through local steps (Karimireddy et al. 2020); ``server_opt``
    applies a FedOpt server step to the aggregate (Reddi et al. 2021).
    """
    n = mask.shape[0]

    # SCAFFOLD correction c − c_i: materialized ONCE for all nodes outside
    # ``node_fn``, pre-cast to the param compute dtype — under vmap the
    # per-node closure re-derived it from the replicated fp32 ``c_global``
    # inside the batched program (an N-way broadcast of the full variate
    # plus a per-step astype); hoisted, it is one subtraction + cast whose
    # result the epoch scans consume directly.
    corr_all = (
        jax.tree.map(
            lambda c, cl, p: (c[None] - cl).astype(p.dtype),
            c_global, c_local, stacked_params,
        )
        if scaffold
        else None
    )

    # gather per-epoch batches: idx [epochs, nb, bs] → x[idx] [epochs, nb, bs, ...]
    def node_fn(params, opt_state, x, y, idx, ci, corr, dp_key):
        fused_ci = scaffold and scaffold_fused_ci
        # the anchor (round-start params) is retained across the epoch scan
        # only when something still needs it afterwards — the fused-ci path
        # doesn't, which releases two full-model fp32 buffers per node
        anchor = params if (prox_mu > 0.0 or (scaffold and not fused_ci)) else None

        def epoch_body(carry, ep_idx):
            p, o, k, gs = carry
            xs = jnp.take(x, ep_idx, axis=0)  # [nb, bs, ...]
            ys = jnp.take(y, ep_idx, axis=0)
            sub = None
            if dp_clip > 0.0:
                k, sub = jax.random.split(k)
            out = _local_epoch(
                p, o, xs, ys, module, tx, remat,
                prox_mu=prox_mu, anchor=anchor, corr=corr,
                dp_clip=dp_clip, dp_noise=dp_noise, key=sub,
                accumulate_grads=fused_ci,
            )
            if fused_ci:
                p, o, loss, g_ep = out
                gs = jax.tree.map(jnp.add, gs, g_ep)
            else:
                p, o, loss = out
            return (p, o, k, gs), loss

        k0 = dp_key if dp_clip > 0.0 else jnp.zeros((2,), jnp.uint32)
        gs0 = (
            jax.tree.map(lambda p_: jnp.zeros(p_.shape, jnp.float32), params)
            if fused_ci
            else None
        )
        (params, opt_state, _, gsum), losses = jax.lax.scan(
            epoch_body, (params, opt_state, k0, gs0), idx
        )
        k_steps = idx.shape[0] * idx.shape[1]
        if fused_ci:
            # under plain SGD, y_i = x − η·Σ(g_t + c − c_i), so option II's
            # c_i⁺ = c_i − c + (x − y_i)/(K·η) reduces EXACTLY to mean_t(g_t):
            # the scan's fp32 grad mean IS the new variate — no round-start
            # params retained, no large-magnitude cancellation
            ci_new = jax.tree.map(lambda gs_: gs_ / k_steps, gsum)
        elif scaffold:
            # c_i⁺ = c_i − c + (x_global − y_i)/(K·η)  (SCAFFOLD option II)
            ci_new = jax.tree.map(
                lambda cl, c, a, p: cl
                - c
                + (a.astype(jnp.float32) - p.astype(jnp.float32)) / (k_steps * local_lr),
                ci, c_global, anchor, params,
            )
        else:
            ci_new = ci
        return params, opt_state, jnp.mean(losses), ci_new

    key_ax = 0 if dp_clip > 0.0 else None
    keys = dp_keys if dp_clip > 0.0 else None
    if scaffold:
        trained_p, trained_o, losses, ci_new = jax.vmap(
            node_fn, in_axes=(0, 0, 0, 0, 0, 0, 0, key_ax)
        )(stacked_params, opt_states, x_all, y_all, perm, c_local, corr_all, keys)
    else:
        trained_p, trained_o, losses, _ = jax.vmap(
            node_fn, in_axes=(0, 0, 0, 0, 0, None, None, key_ax)
        )(stacked_params, opt_states, x_all, y_all, perm, None, None, keys)

    # non-train-set nodes contribute their previous params (they don't train)
    def sel(new, old):
        m = mask.reshape((n,) + (1,) * (new.ndim - 1)).astype(new.dtype)
        return new * m + old * (1 - m)

    p_used = jax.tree.map(sel, trained_p, stacked_params)
    # clip center = the round's shared starting model. Under normal
    # diffusion every slot holds it identically; the coordinate-wise median
    # over the elected rows recovers it exactly in that case AND stays
    # robust if a slot's incoming copy was tampered with (taking row 0
    # verbatim would let a poisoned slot choose the center).
    center = (
        jax.tree.map(
            lambda x: jnp.median(
                jnp.take(x, sel_idx, axis=0).astype(jnp.float32), axis=0
            ),
            stacked_params,
        )
        if agg == "clip"
        else None
    )
    agg_params = _aggregate(
        p_used, mask, weights, sel_idx, agg, trim, center=center, clip_tau=clip_tau
    )

    fedopt_state = ()
    if server_opt:
        # FedOpt server step on the pseudo-gradient prev_global − aggregate
        # (node slot 0's incoming params ARE the previous global — diffusion
        # left every slot identical)
        from p2pfl_tpu.ops.aggregation import fedopt_update

        prev_global = jax.tree.map(lambda x: x[0], stacked_params)
        agg_params, opt_m_out, opt_v_out = fedopt_update(
            prev_global, agg_params, opt_m, opt_v, opt_t,
            opt=server_opt, lr=server_lr,
        )
        fedopt_state = (opt_m_out, opt_v_out)

    # diffusion: every node receives the aggregate
    out_params = jax.tree.map(lambda a: jnp.broadcast_to(a[None], (n, *a.shape)), agg_params)
    if out_sharding is not None:
        # pin the node-stacked layout so round k+1 reuses round k's executable
        # (otherwise the broadcast's replicated layout forces a relayout+retrace)
        out_params = jax.tree.map(
            lambda a: jax.lax.with_sharding_constraint(a, out_sharding), out_params
        )
    if keep_opt_state:
        # documented improvement over the reference: carry Adam moments
        # across rounds (the reference rebuilds its Trainer per round,
        # losing them — slower convergence)
        out_opt = trained_o
    else:
        out_opt = jax.vmap(tx.init)(out_params)
    if out_sharding is not None:
        # vmap(tx.init) outputs otherwise come back replicated, flipping the
        # opt-state layout between rounds and forcing a recompile per variant
        out_opt = jax.tree.map(
            lambda a: jax.lax.with_sharding_constraint(a, out_sharding), out_opt
        )
    mean_loss = jnp.mean(losses, where=mask.astype(bool))

    scaffold_state = ()
    if scaffold:
        # only train-set nodes commit their new control variates; the server
        # variate moves by |S|/N times the mean train-set delta
        def selc(new, old):
            m_ = mask.reshape((n,) + (1,) * (new.ndim - 1)).astype(new.dtype)
            return new * m_ + old * (1 - m_)

        c_local_out = jax.tree.map(selc, ci_new, c_local)
        n_train = jnp.maximum(jnp.sum(mask), 1.0)
        frac = n_train / n

        def upd(c, cn, co):
            m_ = mask.reshape((n,) + (1,) * (cn.ndim - 1))
            delta = jnp.sum((cn - co) * m_, axis=0) / n_train
            return c + frac * delta

        c_global_out = jax.tree.map(upd, c_global, ci_new, c_local)
        if out_sharding is not None:
            c_local_out = jax.tree.map(
                lambda a: jax.lax.with_sharding_constraint(a, out_sharding), c_local_out
            )
        scaffold_state = (c_global_out, c_local_out)

    return out_params, out_opt, mean_loss, scaffold_state, fedopt_state, agg_params


def _agg_acc(module, agg_params, x_test, y_test):
    """Mean accuracy of the aggregated model over node-stacked test shards."""

    def node_acc(x, y):
        logits = module.apply({"params": agg_params}, x)
        return jnp.mean((jnp.argmax(logits, axis=-1) == y).astype(jnp.float32))

    return jnp.mean(jax.vmap(node_acc)(x_test, y_test))


_ROUND_STATICS = (
    # clip_tau is deliberately NOT static: it traces as a scalar operand
    # (ops.centered_clip takes tau traced), so tuning it never recompiles
    "module", "tx", "agg", "trim", "out_sharding", "keep_opt_state", "remat",
    "prox_mu", "scaffold", "scaffold_fused_ci", "local_lr", "server_opt",
    "server_lr", "dp_clip", "dp_noise",
)

# SCAFFOLD variates and FedOpt moments are round-carried state exactly like
# params/opt — donating them lets XLA write each round's new variates into
# the old buffers (the fused span otherwise keeps two copies of the fp32
# [N, model] c_local alive across the whole scan)
_ROUND_DONATED_STATE = ("c_global", "c_local", "opt_m", "opt_v")


@partial(
    jax.jit, static_argnames=_ROUND_STATICS, donate_argnums=(0, 1),
    donate_argnames=_ROUND_DONATED_STATE,
)
def spmd_round(
    stacked_params, opt_states, x_all, y_all, perm, mask, weights, sel_idx,
    *, c_global=None, c_local=None, opt_m=None, opt_v=None,
    x_test=None, y_test=None, **kw,
):
    """One federated round for all N nodes.

    Returns (params', opt', mean loss[, c_global', c_local'][, opt_m',
    opt_v'][, test acc]) — the accuracy of the aggregated model is fused
    into the same program when test data is given (one device dispatch for
    train + aggregate + diffuse + eval). See :func:`_round_core` for the
    algorithm knobs.
    """
    out_params, out_opt, mean_loss, scaffold_state, fedopt_state, agg_params = _round_core(
        stacked_params, opt_states, x_all, y_all, perm, mask, weights, sel_idx,
        c_global=c_global, c_local=c_local, opt_m=opt_m, opt_v=opt_v, **kw,
    )
    if x_test is None:
        return (out_params, out_opt, mean_loss, *scaffold_state, *fedopt_state)
    acc = _agg_acc(kw["module"], agg_params, x_test, y_test)
    return (out_params, out_opt, mean_loss, *scaffold_state, *fedopt_state, acc)


@partial(
    jax.jit, static_argnames=_ROUND_STATICS, donate_argnums=(0, 1),
    donate_argnames=_ROUND_DONATED_STATE,
)
def spmd_rounds_fused(
    stacked_params, opt_states, x_all, y_all, perms, mask, weights, sel_idx,
    *,
    c_global=None, c_local=None, opt_m=None, opt_v=None, opt_t=None,
    dp_keys=None, x_test=None, y_test=None, **kw,
):
    """R federated rounds as ONE device dispatch: ``lax.scan`` over rounds.

    ``perms``: [R, N, epochs, nb, bs] per-round shuffle indices. The mask
    (train set) is fixed for the whole span — exactly the reference's
    round semantics, where voting happens only in round 0
    (``round_finished_stage.py:69-70``). At small model scale a federated
    round is dispatch-dominated; fusing R rounds amortizes the host↔device
    round-trip R×. With test data, each round's aggregated model is
    evaluated in-program → accs [R] (an on-device convergence curve).

    Returns (params', opt', losses [R][, c_global', c_local'][, opt_m',
    opt_v'][, accs [R]]).
    """
    scaffold = kw.get("scaffold", False)
    server_opt = kw.get("server_opt", "")
    if opt_t is None:
        opt_t = jnp.float32(0.0)

    def body(carry, xsi):
        perm, kk = xsi
        p, o, cg, cl, m_, v_, t_ = carry
        t_next = t_ + 1.0
        out_p, out_o, loss, sstate, fstate, agg_params = _round_core(
            p, o, x_all, y_all, perm, mask, weights, sel_idx,
            c_global=cg, c_local=cl, opt_m=m_, opt_v=v_, opt_t=t_next,
            dp_keys=kk, **kw,
        )
        cg, cl = sstate if scaffold else (cg, cl)
        m_, v_ = fstate if server_opt else (m_, v_)
        ys = (loss,) if x_test is None else (loss, _agg_acc(kw["module"], agg_params, x_test, y_test))
        return (out_p, out_o, cg, cl, m_, v_, t_next), ys

    carry0 = (stacked_params, opt_states, c_global, c_local, opt_m, opt_v, opt_t)
    (p, o, cg, cl, m_, v_, _), ys = jax.lax.scan(body, carry0, (perms, dp_keys))
    scaffold_state = (cg, cl) if scaffold else ()
    fedopt_state = (m_, v_) if server_opt else ()
    if x_test is None:
        return (p, o, ys[0], *scaffold_state, *fedopt_state)
    return (p, o, ys[0], *scaffold_state, *fedopt_state, ys[1])


@partial(jax.jit, static_argnames=("module",))
def spmd_eval(stacked_params, x_test, y_test, *, module):
    """Per-node eval over node-stacked test shards. Returns ([N] loss, [N] acc)."""
    import optax

    def node_eval(params, x, y):
        logits = module.apply({"params": params}, x)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
        acc = jnp.mean((jnp.argmax(logits, axis=-1) == y).astype(jnp.float32))
        return loss, acc

    return jax.vmap(node_eval)(stacked_params, x_test, y_test)


# ---- host-side driver ----


def tree_has_deleted(tree) -> bool:
    """True if any jax leaf of ``tree`` was consumed by a donated dispatch."""
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, jax.Array):
            try:
                if leaf.is_deleted():
                    return True
            except Exception:  # noqa: BLE001 — backends without the probe
                continue
    return False


def stage_node_shards(datasets, batch_size: int) -> dict:
    """Host-side shard staging policy shared by every node-stacked driver.

    Pads each node's train shard to the common max by wrap-around, clips
    test shards to the common min, and sizes the per-round batch count
    from the common MIN shard (every node's per-round shuffle draws from
    its OWN sample range — see :func:`draw_node_perms`). One
    implementation, because the bit-parity contract between
    :class:`SpmdFederation` and
    :class:`~p2pfl_tpu.parallel.submesh.ShardedNodeFederation` depends on
    both drivers sizing ``nb`` and padding shards identically — a policy
    change here reaches both or neither.

    Returns ``{"x": [N x np [S, ...]], "y": ..., "x_test": ..., "y_test":
    ..., "sizes": [N], "nb": int}``.
    """
    sizes = [d.num_samples for d in datasets]
    tr_min, tr_max = min(sizes), max(sizes)
    te_min = min(len(d.y_test) for d in datasets)
    if tr_min < batch_size:
        raise ValueError(f"smallest shard ({tr_min}) < batch size ({batch_size})")

    def wrap(a: np.ndarray, target: int) -> np.ndarray:
        if len(a) == target:
            return a
        reps = -(-target // len(a))
        return np.concatenate([a] * reps, axis=0)[:target]

    return {
        "x": [wrap(d.x_train, tr_max) for d in datasets],
        "y": [wrap(d.y_train, tr_max) for d in datasets],
        "x_test": [d.x_test[:te_min] for d in datasets],
        "y_test": [d.y_test[:te_min] for d in datasets],
        "sizes": sizes,
        "nb": tr_min // batch_size,
    }


def draw_node_perms(
    rng: np.random.Generator, sizes: list[int], nb: int, batch_size: int, epochs: int
) -> np.ndarray:
    """Per-node per-epoch shuffle indices ``[N, epochs, nb, bs]`` (int32).

    Single source of the round's batch-draw rng stream: node-major, then
    epoch-major, each draw one ``rng.permutation`` over the node's OWN
    sample range. Shared by :class:`SpmdFederation` and
    :class:`~p2pfl_tpu.parallel.submesh.ShardedNodeFederation` so the two
    drivers consume identical rng state — the bit-parity tests compare
    them round for round on one seed.
    """
    take = nb * batch_size  # always <= min shard size
    return np.stack(
        [
            np.stack(
                [
                    rng.permutation(sizes[i])[:take].reshape(nb, batch_size)
                    for _ in range(epochs)
                ]
            )
            for i in range(len(sizes))
        ]
    ).astype(np.int32)


def elect_train_set_mask(n: int, py_rng) -> np.ndarray:
    """Round-0 election: every node casts weighted random votes
    (``vote_train_set_stage.py:78-81``); top ``TRAIN_SET_SIZE`` win.

    Shared by every federation runtime (SpmdFederation, ChunkedFederation)
    so the reference vote semantics have exactly one implementation.
    """
    names = list(range(n))
    tally: dict[int, int] = {}
    k = min(Settings.TRAIN_SET_SIZE, n)
    for _voter in names:
        picks = py_rng.sample(names, k)
        for i, cand in enumerate(picks):
            tally[cand] = tally.get(cand, 0) + math.floor(py_rng.randint(0, 1000) / (i + 1))
    ranked = sorted(tally.items(), key=lambda kv: (kv[1], kv[0]), reverse=True)
    mask = np.zeros(n, dtype=np.float32)
    for cand, _ in ranked[:k]:
        mask[cand] = 1.0
    return mask


class SpmdFederation:
    """N federated nodes as one SPMD program over a device mesh.

    The drop-in high-throughput alternative to running N ``Node`` objects:
    same round semantics, same aggregators, none of the per-message overhead.
    """

    def __init__(
        self,
        model: FlaxModel,
        datasets: list[FederatedDataset],
        mesh: Optional[Mesh] = None,
        batch_size: int = 128,
        learning_rate: float = 1e-3,
        aggregator: str = "fedavg",
        trim: int = 0,
        clip_tau: float = 1.0,
        vote: bool = True,
        keep_opt_state: bool = False,
        remat: bool = False,
        participation: float = 1.0,
        seed: int = 0,
        prox_mu: float = 0.0,
        scaffold: bool = False,
        optimizer: str = "adam",
        server_opt: str = "",
        server_lr: float = 0.1,
        dp_clip: float = 0.0,
        dp_noise: float = 0.0,
        tx: Optional[optax.GradientTransformation] = None,
    ) -> None:
        self.model = model
        self.module = model.module
        self.n = len(datasets)
        if self.n < 1:
            raise ValueError("need at least one dataset shard")
        if Settings.SECURE_AGGREGATION:
            # secagg is a gossip-plane protocol: pairwise masks exist to hide
            # individual updates from the PEERS that relay them. An SPMD
            # federation is one program on one mesh — a single trust domain
            # where every "node" already shares an address space, so masking
            # would add cost while protecting against nobody. Refuse loudly
            # instead of silently training unmasked (docs/design.md,
            # "Secure aggregation and the SPMD runtime").
            raise ValueError(
                "SECURE_AGGREGATION=True has no effect inside SpmdFederation: "
                "the SPMD mesh is one trust domain (one program, one address "
                "space). Use gossip Node mode for secure aggregation, or set "
                "Settings.SECURE_AGGREGATION=False for mesh runs."
            )
        self.datasets = datasets
        self.batch_size = batch_size
        if scaffold and (optimizer != "sgd" or tx is not None):
            # the (x − y_i)/(K·η) variate update assumes η-scaled SGD steps;
            # adaptive local steps break the correction's variance-reduction
            raise ValueError("scaffold=True requires optimizer='sgd'")
        if tx is not None:
            # explicit optax transform — e.g. adam(warmup_cosine_schedule):
            # with keep_opt_state=True the schedule's step count survives
            # round boundaries, giving federated LR schedules (config 2)
            self.tx = tx
        else:
            self.tx = sgd(learning_rate) if optimizer == "sgd" else adam(learning_rate)
        self.learning_rate = learning_rate
        # FedProx proximal strength (0 = plain FedAvg local steps)
        self.prox_mu = float(prox_mu)
        self.scaffold = scaffold
        # FedOpt server optimizer ("" = plain aggregation result)
        if server_opt and server_opt not in ("adam", "yogi", "adagrad"):
            raise ValueError(f"unknown server_opt {server_opt!r}")
        self.server_opt = server_opt
        self.server_lr = server_lr
        # DP-SGD per-node local steps (clip norm + noise multiplier)
        self.dp_clip = float(dp_clip)
        self.dp_noise = float(dp_noise)
        if self.dp_noise > 0.0 and self.dp_clip <= 0.0:
            raise ValueError("dp_noise > 0 requires dp_clip > 0")
        if aggregator not in ("fedavg", "median", "trimmed_mean", "krum", "bulyan", "clip"):
            raise ValueError(f"unknown aggregator {aggregator!r}")
        self.aggregator = aggregator
        self.trim = trim
        if aggregator == "clip" and clip_tau <= 0:
            # tau <= 0 zeroes every clip factor: the aggregate would never
            # leave the center and training silently freezes
            raise ValueError(f"clip_tau must be > 0 (got {clip_tau})")
        self.clip_tau = float(clip_tau)
        self.keep_opt_state = keep_opt_state
        self.remat = remat
        if not 0.0 < participation <= 1.0:
            raise ValueError("participation must be in (0, 1]")
        self.participation = participation
        self._rng = np.random.default_rng(seed)
        self._py_rng = random.Random(seed)

        self.mesh = mesh if mesh is not None else self._default_mesh()
        axis = Settings.MESH_NODES_AXIS
        self._shard = NamedSharding(self.mesh, P(axis))  # shard axis 0 over nodes
        self._repl = NamedSharding(self.mesh, P())

        # device-resident data, truncated to common per-node sizes
        self._stage_data()
        # per-node (ε, δ) tracking: every node runs the same mechanism on
        # its own shard, so one accountant describes each node's guarantee
        self.accountant = None
        if self.dp_clip > 0.0 and self.dp_noise > 0.0:
            from p2pfl_tpu.learning.privacy import PrivacyAccountant

            q = min(1.0, self.batch_size / min(self._sizes))
            self.accountant = PrivacyAccountant(self.dp_noise, q)
        # node-stacked state: every node starts from the same params
        # (reference: initiator's weights seed the network, §3.3)
        self._stage_state()

        # election state (round-0 vote, reused thereafter — reference quirk)
        self.train_mask = np.ones(self.n, dtype=np.float32)
        self._vote = vote
        # failure semantics on a mesh (SURVEY §7 "failure semantics on a
        # pod"): chips don't crash independently, so node failure is modeled
        # by masking slots out of training AND aggregation — the collective
        # analogue of heartbeat eviction
        self.active_mask = np.ones(self.n, dtype=np.float32)
        self.round = 0
        self.history: list[dict] = []
        self.last_profile: Optional[dict] = None

    def reset(self, seed: int = 0) -> None:
        """Back to round 0 with fresh state, keeping mesh/data/executables.

        Use this (not a new federation) to measure or restart: a new object
        builds a new Mesh and misses every jit cache.
        """
        self._rng = np.random.default_rng(seed)
        self._py_rng = random.Random(seed)
        self.train_mask = np.ones(self.n, dtype=np.float32)
        self.active_mask = np.ones(self.n, dtype=np.float32)
        self.round = 0
        self.history = []
        self._stage_state()

    def _stage_state(self) -> None:
        # jitted with out_shardings: the broadcast + init run ON DEVICE and
        # land directly in the mesh layout (a host-side device_put would
        # re-upload N x model_size through the host link)
        n = self.n

        @partial(jax.jit, out_shardings=(self._shard, self._shard))
        def stage(tree):
            stacked = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (n, *x.shape)), tree)
            return stacked, jax.vmap(self.tx.init)(stacked)

        self.params, self.opt_state = stage(self.model.params)
        self._server_t = 0  # FedOpt server step count (stays 0 without server_opt)
        if self.scaffold:
            # control variates start at zero (Karimireddy et al. 2020 §3);
            # the global variate replicates on the MESH (a device-0-committed
            # array would clash with the sharded args under jit)
            self.c_global = jax.device_put(
                jax.tree.map(
                    lambda x: jnp.zeros(x.shape, jnp.float32), self.model.params
                ),
                self._repl,
            )
            self.c_local = jax.device_put(
                jax.tree.map(
                    lambda x: jnp.zeros((n, *x.shape), jnp.float32), self.model.params
                ),
                self._shard,
            )
        if self.server_opt:
            zeros = jax.device_put(
                jax.tree.map(
                    lambda x: jnp.zeros(x.shape, jnp.float32), self.model.params
                ),
                self._repl,
            )
            self.opt_m = zeros
            self.opt_v = jax.tree.map(jnp.copy, zeros)

    def _default_mesh(self) -> Mesh:
        from p2pfl_tpu.parallel.mesh import federation_mesh

        devices = jax.devices()
        slots = min(self.n, len(devices))
        while self.n % slots != 0:  # fold nodes evenly onto mesh slots
            slots -= 1
        return federation_mesh(n_nodes=slots, devices=devices[:slots])

    def _stage_data(self) -> None:
        # node shards are padded (wrap-around) to a common static length so
        # they stack into one [N, S, ...] array, but each node's per-round
        # shuffle indices are drawn from its OWN sample range (``_make_perm``)
        # — so the FedAvg sample-count weights match the data each node
        # actually trains on (over rounds, every node covers its full shard).
        # Policy (padding/clipping/nb sizing) lives in the shared
        # :func:`stage_node_shards` — the submesh driver consumes the same
        # helper, which is what keeps the two drivers' rng streams parity.
        staged = stage_node_shards(self.datasets, self.batch_size)
        self.x_all = jax.device_put(np.stack(staged["x"]), self._shard)
        self.y_all = jax.device_put(np.stack(staged["y"]), self._shard)
        self.x_test = jax.device_put(np.stack(staged["x_test"]), self._shard)
        self.y_test = jax.device_put(np.stack(staged["y_test"]), self._shard)
        self._samples = jax.device_put(
            jnp.asarray([float(s) for s in staged["sizes"]]), self._shard
        )
        self._sizes = staged["sizes"]
        self._tr_size = len(staged["x"][0])
        self._nb = staged["nb"]

    # ---- election (host control plane — reference vote semantics) ----

    def elect_train_set(self) -> np.ndarray:
        """Reference vote semantics — delegates to
        :func:`elect_train_set_mask`."""
        return elect_train_set_mask(self.n, self._py_rng)

    # ---- round driver ----

    def _make_perm_np(self, epochs: int) -> np.ndarray:
        return draw_node_perms(self._rng, self._sizes, self._nb, self.batch_size, epochs)

    def _make_perm(self, epochs: int):
        return jax.device_put(self._make_perm_np(epochs), self._shard)

    def _effective_mask(self) -> np.ndarray:
        """Train-set ∩ active nodes, optionally client-sampled per round."""
        effective = self.train_mask * self.active_mask
        if self.participation < 1.0:
            # FedAvg-style client sampling: each round a random fraction of
            # the eligible nodes trains (McMahan et al. 2017 C-fraction)
            eligible = np.flatnonzero(effective)
            k = max(1, round(self.participation * len(eligible)))
            chosen = self._rng.choice(eligible, size=k, replace=False)
            effective = np.zeros_like(effective)
            effective[chosen] = 1.0
        if effective.sum() == 0:
            raise RuntimeError("no active train-set nodes left")
        return effective

    def drop_node(self, i: int) -> None:
        """Mark a logical node failed: it stops training and contributing
        (the reference's heartbeat-eviction outcome, ``heartbeater.py:91-101``)."""
        self.active_mask[i] = 0.0

    def restore_node(self, i: int) -> None:
        self.active_mask[i] = 1.0

    def _algo_kwargs(self, opt_t: float) -> dict:
        """The ``_round_core`` algorithm knobs — single source of truth for
        run_round / run_fused / round_flops. A missed copy would silently
        change the compiled program (e.g. MFU counting the wrong FLOPs).
        ``opt_t`` is the FedOpt server step the program should use: the
        1-based step for a single round, the 0-based starting counter for a
        fused span (the scan body pre-increments)."""
        return dict(
            prox_mu=self.prox_mu,
            scaffold=self.scaffold,
            # static (traced-program) knob: read per call so flipping the
            # Setting reaches the next round's executable, never a stale one
            scaffold_fused_ci=bool(Settings.SCAFFOLD_FUSED_CI),
            local_lr=self.learning_rate,
            server_opt=self.server_opt,
            server_lr=self.server_lr,
            c_global=self.c_global if self.scaffold else None,
            c_local=self.c_local if self.scaffold else None,
            opt_m=self.opt_m if self.server_opt else None,
            opt_v=self.opt_v if self.server_opt else None,
            opt_t=jnp.float32(opt_t) if self.server_opt else None,
            dp_clip=self.dp_clip,
            dp_noise=self.dp_noise,
        )

    def _dp_round_keys(self, rounds: int = 0) -> Optional[jax.Array]:
        """Per-node DP rng keys: [N, 2] for one round, [R, N, 2] fused."""
        if self.dp_clip <= 0.0:
            return None
        root = jax.random.PRNGKey(int(self._rng.integers(2**31)))
        if rounds:
            keys = jax.random.split(root, rounds * self.n).reshape(rounds, self.n, 2)
            return jax.device_put(
                keys, NamedSharding(self.mesh, P(None, Settings.MESH_NODES_AXIS))
            )
        return jax.device_put(jax.random.split(root, self.n), self._shard)

    def run_round(self, epochs: int = 1, eval: bool = False, profile: bool = False) -> dict:  # noqa: A002
        if self._vote and (self.round == 0 or Settings.VOTE_EVERY_ROUND):
            self.train_mask = self.elect_train_set()
        if profile:
            # per-phase breakdown of the round about to run (train /
            # correction / aggregate) — stashed on self.last_profile
            self.profile_round(epochs)
        perm = self._make_perm(epochs)
        eff = self._effective_mask()
        mask = jax.device_put(jnp.asarray(eff), self._shard)
        # robust aggregators see only the [K] selected rows; K is static per
        # mask pattern, so the executable is reused as long as K is stable
        sel_idx = jax.device_put(np.flatnonzero(eff).astype(np.int32), self._repl)
        from p2pfl_tpu.management.profiling import dispatch_span

        try:
            with dispatch_span("spmd_round", "spmd", nodes=self.n, epochs=epochs):
                result = spmd_round(
                    self.params,
                    self.opt_state,
                    self.x_all,
                    self.y_all,
                    perm,
                    mask,
                    self._samples,
                    sel_idx,
                    module=self.module,
                    tx=self.tx,
                    agg=self.aggregator,
                    trim=self.trim,
                    clip_tau=self.clip_tau,
                    out_sharding=self._shard,
                    keep_opt_state=self.keep_opt_state,
                    remat=self.remat,
                    x_test=self.x_test if eval else None,
                    y_test=self.y_test if eval else None,
                    dp_keys=self._dp_round_keys(),
                    **self._algo_kwargs(self._server_t + 1 if self.server_opt else 0),
                )
        except Exception:
            self._recover_donated_state()
            raise
        self.params, self.opt_state, loss = result[:3]
        i = 3
        if self.scaffold:
            self.c_global, self.c_local = result[i:i + 2]
            i += 2
        if self.server_opt:
            self.opt_m, self.opt_v = result[i:i + 2]
            self._server_t += 1
        if self.accountant is not None:
            self.accountant.step(epochs * self._nb)
        self.round += 1
        # keep the loss as a device scalar: rounds pipeline back-to-back with
        # no host sync; it coerces to float lazily (e.g. when printed)
        entry = {"round": self.round, "train_loss": loss}
        if eval:
            entry["test_acc"] = result[-1]  # acc is last (scaffold adds outputs)
        self.history.append(entry)
        return entry

    def profile_round(self, epochs: int = 1, iters: int = 3) -> dict:
        """Per-phase wall-clock attribution of one round (no state change).

        Times three compiled programs on the federation's real inputs:

        - ``train_s`` — the matched PLAIN round (scaffold math stripped,
          same ``tx``/mask/perm shapes): local epochs + aggregate + diffuse;
        - ``total_s`` — the round as configured (with SCAFFOLD correction
          and variate updates when ``scaffold=True``);
        - ``aggregate_s`` — the masked weighted reduce + diffusion alone;
        - ``correction_s`` — the residual ``total − train``: what the
          per-step correction adds + both variate updates cost together.

        Donated inputs are re-copied per timed call (copies materialized
        BEFORE the timer starts), so profiling consumes nothing the
        federation still needs. Medians over ``iters`` calls. Sets
        ``self.last_profile`` and returns it.
        """
        rng_state = self._rng.bit_generator.state
        try:
            profile = self._profile_round_body(epochs, iters)
        finally:
            # restored on EVERY exit, including a failed probe dispatch:
            # profiling must never perturb the federation's round stream
            # (the pre-fix path skipped the restore when a probe raised,
            # silently desynchronizing every later perm draw)
            self._rng.bit_generator.state = rng_state
        self.last_profile = profile
        return profile

    def _profile_round_body(self, epochs: int, iters: int) -> dict:
        import time

        from p2pfl_tpu.management.profiling import force_execution

        perm = self._make_perm(epochs)
        eff = self._effective_mask()
        mask = jax.device_put(jnp.asarray(eff), self._shard)
        sel_idx = jax.device_put(np.flatnonzero(eff).astype(np.int32), self._repl)
        common = dict(
            module=self.module, tx=self.tx, agg=self.aggregator, trim=self.trim,
            clip_tau=self.clip_tau, out_sharding=self._shard,
            keep_opt_state=self.keep_opt_state, remat=self.remat,
        )

        def timed(algo_kw: dict) -> float:
            def stage_inputs():
                copies = {
                    k: jax.tree.map(jnp.copy, v)
                    for k, v in algo_kw.items()
                    if k in ("c_global", "c_local", "opt_m", "opt_v") and v is not None
                }
                p = jax.tree.map(jnp.copy, self.params)
                o = jax.tree.map(jnp.copy, self.opt_state)
                force_execution((p, o, copies))
                return p, o, {**algo_kw, **copies}

            def call(p, o, kw):
                return spmd_round(
                    p, o, self.x_all, self.y_all, perm, mask, self._samples,
                    sel_idx, dp_keys=self._dp_round_keys(), **common, **kw,
                )

            force_execution(call(*stage_inputs()))  # compile + warm
            ts = []
            for _ in range(iters):
                p, o, kw = stage_inputs()
                t0 = time.monotonic()
                force_execution(call(p, o, kw))
                ts.append(time.monotonic() - t0)
            return sorted(ts)[len(ts) // 2]

        full_kw = self._algo_kwargs(self._server_t + 1 if self.server_opt else 0)
        plain_kw = {
            **full_kw,
            "scaffold": False, "c_global": None, "c_local": None,
            "server_opt": "", "opt_m": None, "opt_v": None, "opt_t": None,
        }
        t_total = timed(full_kw)
        t_train = timed(plain_kw) if (self.scaffold or self.server_opt) else t_total

        @partial(jax.jit, static_argnames=("agg", "trim"))
        def agg_probe(stacked, mask_, weights, sel, *, agg, trim):
            agg_p = _aggregate(stacked, mask_, weights, sel, agg, trim)
            return jax.tree.map(
                lambda a: jnp.broadcast_to(a[None], (mask_.shape[0], *a.shape)), agg_p
            )

        def agg_call():
            # "clip" needs a center operand the probe doesn't carry; its
            # reduce+diffuse cost is the fedavg probe's to first order
            probe_agg = "fedavg" if self.aggregator == "clip" else self.aggregator
            return agg_probe(
                self.params, mask, self._samples, sel_idx,
                agg=probe_agg, trim=self.trim,
            )

        force_execution(agg_call())
        ts = []
        for _ in range(iters):
            t0 = time.monotonic()
            force_execution(agg_call())
            ts.append(time.monotonic() - t0)
        t_agg = sorted(ts)[len(ts) // 2]

        return {
            "total_s": round(t_total, 4),
            "train_s": round(t_train, 4),
            "correction_s": round(max(t_total - t_train, 0.0), 4),
            "aggregate_s": round(t_agg, 4),
            "overhead_x": round(t_total / t_train, 2) if t_train > 0 else None,
        }

    def _recover_donated_state(self) -> None:
        """Failed round dispatch: drop and rebuild any consumed donated state.

        ``spmd_round`` / ``spmd_rounds_fused`` donate params, opt state and
        the SCAFFOLD/FedOpt carries. A dispatch that dies mid-execution may
        already have consumed those buffers — leaving them in place poisons
        EVERY later round with "array has been deleted" deep inside jit
        argument processing (the exact failure mode PR 4 fixed for the
        encode path's EF store). Same remedy: drop and rebuild. Rebuilt
        state is the round-0 init (the consumed training progress is gone
        with the buffers — recorded loudly), which keeps the federation
        object usable for a retry/diagnosis instead of bricked.
        """
        from p2pfl_tpu.management.logger import logger

        donated = [self.params, self.opt_state]
        if self.scaffold:
            donated += [self.c_global, self.c_local]
        if self.server_opt:
            donated += [self.opt_m, self.opt_v]
        if not any(tree_has_deleted(t) for t in donated):
            return
        logger.warning(
            "spmd",
            "Round dispatch failed after consuming donated buffers — "
            "rebuilding federation state from the round-0 init (training "
            "progress in the consumed buffers is lost)",
        )
        self._stage_state()

    def run(self, rounds: int, epochs: int = 1, eval_every: int = 0) -> list[dict]:
        for r in range(rounds):
            entry = self.run_round(epochs)
            if eval_every and (r + 1) % eval_every == 0:
                entry.update(self.evaluate())
        return self.history

    def _fused_inputs(self, rounds: int, epochs: int):
        """Guards + staged device inputs shared by every fused-span runner.

        Elects the round-0 train set if needed, rejects per-round
        voting/client sampling (a fused span needs one fixed mask), and
        returns ``(perms [R,N,epochs,nb,bs], mask, sel_idx)`` device-put
        with the span's shardings.
        """
        if self._vote and self.round == 0:
            self.train_mask = self.elect_train_set()
        if (self._vote and Settings.VOTE_EVERY_ROUND) or self.participation < 1.0:
            raise ValueError(
                "run_fused needs a fixed mask: per-round voting/client "
                "sampling re-elects between rounds — use run_round"
            )
        perms = jax.device_put(
            np.stack([self._make_perm_np(epochs) for _ in range(rounds)]),
            NamedSharding(self.mesh, P(None, Settings.MESH_NODES_AXIS)),
        )
        eff = self._effective_mask()
        mask = jax.device_put(jnp.asarray(eff), self._shard)
        sel_idx = jax.device_put(np.flatnonzero(eff).astype(np.int32), self._repl)
        return perms, mask, sel_idx

    def run_fused(self, rounds: int, epochs: int = 1, eval: bool = False) -> list[dict]:  # noqa: A002
        """Run ``rounds`` rounds as ONE device dispatch (``lax.scan``).

        At small model scale a round is dispatch-dominated — fusing
        amortizes the host↔device round-trip. The train set is fixed for
        the span (the reference's own semantics: voting happens only in
        round 0); per-round voting or client sampling needs
        :meth:`run_round`. With ``eval=True`` the per-round accuracy curve
        is computed on-device and returned in the history entries.
        """
        perms, mask, sel_idx = self._fused_inputs(rounds, epochs)
        from p2pfl_tpu.management.profiling import dispatch_span

        try:
            with dispatch_span("spmd_rounds_fused", "spmd", nodes=self.n, rounds=rounds):
                result = spmd_rounds_fused(
                    self.params, self.opt_state, self.x_all, self.y_all, perms, mask,
                    self._samples, sel_idx,
                    module=self.module, tx=self.tx, agg=self.aggregator, trim=self.trim, clip_tau=self.clip_tau,
                    out_sharding=self._shard, keep_opt_state=self.keep_opt_state,
                    remat=self.remat,
                    x_test=self.x_test if eval else None,
                    y_test=self.y_test if eval else None,
                    dp_keys=self._dp_round_keys(rounds),
                    **self._algo_kwargs(self._server_t),
                )
        except Exception:
            self._recover_donated_state()
            raise
        self.params, self.opt_state, losses = result[:3]
        i = 3
        if self.scaffold:
            self.c_global, self.c_local = result[i:i + 2]
            i += 2
        if self.server_opt:
            self.opt_m, self.opt_v = result[i:i + 2]
            self._server_t += rounds
            i += 2
        if self.accountant is not None:
            self.accountant.step(rounds * epochs * self._nb)
        accs = result[i] if eval else None
        entries = []
        for r in range(rounds):
            self.round += 1
            entry = {"round": self.round, "train_loss": losses[r]}
            if eval:
                entry["test_acc"] = accs[r]
            self.history.append(entry)
            entries.append(entry)
        return entries

    def round_flops(self, epochs: int = 1) -> Optional[float]:
        """FLOPs of one no-eval round, scan-trip-count aware.

        XLA's ``cost_analysis`` counts a ``lax.scan`` body ONCE regardless
        of trip count, so the whole-round program's figure misses
        ``epochs × nb − 1`` of every node's SGD steps (a ~16× undercount at
        nb=16 — this made round-1's MFU look 1.7% when the chip was really
        running ~10×+ that). Corrected here: the whole-round analysis (which
        counts aggregation/diffusion plus exactly one step per node) plus a
        scan-free single-step probe times the steps the analysis missed.
        """
        from p2pfl_tpu.management.profiling import compiled_flops

        perm = self._make_perm(epochs)
        eff = self._effective_mask()
        mask = jax.device_put(jnp.asarray(eff), self._shard)
        sel_idx = jax.device_put(np.flatnonzero(eff).astype(np.int32), self._repl)
        # algorithm knobs change the compiled program — MFU must count the
        # program that actually runs
        base = compiled_flops(
            spmd_round,
            self.params, self.opt_state, self.x_all, self.y_all, perm, mask,
            self._samples, sel_idx,
            module=self.module, tx=self.tx, agg=self.aggregator, trim=self.trim, clip_tau=self.clip_tau,
            out_sharding=self._shard, keep_opt_state=self.keep_opt_state,
            remat=self.remat,
            dp_keys=self._dp_round_keys(),
            **self._algo_kwargs(self._server_t + 1 if self.server_opt else 0),
        )
        if base is None:
            return None
        step = self._single_step_flops()
        if step is None:
            return base
        return base + self.n * (epochs * self._nb - 1) * step

    def _probe_step_flops(self, loss_fn) -> Optional[float]:
        """Compiled FLOPs of ONE node's ONE SGD step, from shape-only probes.

        ``loss_fn(params, bx, by) -> scalar``. Shared by the LoRA and
        full-LM federations' ``round_flops`` (scan-trip-count pitfall: the
        probe is scan-free, so cost analysis counts it exactly once);
        honors ``remat`` so recompute shows up the same way it executes.
        """
        import optax

        from p2pfl_tpu.management.profiling import compiled_flops

        p1 = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype), self.params
        )
        o1 = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype), self.opt_state
        )
        bx = jax.ShapeDtypeStruct(
            (self.batch_size,) + tuple(self.x_all.shape[2:]), self.x_all.dtype
        )
        by = jax.ShapeDtypeStruct(
            (self.batch_size,) + tuple(self.y_all.shape[2:]), self.y_all.dtype
        )

        def one_step(p, o, bx_, by_):
            lf = jax.checkpoint(loss_fn) if self.remat else loss_fn
            _loss, grads = jax.value_and_grad(lf)(p, bx_, by_)
            updates, o = self.tx.update(grads, o, p)
            return optax.apply_updates(p, updates), o

        return compiled_flops(jax.jit(one_step), p1, o1, bx, by)

    def _single_step_flops(self) -> Optional[float]:
        """Compiled FLOPs of ONE node's ONE SGD step (trip-count-1 scan, so
        the cost analysis counts it exactly once). Mirrors the round's
        per-step math including remat/FedProx/DP variants."""
        from p2pfl_tpu.management.profiling import compiled_flops

        def one(a):
            return jax.ShapeDtypeStruct(a.shape[1:], a.dtype)

        p1 = jax.tree.map(one, self.params)
        o1 = jax.tree.map(one, self.opt_state)
        xs = jax.ShapeDtypeStruct(
            (1, self.batch_size) + tuple(self.x_all.shape[2:]), self.x_all.dtype
        )
        ys = jax.ShapeDtypeStruct(
            (1, self.batch_size) + tuple(self.y_all.shape[2:]), self.y_all.dtype
        )
        dp = self.dp_clip > 0.0

        def one_epoch(p, o, xs_, ys_, key=None):
            anchor = p if (self.prox_mu > 0.0 or self.scaffold) else None
            return _local_epoch(
                p, o, xs_, ys_, self.module, self.tx, self.remat,
                prox_mu=self.prox_mu, anchor=anchor,
                dp_clip=self.dp_clip, dp_noise=self.dp_noise, key=key,
            )

        args = [p1, o1, xs, ys]
        if dp:
            args.append(jax.ShapeDtypeStruct((2,), jnp.uint32))
        return compiled_flops(jax.jit(one_epoch), *args)

    def evaluate(self) -> dict:
        loss, acc = spmd_eval(self.params, self.x_test, self.y_test, module=self.module)
        return {
            "test_loss": float(jnp.mean(loss)),
            "test_acc": float(jnp.mean(acc)),
            "per_node_acc": np.asarray(acc).tolist(),
        }

    # ---- checkpoint / resume (absent in the reference; SURVEY §5) ----

    def save(self, directory: str) -> None:
        from p2pfl_tpu.learning.checkpoint import save_federation

        save_federation(directory, self)

    def restore(self, directory: str, step: Optional[int] = None) -> None:
        from p2pfl_tpu.learning.checkpoint import restore_federation

        restore_federation(directory, self, step)

    # ---- interop ----

    def node_params(self, i: int) -> Pytree:
        """Extract one node's params (for parity checks with Node mode)."""
        return jax.tree.map(lambda x: x[i], self.params)

    @classmethod
    def from_dataset(
        cls,
        model: FlaxModel,
        dataset: FederatedDataset,
        n_nodes: int,
        strategy: str = "iid",
        alpha: float = 0.5,
        **kwargs,
    ) -> "SpmdFederation":
        shards = [dataset.partition(i, n_nodes, strategy, alpha) for i in range(n_nodes)]
        return cls(model, shards, **kwargs)
