"""One-program SPMD federation: the ICI "transport".

The reference moves weights between nodes as pickled gRPC payloads and
aggregates them in Python (``p2pfl/learning/aggregators/fedavg.py:43-60``,
``grpc_client.py:142-179``). Here an entire federated round is ONE jitted
SPMD program over a ``(nodes, model)`` mesh:

- node-stacked params/opt-state/data arrays ``[N, ...]`` are sharded over
  the ``nodes`` axis — each chip owns its nodes' replicas;
- local training is a per-node ``lax.scan`` epoch, vectorized over the node
  axis (XLA partitions it across the mesh — zero communication);
- FedAvg is a masked, sample-weighted reduction over the node axis that XLA
  lowers to a single fp32 all-reduce over ICI, and the broadcast back is the
  reference's "diffusion" stage;
- election (the reference's vote protocol, ``vote_train_set_stage.py``) runs
  on host — it's a few hundred bytes — and enters the program as a ``[N]``
  mask.

Nothing touches the host inside a round: data lives device-resident across
rounds, per-round shuffles enter as ``[N, take]`` int32 index arrays.

Semantics preserved from the reference round (SURVEY §3.3): train-set
election in round 0 only, sample-weighted FedAvg over the train set,
aggregated model diffused to every node, optimizer state reset on
aggregation (the reference's ``set_parameters`` builds a fresh ``Trainer``
each round, ``lightning_learner.py:180-198``). Trades the reference's
asynchronous gossip for bulk-synchronous collectives — same round outcome,
orders of magnitude less overhead (SURVEY §7 "gossip semantics on
collectives").
"""

from __future__ import annotations

import math
import random
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import _loss, adam
from p2pfl_tpu.models.base import FlaxModel
from p2pfl_tpu.settings import Settings

Pytree = Any


# ---- pure round program (module-level => one jit cache for all federations) ----


def _local_epoch(params, opt_state, xs, ys, module, tx, remat: bool = False):
    """One node's epoch: scan of SGD steps (identical math to JaxLearner).

    ``remat=True`` wraps the loss in :func:`jax.checkpoint`: the backward
    pass recomputes activations instead of the scan storing every batch's —
    the HBM↔FLOPs trade that lets big models (ResNet-50 × many nodes) train
    on one chip.
    """
    import optax

    def step(carry, batch):
        p, o = carry
        x, y = batch

        def loss_fn(p_):
            return _loss(p_, module, x, y)[0]  # CE + sown aux (canonical definition)

        if remat:
            loss_fn = jax.checkpoint(loss_fn)
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = tx.update(grads, o, p)
        p = optax.apply_updates(p, updates)
        return (p, o), loss

    (params, opt_state), losses = jax.lax.scan(step, (params, opt_state), (xs, ys))
    return params, opt_state, jnp.mean(losses)


def _aggregate(p_used, mask, weights, sel_idx, agg: str, trim: int):
    """Combine node-stacked params [N, ...] into one model (fp32 accumulate).

    ``sel_idx`` is the [K] array of train-set ∩ active node indices
    (host-computed, K static per trace). The robust aggregators operate on
    the gathered [K, ...] stack only — non-elected / dropped slots hold
    stale copies of the previous aggregate and would otherwise dominate the
    coordinate-wise median and win Krum's distance score, silently freezing
    training (mirrors host Node mode, where robust aggregators only ever
    see train-set models).
    """
    from p2pfl_tpu.ops import aggregation as ops

    if agg == "fedavg":
        w = (mask * weights).astype(jnp.float32)
        wn = w / jnp.sum(w)
        return jax.tree.map(
            lambda x: jnp.tensordot(wn, x.astype(jnp.float32), axes=(0, 0)).astype(x.dtype),
            p_used,
        )
    k = sel_idx.shape[0]
    p_sel = jax.tree.map(lambda x: jnp.take(x, sel_idx, axis=0), p_used)
    if agg == "median":
        return jax.tree.map(
            lambda x: jnp.median(x.astype(jnp.float32), axis=0).astype(x.dtype), p_sel
        )
    if agg == "trimmed_mean":
        # clamp like the host-side TrimmedMean class: 2*trim must leave >=1 row
        t = min(trim, (k - 1) // 2)

        def tm(x):
            xs = jnp.sort(x.astype(jnp.float32), axis=0)
            kept = jax.lax.slice_in_dim(xs, t, k - t, axis=0)
            return jnp.mean(kept, axis=0).astype(x.dtype)

        return jax.tree.map(tm, p_sel)
    if agg == "krum":
        idx = ops.krum_select(p_sel, n_byzantine=trim, multi=1)

        def pick(x):
            return jnp.take(x, idx, axis=0).astype(jnp.float32).mean(axis=0).astype(x.dtype)

        return jax.tree.map(pick, p_sel)
    raise ValueError(f"unknown aggregator {agg}")


@partial(
    jax.jit,
    static_argnames=("module", "tx", "agg", "trim", "out_sharding", "keep_opt_state", "remat"),
    donate_argnums=(0, 1),
)
def spmd_round(
    stacked_params,  # [N, ...] pytree
    opt_states,  # [N, ...] pytree
    x_all,  # [N, S, ...] node-resident datasets
    y_all,  # [N, S]
    perm,  # [N, epochs, nb, bs] int32 shuffle indices (host-generated)
    mask,  # [N] 1.0 = in train set
    weights,  # [N] sample counts
    sel_idx,  # [K] int32 indices of mask==1 rows (robust aggregation support)
    *,
    module,
    tx,
    agg: str = "fedavg",
    trim: int = 0,
    out_sharding=None,
    keep_opt_state: bool = False,
    remat: bool = False,
    x_test=None,
    y_test=None,
):
    """One federated round for all N nodes.

    Returns (params', opt', mean loss[, test acc]) — the accuracy of the
    aggregated model is fused into the same program when test data is given
    (one device dispatch for train + aggregate + diffuse + eval).
    """
    n = mask.shape[0]

    # gather per-epoch batches: idx [epochs, nb, bs] → x[idx] [epochs, nb, bs, ...]
    def node_fn(params, opt_state, x, y, idx):
        def epoch_body(carry, ep_idx):
            p, o = carry
            xs = jnp.take(x, ep_idx, axis=0)  # [nb, bs, ...]
            ys = jnp.take(y, ep_idx, axis=0)
            p, o, loss = _local_epoch(p, o, xs, ys, module, tx, remat)
            return (p, o), loss

        (params, opt_state), losses = jax.lax.scan(epoch_body, (params, opt_state), idx)
        return params, opt_state, jnp.mean(losses)

    trained_p, trained_o, losses = jax.vmap(node_fn)(stacked_params, opt_states, x_all, y_all, perm)

    # non-train-set nodes contribute their previous params (they don't train)
    def sel(new, old):
        m = mask.reshape((n,) + (1,) * (new.ndim - 1)).astype(new.dtype)
        return new * m + old * (1 - m)

    p_used = jax.tree.map(sel, trained_p, stacked_params)
    agg_params = _aggregate(p_used, mask, weights, sel_idx, agg, trim)

    # diffusion: every node receives the aggregate
    out_params = jax.tree.map(lambda a: jnp.broadcast_to(a[None], (n, *a.shape)), agg_params)
    if out_sharding is not None:
        # pin the node-stacked layout so round k+1 reuses round k's executable
        # (otherwise the broadcast's replicated layout forces a relayout+retrace)
        out_params = jax.tree.map(
            lambda a: jax.lax.with_sharding_constraint(a, out_sharding), out_params
        )
    if keep_opt_state:
        # documented improvement over the reference: carry Adam moments
        # across rounds (the reference rebuilds its Trainer per round,
        # losing them — slower convergence)
        out_opt = trained_o
    else:
        out_opt = jax.vmap(tx.init)(out_params)
    if out_sharding is not None:
        # vmap(tx.init) outputs otherwise come back replicated, flipping the
        # opt-state layout between rounds and forcing a recompile per variant
        out_opt = jax.tree.map(
            lambda a: jax.lax.with_sharding_constraint(a, out_sharding), out_opt
        )
    mean_loss = jnp.mean(losses, where=mask.astype(bool))
    if x_test is None:
        return out_params, out_opt, mean_loss

    def node_acc(x, y):
        logits = module.apply({"params": agg_params}, x)
        return jnp.mean((jnp.argmax(logits, axis=-1) == y).astype(jnp.float32))

    acc = jnp.mean(jax.vmap(node_acc)(x_test, y_test))
    return out_params, out_opt, mean_loss, acc


@partial(jax.jit, static_argnames=("module",))
def spmd_eval(stacked_params, x_test, y_test, *, module):
    """Per-node eval over node-stacked test shards. Returns ([N] loss, [N] acc)."""
    import optax

    def node_eval(params, x, y):
        logits = module.apply({"params": params}, x)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
        acc = jnp.mean((jnp.argmax(logits, axis=-1) == y).astype(jnp.float32))
        return loss, acc

    return jax.vmap(node_eval)(stacked_params, x_test, y_test)


# ---- host-side driver ----


class SpmdFederation:
    """N federated nodes as one SPMD program over a device mesh.

    The drop-in high-throughput alternative to running N ``Node`` objects:
    same round semantics, same aggregators, none of the per-message overhead.
    """

    def __init__(
        self,
        model: FlaxModel,
        datasets: list[FederatedDataset],
        mesh: Optional[Mesh] = None,
        batch_size: int = 128,
        learning_rate: float = 1e-3,
        aggregator: str = "fedavg",
        trim: int = 0,
        vote: bool = True,
        keep_opt_state: bool = False,
        remat: bool = False,
        participation: float = 1.0,
        seed: int = 0,
    ) -> None:
        self.model = model
        self.module = model.module
        self.n = len(datasets)
        if self.n < 1:
            raise ValueError("need at least one dataset shard")
        self.datasets = datasets
        self.batch_size = batch_size
        self.tx = adam(learning_rate)
        self.aggregator = aggregator
        self.trim = trim
        self.keep_opt_state = keep_opt_state
        self.remat = remat
        if not 0.0 < participation <= 1.0:
            raise ValueError("participation must be in (0, 1]")
        self.participation = participation
        self._rng = np.random.default_rng(seed)
        self._py_rng = random.Random(seed)

        self.mesh = mesh if mesh is not None else self._default_mesh()
        axis = Settings.MESH_NODES_AXIS
        self._shard = NamedSharding(self.mesh, P(axis))  # shard axis 0 over nodes
        self._repl = NamedSharding(self.mesh, P())

        # device-resident data, truncated to common per-node sizes
        self._stage_data()
        # node-stacked state: every node starts from the same params
        # (reference: initiator's weights seed the network, §3.3)
        self._stage_state()

        # election state (round-0 vote, reused thereafter — reference quirk)
        self.train_mask = np.ones(self.n, dtype=np.float32)
        self._vote = vote
        # failure semantics on a mesh (SURVEY §7 "failure semantics on a
        # pod"): chips don't crash independently, so node failure is modeled
        # by masking slots out of training AND aggregation — the collective
        # analogue of heartbeat eviction
        self.active_mask = np.ones(self.n, dtype=np.float32)
        self.round = 0
        self.history: list[dict] = []

    def reset(self, seed: int = 0) -> None:
        """Back to round 0 with fresh state, keeping mesh/data/executables.

        Use this (not a new federation) to measure or restart: a new object
        builds a new Mesh and misses every jit cache.
        """
        self._rng = np.random.default_rng(seed)
        self._py_rng = random.Random(seed)
        self.train_mask = np.ones(self.n, dtype=np.float32)
        self.active_mask = np.ones(self.n, dtype=np.float32)
        self.round = 0
        self.history = []
        self._stage_state()

    def _stage_state(self) -> None:
        # jitted with out_shardings: the broadcast + init run ON DEVICE and
        # land directly in the mesh layout (a host-side device_put would
        # re-upload N x model_size through the host link)
        n = self.n

        @partial(jax.jit, out_shardings=(self._shard, self._shard))
        def stage(tree):
            stacked = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (n, *x.shape)), tree)
            return stacked, jax.vmap(self.tx.init)(stacked)

        self.params, self.opt_state = stage(self.model.params)

    def _default_mesh(self) -> Mesh:
        from p2pfl_tpu.parallel.mesh import federation_mesh

        devices = jax.devices()
        slots = min(self.n, len(devices))
        while self.n % slots != 0:  # fold nodes evenly onto mesh slots
            slots -= 1
        return federation_mesh(n_nodes=slots, devices=devices[:slots])

    def _stage_data(self) -> None:
        # node shards are padded (wrap-around) to a common static length so
        # they stack into one [N, S, ...] array, but each node's per-round
        # shuffle indices are drawn from its OWN sample range (``_make_perm``)
        # — so the FedAvg sample-count weights match the data each node
        # actually trains on (over rounds, every node covers its full shard)
        sizes = [d.num_samples for d in self.datasets]
        tr_min, tr_max = min(sizes), max(sizes)
        te_min = min(len(d.y_test) for d in self.datasets)
        if tr_min < self.batch_size:
            raise ValueError(f"smallest shard ({tr_min}) < batch size ({self.batch_size})")

        def wrap(a: np.ndarray, target: int) -> np.ndarray:
            if len(a) == target:
                return a
            reps = -(-target // len(a))
            return np.concatenate([a] * reps, axis=0)[:target]

        self.x_all = jax.device_put(
            np.stack([wrap(d.x_train, tr_max) for d in self.datasets]), self._shard
        )
        self.y_all = jax.device_put(
            np.stack([wrap(d.y_train, tr_max) for d in self.datasets]), self._shard
        )
        self.x_test = jax.device_put(
            np.stack([d.x_test[:te_min] for d in self.datasets]), self._shard
        )
        self.y_test = jax.device_put(
            np.stack([d.y_test[:te_min] for d in self.datasets]), self._shard
        )
        self._samples = jax.device_put(
            jnp.asarray([float(s) for s in sizes]), self._shard
        )
        self._sizes = sizes
        self._tr_size = tr_max
        self._nb = tr_min // self.batch_size

    # ---- election (host control plane — reference vote semantics) ----

    def elect_train_set(self) -> np.ndarray:
        """Round-0 election: every node casts weighted random votes
        (``vote_train_set_stage.py:78-81``); top ``TRAIN_SET_SIZE`` win."""
        names = list(range(self.n))
        tally: dict[int, int] = {}
        k = min(Settings.TRAIN_SET_SIZE, self.n)
        for _voter in names:
            picks = self._py_rng.sample(names, k)
            for i, cand in enumerate(picks):
                tally[cand] = tally.get(cand, 0) + math.floor(self._py_rng.randint(0, 1000) / (i + 1))
        ranked = sorted(tally.items(), key=lambda kv: (kv[1], kv[0]), reverse=True)
        mask = np.zeros(self.n, dtype=np.float32)
        for cand, _ in ranked[:k]:
            mask[cand] = 1.0
        return mask

    # ---- round driver ----

    def _make_perm(self, epochs: int):
        take = self._nb * self.batch_size  # always <= min shard size
        perm = np.stack(
            [
                np.stack(
                    [
                        self._rng.permutation(self._sizes[i])[:take].reshape(
                            self._nb, self.batch_size
                        )
                        for _ in range(epochs)
                    ]
                )
                for i in range(self.n)
            ]
        ).astype(np.int32)
        return jax.device_put(perm, self._shard)

    def _effective_mask(self) -> np.ndarray:
        """Train-set ∩ active nodes, optionally client-sampled per round."""
        effective = self.train_mask * self.active_mask
        if self.participation < 1.0:
            # FedAvg-style client sampling: each round a random fraction of
            # the eligible nodes trains (McMahan et al. 2017 C-fraction)
            eligible = np.flatnonzero(effective)
            k = max(1, round(self.participation * len(eligible)))
            chosen = self._rng.choice(eligible, size=k, replace=False)
            effective = np.zeros_like(effective)
            effective[chosen] = 1.0
        if effective.sum() == 0:
            raise RuntimeError("no active train-set nodes left")
        return effective

    def drop_node(self, i: int) -> None:
        """Mark a logical node failed: it stops training and contributing
        (the reference's heartbeat-eviction outcome, ``heartbeater.py:91-101``)."""
        self.active_mask[i] = 0.0

    def restore_node(self, i: int) -> None:
        self.active_mask[i] = 1.0

    def run_round(self, epochs: int = 1, eval: bool = False) -> dict:  # noqa: A002
        if self._vote and (self.round == 0 or Settings.VOTE_EVERY_ROUND):
            self.train_mask = self.elect_train_set()
        perm = self._make_perm(epochs)
        eff = self._effective_mask()
        mask = jax.device_put(jnp.asarray(eff), self._shard)
        # robust aggregators see only the [K] selected rows; K is static per
        # mask pattern, so the executable is reused as long as K is stable
        sel_idx = jax.device_put(np.flatnonzero(eff).astype(np.int32), self._repl)
        result = spmd_round(
            self.params,
            self.opt_state,
            self.x_all,
            self.y_all,
            perm,
            mask,
            self._samples,
            sel_idx,
            module=self.module,
            tx=self.tx,
            agg=self.aggregator,
            trim=self.trim,
            out_sharding=self._shard,
            keep_opt_state=self.keep_opt_state,
            remat=self.remat,
            x_test=self.x_test if eval else None,
            y_test=self.y_test if eval else None,
        )
        self.params, self.opt_state, loss = result[:3]
        self.round += 1
        # keep the loss as a device scalar: rounds pipeline back-to-back with
        # no host sync; it coerces to float lazily (e.g. when printed)
        entry = {"round": self.round, "train_loss": loss}
        if eval:
            entry["test_acc"] = result[3]
        self.history.append(entry)
        return entry

    def run(self, rounds: int, epochs: int = 1, eval_every: int = 0) -> list[dict]:
        for r in range(rounds):
            entry = self.run_round(epochs)
            if eval_every and (r + 1) % eval_every == 0:
                entry.update(self.evaluate())
        return self.history

    def round_flops(self, epochs: int = 1) -> Optional[float]:
        """Compiled FLOPs of one no-eval round (XLA cost analysis).

        Used by the benchmarks for MFU; returns None when the backend
        exposes no cost analysis.
        """
        from p2pfl_tpu.management.profiling import compiled_flops

        perm = self._make_perm(epochs)
        eff = self._effective_mask()
        mask = jax.device_put(jnp.asarray(eff), self._shard)
        sel_idx = jax.device_put(np.flatnonzero(eff).astype(np.int32), self._repl)
        return compiled_flops(
            spmd_round,
            self.params, self.opt_state, self.x_all, self.y_all, perm, mask,
            self._samples, sel_idx,
            module=self.module, tx=self.tx, agg=self.aggregator, trim=self.trim,
            out_sharding=self._shard, keep_opt_state=self.keep_opt_state,
            remat=self.remat,
        )

    def evaluate(self) -> dict:
        loss, acc = spmd_eval(self.params, self.x_test, self.y_test, module=self.module)
        return {
            "test_loss": float(jnp.mean(loss)),
            "test_acc": float(jnp.mean(acc)),
            "per_node_acc": np.asarray(acc).tolist(),
        }

    # ---- checkpoint / resume (absent in the reference; SURVEY §5) ----

    def save(self, directory: str) -> None:
        from p2pfl_tpu.learning.checkpoint import save_federation

        save_federation(directory, self)

    def restore(self, directory: str, step: Optional[int] = None) -> None:
        from p2pfl_tpu.learning.checkpoint import restore_federation

        restore_federation(directory, self, step)

    # ---- interop ----

    def node_params(self, i: int) -> Pytree:
        """Extract one node's params (for parity checks with Node mode)."""
        return jax.tree.map(lambda x: x[i], self.params)

    @classmethod
    def from_dataset(
        cls,
        model: FlaxModel,
        dataset: FederatedDataset,
        n_nodes: int,
        strategy: str = "iid",
        alpha: float = 0.5,
        **kwargs,
    ) -> "SpmdFederation":
        shards = [dataset.partition(i, n_nodes, strategy, alpha) for i in range(n_nodes)]
        return cls(model, shards, **kwargs)
