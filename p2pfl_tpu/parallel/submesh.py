"""Sharded nodes: one federation node = a ``(data, model)`` pjit submesh.

:class:`~p2pfl_tpu.parallel.spmd.SpmdFederation` assumes a node fits one
chip — the 1B nameplate row already strains that. Here the global device
mesh is carved into N node slices (:func:`~p2pfl_tpu.parallel.mesh.
submesh_federation_mesh` / ``node_slices``): each federated node owns a
``(data, model)`` submesh, its params AND optimizer state placed by the
partition-rule engine (``parallel/sharding.py match_partition_rules``)
via ``NamedSharding``, and its whole round runs as ONE donated sharded
dispatch on its own slice (:func:`submesh_node_round` — the same
:func:`~p2pfl_tpu.parallel.spmd._node_round_core` program the overlay
fused round compiles, so ``model_parallel=1`` is the bit-parity
baseline). Node size is now independent of chip size: federate 8 nodes
× 8-chip submeshes on a v4-64.

Cross-slice aggregation is a collective, not a gather:

1. every node's fused round already folds its own ``weight × params``
   partial accumulator (``psum``/``wsum`` in ``Settings.AGG_DTYPE`` — the
   fused-overlay contract) with a leading length-1 node axis;
2. the per-slice accumulators are assembled ZERO-COPY into one
   node-stacked global array (``jax.make_array_from_single_device_arrays``
   — device ``(i, j, k)`` of the global mesh already holds exactly block
   ``(i, k)`` of the stack, so assembly is metadata only);
3. one jit over the global mesh reduces the sharded node axis
   (:func:`~p2pfl_tpu.ops.aggregation.fedavg_fold_stacked`) — XLA lowers
   it to a per-shard partial sum + all-reduce over ICI across slices.
   The output is model-axis-sharded and node-axis-replicated: that
   replication IS the diffusion, landing every node's next-round shards
   in place. No device ever materializes a full model (asserted on the
   fold's input/output sharding specs every round).

Numerics: the fold accumulates-then-divides (``fedavg_fold_acc``
algebra). With equal node weights that is bit-identical to
:class:`SpmdFederation`'s normalize-then-tensordot (common-factor scaling
commutes with rounding); with unequal weights they agree to
summation-order ulp — see ``ops/aggregation.py``.

Scope: FedAvg (+ FedProx local steps), and — via ``robust_agg=
"median"|"trimmed-mean"`` — per-coordinate ROBUST folds over the same
node-stacked layout (:func:`~p2pfl_tpu.ops.aggregation.
robust_fold_stacked`): the raw params stack assembles through the same
zero-copy GDA idiom and the partitioner re-shards node-stacks to
coordinate-shards for the per-coordinate sort, so each device only ever
holds the N values of its own model shard — the no-materialization
contract holds for the robust fold too (same sharding asserts). Robust
folds require full participation per round (a stale non-elected stack
entry would be folded as if Byzantine). Krum-family strategies need the
full ``[K, P]`` distance matrix on one program and the SPMD runtime
already serves them; SCAFFOLD / FedOpt / DP-SGD stay on
:class:`SpmdFederation` (rejected loudly here). Non-elected nodes are not dispatched at all —
they contribute an all-zeros accumulator to the fold (the exact ``w=0``
term the SPMD masked reduce carries) and receive the aggregate like
everyone else; under ``keep_opt_state=True`` their optimizer state
therefore stays at its pre-round value, where ``SpmdFederation`` trains
every slot and keeps even non-elected moments (a documented divergence —
irrelevant at full participation, which is also the parity-test regime).
"""

from __future__ import annotations

import random
from functools import partial
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import adam, sgd
from p2pfl_tpu.models.base import FlaxModel
from p2pfl_tpu.ops.aggregation import fedavg_fold_stacked
from p2pfl_tpu.parallel.mesh import node_slices, submesh_federation_mesh
from p2pfl_tpu.parallel.sharding import (
    DEFAULT_TRANSFORMER_RULES,
    PartitionRules,
    check_partition_rules,
    tree_shardings,
)
from p2pfl_tpu.parallel.spmd import (
    _node_round_core,
    draw_node_perms,
    elect_train_set_mask,
    stage_node_shards,
    tree_has_deleted,
)
from p2pfl_tpu.settings import Settings

Pytree = Any


@partial(
    jax.jit,
    static_argnames=(
        "module", "tx", "prox_mu", "with_acc", "agg_dtype", "batch_shardings"
    ),
    donate_argnums=(1,),
)
def submesh_node_round(
    params,
    opt_state,
    x,  # [S, ...] the node's full device-resident train shard
    y,  # [S]
    perm,  # [E, nb, bs] int32 shuffle indices (host-drawn, tiny)
    weight,  # fp32 scalar sample count
    x_test=None,
    y_test=None,
    *,
    module,
    tx,
    prox_mu: float = 0.0,
    with_acc: bool = True,
    agg_dtype: str = "float32",
    batch_shardings=None,
):
    """One sharded node's whole round as one donated dispatch on its slice.

    Exactly :func:`~p2pfl_tpu.parallel.spmd.fused_node_round` (same
    :func:`~p2pfl_tpu.parallel.spmd._node_round_core` trace — the
    bit-parity contract), with two differences:

    - the round's batches are gathered IN-PROGRAM from the node's
      device-resident shard (``jnp.take(x, perm)`` — the same gather the
      SPMD ``node_fn`` compiles), so only the tiny int32 ``perm`` crosses
      host→device per round instead of the whole training slice;
    - ``psum``/``wsum`` come back with a leading length-1 node axis, so
      each device's shard is already shaped ``[1, ...]`` — block
      ``(i, k)`` of the node-stacked global accumulator — and the
      cross-slice stack assembles zero-copy.

    ``batch_shardings`` (static ``(xs, ys)`` NamedShardings) pins the
    gathered batches' layout — batch dim over the node's ``data`` axis —
    so data-parallel slices split the epoch compute. Otherwise the
    program carries no explicit shardings: computation follows the
    arguments, params sharded over a node's submesh compile to a GSPMD
    program on that slice (XLA inserts the row-parallel all-reduces the
    partition rules imply), and the same call at ``model_parallel=1``
    compiles the single-chip program unchanged. ``opt_state`` is donated;
    ``params`` is not (the federation driver still owns them).
    """
    xs = jnp.take(x, perm, axis=0)  # [E, nb, bs, ...]
    ys = jnp.take(y, perm, axis=0)
    if batch_shardings is not None:
        xs = jax.lax.with_sharding_constraint(xs, batch_shardings[0])
        ys = jax.lax.with_sharding_constraint(ys, batch_shardings[1])
    out = _node_round_core(
        params, opt_state, xs, ys, weight, x_test, y_test,
        module=module, tx=tx, prox_mu=prox_mu, with_acc=with_acc,
        agg_dtype=agg_dtype,
    )
    if with_acc:
        out["psum"] = jax.tree.map(lambda v: v[None], out["psum"])
        out["wsum"] = out["wsum"][None]
    return out


def _ordered_device_arrays(sharding, shape, device_map):
    """Per-shard arrays in the order ``make_array_from_single_device_arrays``
    expects (this process's devices of ``sharding``, assignment order)."""
    return [device_map[d] for d in sharding.addressable_devices_indices_map(shape)]


def _device_map(arr) -> dict:
    return {s.device: s.data for s in arr.addressable_shards}


def stack_across_slices(global_mesh: Mesh, per_node: Sequence[Pytree]) -> Pytree:
    """Node-stacked global arrays from per-slice ``[1, ...]`` leaves, zero-copy.

    ``per_node[i]`` leaves live on node ``i``'s submesh with a leading
    length-1 node dim and spec ``P(None, *axes)``; the result's leaves are
    ``[N, ...]`` on ``global_mesh`` with spec ``P(nodes, *axes)``. Device
    ``(i, j, k)`` already holds exactly block ``(i, k)`` of the stack, so
    this is metadata assembly (``make_array_from_single_device_arrays``),
    not a transfer — the GDA idiom. Works multi-process: each process
    contributes the shards it addresses.
    """
    nodes_axis = Settings.MESH_NODES_AXIS
    n = len(per_node)
    flat = [jax.tree.leaves(t) for t in per_node]
    treedef = jax.tree.structure(per_node[0])
    out_leaves = []
    for li in range(len(flat[0])):
        leaves = [flat[i][li] for i in range(n)]
        spec = leaves[0].sharding.spec
        gshape = (n,) + tuple(leaves[0].shape[1:])
        gsharding = NamedSharding(global_mesh, P(nodes_axis, *spec[1:]))
        device_map = {}
        for leaf in leaves:
            device_map.update(_device_map(leaf))
        out_leaves.append(
            jax.make_array_from_single_device_arrays(
                gshape, gsharding, _ordered_device_arrays(gsharding, gshape, device_map)
            )
        )
    return jax.tree.unflatten(treedef, out_leaves)


def slice_views(garr_tree: Pytree, slice_mesh: Mesh, shardings: Pytree) -> Pytree:
    """A node's view of node-replicated global arrays, zero-copy.

    ``garr_tree`` leaves are global-mesh arrays replicated over the nodes
    (and data) axes — the fold's diffusion output. The slice's devices
    already hold the node's shards, so re-wrapping them under the node's
    submesh ``shardings`` is again metadata only.
    """
    devs = set(np.asarray(slice_mesh.devices).flat)

    def one(garr, sharding):
        dmap = {d: s for d, s in _device_map(garr).items() if d in devs}
        return jax.make_array_from_single_device_arrays(
            garr.shape, sharding, _ordered_device_arrays(sharding, garr.shape, dmap)
        )

    return jax.tree.map(one, garr_tree, shardings)


def per_device_bytes(*trees: Pytree) -> dict:
    """Addressable bytes each device holds across ``trees`` (live-buffer
    accounting for the no-replicated-model assertion and the HBM
    high-water bench column)."""
    out: dict = {}
    for tree in trees:
        for leaf in jax.tree.leaves(tree):
            if not isinstance(leaf, jax.Array):
                continue
            for s in leaf.addressable_shards:
                out[s.device] = out.get(s.device, 0) + s.data.nbytes
    return out


class ShardedNodeFederation:
    """N federated nodes, each a pjit submesh — FedAvg across slices.

    The sibling of :class:`~p2pfl_tpu.parallel.spmd.SpmdFederation` for
    models bigger than a chip: same election, same perm rng stream
    (:func:`~p2pfl_tpu.parallel.spmd.draw_node_perms`), same
    ``AGG_DTYPE`` accumulator contract — at ``model_parallel=1`` a round
    is bit-identical to the SPMD round on the same seed (pinned by
    ``tests/test_submesh.py``), at ``model_parallel>1`` it matches to
    summation-order ulp while every tensor the rules shard never exists
    whole on any single device.

    ``rules`` is a partition-rule set (``parallel/sharding.py``); it is
    linted against the model's named pytree and the node submesh at
    construction — unmatched paths, dead rules and unknown axes raise
    here, not after an hour of silent full replication. The same rules
    place the optimizer state (optax paths embed the param path).
    """

    def __init__(
        self,
        model: FlaxModel,
        datasets: list[FederatedDataset],
        *,
        model_parallel: int = 1,
        data_parallel: int = 1,
        rules: Optional[PartitionRules] = None,
        mesh: Optional[Mesh] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        batch_size: int = 128,
        learning_rate: float = 1e-3,
        optimizer: str = "adam",
        vote: bool = True,
        keep_opt_state: bool = False,
        prox_mu: float = 0.0,
        seed: int = 0,
        robust_agg: Optional[str] = None,
    ) -> None:
        self.model = model
        self.module = model.module
        self.n = len(datasets)
        if self.n < 1:
            raise ValueError("need at least one dataset shard")
        if Settings.SECURE_AGGREGATION:
            # same trust-domain argument as SpmdFederation: one process,
            # one address space — masking would protect against nobody
            raise ValueError(
                "SECURE_AGGREGATION=True has no effect inside "
                "ShardedNodeFederation: the mesh is one trust domain. Use "
                "gossip Node mode for secure aggregation."
            )
        if robust_agg not in (None, "median", "trimmed-mean"):
            raise ValueError(
                f"robust_agg must be None | 'median' | 'trimmed-mean', got {robust_agg!r}"
            )
        #: robust cross-slice fold (ROADMAP 3): per-coordinate
        #: median/trimmed-mean over the node-stacked PARAMS shard-by-shard
        #: instead of the weighted accumulator mean — same zero-copy stack
        #: assembly, same sharding asserts (no device ever materializes a
        #: full model). Requires full participation per round: a rank
        #: statistic over a stack holding non-elected nodes' stale params
        #: would silently fold garbage, so run_round raises instead.
        self.robust_agg = robust_agg
        self.datasets = datasets
        self.batch_size = batch_size
        self.learning_rate = learning_rate
        self.prox_mu = float(prox_mu)
        self.keep_opt_state = keep_opt_state
        if optimizer not in ("adam", "sgd"):
            raise ValueError(f"optimizer must be 'adam'|'sgd', got {optimizer!r}")
        self.tx = sgd(learning_rate) if optimizer == "sgd" else adam(learning_rate)
        self._rng = np.random.default_rng(seed)
        self._py_rng = random.Random(seed)

        if mesh is None:
            if devices is None:
                needed = self.n * data_parallel * model_parallel
                devices = jax.devices()[:needed]
            mesh = submesh_federation_mesh(
                self.n, model_parallel, data_parallel, devices=devices
            )
        nodes_axis = Settings.MESH_NODES_AXIS
        if mesh.shape.get(nodes_axis) != self.n:
            raise ValueError(
                f"mesh {dict(mesh.shape)} does not carry {self.n} slots on "
                f"the {nodes_axis!r} axis"
            )
        self.mesh = mesh
        self.slices = node_slices(mesh)

        # --- partition rules: lint loudly at construction ---
        explicit_rules = rules is not None
        self.rules: PartitionRules = tuple(rules) if explicit_rules else DEFAULT_TRANSFORMER_RULES
        # the builtin default set is deliberately wider than any one model
        # (dead transformer rules on an MLP are by design); explicit user
        # rules must be exactly right
        check_partition_rules(
            self.rules, model.params, self.slices[0], allow_dead=not explicit_rules
        )

        self._param_shardings = [
            tree_shardings(s, model.params, self.rules) for s in self.slices
        ]
        opt_struct = jax.eval_shape(self.tx.init, model.params)
        self._opt_shardings = [
            tree_shardings(s, opt_struct, self.rules) for s in self.slices
        ]
        self._opt_init = [
            jax.jit(self.tx.init, out_shardings=self._opt_shardings[i])
            for i in range(self.n)
        ]
        # psum carries a leading length-1 node axis (submesh_node_round);
        # its accumulate-dtype shardings mirror the params'
        self._acc_shardings = [
            jax.tree.map(
                lambda s: NamedSharding(s.mesh, P(None, *s.spec)), shardings
            )
            for shardings in self._param_shardings
        ]

        self._stage_data()
        self._stage_state()
        self._build_fold()

        self.train_mask = np.ones(self.n, dtype=np.float32)
        self._vote = vote
        self.active_mask = np.ones(self.n, dtype=np.float32)
        self.round = 0
        self.history: list[dict] = []
        # set by run_round: {"psum_shardings": pytree of the fold-input
        # shardings, "wsum": [N] weight vector} — metadata only, never the
        # accumulator buffers themselves
        self.last_fold: Optional[dict] = None

    # ---- staging ----

    def _stage_state(self) -> None:
        self.params = [
            jax.device_put(self.model.params, self._param_shardings[i])
            for i in range(self.n)
        ]
        self.opt_state = [self._opt_init[i](self.params[i]) for i in range(self.n)]

    def _stage_data(self) -> None:
        # padding/truncation/nb policy lives in the SHARED
        # stage_node_shards helper — the bit-parity rng contract between
        # the two drivers depends on identical sizing, so there is exactly
        # one implementation to drift
        staged = stage_node_shards(self.datasets, self.batch_size)
        self._sizes = staged["sizes"]
        self._nb = staged["nb"]
        data_axis = Settings.MESH_DATA_AXIS
        # each node's shard is staged device-resident ONCE, replicated
        # over its slice (data ≪ model is this runtime's premise); each
        # round ships only the tiny int32 perm and gathers in-program —
        # the SpmdFederation treatment, per slice
        self._x_dev = [
            jax.device_put(staged["x"][i], NamedSharding(self.slices[i], P()))
            for i in range(self.n)
        ]
        self._y_dev = [
            jax.device_put(staged["y"][i], NamedSharding(self.slices[i], P()))
            for i in range(self.n)
        ]
        self._xt_dev = [
            jax.device_put(staged["x_test"][i], NamedSharding(self.slices[i], P(data_axis)))
            for i in range(self.n)
        ]
        self._yt_dev = [
            jax.device_put(staged["y_test"][i], NamedSharding(self.slices[i], P(data_axis)))
            for i in range(self.n)
        ]
        # gathered batches [E, nb, bs, ...]: batch dim over the node's
        # data axis, replicated over model (in-program constraint)
        self._batch_shardings = [
            (
                NamedSharding(s, P(None, None, data_axis)),
                NamedSharding(s, P(None, None, data_axis)),
            )
            for s in self.slices
        ]

    def _build_fold(self) -> None:
        nodes_axis = Settings.MESH_NODES_AXIS
        ref = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self.model.params
        )
        # diffusion layout: model-sharded, node/data-replicated — each
        # slice's devices receive exactly their next-round shards
        agg_shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s.spec), self._param_shardings[0]
        )
        self._agg_shardings = agg_shardings

        def fold(stacked_psum, stacked_wsum):
            return fedavg_fold_stacked(stacked_psum, stacked_wsum, ref)

        self._fold = jax.jit(fold, out_shardings=agg_shardings)
        self._robust_fold = None
        self._expand_params = None
        if self.robust_agg is not None:
            from p2pfl_tpu.ops.aggregation import robust_fold_stacked

            kind = self.robust_agg
            # read OUTSIDE the traced fn: a Settings read inside would be
            # baked at first trace and silently go stale (jit-staleness)
            trim = int(Settings.ASYNC_TRIM)

            def rfold(stacked_params):
                return robust_fold_stacked(stacked_params, ref, kind, trim=trim)

            # model-sharded out_shardings: the partitioner re-shards the
            # node-stack to coordinate-shards for the per-coordinate sort,
            # so each device only ever holds the N values of ITS model
            # shard — N × (1/m) of the model, never a full copy
            self._robust_fold = jax.jit(rfold, out_shardings=agg_shardings)
            # per-slice leading-axis expansion [*] -> [1, *] so the raw
            # params stack assembles through the same zero-copy GDA idiom
            # as the accumulators (stack_across_slices wants P(None, *))
            self._expand_params = [
                jax.jit(
                    lambda p: jax.tree.map(lambda x: x[None], p),
                    out_shardings=jax.tree.map(
                        lambda s: NamedSharding(self.slices[i], P(None, *s.spec)),
                        self._param_shardings[i],
                    ),
                )
                for i in range(self.n)
            ]
        self._nodes_axis = nodes_axis
        # zero accumulator programs for non-elected nodes: the explicit
        # w=0 term of the SPMD masked reduce, keeping the fold's stacked
        # shape static at N
        acc_struct = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct((1, *x.shape), jnp.dtype(Settings.AGG_DTYPE)),
            self.model.params,
        )

        def zeros_like_struct(struct):
            return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), struct)

        self._zero_acc = [
            jax.jit(
                partial(zeros_like_struct, acc_struct),
                out_shardings=self._acc_shardings[i],
            )
            for i in range(self.n)
        ]
        self._zero_w = [
            jax.jit(
                lambda: jnp.zeros((1,), jnp.dtype(Settings.AGG_DTYPE)),
                out_shardings=NamedSharding(self.slices[i], P()),
            )
            for i in range(self.n)
        ]

    # ---- election / failure (host control plane, SPMD semantics) ----

    def elect_train_set(self) -> np.ndarray:
        return elect_train_set_mask(self.n, self._py_rng)

    def drop_node(self, i: int) -> None:
        self.active_mask[i] = 0.0

    def restore_node(self, i: int) -> None:
        self.active_mask[i] = 1.0

    # ---- round driver ----

    def _effective_mask(self) -> np.ndarray:
        effective = self.train_mask * self.active_mask
        if effective.sum() == 0:
            raise RuntimeError("no active train-set nodes left")
        return effective

    def _assert_fold_shardings(self, stacked_psum: Pytree, agg: Pytree) -> None:
        """The no-replicated-model contract, checked every round.

        Every stacked input leaf must be sharded over the nodes axis
        (spec[0] == nodes — a replicated stack would mean some device
        holds all N accumulators), and every output leaf must carry the
        node's param spec (model-sharded wherever the rules shard).
        Metadata-only checks; raising here beats OOMing a pod.
        """
        for path, leaf in zip(
            [p for p, _ in jax.tree_util.tree_flatten_with_path(stacked_psum)[0]],
            jax.tree.leaves(stacked_psum),
        ):
            if leaf.sharding.spec[0] != self._nodes_axis:
                raise RuntimeError(
                    f"cross-slice fold input {path} is not sharded over "
                    f"{self._nodes_axis!r}: {leaf.sharding.spec} — the stack "
                    "would replicate every node's accumulator"
                )
        expected = jax.tree.leaves(self._agg_shardings)
        for leaf, want in zip(jax.tree.leaves(agg), expected):
            if leaf.sharding.spec != want.spec:
                raise RuntimeError(
                    f"cross-slice fold output spec {leaf.sharding.spec} != "
                    f"expected {want.spec} — the aggregate left its sharded "
                    "layout"
                )

    def run_round(self, epochs: int = 1, eval: bool = False) -> dict:  # noqa: A002
        if self._vote and (self.round == 0 or Settings.VOTE_EVERY_ROUND):
            self.train_mask = self.elect_train_set()
        perms = draw_node_perms(self._rng, self._sizes, self._nb, self.batch_size, epochs)
        eff = self._effective_mask()
        robust = self.robust_agg is not None
        if robust and not all(eff):
            raise RuntimeError(
                f"robust_agg={self.robust_agg!r} requires full "
                "participation: a rank statistic over a stack holding "
                "non-elected/dropped nodes' stale params would silently "
                "fold garbage (elect everyone, or use the FedAvg fold)"
            )
        agg_dtype = Settings.AGG_DTYPE
        from p2pfl_tpu.management.profiling import dispatch_span

        psums, wsums, losses, evals = [], [], [], []
        for i in range(self.n):
            if not eff[i]:
                psums.append(self._zero_acc[i]())
                wsums.append(self._zero_w[i]())
                continue
            xt = yt = None
            if eval:
                xt, yt = self._xt_dev[i], self._yt_dev[i]
            try:
                with dispatch_span(
                    "submesh_node_round", "spmd", node_idx=i, epochs=epochs
                ):
                    out = submesh_node_round(
                        self.params[i], self.opt_state[i],
                        self._x_dev[i], self._y_dev[i], perms[i],
                        jnp.float32(self._sizes[i]), xt, yt,
                        module=self.module, tx=self.tx, prox_mu=self.prox_mu,
                        # the robust fold consumes raw params, never the
                        # weight x params accumulator — compile it out
                        # (saves a full fp32 params copy per node)
                        with_acc=not robust,
                        agg_dtype=agg_dtype,
                        batch_shardings=self._batch_shardings[i],
                    )
            except Exception:
                self._recover_donated_state(i)
                raise
            self.params[i] = out["params"]
            self.opt_state[i] = out["opt_state"]
            if not robust:
                psums.append(out["psum"])
                wsums.append(out["wsum"])
            losses.append(out["train_losses"])
            if eval:
                evals.append((out["eval_loss"], out["eval_acc"]))

        if robust:
            # robust fold runs over the raw node-stacked PARAMS (a median
            # of weight x params accumulators is not a median of models);
            # assembly is the same zero-copy GDA idiom as the accumulators
            expanded = [self._expand_params[i](self.params[i]) for i in range(self.n)]
            stacked = stack_across_slices(self.mesh, expanded)
            with dispatch_span("cross_slice_robust_fold", "spmd", nodes=self.n):
                agg = self._robust_fold(stacked)
            self._assert_fold_shardings(stacked, agg)
            self.last_fold = {
                "psum_shardings": jax.tree.map(lambda l: l.sharding, stacked),
                # rank-based fold: no weight vector enters the aggregate
                "wsum": None,
            }
        else:
            stacked_psum = stack_across_slices(self.mesh, psums)
            stacked_wsum = stack_across_slices(self.mesh, wsums)
            with dispatch_span("cross_slice_fold", "spmd", nodes=self.n):
                agg = self._fold(stacked_psum, stacked_wsum)
            self._assert_fold_shardings(stacked_psum, agg)
            # introspection record for tests/benches: the fold INPUT
            # shardings (metadata) and the tiny [N] weight vector —
            # deliberately NOT the stacked psum itself, which is a full
            # fp32 weight x params shard per device that must not outlive
            # the fold (it would silently add ~one params copy per device
            # to steady-state HBM)
            self.last_fold = {
                "psum_shardings": jax.tree.map(lambda l: l.sharding, stacked_psum),
                "wsum": stacked_wsum,
            }

        # diffusion: every node's slice already holds its shards of the
        # node-replicated aggregate — re-wrap per slice, zero copy
        for i in range(self.n):
            self.params[i] = slice_views(agg, self.slices[i], self._param_shardings[i])
            if not self.keep_opt_state:
                self.opt_state[i] = self._opt_init[i](self.params[i])
        self.round += 1
        entry: dict = {
            "round": self.round,
            # one host sync per round, matching the fused-overlay metric
            # contract (metrics flushed once, not per step)
            "train_loss": float(np.mean([np.mean(np.asarray(ls)) for ls in losses])),
        }
        if eval:
            entry["test_loss"] = float(np.mean([float(l) for l, _ in evals]))
            entry["test_acc"] = float(np.mean([float(a) for _, a in evals]))
        self.history.append(entry)
        return entry

    def run(self, rounds: int, epochs: int = 1) -> list[dict]:
        for _ in range(rounds):
            self.run_round(epochs)
        return self.history

    def _recover_donated_state(self, i: int) -> None:
        """A failed dispatch may have consumed node ``i``'s donated opt
        state — rebuild it (round-0 init) instead of poisoning every later
        round with deleted-array errors (the SpmdFederation remedy)."""
        if not tree_has_deleted(self.opt_state[i]):
            return
        from p2pfl_tpu.management.logger import logger

        logger.warning(
            "submesh",
            f"node {i} round dispatch failed after consuming donated opt "
            "state — rebuilding from init (its moment carry is lost)",
        )
        self.opt_state[i] = self._opt_init[i](self.params[i])

    # ---- evaluation / interop ----

    def evaluate(self) -> dict:
        """Per-node eval of each node's current params on its own slice."""
        from p2pfl_tpu.learning.learner import eval_step

        accs, tlosses = [], []
        for i in range(self.n):
            loss, acc = eval_step(
                self.params[i], self._xt_dev[i], self._yt_dev[i], module=self.module
            )
            tlosses.append(float(loss))
            accs.append(float(acc))
        return {
            "test_loss": float(np.mean(tlosses)),
            "test_acc": float(np.mean(accs)),
            "per_node_acc": accs,
        }

    def node_params(self, i: int) -> Pytree:
        """One node's params (sharded over its slice) — parity-check seam."""
        return self.params[i]

    def per_device_bytes(self) -> dict:
        """Live params+opt bytes per device (the HBM high-water proxy)."""
        return per_device_bytes(self.params, self.opt_state)

    @classmethod
    def from_dataset(
        cls,
        model: FlaxModel,
        dataset: FederatedDataset,
        n_nodes: int,
        strategy: str = "iid",
        alpha: float = 0.5,
        **kwargs,
    ) -> "ShardedNodeFederation":
        shards = [dataset.partition(i, n_nodes, strategy, alpha) for i in range(n_nodes)]
        return cls(model, shards, **kwargs)
