"""Framework exceptions.

Reference equivalents: ``p2pfl/exceptions.py:21-36``,
``p2pfl/learning/exceptions.py:21-31``,
``p2pfl/communication/exceptions.py:20``.
"""


class NodeRunningException(Exception):
    """Raised when an operation requires the node to be stopped (or vice versa)."""


class LearnerNotSetException(Exception):
    """Raised when a learning operation runs before a learner exists."""


class ZeroRoundsException(Exception):
    """Raised when learning is started with zero rounds."""


class DecodingParamsError(Exception):
    """Raised when a serialized weights payload cannot be decoded."""


class ModelNotMatchingError(Exception):
    """Raised when received parameters do not match the local model structure."""


class NeighborNotConnectedError(Exception):
    """Raised when sending to a neighbor that is not connected."""


class CommunicationError(Exception):
    """Raised on transport-level send failures."""
