"""Global configuration knobs.

Mirrors the reference's single mutable ``Settings`` class
(``p2pfl/settings.py:26-115``): class attributes mutated in place, read by
every layer. Same knob names where the concept is the same, so users of the
reference find what they expect; TPU-specific knobs are added at the bottom.
"""

from __future__ import annotations

from typing import Optional


class Settings:
    """Mutable global settings (class attributes, no instances needed)."""

    # --- general ---
    GRPC_TIMEOUT: float = 10.0  # seconds; also used by the memory transport
    LOG_LEVEL: str = "INFO"
    LOG_DIR: str = "logs"
    EXCLUDE_BEAT_LOGS: bool = True

    # --- heartbeat (membership / failure detection) ---
    HEARTBEAT_PERIOD: float = 2.0
    HEARTBEAT_TIMEOUT: float = 5.0

    # --- gossip (message plane) ---
    GOSSIP_PERIOD: float = 0.1
    TTL: int = 10
    GOSSIP_MESSAGES_PER_PERIOD: int = 100
    AMOUNT_LAST_MESSAGES_SAVED: int = 100

    # --- gossip (model plane) ---
    GOSSIP_MODELS_PERIOD: float = 1.0
    GOSSIP_MODELS_PER_ROUND: int = 2
    GOSSIP_EXIT_ON_X_EQUAL_ROUNDS: int = 10

    # --- gossip (data plane: encode-once + concurrent fan-out) ---
    # Worker threads per gossiper for dispatching sends (both planes). A
    # stalled peer occupies one worker slot instead of serializing the
    # whole tick behind it; 1 restores the pre-overhaul strictly sequential
    # behavior — sends run inline on the calling thread with NO send
    # budget, so a stalled peer once again blocks its whole tick.
    GOSSIP_SEND_WORKERS: int = 4
    # Per-send wall-clock budget: a tick stops waiting for a send after
    # this many seconds (the send keeps running on its worker and the
    # neighbor is skipped while it is still in flight).
    GOSSIP_SEND_TIMEOUT: float = 5.0
    # Reuse encoded weight payload bytes across candidates/ticks while the
    # model version is unchanged (learning/weights.py PayloadCache). False
    # re-encodes per send — only useful for benchmarking the cache itself.
    GOSSIP_PAYLOAD_CACHE: bool = True
    # In-memory transport: round-trip weight payloads through the wire
    # codec (encode on send, materialize on receive) instead of passing
    # the pytree by reference. Simulations stay zero-copy by default; True
    # exercises/benches the real byte path without sockets (bench_gossip).
    MEMORY_WIRE_CODEC: bool = False

    # --- control-plane reliability (communication/reliability.py) ---
    # Failed message-plane sends are retried with exponential backoff +
    # jitter up to this many attempts (0 restores the old fire-and-forget
    # behavior where a False return silently lost the broadcast).
    MESSAGE_RETRY_MAX: int = 4
    # First-retry backoff; attempt a waits BASE * 2**(a-1), capped below.
    MESSAGE_RETRY_BASE: float = 0.25
    MESSAGE_RETRY_CAP: float = 2.0
    # Consecutive send failures (any plane) before a neighbor is SUSPECT.
    BREAKER_THRESHOLD: int = 3
    # A suspect neighbor is evicted after this many seconds of beat
    # silence instead of the full HEARTBEAT_TIMEOUT — send failures feed
    # failure detection continuously (accrual-style) rather than relying
    # on one binary timeout. Must exceed HEARTBEAT_PERIOD with slack
    # (keep ~2x): a live suspect's last_beat age reaches a full period
    # between beats, and a window equal to the period would evict on
    # ordinary delivery jitter rather than actual silence.
    BREAKER_SUSPECT_TIMEOUT: float = 4.0
    # Mid-round train-set repair (learning/aggregators/aggregator.py):
    # when a train-set member is evicted mid-round, shrink the round's
    # coverage target to the live members and re-announce coverage, so
    # aggregation resolves to the survivors' partial instead of burning
    # the full AGGREGATION_TIMEOUT. Automatically inert under
    # SECURE_AGGREGATION (secagg's seed-recovery machinery owns dropouts
    # there — masks must be recovered, not skipped).
    TRAIN_SET_REPAIR: bool = True
    # An init_model that arrives BEFORE this node processed start_learning
    # (the weights plane can beat the TTL-flooded control broadcast,
    # especially when start_learning rides a retry backoff) is stashed and
    # consumed by StartLearningStage if the experiment starts within this
    # many seconds — instead of being dropped and relying on a redelivery
    # the initiator's push loop may never make (it exits once its status
    # view stops changing). The window is the ONLY discriminator between
    # that race and a LATE init from a previous aborted experiment for
    # frames from OLD senders that lack the optional "xp" experiment-
    # identity header (frames that carry it are filtered exactly, with no
    # heuristics — Node.take_early_init), so keep it just wide enough
    # for the race: total message-plane retry backoff (~ MESSAGE_RETRY_MAX
    # backoffs capped at MESSAGE_RETRY_CAP) plus flood relay lag — and
    # well under any realistic gap between experiments, or a stale stash
    # could seed the next experiment and shadow its real init.
    EARLY_INIT_TTL: float = 15.0

    # --- learning round ---
    TRAIN_SET_SIZE: int = 4
    VOTE_TIMEOUT: float = 60.0
    AGGREGATION_TIMEOUT: float = 300.0
    WAIT_HEARTBEATS_CONVERGENCE: float = 1.0
    # The reference votes only in round 0 and reuses that train set forever
    # (``round_finished_stage.py:69-70``). False replicates that; True
    # re-elects every round (recommended when nodes churn).
    VOTE_EVERY_ROUND: bool = False

    # --- monitoring ---
    # Round flight recorder (management/telemetry.py): wire-propagated
    # trace spans, the unified counter/histogram registry and the
    # Perfetto-loadable exporter. Disabling skips span/histogram recording
    # entirely (counters — comm metrics, dispatch counts — always stay on:
    # they are load-bearing for tests and benches, and one locked dict
    # increment is not measurable overhead).
    TELEMETRY_ENABLED: bool = True
    # Per-node span ring-buffer bound: a flight recorder keeps the recent
    # past, not an archive — old spans fall off instead of growing memory
    # for the life of a long federation.
    TELEMETRY_RING_SPANS: int = 4096
    # Record spans for heartbeat 'beat' sends/receives. Off by default:
    # beats flood at 1/HEARTBEAT_PERIOD per neighbor and would both crowd
    # the ring and dominate the overhead budget (the same rationale as
    # EXCLUDE_BEAT_LOGS; beat *evictions* and breaker transitions are
    # always recorded as events).
    TELEMETRY_BEAT_SPANS: bool = False
    # Bridge dispatch spans to jax.profiler.TraceAnnotation so the host-side
    # dispatch timeline lines up with XLA's device timeline in a captured
    # profiler trace. None = auto (annotate on accelerators, skip on CPU
    # where there is no separate device timeline to correlate).
    TELEMETRY_JAX_ANNOTATIONS: Optional[bool] = None
    RESOURCE_MONITOR_PERIOD: float = 1.0
    # Stall watchdog (management/watchdog.py): when > 0, a daemon thread
    # dumps every thread's stack if a learning node makes no stage
    # transition for this many seconds. Detection only; 0 disables.
    STALL_WATCHDOG_S: float = 0.0

    # --- TPU-native additions ---
    # Default dtype for on-wire / aggregation math. bfloat16 keeps matmuls on
    # the MXU; aggregation accumulates in float32 for exactness.
    COMPUTE_DTYPE: str = "bfloat16"
    AGG_DTYPE: str = "float32"
    # Donate weight buffers into jitted aggregation / train steps.
    DONATE_BUFFERS: bool = True
    # Mesh axis names used by the parallel runtime. ``nodes`` indexes
    # federated nodes (or node slices), ``model`` is intra-node tensor
    # parallelism, ``data`` is intra-node batch parallelism (submesh
    # federations — parallel/submesh.py — give every node a
    # ``(data, model)`` slice of the global ``(nodes, data, model)`` mesh).
    MESH_NODES_AXIS: str = "nodes"
    MESH_MODEL_AXIS: str = "model"
    MESH_DATA_AXIS: str = "data"
    # ``clients`` is the megafleet engine's 1-D mesh axis: the simulated
    # edge population's parameter rows are sharded over it while the
    # small admission/window state stays replicated
    # (parallel/fleet_mesh.py, ops/fleet_kernels.py
    # run_fleet_program_sharded).
    MESH_CLIENTS_AXIS: str = "clients"
    # Outgoing gRPC frame format: "envelope" (compact JSON-header frames,
    # the default) | "protobuf" (the reference's node.proto schema —
    # communication/proto_wire.py; control plane fully interoperable with
    # a reference node, weight payloads stay the safe P2TW codec).
    # Receivers sniff per frame, so mixed-format federations interoperate
    # regardless of this knob.
    WIRE_FORMAT: str = "envelope"
    # Wire compression for network transports: "none" | "int8" | "topk8"
    # (int8 = symmetric per-tensor quantization, 4x smaller gossip payloads,
    # native C++ hot loop when p2pfl_tpu/native is built; topk8 = top-k
    # sparsified int8 DELTAS against the round-start global model — 0.25
    # bytes/param at the default fraction, 16x under dense float32 — with
    # error feedback).
    WIRE_COMPRESSION: str = "none"
    # Fraction of delta coordinates kept per tensor by topk8.
    TOPK_FRACTION: float = 0.05
    # Run the int8/topk8 encode as one fused device program
    # (ops/compression.py): (params − anchor) delta, error-feedback add,
    # batched top-k and int8 quantization in a single jit dispatch, with
    # only the compressed (idx, q, scale) buffers crossing device→host and
    # the EF residual staying device-resident between rounds. Engages only
    # when the params are already jax Arrays; False forces the host numpy
    # path (bit-format-compatible baseline — one decoder decodes both).
    # The decode side mirrors it: a device-resident anchor is updated by a
    # fused scatter-add instead of a host ravel-copy.
    #
    # None (the default) auto-selects by backend: the device producer on
    # accelerators (where eliminating the D2H pull is the point), the host
    # producer on XLA:CPU (where "device" is the same host and XLA's exact
    # TopK loses wall-clock to numpy's introselect — the PR-4 measurement).
    # An explicit True/False overrides the auto-select either way; read the
    # resolved value through :func:`wire_compression_device`.
    WIRE_COMPRESSION_DEVICE: Optional[bool] = None
    # Fuse the overlay round's node compute (eval forward + local epochs +
    # the node's own weighted fp32 partial-aggregation fold) into ONE
    # donated jit dispatch per node per round (parallel/spmd.py
    # fused_node_round, driven by JaxLearner.fused_round). The staged path
    # (eval dispatch + one train dispatch per epoch + host-side metric
    # syncs between them) is kept as the bit-parity baseline behind
    # False — the same pattern as CHUNK_FUSED_REDUCE. Learners that
    # cannot fuse (DummyLearner, LoRA, personalization, DP-SGD) fall back
    # to the staged path automatically.
    ROUND_FUSED: bool = True
    # Error feedback for topk8: dropped coordinates accumulate locally and
    # re-enter the next round's delta (Seide et al. 2014).
    TOPK_ERROR_FEEDBACK: bool = True

    # --- streaming byte plane (learning/weights.py + grpc_transport.py) ---
    # Client-streaming weights sends: a payload estimated at/above
    # WIRE_STREAM_THRESHOLD megabytes ships as a sequence of
    # self-delimiting P2TC chunk frames over ``send_weights_stream``
    # instead of one unary blob — encode of chunk i+1, wire transfer of
    # chunk i and receiver-side decode of chunk i−1 overlap, and the
    # receiver's peak payload memory is O(chunk × window) instead of
    # O(model). Chunk bodies concatenate to EXACTLY the unary P2TW frame
    # (one decoder core, byte-compatible at the leaf level). False
    # disables both sending streams and accepting them (a peer with
    # streaming off answers "stream-unsupported" and senders fall back
    # loudly to unary for that peer — ``stream_fallback_unary`` metric).
    # Protobuf-interop peers (WIRE_FORMAT="protobuf") never stream.
    WIRE_STREAM_ENABLED: bool = True
    # Stream-vs-unary cut, in MB of ESTIMATED payload (cheap metadata walk,
    # no encode): small payloads keep the one-round-trip unary path — the
    # pipeline only pays for itself when a payload spans many chunks.
    WIRE_STREAM_THRESHOLD: float = 8.0
    # Chunk slab size (MB). Cuts are leaf-aligned when leaves are smaller
    # than a slab (so the receiver decodes whole leaves per chunk); leaves
    # larger than a slab are split. 1–4 MB amortizes per-chunk overhead
    # (17-byte frame + CRC32C pass) while keeping the bounded-memory
    # window small.
    WIRE_CHUNK_MB: float = 2.0
    # In-flight chunk budget of the memory transport's streaming pump (a
    # bounded queue between the producer thread and the receiving
    # dispatch) — the backpressure window a real socket's flow control
    # gives the gRPC path. Receiver scratch is bounded by roughly
    # WIRE_CHUNK_MB × this window plus one leaf.
    WIRE_STREAM_WINDOW: int = 4
    # gRPC max send/receive message size (MB), applied to every channel
    # AND the server. gRPC's 4 MB default silently caps unary weights
    # payloads (RESOURCE_EXHAUSTED); raise this for big unary models —
    # streamed chunks stay ~WIRE_CHUNK_MB regardless.
    GRPC_MAX_MESSAGE_MB: int = 512
    # gRPC server executor threads (was hardcoded 4): a high-fan-in
    # aggregator otherwise serializes every inbound handler behind 4
    # threads.
    GRPC_SERVER_WORKERS: int = 4

    # --- shard-native ICI weights plane (communication/ici.py) ---
    # Which transport carries MODEL payloads between co-located nodes:
    # "bytes" is the existing behavior (the weights plane rides the same
    # transport as the control plane — encoded frames over gRPC, or the
    # in-memory reference/byte path); "ici" exchanges SHARDS shard-to-
    # shard between nodes registered on the shard-plane registry — each
    # device copies its parameter block directly to the matching device
    # of the peer's slice (a collective-permute / Pallas remote DMA over
    # the interconnect), composing with the device-side top-k/int8 codec
    # so the encode→transfer→decode→merge chain never touches the host.
    # The control plane (votes, coverage, beats) ALWAYS keeps riding the
    # byte transport; per-peer ineligibility (unregistered peer,
    # different process, mismatched slice topology) falls back loudly to
    # the byte path for that peer only (``ici_fallback_bytes`` metric),
    # never aborts the round. "dcn" is the superset plane: co-resident
    # peers still ride ICI, and peers in a DIFFERENT process of the same
    # ``jax.distributed`` world move model payloads as device arrays over
    # XLA's cross-host collectives (communication/dcn.py +
    # parallel/dcn_plane.py) — never pickled numpy over gRPC — with the
    # same per-edge loud byte fallback (``dcn_fallback_bytes``) for
    # everything else. Per-edge ladder under "dcn": ICI → DCN → bytes.
    WEIGHTS_PLANE: str = "bytes"
    # Shard-transfer backend for the ICI plane: "pallas" is the TPU
    # remote-DMA kernel (parallel/ici_plane.py — each device RDMAs its
    # block straight to the partner device's HBM), "ppermute" the pure-
    # XLA collective-permute program that runs anywhere (the CPU-runnable
    # bit-parity fallback the chaos suite and tier-1 exercise). "auto"
    # resolves by backend via :func:`ici_backend`: pallas on TPU,
    # ppermute elsewhere. Both move the same shards — backend choice can
    # never change what the receiver decodes.
    ICI_BACKEND: str = "auto"
    # --- DCN weights-plane rendezvous (communication/dcn.py) ---
    # World-directory snapshot TTL: peer-address → process-placement
    # lookups read the distributed runtime's KV store at most once per
    # this many seconds and serve from the snapshot in between.
    DCN_DIR_TTL_S: float = 2.0
    # How long a sender waits for the receiver's accept/nack before
    # aborting the rendezvous and falling back to the byte path.
    DCN_ACCEPT_TIMEOUT_S: float = 5.0
    # How long either side waits for the peer's ready (and for this
    # process's dispatch-order lock) before aborting — the bound that
    # turns any rendezvous disorder into a loud fallback, never a hang.
    DCN_READY_TIMEOUT_S: float = 10.0
    # How long a sender waits for the receiver's decode+delivery verdict
    # AFTER the collective fired. Expiry FAILS the send (gossip retry
    # machinery takes over) instead of falling back — the payload may
    # already have landed, and a byte resend could double-deliver.
    DCN_DONE_TIMEOUT_S: float = 60.0

    # --- async bounded-staleness federation (p2pfl_tpu/federation/) ---
    # Which control plane drives the learning thread: "sync" is the round
    # FSM (stages/learning_stages.py — barrier-synchronized rounds, the
    # reference semantics); "async" is the FedBuff-style buffered control
    # plane (federation/workflow.py — contributions apply as they arrive
    # with a staleness weight, no round barrier; Nguyen et al. 2022).
    FEDERATION_MODE: str = "sync"
    # Buffer size K: an aggregator merges once K accepted contributions
    # are buffered (FedBuff's one tunable). Aggregator tiers clamp it to
    # their fan-in (min(K, #children)) so a small cluster still flushes.
    FEDBUFF_K: int = 4
    # Staleness-weight exponent α in w(τ) = 1/(1+τ)^α: τ is how many
    # global model versions elapsed between the version a contribution
    # was trained FROM and the version it merges INTO. 0 disables
    # down-weighting; 0.5 is FedBuff's default polynomial weighting.
    FEDBUFF_ALPHA: float = 0.5
    # Server mixing rate η: new_global = (1-η)·global + η·weighted_avg.
    # 1.0 replaces the global with the buffer's staleness-weighted average
    # (the FedAvg-like limit); lower values damp each merge.
    FEDBUFF_SERVER_LR: float = 1.0
    # BOUNDED staleness: contributions older than this many global
    # versions are dropped (counted async_stale_drop) instead of merged
    # with a vanishing weight — the bound that keeps a wedged straggler's
    # months-old update from ever touching the model.
    ASYNC_MAX_STALENESS: int = 16
    # Hierarchical topology (federation/topology.py): members are chunked
    # into edge clusters of this size, each with an elected regional
    # aggregator buffering locally and pushing one aggregate per flush to
    # the global tier. 0 = flat (single global aggregator, FedBuff
    # classic). Clamped to the fleet size.
    HIER_CLUSTER_SIZE: int = 0
    # How long an aggregator keeps serving after finishing its own local
    # update budget, waiting for slower members' async_done announcements
    # (eviction of a dead member also releases it) before it exits.
    ASYNC_DRAIN_TIMEOUT: float = 30.0
    # How long a node JOINING a running async experiment
    # (Node.join_async_experiment) waits for its bootstrap pull — the
    # nearest aggregator's current global, requested via async_pull —
    # before contributing from its own local init instead. The pull is a
    # single direct round-trip, so this only needs to cover connection
    # setup plus one full-model push.
    ASYNC_JOIN_TIMEOUT: float = 15.0
    # --- crash-resurrection journal (federation/durability.py) ---
    # Snapshot cadence: a node with a journal attached commits one
    # snapshot every N of its own training updates (plus one final
    # snapshot at drain/leave). 1 = after every update — the tightest
    # recovery point; raise to amortize the disk write against very
    # short local epochs.
    JOURNAL_EVERY_N_UPDATES: int = 1
    # Journal retention: keep the newest N committed snapshots (the
    # manifest-committed one is always kept). 0 = keep all — only for
    # forensic runs; a long-lived fleet member writes one snapshot per
    # update forever.
    JOURNAL_KEEP_N: int = 3
    # Resurrection sequence margin: a resumed node restarts its own
    # train/up sequence counters at journaled_next + margin, covering
    # updates minted AFTER the last snapshot but BEFORE the crash (at
    # most JOURNAL_EVERY_N_UPDATES of them in flight, but duplicate
    # timers can re-deliver). Upstream VersionVectors accept seq gaps by
    # design (a gap is a lost update, not a protocol error), so the only
    # cost of a generous margin is a cosmetic hole in the sequence.
    JOURNAL_SEQ_MARGIN: int = 16
    # Orbax retention for learning/checkpoint.py save_state: keep the
    # newest N checkpoint steps (CheckpointManagerOptions.max_to_keep).
    # 0 = unbounded (the pre-durability behavior, kept as the default
    # for standalone checkpointing); the journal passes its own
    # JOURNAL_KEEP_N explicitly.
    CHECKPOINT_KEEP_N: int = 0
    # --- Megafleet (federation/megafleet.py, ops/fleet_kernels.py) ---
    # Default Bonawitz production knobs for the vectorized fleet engine,
    # read ONCE at MegaFleet construction (never inside a traced body —
    # the jit-staleness contract). Pace steering: each simulated client's
    # whole schedule is offset by a seeded uniform draw in
    # [0, PACE_WINDOW) virtual seconds, spreading the first-wave
    # thundering herd (0 disables).
    MEGAFLEET_PACE_WINDOW: float = 0.0
    # Selection: each (client, update) slot participates with this
    # probability (an unselected device idles the period — Bonawitz §4's
    # device selection; over-provisioning = selecting more than the
    # buffers need and measuring the wasted work). 1.0 = everyone.
    MEGAFLEET_SELECT_FRAC: float = 1.0
    # Per-tier rate limits (virtual seconds between ACCEPTED offers at a
    # regional window / the global window): a tier refuses offers landing
    # inside the gap (counted rate_limited, never raising). 0 disables
    # and compiles the gate out of the scan.
    MEGAFLEET_REGIONAL_RATE_S: float = 0.0
    MEGAFLEET_GLOBAL_RATE_S: float = 0.0
    # lax.scan unroll factor for the fleet program — a throughput/compile
    # -time trade on multi-million-event scans.
    MEGAFLEET_SCAN_UNROLL: int = 1
    # Events per scan step of the chunked engine (ops/fleet_kernels.py
    # run_fleet_program_chunked): each step batch-gathers CHUNK sorted
    # arrivals, runs the sequential admission logic as cheap scalar ops,
    # and scatters every dense-carry write back in one predicated pass —
    # amortizing XLA:CPU's per-op dispatch over the chunk. 1 selects the
    # per-event reference engine (the bit-parity baseline).
    # 0 = autotune: measure a handful of candidate chunk sizes on the
    # live device once and pin the winner in the fleet-tune disk cache
    # (ops/fleet_autotune.py — the ops/autotune.py device-kind-keyed
    # pattern), so later runs replay the choice without re-measuring.
    MEGAFLEET_CHUNK: int = 256
    # Device shards of the sharded megafleet engine
    # (run_fleet_program_sharded): the per-client parameter rows are
    # partitioned over MESH_CLIENTS_AXIS while admission stays
    # replicated, so verdicts are bit-identical to the single-device
    # chunked engine. 0/1 = single-device chunked engine.
    MEGAFLEET_SHARDS: int = 0
    # Per-shard segment head-room of the sharded chunk layout: each
    # shard owns ceil(SLACK * chunk / shards) lanes of a chunk, so a
    # mildly imbalanced chunk (one shard's clients over-represented)
    # still packs without closing the chunk early. Raising it trades
    # per-shard FLOPs (chunk/shards * SLACK trained lanes per shard)
    # for fewer short chunks; 2.0 keeps the vectorized layout path on
    # every schedule the simulator generates.
    MEGAFLEET_SHARD_SLACK: float = 2.0
    # Override path of the fleet-tune cache file (chunk-size winners per
    # device kind / shard count). Empty = $P2PFL_FLEET_TUNE_CACHE or
    # ~/.cache/p2pfl_tpu/fleet_tune.json.
    FLEET_TUNE_CACHE: str = ""
    # --- Byzantine robustness (federation/defense.py, ops/aggregation.py) ---
    # Which merge kernel the async plane's BufferedAggregator folds a
    # flushed buffer with: "fedavg" is the FedBuff staleness-weighted mean
    # (the pre-robustness behavior); "trimmed-mean" and "median" are the
    # per-coordinate rank-based robust rules (they ignore the staleness
    # weights by construction — rank statistics have no weighted analogue
    # that keeps their breakdown point); "krum-screen" runs Krum selection
    # to DROP the BYZ_F most outlying contributions and then applies the
    # normal staleness-weighted mean over the survivors (weights kept).
    # Every kernel folds the same (origin, seq)-sorted buffer, so the
    # arrival-order-independence determinism contract is unchanged.
    ASYNC_ROBUST_AGG: str = "fedavg"
    # Coordinates trimmed from EACH side per coordinate by the
    # "trimmed-mean" kernel (clamped to (K-1)//2 — at least one value must
    # survive). Robust to ASYNC_TRIM Byzantine contributions per buffer.
    ASYNC_TRIM: int = 1
    # Assumed Byzantine contribution count f for "krum-screen" (and the
    # sharded robust folds' krum variant): f contributions are screened
    # out of each flush. Clamped so at least one contribution survives.
    BYZ_F: int = 1
    # Defense-in-depth admission screen (federation/defense.py): every
    # single-origin contribution at BOTH aggregator seams (the sync
    # Aggregator.add_model and the async BufferedAggregator.offer) is
    # checked against the current global — an L2-norm gate plus a
    # cosine-distance outlier score, one tiny jitted reduction per
    # contribution — before it may enter a fold. Rejections feed a
    # per-origin suspicion EWMA; past BYZ_SUSPICION_THRESHOLD the origin
    # is QUARANTINED through the existing eviction path (breaker /
    # mark_dead / TierRouter re-derivation), so a semantic attacker is
    # removed by the same machinery that removes a corpse. Off by
    # default: screening is a behavioral change (it can reject honest
    # outliers under extreme non-IID data) and is opt-in like the robust
    # kernels.
    BYZ_SCREEN: bool = False
    # Norm gate: reject a contribution whose L2 norm is more than this
    # factor away from the current global's (ratio outside
    # [1/gate, gate]). Sized for weights-space updates (a local step's
    # norm stays near the global's); scale attacks at |λ| >= gate are
    # caught here.
    BYZ_NORM_GATE: float = 4.0
    # Cosine gate: reject when cos(update, global) falls below this.
    # Honest weights-space updates stay close to the global they trained
    # from (cos ≈ 1); sign flips sit at −1, heavy noise near 0.
    BYZ_COS_GATE: float = 0.5
    # Suspicion EWMA step: s ← (1−β)·s + β·[rejected]. At 0.5 two
    # consecutive rejections cross the default threshold.
    BYZ_SUSPICION_BETA: float = 0.5
    # Suspicion level at which an origin is quarantined (monotone: once
    # quarantined, an origin's contributions are dropped for the rest of
    # the experiment even if it starts behaving).
    BYZ_SUSPICION_THRESHOLD: float = 0.7

    # Secure aggregation (pairwise masking, learning/secagg.py): when True,
    # train-set nodes Diffie-Hellman a seed per peer at experiment start and
    # mask their model contribution; masks cancel in the FedAvg sum, so no
    # individual model ever crosses the wire in the clear. FedAvg only.
    SECURE_AGGREGATION: bool = False
    # Per-pair Gaussian mask scale: pair (i,j) is masked at
    # STD*sqrt(w_j/w_i) on node i (sample counts announced with the DH
    # keys), so the mask drowns the parameters regardless of how large the
    # local datasets are. Requires WIRE_COMPRESSION="none".
    SECAGG_MASK_STD: float = 100.0
    # --- federation round hot path (parallel/chunked.py, parallel/spmd.py) ---
    # How many chunks ahead ChunkedFederation stages inputs (per-round perm
    # indices, and x/y chunks when the dataset is not device-resident)
    # while earlier chunks compute. 1 = stage each chunk immediately before
    # its dispatch (the pre-overhaul serial behavior); 2 = classic double
    # buffering (chunk k+1's host→device copies overlap chunk k's compute).
    # Host-side knob — changing it never retraces or recompiles.
    CHUNK_STAGING_DEPTH: int = 2
    # Fold the per-chunk weighted reduce into the chunk program: partial
    # sums ride donated accumulator arguments and update ON DEVICE (one
    # dispatch per chunk). False restores the host-side
    # ``jax.tree.map(jnp.add, ...)`` over full pytrees after every chunk —
    # 2×leaf-count eager dispatches per chunk — kept as the reference
    # semantics for the bit-parity test and for debugging.
    CHUNK_FUSED_REDUCE: bool = True
    # Donate the running accumulators (param/opt partial sums) into the
    # chunk program so XLA writes each chunk's update into the same HBM
    # buffers instead of allocating a fresh full-model set per chunk.
    # False keeps every chunk's inputs alive (copy-safe debugging path).
    CHUNK_DONATE_BUFFERS: bool = True
    # SCAFFOLD fast path: derive each node's new control variate from the
    # mean of its local raw gradients accumulated in the epoch scan carry
    # (algebraically identical to Karimireddy et al. 2020 option II under
    # plain SGD: (x − y_i)/(K·η) = mean_t(g_t) + (c − c_i)), instead of
    # re-deriving it from the retained round-start params. Kills the fp32
    # anchor round-trip after the scan; False restores the anchor-based
    # formula (parity-tested — tests/test_round_pipeline.py). Participates
    # in the jit cache key (traced-program knob).
    SCAFFOLD_FUSED_CI: bool = True
    # Sequence length at/above which attn="auto" picks the Pallas flash
    # kernel over fused dense XLA attention (TPU backends only — anywhere
    # else the kernel runs in interpret mode and "auto" stays dense).
    # Crossover measured on the real chip by bench config 7 (BASELINE.md
    # row 7, BENCH_SUITE.json). Round-4 re-measurement (bf16 MXU kernels,
    # slope-based in-dispatch timing): at block 512 flash beats dense
    # 1.38x at T=1024, 1.89x at 2048, 4.15x at 4096 on the train step,
    # and LOSES 0.55x at T=512 — the threshold stays 1024.
    # Re-tune with `python bench_suite.py 7` if the model shape changes.
    FLASH_MIN_SEQ_LEN: int = 1024
    # Autotune the flash-attention kernel schedule at model-build time:
    # tiny_transformer(attn="flash"|"ring_flash") sweeps (block_q, block_k,
    # q_span) + backward mode for the model's (seq_len, head_dim, dtype)
    # and caches the winner (ops/autotune.py — in-process + on-disk, keyed
    # on device kind). False = pure lookup: pinned config → existing tune
    # cache → shipped defaults table (no kernels run at build time).
    FLASH_AUTOTUNE: bool = False
    # Path of the on-disk autotune cache; "" = the default
    # ~/.cache/p2pfl_tpu/flash_tune.json (P2PFL_FLASH_TUNE_CACHE env var
    # also honored).
    FLASH_TUNE_CACHE: str = ""
    # How long a train-set node waits for peers' secagg_recover seed
    # disclosures after an aggregation timeout with dropouts, before giving
    # the round up (keeping the previous global instead of applying noise).
    SECAGG_RECOVERY_TIMEOUT: float = 30.0
    # Full Bonawitz double masking: each contribution also carries a
    # per-round SELF mask whose seed is t-of-n Shamir-shared with the train
    # set (learning/secagg.py). Guarantees that for every (node, round) at
    # most one of {pair seeds, self seed} ever becomes public, so a masked
    # update captured on the wire stays masked even through dropout
    # recovery. Costs one extra mask stream + two small control broadcasts
    # per node per round. False = round-3 behavior (pairwise masks only,
    # with the documented single-update disclosure risk on dropout).
    SECAGG_DOUBLE_MASK: bool = True


def wire_compression_device() -> bool:
    """Resolve ``Settings.WIRE_COMPRESSION_DEVICE`` (None = by backend).

    The auto-select encodes the PR-4 measurement: the fused device
    producer exists to keep the full fp32 model + anchor pull off the
    D2H link, which only pays on a real accelerator; on XLA:CPU the
    "device" is the same host and its exact TopK (partial sort) loses
    wall-clock to numpy's introselect, so the host producer wins there.
    Both producers emit bit-layout-identical frames, so the auto-select
    can never change what a receiver decodes — only who does the work.
    """
    explicit = Settings.WIRE_COMPRESSION_DEVICE
    if explicit is not None:
        return bool(explicit)
    import jax

    return jax.default_backend() != "cpu"


def ici_backend() -> str:
    """Resolve ``Settings.ICI_BACKEND`` ("auto" = by backend).

    The Pallas remote-DMA kernel only lowers on real TPU hardware; the
    pure-XLA ``ppermute`` program is the bit-parity fallback everywhere
    else (including the 8-virtual-device CPU mesh tier-1 runs on). An
    explicit "pallas"/"ppermute" overrides the auto-select either way.
    """
    explicit = Settings.ICI_BACKEND
    if explicit != "auto":
        return explicit
    import jax

    return "pallas" if jax.default_backend() == "tpu" else "ppermute"


def telemetry_jax_annotations() -> bool:
    """Resolve ``Settings.TELEMETRY_JAX_ANNOTATIONS`` (None = by backend).

    The annotation bridge exists to line host dispatch spans up with XLA's
    device timeline inside a captured ``jax.profiler`` trace — which only
    exists on a real accelerator; on CPU the extra TraceAnnotation call is
    pure overhead with nothing to correlate against.
    """
    explicit = Settings.TELEMETRY_JAX_ANNOTATIONS
    if explicit is not None:
        return bool(explicit)
    import jax

    return jax.default_backend() != "cpu"


def set_low_latency_settings() -> None:
    """Documented low-latency profile for reliable local networks.

    The defaults above mirror the reference's knobs, which are tuned for
    lossy wide-area overlays (1 s model-gossip ticks, 2 s heartbeats,
    60 s vote windows). On a reliable local network — one host, a rack,
    or a TPU-pod's DCN — those quantize every round to multiples of
    whole seconds for no benefit. This profile keeps EVERY semantic
    (same verbs, same stall/timeout exits, same vote formula; only the
    clocks shrink) while cutting protocol overhead per round to
    sub-second (fan-out and capacity knobs like GOSSIP_MODELS_PER_ROUND
    are deliberately untouched):

    - model-gossip tick 1 s → 0.05 s: the tick loop re-checks peer
      status 20×/s instead of 1×/s, so the diffusion/partial loops exit
      ~0.5 s after the decisive message instead of up to 1 s + stall
      window (stall exit stays at GOSSIP_EXIT_ON_X_EQUAL_ROUNDS ticks —
      the same number of unchanged observations).
    - heartbeats 2/5 s → 0.3/1.5 s: membership converges in ~0.3 s; the
      WAIT_HEARTBEATS_CONVERGENCE pause shrinks to match.
    - vote/aggregation ceilings 60/300 s → 15/60 s: failure detection
      latency, not steady-state cost — rounds that complete never see
      them.

    Measured effect (BASELINE config 1, 2-node MNIST MLP, CPU): protocol
    overhead drops under the per-round compute (fit + eval dominate).
    """
    Settings.GRPC_TIMEOUT = 2.0
    Settings.HEARTBEAT_PERIOD = 0.3
    Settings.HEARTBEAT_TIMEOUT = 1.5
    Settings.GOSSIP_PERIOD = 0.02
    Settings.GOSSIP_MODELS_PERIOD = 0.05
    Settings.VOTE_TIMEOUT = 15.0
    Settings.AGGREGATION_TIMEOUT = 60.0
    Settings.SECAGG_RECOVERY_TIMEOUT = 10.0
    Settings.WAIT_HEARTBEATS_CONVERGENCE = 0.4
    Settings.MESSAGE_RETRY_BASE = 0.1
    Settings.MESSAGE_RETRY_CAP = 0.8
    Settings.BREAKER_SUSPECT_TIMEOUT = 0.8


def set_test_settings() -> None:
    """Shrink every timeout for fast tests.

    Reference equivalent: ``p2pfl/utils.py:37-53``.
    """
    Settings.GRPC_TIMEOUT = 0.5
    Settings.HEARTBEAT_PERIOD = 0.3
    Settings.HEARTBEAT_TIMEOUT = 1.5
    Settings.GOSSIP_PERIOD = 0.05
    Settings.TTL = 10
    Settings.GOSSIP_MESSAGES_PER_PERIOD = 100
    Settings.AMOUNT_LAST_MESSAGES_SAVED = 100
    Settings.GOSSIP_MODELS_PERIOD = 0.1
    Settings.GOSSIP_MODELS_PER_ROUND = 4
    Settings.GOSSIP_EXIT_ON_X_EQUAL_ROUNDS = 4
    Settings.GOSSIP_SEND_WORKERS = 4
    Settings.GOSSIP_SEND_TIMEOUT = 2.0
    Settings.GOSSIP_PAYLOAD_CACHE = True
    Settings.MESSAGE_RETRY_MAX = 4
    Settings.MESSAGE_RETRY_BASE = 0.05
    Settings.MESSAGE_RETRY_CAP = 0.4
    Settings.BREAKER_THRESHOLD = 3
    Settings.BREAKER_SUSPECT_TIMEOUT = 0.6
    Settings.TRAIN_SET_REPAIR = True
    Settings.EARLY_INIT_TTL = 15.0
    Settings.MEMORY_WIRE_CODEC = False
    # explicit (not auto): tests exercise the device-producer code paths
    # on whatever backend CI runs them on
    Settings.WIRE_COMPRESSION_DEVICE = True
    # streaming on but the threshold far above any test model: streams
    # engage only where a test forces the threshold down
    Settings.WIRE_STREAM_ENABLED = True
    Settings.WIRE_STREAM_THRESHOLD = 8.0
    Settings.WIRE_CHUNK_MB = 2.0
    Settings.WIRE_STREAM_WINDOW = 4
    Settings.GRPC_MAX_MESSAGE_MB = 512
    Settings.GRPC_SERVER_WORKERS = 4
    Settings.ROUND_FUSED = True
    Settings.CHUNK_STAGING_DEPTH = 2
    Settings.CHUNK_FUSED_REDUCE = True
    Settings.CHUNK_DONATE_BUFFERS = True
    Settings.SCAFFOLD_FUSED_CI = True
    Settings.TELEMETRY_ENABLED = True
    Settings.TELEMETRY_RING_SPANS = 4096
    Settings.TELEMETRY_BEAT_SPANS = False
    Settings.WEIGHTS_PLANE = "bytes"
    Settings.ICI_BACKEND = "auto"
    # tight DCN rendezvous bounds: a multi-process test that degrades to
    # the byte path should do so in seconds, not minutes
    Settings.DCN_DIR_TTL_S = 0.5
    Settings.DCN_ACCEPT_TIMEOUT_S = 2.0
    Settings.DCN_READY_TIMEOUT_S = 4.0
    Settings.DCN_DONE_TIMEOUT_S = 20.0
    Settings.FEDERATION_MODE = "sync"
    Settings.ASYNC_ROBUST_AGG = "fedavg"
    Settings.ASYNC_TRIM = 1
    Settings.BYZ_F = 1
    Settings.BYZ_SCREEN = False
    Settings.BYZ_NORM_GATE = 4.0
    Settings.BYZ_COS_GATE = 0.5
    Settings.BYZ_SUSPICION_BETA = 0.5
    Settings.BYZ_SUSPICION_THRESHOLD = 0.7
    Settings.FEDBUFF_K = 4
    Settings.FEDBUFF_ALPHA = 0.5
    Settings.FEDBUFF_SERVER_LR = 1.0
    Settings.ASYNC_MAX_STALENESS = 16
    Settings.HIER_CLUSTER_SIZE = 0
    Settings.ASYNC_DRAIN_TIMEOUT = 15.0
    Settings.ASYNC_JOIN_TIMEOUT = 5.0
    Settings.JOURNAL_EVERY_N_UPDATES = 1
    Settings.JOURNAL_KEEP_N = 3
    Settings.JOURNAL_SEQ_MARGIN = 16
    Settings.CHECKPOINT_KEEP_N = 0
    Settings.MEGAFLEET_PACE_WINDOW = 0.0
    Settings.MEGAFLEET_SELECT_FRAC = 1.0
    Settings.MEGAFLEET_REGIONAL_RATE_S = 0.0
    Settings.MEGAFLEET_GLOBAL_RATE_S = 0.0
    Settings.MEGAFLEET_SCAN_UNROLL = 1
    # small odd chunk in tests: every parity suite then crosses chunk
    # boundaries (masked tails, mid-chunk flushes, fresh-mint adoption)
    Settings.MEGAFLEET_CHUNK = 48
    Settings.MEGAFLEET_SHARDS = 0
    Settings.MEGAFLEET_SHARD_SLACK = 2.0
    Settings.FLEET_TUNE_CACHE = ""
    Settings.TRAIN_SET_SIZE = 4
    Settings.VOTE_TIMEOUT = 10.0
    Settings.AGGREGATION_TIMEOUT = 10.0
    Settings.SECAGG_RECOVERY_TIMEOUT = 6.0
    Settings.WAIT_HEARTBEATS_CONVERGENCE = 0.4
    Settings.LOG_LEVEL = "DEBUG"
