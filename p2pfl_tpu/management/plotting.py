"""Metric-curve rendering for examples and experiment post-mortems.

Parity with the reference's flagship example, which renders local/global
metric curves with matplotlib (``p2pfl/examples/mnist.py:124-157``). The
reference calls ``plt.show()``; this rig is headless, so curves render to
PNG files instead (the ``--plot`` flag on the examples).
"""

from __future__ import annotations

from typing import Optional


def _plt():
    import matplotlib

    matplotlib.use("Agg")  # headless rig: render to file, never a display
    import matplotlib.pyplot as plt

    return plt


def plot_global_metrics(out_path: str, experiment: Optional[str] = None) -> Optional[str]:
    """Render per-node GLOBAL metric curves (metric vs round) to ``out_path``.

    Pulls from the logger's global metric store (one point per round per
    node, reference ``mnist.py:143-157``). Returns the written path, or
    None when the store has nothing to plot.
    """
    from p2pfl_tpu.management.logger import logger

    logs = logger.get_global_logs()
    if not logs:
        return None
    exp = experiment if experiment is not None else sorted(logs)[0]
    per_node = logs.get(exp, {})
    if not per_node:
        return None
    metrics = sorted({m for node_metrics in per_node.values() for m in node_metrics})
    plt = _plt()
    fig, axes = plt.subplots(1, len(metrics), figsize=(6 * len(metrics), 4), squeeze=False)
    for ax, metric in zip(axes[0], metrics):
        for node in sorted(per_node):
            series = per_node[node].get(metric)
            if not series:
                continue
            rounds, values = zip(*series)
            ax.plot(rounds, values, marker="o", markersize=3, label=node)
            ax.scatter(rounds[-1], values[-1], color="red", zorder=3)
        ax.set_title(f"{exp} — {metric}")
        ax.set_xlabel("round")
        ax.set_ylabel(metric)
        ax.legend(fontsize=7)
        ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def plot_local_metrics(out_path: str, experiment: Optional[str] = None) -> Optional[str]:
    """Render LOCAL (per-step) metric curves, one panel per round.

    Mirrors the reference's local-log loop (``mnist.py:124-141``): for each
    round, every node's per-step series (e.g. ``train_loss``) on one axis.
    """
    from p2pfl_tpu.management.logger import logger

    logs = logger.get_local_logs()
    if not logs:
        return None
    exp = experiment if experiment is not None else sorted(logs)[0]
    rounds = logs.get(exp, {})
    if not rounds:
        return None
    plt = _plt()
    ordered = sorted(rounds)
    fig, axes = plt.subplots(1, len(ordered), figsize=(5 * len(ordered), 4), squeeze=False)
    for ax, rnd in zip(axes[0], ordered):
        for node in sorted(rounds[rnd]):
            for metric, series in sorted(rounds[rnd][node].items()):
                if not series:
                    continue
                steps, values = zip(*series)
                ax.plot(steps, values, label=f"{node}:{metric}")
                ax.scatter(steps[-1], values[-1], color="red", zorder=3)
        ax.set_title(f"round {rnd}")
        ax.set_xlabel("step")
        ax.legend(fontsize=7)
        ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def plot_history(history: list, out_path: str, title: str = "federation") -> Optional[str]:
    """Render an SPMD federation's ``history`` (list of round dicts) to PNG.

    Every numeric key in the round entries (``train_loss``, ``test_acc``,
    ...) becomes one curve; x is the round number.
    """
    if not history:
        return None

    def _scalar(v):
        # round entries may carry device scalars (run_round keeps the loss
        # on-device); anything float()-able is plottable
        try:
            return float(v)
        except (TypeError, ValueError):
            return None

    # union of numeric keys across ALL entries — metrics that first appear
    # mid-run (e.g. test_acc logged from round 2) still get a curve
    keys = sorted(
        {k for e in history for k in e if k != "round" and _scalar(e[k]) is not None}
    )
    if not keys:
        return None
    plt = _plt()
    fig, axes = plt.subplots(1, len(keys), figsize=(6 * len(keys), 4), squeeze=False)
    rounds = [e.get("round", i + 1) for i, e in enumerate(history)]
    for ax, k in zip(axes[0], keys):
        values = [_scalar(e.get(k)) for e in history]
        pts = [(r, v) for r, v in zip(rounds, values) if v is not None]
        if not pts:
            continue
        xs, ys = zip(*pts)
        ax.plot(xs, ys, marker="o", markersize=3)
        ax.scatter(xs[-1], ys[-1], color="red", zorder=3)
        ax.set_title(f"{title} — {k}")
        ax.set_xlabel("round")
        ax.set_ylabel(k)
        ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path
