"""Round flight recorder: spans, unified counters/histograms, Perfetto export.

The round FSM (vote → train → gossip partials → diffuse) is a distributed
causal process, but observability used to be disconnected accumulators —
``record_dispatch`` site counters, ``log_comm_metric`` tallies, ``Stopwatch``
sections, the stall watchdog — none of which could answer *which peer/edge/
stage gated this round*. This module is the Dapper-style fix (Sigelman et
al., 2010): request-scoped **spans** with trace context propagated on the
wire, so one round forms one causal tree across every node in the process.

Three layers, one registry (the module-level :data:`telemetry` singleton):

- **Spans** — ``with telemetry.span(node, name, kind=..., attrs=...)``
  records monotonic-ns start/end into a bounded per-node ring buffer
  (``Settings.TELEMETRY_RING_SPANS`` entries; old spans fall off — a flight
  recorder, not an archive). Nesting is tracked per thread; an explicit
  ``parent`` (a wire ``(trace_id, span_id)`` pair) overrides it, which is
  how a receiver's span becomes the child of the sender's.
  :meth:`Telemetry.event` records instant (zero-duration) spans — fault
  injections, breaker transitions, evictions — parented the same way.
- **Counters + histograms** — the single registry behind
  ``logger.log_comm_metric`` (group ``"comm"``) and
  ``profiling.record_dispatch`` (group ``"dispatch"``); the old accessors
  are thin views. ``snapshot_and_reset`` reads and clears atomically, so a
  bench cannot lose increments landing between a ``get_*`` and a
  ``reset_*``. Span durations auto-feed log-bucket latency histograms
  (p50/p95/p99 via :meth:`LatencyHistogram.percentile`).
- **Exports** — :meth:`Telemetry.export_chrome_trace` emits Chrome
  trace-event JSON (one ``pid`` per node, one ``tid`` per plane:
  stages/gossip/heartbeat/dispatch/retry/fault) loadable in Perfetto
  (ui.perfetto.dev → *Open trace file*); :meth:`Telemetry.round_report`
  walks the span tree of one round and attributes its wall-clock to
  stages, peers, retry/backoff waits and aggregation-wait burn, naming the
  critical-path node/stage/edge.

Wire contract: ``Message``/``WeightsEnvelope`` carry an optional
``trace_ctx=(trace_id, parent_span_id)``; ``protocol.build_msg/build_weights``
stamp the sender's current context, the single ``_do_send`` seam wraps the
transport send in a span, and the receive dispatch opens the receiver's span
with the wire context as parent. A frame without the field decodes exactly
as before (old wire format stays valid).

Setting ``P2PFL_TELEMETRY_DUMP=<dir>`` dumps ``trace.json`` + per-round
``round_reports.json`` at process exit — CI uploads these as artifacts when
a chaos run fails, so every failure is self-explaining.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from p2pfl_tpu.settings import Settings

TraceCtx = Tuple[str, str]  # (trace_id, span_id)

#: span kinds → Chrome trace ``tid`` (one timeline lane per plane)
PLANES: Dict[str, int] = {
    "stage": 1,
    "gossip": 2,
    "heartbeat": 3,
    "dispatch": 4,
    "retry": 5,
    "fault": 6,
}
_OTHER_PLANE = 9

#: the round FSM's top-level stage names — RoundReport attributes per-stage
#: time from these only, so nested sub-spans (aggregation_wait, diffusion)
#: never double-count into the stage split
FSM_STAGES = (
    "StartLearningStage",
    "VoteTrainSetStage",
    "TrainStage",
    "WaitAggregatedModelsStage",
    "GossipModelStage",
    "RoundFinishedStage",
)

_seq = itertools.count(1)
# per-process entropy in every id: trace ids are DELIBERATELY identical
# across all nodes of a round (the coordination-free cross-node trace), so
# a bare sequential span id would collide when flight records from
# separate gRPC node PROCESSES are merged into one timeline
_proc_tag = f"{os.getpid():x}-{os.urandom(3).hex()}"


def _new_id(prefix: str = "s") -> str:
    return f"{prefix}{_proc_tag}-{next(_seq):x}"


class Span:
    """One recorded operation: [t0_ns, t1_ns) on one node, one plane."""

    __slots__ = (
        "trace_id",
        "span_id",
        "parent_id",
        "node",
        "name",
        "kind",
        "t0_ns",
        "t1_ns",
        "attrs",
    )

    def __init__(
        self,
        node: str,
        name: str,
        kind: str,
        trace_id: str,
        parent_id: Optional[str],
        attrs: Optional[dict],
    ) -> None:
        self.node = node
        self.name = name
        self.kind = kind
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.t0_ns = time.monotonic_ns()
        self.t1_ns = self.t0_ns
        self.attrs: dict = attrs if attrs is not None else {}

    @property
    def duration_ns(self) -> int:
        return self.t1_ns - self.t0_ns

    @property
    def ctx(self) -> TraceCtx:
        return (self.trace_id, self.span_id)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "node": self.node,
            "name": self.name,
            "kind": self.kind,
            "t0_ns": self.t0_ns,
            "t1_ns": self.t1_ns,
            "attrs": dict(self.attrs),
        }


class _SpanHandle:
    """Context manager returned by :meth:`Telemetry.span` (enabled path)."""

    __slots__ = ("_registry", "span")

    def __init__(self, registry: "Telemetry", span: Span) -> None:
        self._registry = registry
        self.span = span

    def __enter__(self) -> Span:
        self._registry._push(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        span = self.span
        span.t1_ns = time.monotonic_ns()
        if exc_type is not None:
            span.attrs.setdefault("error", repr(exc))
        self._registry._pop(span)
        self._registry._commit(span)
        return False


class _NoopHandle:
    """Shared do-nothing handle — the disabled path allocates nothing."""

    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NOOP = _NoopHandle()


class LatencyHistogram:
    """Log2-bucketed latency histogram (thread-safe).

    Buckets are powers of two in nanoseconds (bucket ``i`` holds samples
    with ``bit_length == i``), so 60 buckets cover 1 ns → 36 years with
    ≤2× quantile error — the standard trade for lock-cheap histograms.
    """

    __slots__ = ("_lock", "counts", "count", "sum_ns", "max_ns")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counts: Dict[int, int] = {}
        self.count = 0
        self.sum_ns = 0
        self.max_ns = 0

    def record(self, ns: int) -> None:
        ns = max(int(ns), 0)
        bucket = ns.bit_length()
        with self._lock:
            self.counts[bucket] = self.counts.get(bucket, 0) + 1
            self.count += 1
            self.sum_ns += ns
            if ns > self.max_ns:
                self.max_ns = ns

    def percentile(self, q: float) -> float:
        """Approximate q-th percentile in ns (geometric bucket midpoint)."""
        with self._lock:
            if self.count == 0:
                return 0.0
            target = q / 100.0 * self.count
            seen = 0
            for bucket in sorted(self.counts):
                seen += self.counts[bucket]
                if seen >= target:
                    lo = 0 if bucket <= 1 else 1 << (bucket - 1)
                    hi = (1 << bucket) - 1 if bucket > 0 else 0
                    return (lo + hi) / 2.0
            return float(self.max_ns)

    def summary(self) -> dict:
        with self._lock:
            count, sum_ns = self.count, self.sum_ns
        if count == 0:
            return {"count": 0}
        return {
            "count": count,
            "total_s": round(sum_ns / 1e9, 6),
            "mean_ms": round(sum_ns / count / 1e6, 4),
            "p50_ms": round(self.percentile(50) / 1e6, 4),
            "p95_ms": round(self.percentile(95) / 1e6, 4),
            "p99_ms": round(self.percentile(99) / 1e6, 4),
            "max_ms": round(self.max_ns / 1e6, 4),
        }


class ValueHistogram:
    """Exact counts over small non-negative integer values (thread-safe).

    The unit-agnostic sibling of :class:`LatencyHistogram` for quantities
    with a naturally tiny support — e.g. the async federation's staleness
    τ in *model versions* (0, 1, 2, …, bounded by
    ``Settings.ASYNC_MAX_STALENESS``) — where log2 latency buckets would
    both blur the distribution and mislabel the units as time.
    """

    __slots__ = ("_lock", "counts", "count", "total")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counts: Dict[int, int] = {}
        self.count = 0
        self.total = 0

    def record(self, value: int) -> None:
        value = max(int(value), 0)
        with self._lock:
            self.counts[value] = self.counts.get(value, 0) + 1
            self.count += 1
            self.total += value

    def summary(self) -> dict:
        with self._lock:
            if self.count == 0:
                return {"count": 0}
            values = sorted(self.counts)
            cum, p50, p95 = 0, values[-1], values[-1]
            for v in values:
                cum += self.counts[v]
                if p50 == values[-1] and cum >= 0.50 * self.count:
                    p50 = v
                if cum >= 0.95 * self.count:
                    p95 = v
                    break
            return {
                "count": self.count,
                "mean": round(self.total / self.count, 4),
                "p50": p50,
                "p95": p95,
                "max": values[-1],
                "counts": {str(v): self.counts[v] for v in values},
            }


class Telemetry:
    """Process-wide registry. Use the module-level :data:`telemetry`."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # node → bounded ring of completed spans (append is atomic; the
        # lock only guards ring creation so writers never serialize)
        self._rings: Dict[str, deque] = {}
        # group → node → name → value (group "comm" backs
        # logger.get_comm_metrics, "dispatch" backs get_dispatch_counts)
        self._counters: Dict[str, Dict[str, Dict[str, float]]] = {}
        # (node, name) → LatencyHistogram (span durations auto-feed these)
        self._hists: Dict[Tuple[str, str], LatencyHistogram] = {}
        # (node, name) → ValueHistogram (e.g. async staleness per merge)
        self._value_hists: Dict[Tuple[str, str], ValueHistogram] = {}
        self._tls = threading.local()

    # ---- span API ----

    @staticmethod
    def enabled() -> bool:
        return bool(Settings.TELEMETRY_ENABLED)

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop(self, span: Span) -> None:
        stack = self._stack()
        if stack and stack[-1] is span:
            stack.pop()
        elif span in stack:  # defensive: exits out of order never corrupt
            stack.remove(span)

    def _ring(self, node: str) -> deque:
        ring = self._rings.get(node)
        if ring is None:
            with self._lock:
                ring = self._rings.setdefault(
                    node, deque(maxlen=max(int(Settings.TELEMETRY_RING_SPANS), 1))
                )
        return ring

    def _commit(self, span: Span) -> None:
        self._ring(span.node).append(span)
        self.observe(span.node, f"{span.kind}.{span.name}", span.duration_ns)

    def span(
        self,
        node: str,
        name: str,
        kind: str = "stage",
        attrs: Optional[dict] = None,
        parent: Optional[TraceCtx] = None,
        trace_id: Optional[str] = None,
    ):
        """Open a span. ``parent`` is an explicit wire ``(trace_id,
        span_id)`` (overrides this thread's current span); ``trace_id``
        forces the trace identity (the workflow pins one deterministic id
        per round so every node's round tree shares it). Returns a context
        manager yielding the live :class:`Span` (attrs may be mutated
        until exit) — or a no-op handle when telemetry is off."""
        if not self.enabled():
            return _NOOP
        parent_id: Optional[str] = None
        if parent is not None:
            tid = parent[0]
            parent_id = parent[1]
        else:
            stack = self._stack()
            if stack:
                top = stack[-1]
                tid = top.trace_id
                parent_id = top.span_id
            else:
                tid = _new_id("t")
        if trace_id is not None:
            tid = trace_id
        return _SpanHandle(self, Span(node, name, kind, tid, parent_id, attrs))

    def event(
        self,
        node: str,
        name: str,
        kind: str = "fault",
        attrs: Optional[dict] = None,
    ) -> None:
        """Record an instant (zero-duration) span — breaker transitions,
        fault-plan decisions, evictions. Parented to this thread's current
        span when one is active, so a fault injected inside a send shows
        up on that edge's timeline."""
        if not self.enabled():
            return
        stack = self._stack()
        if stack:
            top = stack[-1]
            tid, parent_id = top.trace_id, top.span_id
        else:
            tid, parent_id = _new_id("t"), None
        span = Span(node, name, kind, tid, parent_id, attrs)
        self._ring(node).append(span)

    def current_ctx(self) -> Optional[TraceCtx]:
        """The calling thread's active ``(trace_id, span_id)`` — what
        ``build_msg``/``build_weights`` stamp onto outgoing envelopes."""
        stack = getattr(self._tls, "stack", None)
        if stack:
            return stack[-1].ctx
        return None

    def spans(self, node: Optional[str] = None) -> List[Span]:
        """Snapshot of the recorded spans (all nodes, or one)."""
        with self._lock:
            rings = [self._rings[node]] if node in self._rings else []
            if node is None:
                rings = list(self._rings.values())
        out: List[Span] = []
        for ring in rings:
            out.extend(list(ring))
        out.sort(key=lambda s: s.t0_ns)
        return out

    def reset_spans(self) -> None:
        with self._lock:
            self._rings.clear()

    # ---- counters (the one registry behind comm metrics + dispatch counts) ----

    def inc(self, group: str, node: str, name: str, value: float = 1.0) -> None:
        with self._lock:
            per_node = self._counters.setdefault(group, {}).setdefault(node, {})
            per_node[name] = per_node.get(name, 0.0) + value

    def counters(self, group: str, node: Optional[str] = None) -> Dict:
        """Snapshot: ``{name: value}`` for one node, or ``{node: {...}}``."""
        with self._lock:
            g = self._counters.get(group, {})
            if node is not None:
                return dict(g.get(node, {}))
            return {n: dict(d) for n, d in g.items()}

    def reset_counters(self, group: str) -> None:
        with self._lock:
            self._counters.pop(group, None)

    def snapshot_and_reset(self, group: str, node: Optional[str] = None) -> Dict:
        """Atomically read *and clear* a counter group (or one node's slice)
        under one lock hold — increments landing between a ``get`` and a
        ``reset`` can no longer be lost."""
        with self._lock:
            g = self._counters.get(group)
            if g is None:
                return {}
            if node is not None:
                return dict(g.pop(node, {}))
            self._counters.pop(group, None)
            return {n: dict(d) for n, d in g.items()}

    # ---- histograms ----

    def observe(self, node: str, name: str, ns: int) -> None:
        if not self.enabled():
            return
        key = (node, name)
        hist = self._hists.get(key)
        if hist is None:
            with self._lock:
                hist = self._hists.setdefault(key, LatencyHistogram())
        hist.record(ns)

    def histograms(self, node: Optional[str] = None) -> Dict[str, dict]:
        """``{name: {count, mean_ms, p50_ms, p95_ms, p99_ms, ...}}`` —
        one node's, or all nodes' keyed ``node/name``."""
        with self._lock:
            items = list(self._hists.items())
        out: Dict[str, dict] = {}
        for (n, name), hist in items:
            if node is not None:
                if n == node:
                    out[name] = hist.summary()
            else:
                out[f"{n}/{name}"] = hist.summary()
        return out

    def observe_value(self, node: str, name: str, value: int) -> None:
        """Record a raw (unit-agnostic, small non-negative integer) sample
        into a :class:`ValueHistogram` — always on, like counters: the
        async staleness distribution is load-bearing for tests/benches."""
        key = (node, name)
        hist = self._value_hists.get(key)
        if hist is None:
            with self._lock:
                hist = self._value_hists.setdefault(key, ValueHistogram())
        hist.record(value)

    def value_histograms(self, node: Optional[str] = None) -> Dict[str, dict]:
        """Like :meth:`histograms` but for the raw-value family."""
        with self._lock:
            items = list(self._value_hists.items())
        out: Dict[str, dict] = {}
        for (n, name), hist in items:
            if node is not None:
                if n == node:
                    out[name] = hist.summary()
            else:
                out[f"{n}/{name}"] = hist.summary()
        return out

    def reset_histograms(self) -> None:
        with self._lock:
            self._hists.clear()
            self._value_hists.clear()

    def reset(self) -> None:
        """Full wipe: spans, every counter group, histograms."""
        with self._lock:
            self._rings.clear()
            self._counters.clear()
            self._hists.clear()
            self._value_hists.clear()

    # ---- Chrome trace-event export (Perfetto-loadable) ----

    def export_chrome_trace(
        self, path: Optional[str] = None, nodes: Optional[List[str]] = None
    ) -> dict:
        """Chrome trace-event JSON: one ``pid`` per node, one ``tid`` per
        plane, ``X`` complete events for spans, ``i`` instants for events.
        Open at ui.perfetto.dev (or chrome://tracing). Returns the document;
        also writes it to ``path`` when given."""
        spans = self.spans()
        if nodes is not None:
            wanted = set(nodes)
            spans = [s for s in spans if s.node in wanted]
        pid_of = {n: i + 1 for i, n in enumerate(sorted({s.node for s in spans}))}
        events: List[dict] = []
        for node, pid in pid_of.items():
            events.append(
                {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                 "args": {"name": node}}
            )
        named_lanes = set()
        for s in spans:
            pid = pid_of[s.node]
            tid = PLANES.get(s.kind, _OTHER_PLANE)
            if (pid, tid) not in named_lanes:
                named_lanes.add((pid, tid))
                events.append(
                    {"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                     "args": {"name": s.kind}}
                )
            args = {k: v for k, v in s.attrs.items() if v is not None}
            args["trace_id"] = s.trace_id
            args["span_id"] = s.span_id
            if s.parent_id:
                args["parent_span_id"] = s.parent_id
            base = {
                "name": s.name,
                "pid": pid,
                "tid": tid,
                "ts": s.t0_ns / 1000.0,  # trace-event timestamps are µs
                "args": args,
                "cat": s.kind,
            }
            if s.duration_ns > 0:
                base["ph"] = "X"
                base["dur"] = s.duration_ns / 1000.0
            else:
                base["ph"] = "i"
                base["s"] = "t"  # thread-scoped instant
            events.append(base)
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        if path is not None:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc

    # ---- per-round attribution ----

    def round_report(
        self, round_no: int, experiment: Optional[str] = None
    ) -> "RoundReport":
        """Walk the span tree of one round and say what gated it.

        Stage spans carry ``attrs={"round", "experiment"}`` (stamped by the
        workflow); everything else — gossip sends, retry events, faults —
        is attributed by falling inside the round's time window. The
        critical path names (a) the node whose round wall-clock is
        longest, (b) its longest stage, and (c) the edge that burned the
        most send time + retry backoff (+ a failure ranking that surfaces
        crashed peers, whose sends fail *fast* but repeatedly)."""
        spans = self.spans()
        stage_spans = [
            s
            for s in spans
            if s.kind == "stage"
            and s.attrs.get("round") == round_no
            and (experiment is None or s.attrs.get("experiment") == experiment)
        ]
        per_node: Dict[str, dict] = {}
        for s in stage_spans:
            info = per_node.setdefault(
                s.node, {"t0_ns": s.t0_ns, "t1_ns": s.t1_ns, "stages": {}, "waits": {}}
            )
            info["t0_ns"] = min(info["t0_ns"], s.t0_ns)
            info["t1_ns"] = max(info["t1_ns"], s.t1_ns)
            bucket = "stages" if s.name in FSM_STAGES else "waits"
            info[bucket][s.name] = info[bucket].get(s.name, 0) + s.duration_ns
        if not per_node:
            return RoundReport(round_no=round_no, experiment=experiment)
        if experiment is None:
            experiment = next(
                (s.attrs.get("experiment") for s in stage_spans if s.attrs.get("experiment")),
                None,
            )

        w0 = min(i["t0_ns"] for i in per_node.values())
        w1 = max(i["t1_ns"] for i in per_node.values())

        edges: Dict[Tuple[str, str], dict] = {}
        retry_wait: Dict[str, float] = {}
        faults: Dict[str, int] = {}
        for s in spans:
            if s.t0_ns > w1 or s.t1_ns < w0:
                continue
            if s.kind == "gossip" and s.name.startswith("send:"):
                peer = s.attrs.get("peer")
                if peer is None:
                    continue
                e = edges.setdefault(
                    (s.node, peer), {"busy_ns": 0, "sends": 0, "failures": 0}
                )
                e["busy_ns"] += s.duration_ns
                e["sends"] += 1
                if s.attrs.get("ok") is False:
                    e["failures"] += 1
            elif s.kind == "retry":
                peer = s.attrs.get("peer")
                if peer is not None:
                    retry_wait[peer] = retry_wait.get(peer, 0.0) + float(
                        s.attrs.get("delay_s", 0.0)
                    )
            elif s.kind == "fault":
                faults[s.name] = faults.get(s.name, 0) + 1

        critical_node = max(per_node, key=lambda n: per_node[n]["t1_ns"] - per_node[n]["t0_ns"])
        crit = per_node[critical_node]
        critical_stage = (
            max(crit["stages"], key=crit["stages"].get) if crit["stages"] else None
        )
        critical_edge = None
        if edges:
            src, dst = max(
                edges,
                key=lambda e: edges[e]["busy_ns"] + retry_wait.get(e[1], 0.0) * 1e9,
            )
            e = edges[(src, dst)]
            # same units as the edges table below (seconds) — one document,
            # one unit, whichever entry a consumer reads
            critical_edge = {
                "src": src,
                "dst": dst,
                "busy_s": round(e["busy_ns"] / 1e9, 4),
                "sends": e["sends"],
                "failures": e["failures"],
                "retry_wait_s": round(retry_wait.get(dst, 0.0), 4),
            }
        most_failed_peer = None
        fail_by_dst: Dict[str, int] = {}
        for (_src, dst), e in edges.items():
            fail_by_dst[dst] = fail_by_dst.get(dst, 0) + e["failures"]
        for peer in retry_wait:
            fail_by_dst.setdefault(peer, 0)
        if fail_by_dst and max(fail_by_dst.values()) > 0:
            most_failed_peer = max(fail_by_dst, key=fail_by_dst.get)

        return RoundReport(
            round_no=round_no,
            experiment=experiment,
            wall_s=round((w1 - w0) / 1e9, 4),
            per_node={
                n: {
                    "wall_s": round((i["t1_ns"] - i["t0_ns"]) / 1e9, 4),
                    "stages_s": {k: round(v / 1e9, 4) for k, v in i["stages"].items()},
                    "waits_s": {k: round(v / 1e9, 4) for k, v in i["waits"].items()},
                }
                for n, i in per_node.items()
            },
            edges={
                f"{src}->{dst}": {
                    "busy_s": round(e["busy_ns"] / 1e9, 4),
                    "sends": e["sends"],
                    "failures": e["failures"],
                }
                for (src, dst), e in edges.items()
            },
            retry_wait_s={k: round(v, 4) for k, v in retry_wait.items()},
            faults=faults,
            critical_node=critical_node,
            critical_stage=critical_stage,
            critical_edge=critical_edge,
            most_failed_peer=most_failed_peer,
        )

    def observed_rounds(self) -> List[Tuple[Optional[str], int]]:
        """Distinct ``(experiment, round)`` pairs with stage spans — what
        the at-exit dump iterates."""
        seen = set()
        for s in self.spans():
            if s.kind == "stage" and isinstance(s.attrs.get("round"), int):
                seen.add((s.attrs.get("experiment"), s.attrs["round"]))
        return sorted(seen, key=lambda er: (er[0] or "", er[1]))


class RoundReport:
    """One round's wall-clock attribution (see :meth:`Telemetry.round_report`)."""

    def __init__(
        self,
        round_no: int,
        experiment: Optional[str] = None,
        wall_s: float = 0.0,
        per_node: Optional[dict] = None,
        edges: Optional[dict] = None,
        retry_wait_s: Optional[dict] = None,
        faults: Optional[dict] = None,
        critical_node: Optional[str] = None,
        critical_stage: Optional[str] = None,
        critical_edge: Optional[dict] = None,
        most_failed_peer: Optional[str] = None,
    ) -> None:
        self.round_no = round_no
        self.experiment = experiment
        self.wall_s = wall_s
        self.per_node = per_node or {}
        self.edges = edges or {}
        self.retry_wait_s = retry_wait_s or {}
        self.faults = faults or {}
        self.critical_node = critical_node
        self.critical_stage = critical_stage
        self.critical_edge = critical_edge
        self.most_failed_peer = most_failed_peer

    def to_dict(self) -> dict:
        return {
            "round": self.round_no,
            "experiment": self.experiment,
            "wall_s": self.wall_s,
            "per_node": self.per_node,
            "edges": self.edges,
            "retry_wait_s": self.retry_wait_s,
            "faults": self.faults,
            "critical_path": {
                "node": self.critical_node,
                "stage": self.critical_stage,
                "edge": self.critical_edge,
                "most_failed_peer": self.most_failed_peer,
            },
        }

    def describe(self) -> str:
        """One-paragraph human summary — what a failed chaos run prints."""
        if not self.per_node:
            return f"round {self.round_no}: no spans recorded"
        lines = [
            f"round {self.round_no} ({self.experiment or 'unknown-exp'}): "
            f"wall {self.wall_s:.2f}s across {len(self.per_node)} node(s)"
        ]
        if self.critical_node is not None:
            node = self.per_node[self.critical_node]
            lines.append(
                f"  critical node: {self.critical_node} "
                f"({node['wall_s']:.2f}s, longest stage: {self.critical_stage})"
            )
        if self.critical_edge is not None:
            e = self.critical_edge
            lines.append(
                f"  critical edge: {e['src']}->{e['dst']} "
                f"({e['busy_s']:.2f}s busy, {e['failures']} failure(s))"
            )
        if self.most_failed_peer is not None:
            lines.append(f"  most-failed peer: {self.most_failed_peer}")
        if self.faults:
            lines.append(f"  injected faults: {self.faults}")
        return "\n".join(lines)


def validate_chrome_trace(doc: dict) -> int:
    """Structural check against the Chrome trace-event schema; returns the
    event count or raises ``ValueError`` (used by tests and the CI smoke)."""
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError("missing traceEvents")
    events = doc["traceEvents"]
    if not isinstance(events, list):
        raise ValueError("traceEvents must be a list")
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"event {i} is not an object")
        ph = ev.get("ph")
        if ph not in ("X", "i", "M", "B", "E"):
            raise ValueError(f"event {i}: unknown ph {ph!r}")
        for key in ("name", "pid", "tid"):
            if key not in ev:
                raise ValueError(f"event {i}: missing {key}")
        if ph == "X":
            if not isinstance(ev.get("ts"), (int, float)) or not isinstance(
                ev.get("dur"), (int, float)
            ):
                raise ValueError(f"event {i}: X event needs numeric ts/dur")
            if ev["dur"] < 0:
                raise ValueError(f"event {i}: negative dur")
        elif ph == "i":
            if not isinstance(ev.get("ts"), (int, float)):
                raise ValueError(f"event {i}: instant needs numeric ts")
    json.dumps(doc)  # must be serializable as-is
    return len(events)


#: the process-wide registry
telemetry = Telemetry()


# ---- at-exit flight-recorder dump (chaos CI artifact) ----


def dump_flight_record(out_dir: str) -> List[str]:
    """Write ``trace.json`` + ``round_reports.json`` under ``out_dir``."""
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    trace_path = os.path.join(out_dir, "trace.json")
    telemetry.export_chrome_trace(path=trace_path)
    paths.append(trace_path)
    reports = [
        telemetry.round_report(rnd, experiment=exp).to_dict()
        for exp, rnd in telemetry.observed_rounds()
    ]
    report_path = os.path.join(out_dir, "round_reports.json")
    with open(report_path, "w") as f:
        json.dump(reports, f, indent=1)
    paths.append(report_path)
    # async runs: the per-node staleness distribution (empty dict on sync
    # runs — written only when something was observed, keeping sync-mode
    # artifacts byte-stable)
    value_hists = telemetry.value_histograms()
    if value_hists:
        vh_path = os.path.join(out_dir, "value_histograms.json")
        with open(vh_path, "w") as f:
            json.dump(value_hists, f, indent=1)
        paths.append(vh_path)
    return paths


def _install_exit_dump() -> None:
    import atexit

    out_dir = os.environ.get("P2PFL_TELEMETRY_DUMP")
    if not out_dir:
        return

    def _dump() -> None:
        try:
            dump_flight_record(out_dir)
        except Exception:  # noqa: BLE001 — an exit dump must never mask the exit code
            pass

    atexit.register(_dump)


_install_exit_dump()
