"""In-memory metric stores.

Reference: ``p2pfl/management/metric_storage.py:30-247``.

- :class:`LocalMetricStorage` — per-step training metrics:
  ``exp -> round -> node -> metric -> [(step, value), ...]``
- :class:`GlobalMetricStorage` — per-round evaluation metrics:
  ``exp -> node -> metric -> [(round, value), ...]`` with round dedup.
"""

from __future__ import annotations

import bisect
import copy
import threading
from typing import Dict, List, Set, Tuple

LocalLogs = Dict[str, Dict[int, Dict[str, Dict[str, List[Tuple[int, float]]]]]]
GlobalLogs = Dict[str, Dict[str, Dict[str, List[Tuple[int, float]]]]]


class LocalMetricStorage:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._logs: LocalLogs = {}

    def add_log(self, exp: str, rnd: int, metric: str, node: str, value: float, step: int) -> None:
        with self._lock:
            series = (
                self._logs.setdefault(exp, {})
                .setdefault(rnd, {})
                .setdefault(node, {})
                .setdefault(metric, [])
            )
            series.append((step, float(value)))

    def get_all_logs(self) -> LocalLogs:
        with self._lock:
            return copy.deepcopy(self._logs)

    def get_experiment_logs(self, exp: str):
        with self._lock:
            return copy.deepcopy(self._logs.get(exp, {}))

    def get_experiment_round_logs(self, exp: str, rnd: int):
        with self._lock:
            return copy.deepcopy(self._logs.get(exp, {}).get(rnd, {}))


class GlobalMetricStorage:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._logs: GlobalLogs = {}
        # per-series round membership: the dedup check is O(1) instead of
        # a full scan, and insertion keeps the series sorted via
        # bisect.insort instead of re-sorting the whole list per append —
        # add_log used to be O(n) per call, quadratic over an experiment
        self._rounds: Dict[Tuple[str, str, str], Set[int]] = {}

    def add_log(self, exp: str, rnd: int, metric: str, node: str, value: float) -> None:
        with self._lock:
            seen = self._rounds.setdefault((exp, node, metric), set())
            if rnd in seen:  # dedup by round, first write wins (reference 156-247)
                return
            seen.add(rnd)
            series = self._logs.setdefault(exp, {}).setdefault(node, {}).setdefault(metric, [])
            bisect.insort(series, (rnd, float(value)), key=lambda rv: rv[0])

    def get_all_logs(self) -> GlobalLogs:
        with self._lock:
            return copy.deepcopy(self._logs)

    def get_experiment_logs(self, exp: str):
        with self._lock:
            return copy.deepcopy(self._logs.get(exp, {}))
