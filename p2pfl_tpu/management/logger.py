"""Logger singleton facade — the single observability funnel.

Reference: ``p2pfl/management/logger.py:144-584``. Re-designed without the
multiprocessing queue (plain stdlib logging handlers are enough and far
simpler): colored stdout + optional rotating file, a per-node registry, the
two metric stores, and lifecycle hooks.

Per-node log lines are prefixed ``[addr]`` so N in-process simulated nodes
remain distinguishable — same UX as the reference.
"""

from __future__ import annotations

import logging
import os
import threading
from logging.handlers import RotatingFileHandler
from typing import Any, Dict, Optional, Tuple

from p2pfl_tpu.management.metric_storage import GlobalMetricStorage, LocalMetricStorage
from p2pfl_tpu.settings import Settings

_COLORS = {
    "DEBUG": "\033[90m",
    "INFO": "\033[32m",
    "WARNING": "\033[33m",
    "ERROR": "\033[31m",
    "CRITICAL": "\033[41m",
}
_RESET = "\033[0m"


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        color = _COLORS.get(record.levelname, "")
        record.levelcolor = f"{color}{record.levelname}{_RESET}"
        return super().format(record)


class _WebLogHandler(logging.Handler):
    """Ships every log line to the dashboard (reference logger.py:224-232).

    Always placed behind a ``QueueListener`` so a slow/dead dashboard never
    blocks the thread that logged (the reference decouples via a
    multiprocessing queue; a thread-side queue is the right scope here —
    nothing crosses process boundaries).
    """

    def __init__(self, web: Any) -> None:
        super().__init__()
        self._web = web

    def emit(self, record: logging.LogRecord) -> None:
        try:
            import time as _time

            node = getattr(record, "node", "unknown")
            self._web.send_log(
                _time.strftime("%Y-%m-%d %H:%M:%S"), node, record.levelname, record.getMessage()
            )
        except Exception:  # noqa: BLE001 — dashboard failures never break training
            pass


class P2pflLogger:
    """Singleton. Use the module-level ``logger`` instance."""

    _instance: Optional["P2pflLogger"] = None
    _instance_lock = threading.Lock()

    def __new__(cls) -> "P2pflLogger":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = super().__new__(cls)
                cls._instance._init()
            return cls._instance

    def _init(self) -> None:
        self._logger = logging.getLogger("p2pfl_tpu")
        self._logger.setLevel(Settings.LOG_LEVEL)
        self._logger.propagate = False
        if not self._logger.handlers:
            sh = logging.StreamHandler()
            sh.setFormatter(_ColorFormatter("%(asctime)s | %(levelcolor)s | %(message)s", datefmt="%H:%M:%S"))
            self._logger.addHandler(sh)
        self._file_handler: Optional[logging.Handler] = None
        self.local_metrics = LocalMetricStorage()
        self.global_metrics = GlobalMetricStorage()
        # communication-plane counters (gossip data plane: payload-cache
        # hits/misses, send outcomes/timeouts, and the wire-codec byte
        # accounting — wire_raw_bytes vs wire_payload_bytes per node gives
        # the live compression ratio, wire_d2h_bytes the device→host
        # traffic, wire_encode_device/host the producer split) — keyed
        # (node, metric), incremented from gossip worker threads, so they
        # need no experiment context unlike the two metric stores above.
        # Since the flight recorder (management/telemetry.py) these live
        # in the unified telemetry registry (counter group "comm"); the
        # log_comm_metric/get_comm_metrics surface below is a thin view.
        # addr -> (node_state, simulation_flag)
        self._nodes: Dict[str, Tuple[Any, bool]] = {}
        self._nodes_lock = threading.Lock()
        # optional web dashboard (reference logger.py:264-300): when attached,
        # log lines + metrics mirror to REST and a NodeMonitor runs per node
        self._web: Any = None
        self._monitors: Dict[str, Any] = {}
        self._web_listener: Any = None
        self._web_queue_handler: Optional[logging.Handler] = None

    # ---- setup ----

    def set_level(self, level: str) -> None:
        self._logger.setLevel(level)

    def enable_file_logging(self, log_dir: Optional[str] = None) -> None:
        if self._file_handler is not None:
            return
        log_dir = log_dir or Settings.LOG_DIR
        os.makedirs(log_dir, exist_ok=True)
        fh = RotatingFileHandler(os.path.join(log_dir, "p2pfl_tpu.log"), maxBytes=10_000_000, backupCount=3)
        fh.setFormatter(logging.Formatter("%(asctime)s | %(levelname)s | %(message)s"))
        self._logger.addHandler(fh)
        self._file_handler = fh

    def connect_web_services(self, web: Any) -> None:
        """Attach a :class:`~p2pfl_tpu.management.web_services.WebServices`.

        Mirrors the reference's ``init_p2pfl_web_services``: subsequent
        node registrations, log lines and metrics are pushed to the
        dashboard, and a resource monitor starts per node (``logger.py:504-511``).
        """
        import queue
        from logging.handlers import QueueHandler, QueueListener

        self.disconnect_web_services()
        self._web = web
        q: "queue.SimpleQueue[logging.LogRecord]" = queue.SimpleQueue()
        self._web_queue_handler = QueueHandler(q)
        self._web_listener = QueueListener(q, _WebLogHandler(web))
        self._web_listener.start()
        self._logger.addHandler(self._web_queue_handler)

    def disconnect_web_services(self) -> None:
        for monitor in self._monitors.values():
            monitor.stop()
        self._monitors.clear()
        if self._web_queue_handler is not None:
            self._logger.removeHandler(self._web_queue_handler)
            self._web_queue_handler = None
        if self._web_listener is not None:
            self._web_listener.stop()
            self._web_listener = None
        self._web = None

    # ---- leveled logging, keyed by node addr ----

    def log(self, level: int, node: str, message: str) -> None:
        self._logger.log(level, f"[{node}] {message}", extra={"node": node})

    def debug(self, node: str, message: str) -> None:
        self.log(logging.DEBUG, node, message)

    def info(self, node: str, message: str) -> None:
        self.log(logging.INFO, node, message)

    def warning(self, node: str, message: str) -> None:
        self.log(logging.WARNING, node, message)

    def error(self, node: str, message: str) -> None:
        self.log(logging.ERROR, node, message)

    def critical(self, node: str, message: str) -> None:
        self.log(logging.CRITICAL, node, message)

    # ---- metrics (routing mirrors reference logger.py:392-438) ----

    def log_metric(
        self,
        node: str,
        metric: str,
        value: float,
        step: Optional[int] = None,
        round: Optional[int] = None,  # noqa: A002 — reference API name
        experiment: Optional[str] = None,
    ) -> None:
        exp = experiment or self._experiment_for(node) or "unknown-exp"
        if round is None:
            round = self._round_for(node)  # noqa: A001
        if round is None:
            round = 0  # noqa: A001
        if step is None:
            self.global_metrics.add_log(exp, round, metric, node, value)
            if self._web is not None:
                self._web.send_global_metric(exp, round, metric, node, value)
        else:
            self.local_metrics.add_log(exp, round, metric, node, value, step)
            if self._web is not None:
                self._web.send_local_metric(exp, round, metric, node, step, value)

    def get_local_logs(self):
        return self.local_metrics.get_all_logs()

    def get_global_logs(self):
        return self.global_metrics.get_all_logs()

    # ---- communication metrics (gossip data plane observability) ----

    def log_comm_metric(self, node: str, metric: str, value: float = 1.0) -> None:
        """Accumulate a communication counter (thread-safe, no experiment
        context needed — callable from gossip/send worker threads). A thin
        view over the telemetry registry's ``"comm"`` counter group."""
        from p2pfl_tpu.management.telemetry import telemetry

        telemetry.inc("comm", node, metric, value)

    def get_comm_metrics(self, node: Optional[str] = None) -> Dict:
        """Counter snapshot: one node's ``{metric: value}``, or all nodes'."""
        from p2pfl_tpu.management.telemetry import telemetry

        return telemetry.counters("comm", node)

    def reset_comm_metrics(self) -> None:
        from p2pfl_tpu.management.telemetry import telemetry

        telemetry.reset_counters("comm")

    def snapshot_and_reset_comm_metrics(self, node: Optional[str] = None) -> Dict:
        """Atomic read-and-clear of the comm counters: the ``get`` +
        ``reset`` pair benches/tests used to run could lose increments
        landing between the two calls — this cannot."""
        from p2pfl_tpu.management.telemetry import telemetry

        return telemetry.snapshot_and_reset("comm", node)

    # ---- node registry (reference logger.py:491-543) ----

    def register_node(self, node: str, state: Any = None, simulation: bool = False) -> None:
        with self._nodes_lock:
            self._nodes[node] = (state, simulation)
        if self._web is not None:
            self._web.register_node(node, is_simulated=simulation)
            import time as _time

            from p2pfl_tpu.management.node_monitor import NodeMonitor

            monitor = NodeMonitor(
                node,
                report_fn=lambda n, m, v: self._web.send_system_metric(
                    n, m, v, _time.strftime("%Y-%m-%d %H:%M:%S")
                ),
            )
            monitor.start()
            self._monitors[node] = monitor

    def learning_states(self) -> list:
        """(addr, NodeState) snapshot of every registered node that has a
        state object — the stall watchdog's scan source."""
        with self._nodes_lock:
            return [(n, s) for n, (s, _sim) in self._nodes.items() if s is not None]

    def unregister_node(self, node: str) -> None:
        with self._nodes_lock:
            self._nodes.pop(node, None)
        monitor = self._monitors.pop(node, None)
        if monitor is not None:
            monitor.stop()
        if self._web is not None:
            self._web.unregister_node(node)

    def _experiment_for(self, node: str) -> Optional[str]:
        with self._nodes_lock:
            entry = self._nodes.get(node)
        state = entry[0] if entry else None
        return getattr(state, "experiment_name", None) if state is not None else None

    def _round_for(self, node: str) -> Optional[int]:
        with self._nodes_lock:
            entry = self._nodes.get(node)
        state = entry[0] if entry else None
        return getattr(state, "round", None) if state is not None else None

    # ---- lifecycle hooks (stubs in the reference too, logger.py:549-581) ----

    def experiment_started(self, node: str) -> None:
        self.debug(node, "experiment started")

    def experiment_finished(self, node: str) -> None:
        self.debug(node, "experiment finished")

    def round_finished(self, node: str) -> None:
        self.debug(node, "round finished")


logger = P2pflLogger()
