"""Tracing / profiling helpers.

The reference has no tracing at all (SURVEY §5: wall-clock prints only);
``jax.profiler`` integration is the idiomatic TPU upgrade: traces capture
XLA op timelines, collective latencies and host↔device transfers, viewable
in TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax

from p2pfl_tpu.management.logger import logger


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/p2pfl_tpu_trace") -> Iterator[None]:
    """Capture a jax.profiler trace for the enclosed block."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("profiler", f"trace written to {log_dir}")


@contextlib.contextmanager
def annotate(name: str, step: Optional[int] = None) -> Iterator[None]:
    """Label the enclosed device work in the trace timeline."""
    with jax.profiler.StepTraceAnnotation(name, step_num=step or 0):
        yield


class Stopwatch:
    """Cheap wall-clock section timing (the reference's --measure_time,
    generalized): ``with sw.section("fit"): ...`` then ``sw.summary()``."""

    def __init__(self) -> None:
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}

    @contextlib.contextmanager
    def section(self, name: str) -> Iterator[None]:
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.totals[name] = self.totals.get(name, 0.0) + time.monotonic() - t0
            self.counts[name] = self.counts.get(name, 0) + 1

    def summary(self) -> dict[str, dict[str, float]]:
        return {
            k: {"total_s": round(v, 4), "calls": self.counts[k], "mean_s": round(v / self.counts[k], 4)}
            for k, v in self.totals.items()
        }
