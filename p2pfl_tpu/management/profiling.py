"""Tracing / profiling helpers.

The reference has no tracing at all (SURVEY §5: wall-clock prints only);
``jax.profiler`` integration is the idiomatic TPU upgrade: traces capture
XLA op timelines, collective latencies and host↔device transfers, viewable
in TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax

from p2pfl_tpu.management.logger import logger


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/p2pfl_tpu_trace") -> Iterator[None]:
    """Capture a jax.profiler trace for the enclosed block."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("profiler", f"trace written to {log_dir}")


@contextlib.contextmanager
def annotate(name: str, step: Optional[int] = None) -> Iterator[None]:
    """Label the enclosed device work in the trace timeline."""
    with jax.profiler.StepTraceAnnotation(name, step_num=step or 0):
        yield


# bf16 peak matmul FLOP/s per chip by device kind (public spec sheets);
# used to turn achieved FLOP/s into model-FLOPs-utilization
_PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops(device=None) -> Optional[float]:
    """Peak FLOP/s for a device (None when unknown, e.g. CPU)."""
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    for name, peak in _PEAK_FLOPS.items():
        if kind.lower().startswith(name.lower()):
            return peak
    return None


def force_execution(tree) -> float:
    """Block until ``tree``'s pending computation REALLY finished.

    ``jax.block_until_ready`` is not a reliable barrier on remote-attached
    platforms (the axon TPU tunnel acks buffer readiness before the device
    is done — measured 9× under-reads on round timings); a device-to-host
    fetch is. Fetches a SINGLE element (a tiny on-device slice that depends
    on the pending computation), so the barrier itself moves O(bytes) — a
    whole-leaf fetch would bill megabytes of tunnel transfer to whatever
    the caller is timing. All benchmark timers use this.
    """
    import numpy as np

    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return 0.0
    leaf = leaves[0]
    ndim = getattr(leaf, "ndim", None)
    if not ndim:  # Python scalar or 0-d array: nothing to slice
        return float(np.asarray(leaf))
    return float(np.asarray(leaf[(0,) * ndim]))


def compiled_flops(jitted, *args, **kwargs) -> Optional[float]:
    """Total FLOPs of one execution, from the compiled XLA cost analysis."""
    try:
        cost = jitted.lower(*args, **kwargs).compile().cost_analysis()
        if isinstance(cost, list):  # older jax returns one dict per device
            cost = cost[0]
        return float(cost["flops"])
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        return None


def mfu(flops: Optional[float], seconds: float, n_devices: int = 1) -> Optional[float]:
    """Model-FLOPs-utilization: achieved FLOP/s over aggregate peak FLOP/s."""
    peak = peak_flops()
    if flops is None or peak is None or seconds <= 0:
        return None
    return flops / seconds / (peak * n_devices)


# ---- dispatch accounting (ISSUE 6: the host dispatch tax) ----
#
# Process-wide counters of MODEL-PLANE device dispatches, incremented at
# the jit call sites of the overlay round's compute: "eval_step",
# "train_epoch" (one per epoch on the staged path), "fused_round" (the
# whole-round program) and "aggregate" (one per Aggregator.aggregate
# invocation). Deliberately NOT a hook into jax internals — the counter
# measures how many times OUR hot path crosses the host↔device boundary,
# which is the tax the fused round exists to kill; incidental eager ops
# (optimizer re-init, tree utilities) are not the round's dispatch
# structure and are excluded. Per-node counts additionally land in
# ``logger.get_comm_metrics(addr)["device_dispatch"]`` so benches can
# attribute dispatches/round per node.

import threading as _threading

_dispatch_lock = _threading.Lock()
_dispatch_counts: dict = {}


def record_dispatch(site: str, node: str = "") -> None:
    """Count one model-plane device dispatch issued at ``site``."""
    with _dispatch_lock:
        _dispatch_counts[site] = _dispatch_counts.get(site, 0) + 1
    if node:
        logger.log_comm_metric(node, "device_dispatch")


def get_dispatch_counts() -> dict:
    """Snapshot of per-site dispatch counters (``logger.get_comm_metrics``
    style: plain accumulators, reset via :func:`reset_dispatch_counts`)."""
    with _dispatch_lock:
        return dict(_dispatch_counts)


def total_dispatches() -> int:
    with _dispatch_lock:
        return int(sum(_dispatch_counts.values()))


def reset_dispatch_counts() -> None:
    with _dispatch_lock:
        _dispatch_counts.clear()


class Stopwatch:
    """Cheap wall-clock section timing (the reference's --measure_time,
    generalized): ``with sw.section("fit"): ...`` then ``sw.summary()``."""

    def __init__(self) -> None:
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}

    @contextlib.contextmanager
    def section(self, name: str) -> Iterator[None]:
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.totals[name] = self.totals.get(name, 0.0) + time.monotonic() - t0
            self.counts[name] = self.counts.get(name, 0) + 1

    def summary(self) -> dict[str, dict[str, float]]:
        return {
            k: {"total_s": round(v, 4), "calls": self.counts[k], "mean_s": round(v / self.counts[k], 4)}
            for k, v in self.totals.items()
        }
