"""Tracing / profiling helpers.

The reference has no tracing at all (SURVEY §5: wall-clock prints only);
``jax.profiler`` integration is the idiomatic TPU upgrade: traces capture
XLA op timelines, collective latencies and host↔device transfers, viewable
in TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
import threading as _threading
import time
from typing import Iterator, Optional

import jax

from p2pfl_tpu.management.logger import logger


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/p2pfl_tpu_trace") -> Iterator[None]:
    """Capture a jax.profiler trace for the enclosed block."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("profiler", f"trace written to {log_dir}")


@contextlib.contextmanager
def annotate(name: str, step: Optional[int] = None) -> Iterator[None]:
    """Label the enclosed device work in the trace timeline."""
    with jax.profiler.StepTraceAnnotation(name, step_num=step or 0):
        yield


# bf16 peak matmul FLOP/s per chip by device kind (public spec sheets);
# used to turn achieved FLOP/s into model-FLOPs-utilization
_PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops(device=None) -> Optional[float]:
    """Peak FLOP/s for a device (None when unknown, e.g. CPU)."""
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    for name, peak in _PEAK_FLOPS.items():
        if kind.lower().startswith(name.lower()):
            return peak
    return None


def force_execution(tree) -> float:
    """Block until ``tree``'s pending computation REALLY finished.

    ``jax.block_until_ready`` is not a reliable barrier on remote-attached
    platforms (the axon TPU tunnel acks buffer readiness before the device
    is done — measured 9× under-reads on round timings); a device-to-host
    fetch is. Fetches a SINGLE element (a tiny on-device slice that depends
    on the pending computation), so the barrier itself moves O(bytes) — a
    whole-leaf fetch would bill megabytes of tunnel transfer to whatever
    the caller is timing. All benchmark timers use this.
    """
    import numpy as np

    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return 0.0
    leaf = leaves[0]
    ndim = getattr(leaf, "ndim", None)
    if not ndim:  # Python scalar or 0-d array: nothing to slice
        return float(np.asarray(leaf))
    return float(np.asarray(leaf[(0,) * ndim]))


def compiled_flops(jitted, *args, **kwargs) -> Optional[float]:
    """Total FLOPs of one execution, from the compiled XLA cost analysis."""
    try:
        cost = jitted.lower(*args, **kwargs).compile().cost_analysis()
        if isinstance(cost, list):  # older jax returns one dict per device
            cost = cost[0]
        return float(cost["flops"])
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        return None


def mfu(flops: Optional[float], seconds: float, n_devices: int = 1) -> Optional[float]:
    """Model-FLOPs-utilization: achieved FLOP/s over aggregate peak FLOP/s."""
    peak = peak_flops()
    if flops is None or peak is None or seconds <= 0:
        return None
    return flops / seconds / (peak * n_devices)


# ---- dispatch accounting (ISSUE 6: the host dispatch tax) ----
#
# Process-wide counters of MODEL-PLANE device dispatches, incremented at
# the jit call sites of the overlay round's compute: "eval_step",
# "train_epoch" (one per epoch on the staged path), "fused_round" (the
# whole-round program) and "aggregate" (one per Aggregator.aggregate
# invocation). Deliberately NOT a hook into jax internals — the counter
# measures how many times OUR hot path crosses the host↔device boundary,
# which is the tax the fused round exists to kill; incidental eager ops
# (optimizer re-init, tree utilities) are not the round's dispatch
# structure and are excluded. Per-node counts additionally land in
# ``logger.get_comm_metrics(addr)["device_dispatch"]`` so benches can
# attribute dispatches/round per node.
#
# Since the flight recorder the counters live in the unified telemetry
# registry (counter group "dispatch", node "" = process-wide site totals);
# this surface is a thin view, and :func:`dispatch_span` is the preferred
# call-site wrapper — it counts AND records a "dispatch"-plane span (with
# an optional jax.profiler annotation bridge on accelerators).


def record_dispatch(site: str, node: str = "") -> None:
    """Count one model-plane device dispatch issued at ``site``."""
    from p2pfl_tpu.management.telemetry import telemetry

    telemetry.inc("dispatch", "", site)
    if node:
        logger.log_comm_metric(node, "device_dispatch")


def get_dispatch_counts() -> dict:
    """Snapshot of per-site dispatch counters (``logger.get_comm_metrics``
    style: plain accumulators, reset via :func:`reset_dispatch_counts`)."""
    from p2pfl_tpu.management.telemetry import telemetry

    return {k: int(v) for k, v in telemetry.counters("dispatch", "").items()}


def total_dispatches() -> int:
    return int(sum(get_dispatch_counts().values()))


def reset_dispatch_counts() -> None:
    from p2pfl_tpu.management.telemetry import telemetry

    telemetry.reset_counters("dispatch")


def snapshot_and_reset_dispatch_counts() -> dict:
    """Atomic read-and-clear: a ``get`` + ``reset`` pair can lose
    dispatches recorded between the two calls (e.g. a gossip worker's
    decode-side aggregate landing mid-bench) — this cannot."""
    from p2pfl_tpu.management.telemetry import telemetry

    return {
        k: int(v)
        for k, v in telemetry.snapshot_and_reset("dispatch", "").items()
    }


@contextlib.contextmanager
def dispatch_span(site: str, node: str = "", **attrs) -> Iterator[None]:
    """Wrap one model-plane jit call site: counts the dispatch
    (:func:`record_dispatch`) and records a "dispatch"-plane span whose
    duration is the HOST-side dispatch cost (jax returns before the device
    finishes — the async tail bills to whoever blocks, which is exactly
    the host-dispatch-tax accounting ISSUE 6 established). On accelerators
    the span body additionally runs under ``jax.profiler.TraceAnnotation``
    so a captured profiler trace lines the host span up with the device
    timeline (``settings.telemetry_jax_annotations``).

    The count lands only when the body SUCCEEDS: a failed fused-round
    dispatch falls back to the staged path, and counting both would
    inflate dispatches_per_round with a program that never ran to
    completion (the span still records, with the error in its attrs)."""
    from p2pfl_tpu.management.telemetry import telemetry
    from p2pfl_tpu.settings import telemetry_jax_annotations

    with telemetry.span(node, site, kind="dispatch", attrs=attrs or None):
        if telemetry_jax_annotations():
            with jax.profiler.TraceAnnotation(f"p2pfl:{site}"):
                yield
        else:
            yield
    record_dispatch(site, node)


class Stopwatch:
    """Cheap wall-clock section timing (the reference's --measure_time,
    generalized): ``with sw.section("fit"): ...`` then ``sw.summary()``.

    Thread-safe — sections run on gossip worker threads too — and backed
    by the telemetry registry's :class:`~p2pfl_tpu.management.telemetry.
    LatencyHistogram`, so ``summary()`` carries percentiles alongside the
    historical total/mean columns. ``totals``/``counts`` remain readable
    as plain dict snapshots for existing callers.
    """

    def __init__(self) -> None:
        from p2pfl_tpu.management.telemetry import LatencyHistogram

        self._lock = _threading.Lock()
        self._hists: dict[str, LatencyHistogram] = {}

    @contextlib.contextmanager
    def section(self, name: str) -> Iterator[None]:
        from p2pfl_tpu.management.telemetry import LatencyHistogram

        t0 = time.monotonic_ns()
        try:
            yield
        finally:
            hist = self._hists.get(name)
            if hist is None:
                with self._lock:
                    hist = self._hists.setdefault(name, LatencyHistogram())
            hist.record(time.monotonic_ns() - t0)

    @property
    def totals(self) -> dict[str, float]:
        with self._lock:
            items = list(self._hists.items())
        return {k: h.sum_ns / 1e9 for k, h in items}

    @property
    def counts(self) -> dict[str, int]:
        with self._lock:
            items = list(self._hists.items())
        return {k: h.count for k, h in items}

    def summary(self) -> dict[str, dict[str, float]]:
        with self._lock:
            items = list(self._hists.items())
        out: dict[str, dict[str, float]] = {}
        for k, h in items:
            s = h.summary()
            if not s.get("count"):
                continue
            out[k] = {
                "total_s": round(s["total_s"], 4),
                "calls": s["count"],
                "mean_s": round(s["total_s"] / s["count"], 4),
                "p50_ms": s["p50_ms"],
                "p95_ms": s["p95_ms"],
                "p99_ms": s["p99_ms"],
            }
        return out
