"""Stall watchdog: thread-stack dumps when a learning round stops moving.

The reference has no deadlock/stall diagnostics (SURVEY §5 — concurrency
safety is hand-rolled locks, and a wedged round just hangs until a human
attaches a debugger). Here every stage transition stamps
``NodeState.last_transition``; a daemon thread watches all locally
registered learning nodes and, when one sits in the same stage longer
than ``Settings.STALL_WATCHDOG_S``, logs the stuck node/stage plus a
stack trace of EVERY live thread (``sys._current_frames``) — gossip
loops, heartbeaters, gRPC executors, the learning thread — which is
exactly the information needed to see which wait wedged. Detection only:
it never kills anything (the timeout/eviction machinery owns recovery).
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Optional

from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.settings import Settings


class StallWatchdog:
    """Singleton daemon; started lazily by ``Node.start()`` when
    ``Settings.STALL_WATCHDOG_S > 0``."""

    _instance: Optional["StallWatchdog"] = None
    _lock = threading.Lock()

    def __init__(self) -> None:
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: monotonic time of the last dump per node — one report per stall,
        #: not one per poll tick
        self._reported: dict[str, float] = {}

    # ---- lifecycle ----

    @classmethod
    def ensure_started(cls) -> Optional["StallWatchdog"]:
        if Settings.STALL_WATCHDOG_S <= 0:
            return None
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
                cls._instance._start()
            return cls._instance

    @classmethod
    def shutdown(cls) -> None:
        with cls._lock:
            if cls._instance is not None:
                cls._instance._stop.set()
                cls._instance = None

    def _start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="stall-watchdog", daemon=True
        )
        self._thread.start()

    # ---- detection ----

    def _run(self) -> None:
        while True:
            # re-read each tick so lowering/raising the knob takes effect;
            # floor at 0.1s so S=0 (set after start, meaning "disable")
            # pauses scanning instead of busy-spinning
            period = max(min(1.0, Settings.STALL_WATCHDOG_S / 4), 0.1)
            if self._stop.wait(period):
                return
            try:
                self._scan()
            except Exception:  # noqa: BLE001 — diagnostics must never take a node down
                pass

    def _scan(self) -> None:
        if Settings.STALL_WATCHDOG_S <= 0:
            return  # disabled after start
        now = time.monotonic()
        states = logger.learning_states()
        # prune report latches of unregistered nodes (the daemon outlives
        # short-lived simulation nodes; the dict must not grow unboundedly)
        live = {a for a, _s in states}
        for gone in [a for a in self._reported if a not in live]:
            self._reported.pop(gone, None)
        for addr, state in states:
            last = getattr(state, "last_transition", None)
            if last is None or state.status != "Learning":
                self._reported.pop(addr, None)
                continue
            if now - last < Settings.STALL_WATCHDOG_S:
                self._reported.pop(addr, None)
                continue
            if self._reported.get(addr) == last:
                continue  # this stall (same stuck transition) already reported
            self._reported[addr] = last
            stage = getattr(state, "current_stage", "?")
            # countable health signal alongside the human-readable dump —
            # chaos tests and CI assert get_comm_metrics()['stall_detected']
            # stays zero instead of grepping logs
            logger.log_comm_metric(addr, "stall_detected")
            logger.error(
                addr,
                f"STALL: no stage transition for {now - last:.0f}s "
                f"(stuck in {stage}, round {state.round}). Thread stacks:\n"
                + all_thread_stacks(),
            )


def all_thread_stacks() -> str:
    """Formatted stacks of every live thread, tagged with thread names."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(
            f"--- thread {names.get(ident, ident)} ---\n"
            + "".join(traceback.format_stack(frame))
        )
    return "\n".join(out)
