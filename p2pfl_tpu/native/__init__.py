"""Native codec bindings (ctypes) with transparent numpy fallback.

Loads ``libp2tw.so`` (built from ``codec.cpp`` by ``build.sh``); if the
library is missing and a compiler is available it is built once on first
import. Every entry point has a numpy fallback so the framework never
*requires* the native layer — it's the fast path, not a dependency.

API:
- :func:`quantize`   — fp32 array → (int8 array, scale)
- :func:`dequantize` — (int8 array, scale) → fp32 array
- :func:`crc32c`     — Castagnoli CRC of a bytes-like
- :data:`NATIVE`     — True when the C++ library is in use
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libp2tw.so")

_lib: Optional[ctypes.CDLL] = None


def _try_build() -> None:
    """Build the .so atomically, serialized across processes.

    Two node processes importing concurrently must not both run ``g++ -o
    libp2tw.so`` in place — one would ``CDLL`` a half-written library.
    The compile targets a private temp file promoted with :func:`os.replace`
    (atomic on POSIX), and an ``fcntl`` lockfile serializes builders: the
    loser of the race wakes up, sees the finished .so, and skips its build.
    """
    src = os.path.join(_DIR, "codec.cpp")
    if not os.path.exists(src):
        return
    try:
        import fcntl
    except ImportError:
        # no fcntl (Windows): build without the inter-process lock — the
        # temp-file + atomic os.replace promotion alone already prevents a
        # concurrent importer from CDLLing a torn .so
        fcntl = None
    tmp = f"{_SO}.tmp.{os.getpid()}"
    try:
        if fcntl is None:
            _compile(src, tmp)
            return
        with open(f"{_SO}.lock", "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                if os.path.exists(_SO):
                    return  # another process built it while we waited
                _compile(src, tmp)
            finally:
                fcntl.flock(lockf, fcntl.LOCK_UN)
    except (OSError, subprocess.SubprocessError):
        pass
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _compile(src: str, tmp: str) -> None:
    subprocess.run(
        ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, src],
        check=True,
        capture_output=True,
        timeout=120,
    )
    os.replace(tmp, _SO)


def _load() -> Optional[ctypes.CDLL]:
    if not os.path.exists(_SO):
        _try_build()
    if not os.path.exists(_SO):
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    lib.p2tw_quantize_f32_i8.restype = ctypes.c_float
    lib.p2tw_quantize_f32_i8.argtypes = [
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int8),
    ]
    lib.p2tw_dequantize_i8_f32.restype = None
    lib.p2tw_dequantize_i8_f32.argtypes = [
        ctypes.POINTER(ctypes.c_int8),
        ctypes.c_int64,
        ctypes.c_float,
        ctypes.POINTER(ctypes.c_float),
    ]
    lib.p2tw_crc32c.restype = ctypes.c_uint32
    lib.p2tw_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32]
    return lib


_lib = _load()
NATIVE = _lib is not None


def quantize(arr: np.ndarray) -> tuple[np.ndarray, float]:
    """Symmetric per-tensor int8 quantization. Returns (int8 array, scale)."""
    flat = np.ascontiguousarray(arr, dtype=np.float32).reshape(-1)
    out = np.empty(flat.shape, dtype=np.int8)
    if _lib is not None:
        scale = _lib.p2tw_quantize_f32_i8(
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            flat.size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        )
        return out.reshape(arr.shape), float(scale)
    absmax = float(np.abs(flat).max()) if flat.size else 0.0
    scale = absmax / 127.0 if absmax > 0 else 1.0
    q = np.clip(np.rint(flat / scale), -127, 127)
    return q.astype(np.int8).reshape(arr.shape), scale


def dequantize(arr: np.ndarray, scale: float) -> np.ndarray:
    flat = np.ascontiguousarray(arr, dtype=np.int8).reshape(-1)
    if _lib is not None:
        out = np.empty(flat.shape, dtype=np.float32)
        _lib.p2tw_dequantize_i8_f32(
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
            flat.size,
            ctypes.c_float(scale),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        return out.reshape(arr.shape)
    return (flat.astype(np.float32) * scale).reshape(arr.shape)


def crc32c(data, seed: int = 0) -> int:
    """CRC32C of a bytes-like (``bytes`` or ``memoryview`` — the decode hot
    path passes payload-frame slices without copying them out)."""
    if _lib is not None:
        if isinstance(data, bytes):
            return int(_lib.p2tw_crc32c(data, len(data), seed))
        # zero-copy pointer into the buffer (read-only buffers included,
        # which ctypes' from_buffer would reject)
        buf = np.frombuffer(data, dtype=np.uint8)
        ptr = buf.ctypes.data_as(ctypes.c_char_p)
        return int(_lib.p2tw_crc32c(ptr, buf.size, seed))
    return _crc32c_py(data, seed)


_PY_TABLE: Optional[list[int]] = None


def _crc32c_py(data: bytes, seed: int = 0) -> int:
    global _PY_TABLE
    if _PY_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if c & 1 else (c >> 1)
            table.append(c)
        _PY_TABLE = table
    c = seed ^ 0xFFFFFFFF
    for b in data:
        c = _PY_TABLE[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def _gf2_times(mat: list, vec: int) -> int:
    s = 0
    i = 0
    while vec:
        if vec & 1:
            s ^= mat[i]
        vec >>= 1
        i += 1
    return s


def _gf2_square(mat: list) -> list:
    return [_gf2_times(mat, mat[n]) for n in range(32)]


#: shift operators cached per byte count — every chunk of one stream has
#: the same body length, so a whole transfer pays the O(32·log n) matrix
#: build at most twice (slab size + the odd-sized final chunk)
_COMBINE_OPS: dict[int, list] = {}


def crc32c_combine(crc1: int, crc2: int, len2: int) -> int:
    """CRC32C of ``A + B`` from ``crc32c(A)``, ``crc32c(B)`` and ``len(B)``,
    touching zero payload bytes (zlib's ``crc32_combine`` over the
    Castagnoli polynomial).

    The streaming decoder verifies each arriving chunk's own CRC (one
    pass over its bytes) and folds it into the running whole-payload CRC
    with this combine — instead of a second full pass per byte, the fold
    is one cached 32×32 GF(2) matrix-vector product per chunk.
    """
    if len2 <= 0:
        return crc1
    op = _COMBINE_OPS.get(len2)
    if op is None:
        # operator for one zero BIT, squared 3× → one zero byte
        mat = [0x82F63B78] + [1 << n for n in range(31)]
        for _ in range(3):
            mat = _gf2_square(mat)
        # square-and-multiply up to len2 zero bytes
        op = [1 << n for n in range(32)]  # identity
        n = len2
        while n:
            if n & 1:
                op = [_gf2_times(mat, col) for col in op]
            n >>= 1
            if n:
                mat = _gf2_square(mat)
        if len(_COMBINE_OPS) < 256:
            _COMBINE_OPS[len2] = op
    return _gf2_times(op, crc1) ^ crc2
