// Native wire-codec kernels for network transports.
//
// The reference's wire path is pickle over gRPC (no integrity checking, no
// compression — p2pfl/learning/pytorch/lightning_learner.py:113-138). This
// library provides the byte-level hot loops for the rebuild's codec:
//
//   - symmetric per-tensor int8 quantization (4x smaller gossip payloads,
//     fp32 scale chosen from the absmax),
//   - dequantization back to fp32,
//   - CRC32C (Castagnoli, software slice-by-1) integrity checksums for
//     every framed payload.
//
// Exposed with C linkage for ctypes; a numpy fallback in
// p2pfl_tpu/native/__init__.py keeps environments without a compiler
// working. Build: ./build.sh (g++ -O3 -shared -fPIC).

#include <cstdint>
#include <cstddef>
#include <cmath>

extern "C" {

// ---- quantization ----

// Quantize n fp32 values to int8 with a single symmetric scale.
// Returns the scale used (absmax / 127); dst must hold n bytes.
float p2tw_quantize_f32_i8(const float* src, int64_t n, int8_t* dst) {
    float absmax = 0.0f;
    for (int64_t i = 0; i < n; ++i) {
        float a = std::fabs(src[i]);
        if (a > absmax) absmax = a;
    }
    float scale = absmax > 0.0f ? absmax / 127.0f : 1.0f;
    float inv = 1.0f / scale;
    for (int64_t i = 0; i < n; ++i) {
        float q = src[i] * inv;
        q = q > 127.0f ? 127.0f : (q < -127.0f ? -127.0f : q);
        dst[i] = (int8_t)std::lrintf(q);
    }
    return scale;
}

void p2tw_dequantize_i8_f32(const int8_t* src, int64_t n, float scale, float* dst) {
    for (int64_t i = 0; i < n; ++i) {
        dst[i] = (float)src[i] * scale;
    }
}

// ---- CRC32C (Castagnoli), reflected, poly 0x82F63B78 ----
//
// Two engines behind one entry point: the SSE4.2 crc32 instruction
// (8 bytes/cycle — the streaming byte plane checksums every chunk on both
// ends, so this path is what keeps integrity checking out of the wire
// profile) with a table-based software loop as the portable fallback.
// Dispatch is one __builtin_cpu_supports probe, cached after first call.

static uint32_t crc32c_table[256];
static bool crc32c_ready = false;

static void crc32c_init() {
    for (uint32_t i = 0; i < 256; ++i) {
        uint32_t c = i;
        for (int k = 0; k < 8; ++k)
            c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
        crc32c_table[i] = c;
    }
    crc32c_ready = true;
}

static uint32_t crc32c_sw(const uint8_t* buf, int64_t n, uint32_t c) {
    if (!crc32c_ready) crc32c_init();
    for (int64_t i = 0; i < n; ++i)
        c = crc32c_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
    return c;
}

#if defined(__x86_64__)
#include <cstring>

__attribute__((target("sse4.2")))
static uint32_t crc32c_hw(const uint8_t* buf, int64_t n, uint32_t c) {
    uint64_t c64 = c;
    while (n >= 8) {
        uint64_t w;
        std::memcpy(&w, buf, 8);  // unaligned-safe load
        c64 = __builtin_ia32_crc32di(c64, w);
        buf += 8;
        n -= 8;
    }
    uint32_t cc = (uint32_t)c64;
    while (n-- > 0)
        cc = __builtin_ia32_crc32qi(cc, *buf++);
    return cc;
}

static int crc32c_have_hw = -1;
#endif

uint32_t p2tw_crc32c(const uint8_t* buf, int64_t n, uint32_t seed) {
    uint32_t c = seed ^ 0xFFFFFFFFu;
#if defined(__x86_64__)
    if (crc32c_have_hw < 0)
        crc32c_have_hw = __builtin_cpu_supports("sse4.2") ? 1 : 0;
    if (crc32c_have_hw)
        return crc32c_hw(buf, n, c) ^ 0xFFFFFFFFu;
#endif
    return crc32c_sw(buf, n, c) ^ 0xFFFFFFFFu;
}

}  // extern "C"
