"""Checkpoint / resume via orbax.

The reference has NO checkpointing (explicitly disabled,
``lightning_learner.py:188``); SURVEY §5 marks this as the idiomatic
addition. Covers both run modes:

- :func:`save_learner` / :func:`restore_learner` — one node's params,
  optimizer state and round counter;
- :meth:`SpmdFederation.save` / ``.restore`` (wired here) — the whole
  node-stacked federation state, sharding-aware (orbax restores straight
  into the mesh layout).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import orbax.checkpoint as ocp

from p2pfl_tpu.settings import Settings

Pytree = Any


def _path(directory: str) -> str:
    return os.path.abspath(os.path.expanduser(directory))


def _manager(directory: str, keep_n: Optional[int]) -> ocp.CheckpointManager:
    """A CheckpointManager with retention wired: ``keep_n`` newest steps
    are kept (``CheckpointManagerOptions.max_to_keep``), older ones GC'd
    on save. None reads ``Settings.CHECKPOINT_KEEP_N``; 0 = unbounded —
    the pre-retention behavior, still the standalone default, but a
    long-lived fleet member saving every update MUST bound this (the
    node journal passes its own ``JOURNAL_KEEP_N``)."""
    if keep_n is None:
        keep_n = int(Settings.CHECKPOINT_KEEP_N)
    options = ocp.CheckpointManagerOptions(max_to_keep=keep_n) if keep_n > 0 else None
    return ocp.CheckpointManager(_path(directory), options=options)


def save_state(
    directory: str, state: dict, step: int = 0, keep_n: Optional[int] = None
) -> None:
    """Save an arbitrary pytree-of-arrays state dict."""
    with _manager(directory, keep_n) as mgr:
        mgr.save(step, args=ocp.args.StandardSave(state), force=True)
        mgr.wait_until_finished()


def restore_state(directory: str, template: dict, step: Optional[int] = None) -> dict:
    """Restore into the structure/shardings of ``template``."""
    with ocp.CheckpointManager(_path(directory)) as mgr:
        step = mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {directory}")
        return mgr.restore(step, args=ocp.args.StandardRestore(template))


def save_learner(
    directory: str,
    learner,
    round: Optional[int] = None,  # noqa: A002
    keep_n: Optional[int] = None,
) -> None:
    save_state(
        directory,
        {"params": learner.params, "opt_state": learner.opt_state},
        step=round or 0,
        keep_n=keep_n,
    )


def restore_learner(directory: str, learner, step: Optional[int] = None) -> None:
    state = restore_state(
        directory, {"params": learner.params, "opt_state": learner.opt_state}, step
    )
    learner.params = state["params"]
    learner.opt_state = state["opt_state"]


def _federation_state(fed) -> dict:
    """Everything a resumed federation needs: params + opt state + any
    algorithm state (SCAFFOLD control variates, FedOpt server moments) —
    dropping those on resume would silently degrade the algorithm."""
    state = {"params": fed.params, "opt_state": fed.opt_state}
    if getattr(fed, "scaffold", False):
        state["c_global"] = fed.c_global
        state["c_local"] = fed.c_local
    if getattr(fed, "server_opt", ""):
        state["opt_m"] = fed.opt_m
        state["opt_v"] = fed.opt_v
        state["server_t"] = fed._server_t
    return state


def save_federation(directory: str, fed) -> None:
    save_state(directory, _federation_state(fed), step=fed.round)


def restore_federation(directory: str, fed, step: Optional[int] = None) -> None:
    with ocp.CheckpointManager(_path(directory)) as mgr:
        use = mgr.latest_step() if step is None else step
        if use is None:
            raise FileNotFoundError(f"no checkpoint under {directory}")
        state = mgr.restore(use, args=ocp.args.StandardRestore(_federation_state(fed)))
    fed.params = state["params"]
    fed.opt_state = state["opt_state"]
    if getattr(fed, "scaffold", False):
        fed.c_global = state["c_global"]
        fed.c_local = state["c_local"]
    if getattr(fed, "server_opt", ""):
        fed.opt_m = state["opt_m"]
        fed.opt_v = state["opt_v"]
        fed._server_t = int(state["server_t"])
    fed.round = use
