"""Secure aggregation: pairwise additive masking over the gossip overlay.

The reference has no privacy layer — every gossiped payload is a node's raw
model over an insecure channel (``p2pfl/communication/grpc/grpc_server.py``,
insecure channels throughout). This module adds the classic
pairwise-masking scheme (Bonawitz et al., CCS'17) adapted to p2p federated
averaging:

- every node derives one shared seed per train-set peer via Diffie-Hellman
  over the existing message gossip (a single ``secagg_pub`` broadcast at
  experiment start — RFC 3526 group-14 modular DH, no extra dependencies);
- before contributing its model, each node adds a mask built from those
  seeds: ``u_i = Σ_{j≠i} sign(i,j) · (s_ij / w_i) · PRG(seed_ij, round)``
  with pair scale ``s_ij = SECAGG_MASK_STD · sqrt(w_i · w_j)`` (sample
  counts are announced alongside the DH keys) and ``sign(i,j) = +1`` iff
  ``addr_i < addr_j`` — antisymmetric, so in the sample-weighted FedAvg
  sum ``Σ w_i (p_i + u_i) = Σ w_i p_i`` the masks cancel **exactly
  pairwise** (up to float32 rounding). The sqrt law keeps the mask's
  magnitude ``STD · sqrt(w_j / w_i)`` per pair — independent of the
  absolute dataset size, unlike a naive ``c / w_i`` scale that would leave
  large-dataset nodes effectively unmasked;
- FedAvg's partial-aggregation algebra is linear in the weighted sums, so
  masked partials combine correctly through every gossip hop; the true
  model only materializes once the full train set is covered.

What a wire snoop sees is a single masked model — Gaussian noise of scale
``Settings.SECAGG_MASK_STD`` riding on the parameters, useless without the
other train-set members' masks.

**Threat model: passive wire snooping only.** The protected asset is the
model payload crossing an insecure channel; the adversary reads traffic
but does not inject or reorder control messages. Active attackers are out
of scope — control messages (votes, heartbeats, key announcements,
coverage) are unauthenticated plaintext, exactly like the reference's
insecure channels. Two hardenings still apply against cheap active
tricks: degenerate DH keys are rejected (:func:`valid_public_key`) and
the FIRST key announced per (peer, experiment) is latched — a later
``secagg_pub`` claiming the same source cannot replace it
(``commands/control.py``).

Dropout recovery (Bonawitz-style seed re-disclosure): when aggregation
times out with partial train-set coverage, the leftover pairwise masks
between survivors and the dropped nodes do not cancel. Survivors then
re-disclose their pair seeds *for the dropped nodes only*
(``secagg_recover`` messages), letting every aggregating node subtract
the exact uncancelled sum (:func:`dropout_correction`) and recover the
survivors' clean aggregate — availability degrades to a partial
aggregate, like the reference's plain path
(``p2pfl/learning/aggregators/aggregator.py:236-242``), instead of a
destroyed model. A lone survivor never discloses anything — it corrects
locally (its "aggregate" is its own model, which aggregation cannot
protect anyway).

Double masking (``Settings.SECAGG_DOUBLE_MASK``, default on): pair-seed
disclosure alone would let a snoop unmask a dropped node's update that
was captured on the wire but never reached an aggregator. The full
Bonawitz construction closes this: every contribution also carries a
per-round SELF mask (:func:`self_mask`) whose seed is t-of-n
Shamir-shared with the train set (:func:`shamir_split`; shares travel
encrypted under :func:`dh_share_key` — a sibling hash of the DH secret
that disclosure of the pair MASK seed reveals nothing about). The seed
is revealed by its owner once its contribution demonstrably landed, or
reconstructed by the surviving majority when the owner contributed and
then crashed. Invariant, enforced at every disclosure site in both
directions: no honest participant KNOWINGLY publishes the second seed
type for a (node, round) — pair-seed disclosure is refused for members
whose self-seed reveal was observed (and for live members), and
self-seed help is refused for members any pair disclosure or dropout
claim was observed for. The guarantee is per-participant-observation:
with synchronized views at most one of {pair seeds, self seed} becomes
public and a captured masked update stays masked through every recovery
path; the residual exposure requires a member to die mid-protocol while
the overlay is PARTITIONED such that some survivor saw neither its
contribution nor its reveal — adversarially timing that is outside the
passive-snooping threat model above. An unresolvable round degrades to
a no-op rather than a disclosure.

Limits (documented, matching the protocol's nature):

- FedAvg only: robust aggregators (Krum/median/...) need individual
  models, which is exactly what masking forbids.
- Wire compression must be off (``WIRE_COMPRESSION="none"``): per-node
  quantization of the masks breaks exact cancellation. Checked at
  experiment start.
- A node holding the overwhelming majority of the federation's samples
  gets a small mask (``STD·sqrt((W−w_i)/w_i)``) — but such a node's update
  IS essentially the aggregate, so aggregation itself offers it no privacy
  regardless of masking.

The SPMD mesh runtime (``parallel/spmd.py``) deliberately does not mask:
it is a single-process simulation where "nodes" are device slots — there
is no wire to protect, and the all-reduce is already the trusted
aggregator. :func:`masked_stack` exposes the same masking as a pure jitted
op for device-side verification (see ``tests/test_secagg.py``).
"""

from __future__ import annotations

import hashlib
import secrets
from typing import Any, Optional

import numpy as np

from p2pfl_tpu.learning.weights import ModelUpdate, _flatten_named
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.settings import Settings

Pytree = Any

# RFC 3526 group 14: 2048-bit MODP prime, generator 2.
DH_PRIME = int(
    "FFFFFFFFFFFFFFFFC90FDAA22168C234C4C6628B80DC1CD129024E088A67CC74"
    "020BBEA63B139B22514A08798E3404DDEF9519B3CD3A431B302B0A6DF25F1437"
    "4FE1356D6D51C245E485B576625E7EC6F44C42E9A637ED6B0BFF5CB6F406B7ED"
    "EE386BFB5A899FA5AE9F24117C4B1FE649286651ECE45B3DC2007CB8A163BF05"
    "98DA48361C55D39A69163FA8FD24CF5F83655D23DCA3AD961C62F356208552BB"
    "9ED529077096966D670C354E4ABC9804F1746C08CA18217C32905E462E36CE3B"
    "E39E772C180E86039B2783A2EC07A28FB5C55DF06F4C52C9DE2BCBF695581718"
    "3995497CEA956AE515D2261898FA051015728E5A8AACAA68FFFFFFFFFFFFFFFF",
    16,
)
DH_GENERATOR = 2


def dh_keypair() -> tuple[int, int]:
    """A fresh (private, public) modular Diffie-Hellman pair."""
    priv = secrets.randbits(256)
    return priv, pow(DH_GENERATOR, priv, DH_PRIME)


def valid_public_key(pub: int) -> bool:
    """Range check for a peer's DH public key.

    Rejects the degenerate elements 0, 1, p-1 (and anything out of range):
    with pub=1 every shared secret is 1, so an active sender spoofing
    ``secagg_pub`` messages could make a victim's mask seeds computable
    from public information and strip its masks off the wire.
    """
    return 2 <= pub <= DH_PRIME - 2


def dh_pair_seed(priv: int, peer_pub: int, context: str) -> int:
    """The shared 256-bit PRG key for one (self, peer) pair.

    Symmetric: both ends compute ``g^(xy) mod p`` and hash it with the
    experiment context, so seed(x, g^y) == seed(y, g^x).
    """
    if not valid_public_key(peer_pub):
        from p2pfl_tpu.exceptions import SecAggError

        raise SecAggError("degenerate DH public key (value outside [2, p-2])")
    shared = pow(peer_pub, priv, DH_PRIME)
    h = hashlib.sha256(shared.to_bytes(256, "big") + context.encode("utf-8"))
    return int.from_bytes(h.digest(), "big")


# ---- Shamir t-of-n secret sharing over GF(2^521 − 1) ----
#
# The full Bonawitz double-mask (VERDICT r3 #8) needs each node's per-round
# self-mask seed recoverable by the surviving majority when the node
# contributes its masked update and then crashes before disclosing the
# seed itself. 2^521 − 1 is a Mersenne prime comfortably above the 256-bit
# seeds; arithmetic is plain python ints (control-plane sized: one split
# per node per round, shares are 66-byte field elements).

SHAMIR_PRIME = 2**521 - 1

#: pseudo-contributor appended to DIFFUSED (finalized, self-mask-free)
#: aggregates under double masking, so receivers can tell them apart from
#: full-coverage aggregates assembled out of still-self-masked partials
#: ("#" cannot appear in a node address). Stripped by AddModelCommand.
CLEAN_MARKER = "#secagg_clean"


def shamir_split(secret: int, n: int, t: int) -> list[tuple[int, int]]:
    """Split ``secret`` into ``n`` shares, any ``t`` of which reconstruct it.

    Returns ``[(x, y)]`` with x = 1..n. Coefficients are CSPRNG-uniform;
    with fewer than ``t`` shares the secret is information-theoretically
    hidden (every candidate secret is equally consistent).
    """
    if not 1 <= t <= n:
        raise ValueError(f"need 1 <= t <= n (t={t}, n={n})")
    if not 0 <= secret < SHAMIR_PRIME:
        raise ValueError("secret out of field range")
    coeffs = [secret] + [secrets.randbelow(SHAMIR_PRIME) for _ in range(t - 1)]
    out = []
    for x in range(1, n + 1):
        y = 0
        for c in reversed(coeffs):  # Horner
            y = (y * x + c) % SHAMIR_PRIME
        out.append((x, y))
    return out


def shamir_reconstruct(shares: list[tuple[int, int]]) -> int:
    """Lagrange-interpolate the secret (the polynomial at x=0) from ``t``
    distinct shares. Caller is responsible for passing >= t of them."""
    seen = {}
    for x, y in shares:
        seen[x] = y  # duplicates collapse; distinct x values required below
    pts = list(seen.items())
    secret = 0
    for i, (xi, yi) in enumerate(pts):
        num, den = 1, 1
        for j, (xj, _yj) in enumerate(pts):
            if i == j:
                continue
            num = (num * (-xj)) % SHAMIR_PRIME
            den = (den * (xi - xj)) % SHAMIR_PRIME
        secret = (secret + yi * num * pow(den, -1, SHAMIR_PRIME)) % SHAMIR_PRIME
    return secret


def share_threshold(n_members: int) -> int:
    """Bonawitz honest-majority threshold: reconstruction needs more than
    half the TRAIN SET, clamped to the n_members−1 peers who hold shares
    (with 2 members the single peer holds the whole seed — any recovery in
    a 2-party federation reveals everything to the other party anyway)."""
    return max(1, min(n_members - 1, n_members // 2 + 1))


def dh_share_key(priv: int, peer_pub: int, experiment: str) -> int:
    """The pair's SHARE-ENCRYPTION key — a sibling hash of the same DH
    shared secret as :func:`dh_pair_seed`, under a domain-separated
    context. CRITICAL: dropout recovery broadcasts the pair MASK seed
    (``secagg_recover``) in plaintext; had shares been encrypted under
    that same value, a passive snoop could decrypt a dropped node's share
    broadcast and reconstruct its self seed — defeating double masking in
    exactly the scenario it exists for. Deriving both values as
    independent hashes of the (never-disclosed) ``g^xy`` means disclosing
    one reveals nothing about the other."""
    return dh_pair_seed(priv, peer_pub, experiment + "\x00share-enc")


def _share_stream(
    share_key: int, round_no: int, owner: str, holder: str, n_bytes: int
) -> bytes:
    """Keyed XOF stream for encrypting one Shamir share over the plaintext
    gossip plane. Bound to (owner, holder) as well as (key, round): the
    A→B and B→A shares of a round must not reuse a keystream (two-time
    pad — in a 2-member set the XOR of the raw seeds would leak)."""
    return hashlib.shake_256(
        b"p2pfl-secagg-share-enc\x00"
        + share_key.to_bytes(32, "big")
        + round_no.to_bytes(8, "big")
        + owner.encode("utf-8")
        + b"\x00"
        + holder.encode("utf-8")
    ).digest(n_bytes)


SHARE_BYTES = 66  # ceil(521/8): every share/seed travels as a fixed-width field element


def encrypt_share(y: int, share_key: int, round_no: int, owner: str, holder: str) -> bytes:
    raw = y.to_bytes(SHARE_BYTES, "big")
    stream = _share_stream(share_key, round_no, owner, holder, SHARE_BYTES)
    return bytes(a ^ b for a, b in zip(raw, stream))


def decrypt_share(blob: bytes, share_key: int, round_no: int, owner: str, holder: str) -> int:
    if len(blob) != SHARE_BYTES:
        from p2pfl_tpu.exceptions import SecAggError

        raise SecAggError(f"share ciphertext must be {SHARE_BYTES} bytes")
    stream = _share_stream(share_key, round_no, owner, holder, SHARE_BYTES)
    raw = bytes(a ^ b for a, b in zip(blob, stream))
    return int.from_bytes(raw, "big")


def _leaf_mask(
    seed: int, round_no: int, shape: tuple, li: int,
    domain: bytes = b"p2pfl-secagg-mask\x00",
) -> np.ndarray:
    """Deterministic N(0,1) mask block — same stream on both ends of a pair.

    Keyed by (pair seed, round, leaf index) so masks are fresh every round
    (a reused mask would leak the round-to-round parameter delta). The
    stream is SHAKE-256 in XOF mode mapped through Box–Muller: a keyed
    CSPRNG whose byte stream is defined by the hash standard on every
    platform/library version — unlike NumPy's PCG64, whose stream is only
    stable within a NumPy version line and is not cryptographic. The
    Box–Muller ``log``/``cos``/``sin`` are not IEEE-correctly-rounded, so
    heterogeneous numpy/libm builds may differ by ~1 ulp per value; the
    resulting uncancelled residual is O(STD·2⁻²³) per pair — the same
    class as the float32 addition rounding the protocol already tolerates
    (vs. PCG64 version drift, which would diverge the ENTIRE stream).
    """
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    m = 2 * ((n + 1) // 2)  # even count for Box–Muller pairing
    material = hashlib.shake_256(
        domain
        + seed.to_bytes(32, "big")
        + round_no.to_bytes(8, "big")
        + li.to_bytes(8, "big")
    ).digest(8 * m)
    x = np.frombuffer(material, dtype=">u8").astype(np.float64)
    u = (x + 1.0) * 2.0**-64  # uniform in (0, 1]; log() is safe
    half = m // 2
    r = np.sqrt(-2.0 * np.log(u[:half]))
    theta = (2.0 * np.pi) * u[half:]
    z = np.concatenate([r * np.cos(theta), r * np.sin(theta)])[:n]
    return z.astype(np.float32).reshape(shape)


def pairwise_mask(
    template: Pytree,
    my_addr: str,
    pair_seeds: dict[str, int],
    round_no: int,
    pair_scales: Optional[dict[str, float]] = None,
) -> dict[str, np.ndarray]:
    """This node's total mask as a flat {path: array} dict.

    The weighted sum over the full train set telescopes to zero because
    each pair (i, j) contributes ``+s_ij·PRG(seed_ij)`` on one side and
    ``-s_ij·PRG(seed_ij)`` on the other (``pair_scales[j] = s_ij``, the
    SAME value on both ends).
    """
    flat = _flatten_named(template)
    keys = sorted(flat)
    out: dict[str, np.ndarray] = {k: np.zeros(flat[k].shape, np.float32) for k in keys}
    for peer, seed in pair_seeds.items():
        sign = 1.0 if my_addr < peer else -1.0
        s = 1.0 if pair_scales is None else pair_scales[peer]
        for li, k in enumerate(keys):
            out[k] += (sign * s) * _leaf_mask(seed, round_no, flat[k].shape, li)
    return out


def pair_scale(w_i: float, w_j: float) -> float:
    """The pair mask scale ``s_ij = STD·sqrt(w_i·w_j)`` — symmetric, from
    the ANNOUNCED sample counts (both masking and dropout correction must
    use the same values, which is why :func:`mask_update` latches the
    announced count against the actual one)."""
    return Settings.SECAGG_MASK_STD * float(np.sqrt(float(w_i) * float(w_j)))


def mask_update(
    update: ModelUpdate,
    my_addr: str,
    train_set: list[str],
    priv: int,
    pubs: dict[str, tuple[int, int]],
    experiment: str,
    round_no: int,
    announced_samples: Optional[int] = None,
    self_seed: Optional[int] = None,
) -> ModelUpdate:
    """Mask a node's own contribution before it enters the aggregator.

    ``pubs`` maps peer address → (DH public key, announced sample count);
    the pair scale ``s_ij = STD·sqrt(w_i·w_j)`` needs both ends' counts.
    ``self_seed``: the per-round Bonawitz self-mask seed ``b_i^r`` — when
    given, ``STD·PRG_self(b_i^r)`` rides on top of the pairwise masks
    (double masking; see :func:`self_mask`).

    Raises :class:`SecAggError` when masking cannot be done safely (missing
    peer keys, zero sample weight, non-float32 parameters, lossy wire
    compression). The caller must then SKIP contributing rather than send
    unmasked: peers already derived this node's pair seeds and will add
    their half of the pairwise masks regardless, so an unmasked (or
    zero-weighted, or rounding-lossy) contribution leaves uncancelled mask
    terms in a full-coverage aggregate — noise that nothing would detect.
    An aborted contribution instead leaves coverage incomplete, which
    ``wait_and_get_aggregation`` reports as a loud SecAgg error on every
    node.
    """
    import jax
    import jax.numpy as jnp

    from p2pfl_tpu.exceptions import SecAggError

    peers = [n for n in train_set if n != my_addr]
    if not peers:
        return update
    if Settings.WIRE_COMPRESSION != "none":
        # int8/topk8 would quantize each node's masks independently; the
        # per-node quantization residue survives the FedAvg sum exactly
        # like the bf16 rounding residue rejected below
        raise SecAggError(
            f"WIRE_COMPRESSION={Settings.WIRE_COMPRESSION!r} breaks mask "
            "cancellation; secure aggregation needs a lossless wire"
        )
    missing = [n for n in peers if n not in pubs]
    if missing:
        raise SecAggError(f"missing DH public keys for train-set peers {missing}")
    if update.num_samples <= 0:
        # FedAvg would weight this row by 0, annihilating our masks while
        # peers' matching pair terms survive — cancellation breaks
        raise SecAggError("cannot mask a contribution with zero sample weight")
    if announced_samples is not None and update.num_samples != announced_samples:
        # peers scale their half of each pair mask with the count WE
        # announced alongside our DH key; masking with a different actual
        # weight would leave a residual that survives a FULL-coverage
        # aggregate — noise that no coverage check can detect
        raise SecAggError(
            f"num_samples changed since the key announcement "
            f"({announced_samples} announced, {update.num_samples} now); "
            "mask cancellation would silently break"
        )
    if any(w <= 0 for _p, w in pubs.values()):
        raise SecAggError("a peer announced a non-positive sample count")
    bad_dtypes = {
        str(jnp.asarray(leaf).dtype)
        for leaf in jax.tree_util.tree_leaves(update.params)
        if jnp.asarray(leaf).dtype != jnp.float32
    }
    if bad_dtypes:
        # mask cancellation is exact only in float32: casting params+mask to
        # a narrower dtype (bf16 has an 8-bit mantissa) quantizes each
        # node's mask independently, and the rounding residue — ~0.4% of
        # the mask's magnitude, i.e. comparable to the weights themselves —
        # survives the FedAvg sum
        raise SecAggError(
            f"params contain {sorted(bad_dtypes)} leaves; secure aggregation "
            "requires float32 parameters (use param_dtype=float32 — bf16 "
            "compute is unaffected)"
        )
    w_i = float(update.num_samples)
    seeds = {n: dh_pair_seed(priv, pubs[n][0], experiment) for n in peers}
    # s_ij/w_i with s_ij = STD·sqrt(w_i·w_j): per-pair magnitude
    # STD·sqrt(w_j/w_i), never vanishing with absolute dataset size
    scales = {n: pair_scale(w_i, pubs[n][1]) / w_i for n in peers}
    masks = pairwise_mask(update.params, my_addr, seeds, round_no, scales)
    if self_seed is not None:
        for k, m in self_mask(update.params, self_seed, round_no).items():
            masks[k] = masks[k] + m

    from p2pfl_tpu.learning.weights import named_leaves

    treedef, keyed = named_leaves(update.params)
    masked = jax.tree_util.tree_unflatten(
        treedef, [jnp.asarray(leaf, jnp.float32) + masks[key] for key, leaf in keyed]
    )
    return ModelUpdate(masked, list(update.contributors), update.num_samples)


def maybe_reveal_self_seed(node, round_no: int) -> None:
    """Broadcast this node's per-round self-mask seed if — and only if —
    the Bonawitz invariant allows it.

    Single source of truth for the security-critical gate (the seed
    exists, our contribution is in play, no pair-seed disclosure about us
    was observed this round, not already sent). Called from BOTH reveal
    sites: a peer's coverage report naming us (the early path that keeps
    the slowest node's timeout from starving everyone's seed resolution)
    and our own finalize.
    """
    st = node.state
    my_b = st.secagg_self_seed.get(round_no)
    if (
        my_b is None
        or (round_no, st.addr) in st.secagg_round_dropped
        or (round_no, st.addr) in st.secagg_reveal_sent
    ):
        return
    st.secagg_reveal_sent.add((round_no, st.addr))
    node.protocol.broadcast(
        node.protocol.build_msg(
            "secagg_reveal",
            [st.experiment_name or "", st.addr, "0", f"{my_b:x}"],
            round=round_no,
        )
    )


_SELF_DOMAIN = b"p2pfl-secagg-self\x00"


def self_mask(template: Pytree, seed: int, round_no: int) -> dict[str, np.ndarray]:
    """The Bonawitz SELF mask: ``STD · PRG_self(b_i^r)`` per element.

    Domain-separated from the pairwise stream. Magnitude ``SECAGG_MASK_STD``
    on the wire, like each pair term; in the sample-weighted FedAvg sum a
    contributor adds ``w_i · STD · PRG_self(b_i^r)``, which
    :func:`self_mask_correction` subtracts once the seed is disclosed (by
    its owner after a clean round) or reconstructed (t-of-n Shamir, when
    the owner contributed and then crashed).
    """
    flat = _flatten_named(template)
    keys = sorted(flat)
    std = Settings.SECAGG_MASK_STD
    return {
        k: std * _leaf_mask(seed, round_no, flat[k].shape, li, domain=_SELF_DOMAIN)
        for li, k in enumerate(keys)
    }


def self_mask_correction(
    template: Pytree,
    contributors: list[str],
    seeds: dict[str, int],
    weights: dict[str, int],
    round_no: int,
) -> dict[str, np.ndarray]:
    """The summed self-mask term every contributor left in the weighted sum:
    ``Σ_{i∈contributors} w_i · STD · PRG_self(b_i^r)`` as {path: array}.
    Subtract via :func:`apply_dropout_correction` (which divides by the
    aggregate's total weight)."""
    flat = _flatten_named(template)
    keys = sorted(flat)
    std = Settings.SECAGG_MASK_STD
    out: dict[str, np.ndarray] = {k: np.zeros(flat[k].shape, np.float32) for k in keys}
    for i in contributors:
        s = std * float(weights[i])
        seed = seeds[i]
        for li, k in enumerate(keys):
            out[k] += s * _leaf_mask(seed, round_no, flat[k].shape, li, domain=_SELF_DOMAIN)
    return out


def dropout_correction(
    template: Pytree,
    survivors: list[str],
    missing: list[str],
    seeds: dict[tuple[str, str], int],
    weights: dict[str, int],
    round_no: int,
) -> dict[str, np.ndarray]:
    """The uncancelled mask sum left by dropped train-set members.

    In the sample-weighted sum ``Σ_{i∈survivors} w_i·y_i`` each survivor i
    contributes, for every missing peer j, the term
    ``sign(i,j)·s_ij·PRG(seed_ij, round)`` — j's matching opposite term
    never arrived. This returns that double sum as a flat {path: array}
    dict; subtracting it (divided by the survivors' total weight) from the
    partial aggregate recovers the survivors' clean weighted mean.

    ``seeds`` maps (survivor, missing) → the pair seed — each survivor
    knows its own pair seeds and re-discloses them via ``secagg_recover``
    gossip; ``weights`` maps every involved address to its ANNOUNCED
    sample count (the same values the masks were scaled with — enforced by
    :func:`mask_update`'s announced-count latch). Pairs between two
    missing nodes need no correction (neither side contributed), and pairs
    between two survivors cancelled normally.
    """
    flat = _flatten_named(template)
    keys = sorted(flat)
    out: dict[str, np.ndarray] = {k: np.zeros(flat[k].shape, np.float32) for k in keys}
    for i in survivors:
        for j in missing:
            sign = 1.0 if i < j else -1.0
            s = pair_scale(weights[i], weights[j])
            seed = seeds[(i, j)]
            for li, k in enumerate(keys):
                out[k] += (sign * s) * _leaf_mask(seed, round_no, flat[k].shape, li)
    return out


def apply_dropout_correction(
    params: Pytree,
    correction: dict[str, np.ndarray],
    survivor_weight: float,
) -> Pytree:
    """Subtract ``correction / survivor_weight`` from a params pytree.

    The partial aggregate is the weighted MEAN over survivors, so the
    weighted-sum-domain correction is divided by their total weight.
    """
    import jax
    import jax.numpy as jnp

    from p2pfl_tpu.learning.weights import named_leaves

    treedef, keyed = named_leaves(params)
    return jax.tree_util.tree_unflatten(
        treedef,
        [
            jnp.asarray(leaf, jnp.float32) - correction[key] / np.float32(survivor_weight)
            for key, leaf in keyed
        ],
    )


def masked_stack(params_stack: Pytree, weights, key, scale: float = None) -> Pytree:
    """Device-side pairwise masking of a node-stacked ``[N, ...]`` pytree.

    Pure jitted op mirroring the host protocol's math: per-pair N(0,1)
    blocks from ``jax.random.fold_in``, antisymmetric signs, pair scale
    ``scale·sqrt(w_i·w_j)`` applied as ``s_ij/w_i`` on node i — so the
    sample-weighted FedAvg of the result equals that of the input (to
    float32 rounding) while every node's mask magnitude stays O(scale).
    Used to verify cancellation on an 8-device mesh without any wire.
    """
    import jax
    import jax.numpy as jnp

    if scale is None:
        scale = Settings.SECAGG_MASK_STD
    n = weights.shape[0]

    def node_mask(i, leaf_key, shape):
        def pair(j):
            lo, hi = jnp.minimum(i, j), jnp.maximum(i, j)
            pk = jax.random.fold_in(jax.random.fold_in(leaf_key, lo), hi)
            sign = jnp.where(i < j, 1.0, -1.0) * jnp.where(i == j, 0.0, 1.0)
            s = scale * jnp.sqrt(weights[i] * weights[j]) / weights[i]
            return (sign * s) * jax.random.normal(pk, shape, jnp.float32)

        return sum(pair(jnp.uint32(j)) for j in range(n))

    def mask_leaf(li_key, leaf):
        per_node = jax.vmap(
            lambda i: node_mask(i, li_key, leaf.shape[1:])
        )(jnp.arange(n, dtype=jnp.uint32))
        return (leaf.astype(jnp.float32) + per_node).astype(leaf.dtype)

    leaves, treedef = jax.tree_util.tree_flatten(params_stack)
    keys = jax.random.split(key, len(leaves))
    return jax.tree_util.tree_unflatten(
        treedef, [mask_leaf(k, leaf) for k, leaf in zip(keys, leaves)]
    )
