"""Federated dataset: partitioning + static-shape batching.

Reference: ``p2pfl/learning/pytorch/mnist_examples/mnistfederated_dm.py`` —
contiguous IID subsets (:105-125) and sort-by-label non-IID (:86-100). Added
here: Dirichlet(alpha) label-skew partitioning (the standard non-IID
benchmark shape, BASELINE config 3).

TPU notes: batches are materialized as ``[num_batches, batch, ...]`` arrays
with the remainder dropped, so an entire epoch is one statically-shaped
``lax.scan`` — no per-batch dispatch, no dynamic shapes, no host↔device
transfer inside the epoch.

Data source is synthetic by default (this environment has no network egress;
the reference downloads MNIST via torchvision). Real MNIST IDX files are
loaded when a directory is supplied.
"""

from __future__ import annotations

import gzip
import os
import struct
from dataclasses import dataclass
from typing import Optional

import numpy as np


@dataclass
class FederatedDataset:
    """One node's data shard (or the full dataset before partitioning)."""

    x_train: np.ndarray
    y_train: np.ndarray
    x_test: np.ndarray
    y_test: np.ndarray
    num_classes: int = 10
    #: data provenance ("synthetic" | "idx"), recorded by benchmarks
    source: str = "synthetic"

    # ---- construction ----

    @classmethod
    def synthetic_mnist(
        cls,
        n_train: int = 60_000,
        n_test: int = 10_000,
        num_classes: int = 10,
        dim: tuple[int, ...] = (28, 28, 1),
        seed: int = 31,
        noise: float = 0.35,
        modes: int = 1,
        proto_scale: float = 1.0,
    ) -> "FederatedDataset":
        """Deterministic MNIST-shaped classification task.

        Class-conditional prototypes + Gaussian noise, squashed to [0, 1].
        Learnable to >98% by the reference MLP in a few epochs — a drop-in
        stand-in for MNIST where downloads are unavailable.

        ``modes > 1`` draws several prototypes per class (a Gaussian-mixture
        class-conditional), which makes the decision boundary nonlinear and
        convergence take genuinely many optimizer steps — benchmarks use this
        so "time-to-accuracy" measures convergence, not dispatch latency.
        ``proto_scale`` shrinks prototype separation relative to ``noise``.
        """
        rng = np.random.default_rng(seed)
        d = int(np.prod(dim))
        protos = rng.normal(0.0, proto_scale, size=(num_classes, modes, d)).astype(np.float32)

        def make(n: int, split_seed: int):
            r = np.random.default_rng(seed + split_seed)
            y = r.integers(0, num_classes, size=n)
            if modes > 1:
                mode = r.integers(0, modes, size=n)
            else:
                mode = np.zeros(n, dtype=np.int64)
            x = protos[y, mode] + r.normal(0.0, noise, size=(n, d)).astype(np.float32)
            x = 1.0 / (1.0 + np.exp(-x))  # pixel-like range
            return x.reshape((n, *dim)).astype(np.float32), y.astype(np.int32)

        x_tr, y_tr = make(n_train, 1)
        x_te, y_te = make(n_test, 2)
        return cls(x_tr, y_tr, x_te, y_te, num_classes)

    @classmethod
    def synthetic_lm(
        cls,
        vocab_size: int = 2048,
        seq_len: int = 128,
        n_train: int = 2048,
        n_test: int = 256,
        determinism: float = 0.9,
        seed: int = 17,
        shift_frac: float = 0.0,
        shift_seed: Optional[int] = None,
    ) -> "FederatedDataset":
        """Next-token prediction over a near-deterministic Markov chain.

        Each token maps to a fixed successor with probability ``determinism``
        (uniform otherwise), so a causal LM can approach ``determinism``
        next-token accuracy — a learnable, download-free LM task. x = tokens,
        y = tokens shifted left (teacher forcing).

        ``shift_frac``: DOMAIN SHIFT — re-derange that fraction of the
        successor table (deterministically from ``shift_seed``) before
        generating. A model pretrained on the unshifted chain scores about
        ``determinism·(1−shift_frac)`` here; closing the gap is the
        fine-tuning task (bench config 5: LoRA adapters adapt a pretrained
        base to the shifted domain, the real LoRA use case).
        """
        rng = np.random.default_rng(seed)
        succ = rng.permutation(vocab_size)  # deterministic successor table
        if shift_frac > 0.0:
            r2 = np.random.default_rng(seed + 1000 if shift_seed is None else shift_seed)
            k = max(2, int(round(shift_frac * vocab_size)))
            idx = r2.choice(vocab_size, size=k, replace=False)
            # cyclic rotation of the chosen entries: every selected token's
            # successor CHANGES (a random permutation would fix ~1/k of them)
            succ[idx] = np.roll(succ[idx], 1)

        def make(n: int, split_seed: int):
            r = np.random.default_rng(seed + split_seed)
            toks = np.empty((n, seq_len + 1), dtype=np.int32)
            toks[:, 0] = r.integers(0, vocab_size, size=n)
            for t in range(seq_len):
                follow = r.random(n) < determinism
                rand = r.integers(0, vocab_size, size=n)
                toks[:, t + 1] = np.where(follow, succ[toks[:, t]], rand)
            return toks[:, :-1], toks[:, 1:].astype(np.int32)

        x_tr, y_tr = make(n_train, 1)
        x_te, y_te = make(n_test, 2)
        return cls(x_tr, y_tr, x_te, y_te, vocab_size)

    @classmethod
    def mnist(cls, data_dir: Optional[str] = None, **kwargs) -> "FederatedDataset":
        """Real MNIST if IDX files are present in ``data_dir``, else synthetic."""
        if data_dir and os.path.isdir(data_dir):
            try:
                return cls.from_idx(data_dir)
            except FileNotFoundError:
                pass
        return cls.synthetic_mnist(**kwargs)

    @classmethod
    def from_idx(cls, data_dir: str) -> "FederatedDataset":
        """Load MNIST-format IDX files (optionally gzipped)."""

        def read(name: str) -> np.ndarray:
            for candidate in (name, name + ".gz"):
                path = os.path.join(data_dir, candidate)
                if os.path.exists(path):
                    opener = gzip.open if candidate.endswith(".gz") else open
                    with opener(path, "rb") as f:
                        return _parse_idx(f.read())
            raise FileNotFoundError(name)

        x_tr = read("train-images-idx3-ubyte").astype(np.float32)[..., None] / 255.0
        y_tr = read("train-labels-idx1-ubyte").astype(np.int32)
        x_te = read("t10k-images-idx3-ubyte").astype(np.float32)[..., None] / 255.0
        y_te = read("t10k-labels-idx1-ubyte").astype(np.int32)
        return cls(x_tr, y_tr, x_te, y_te, 10, source="idx")

    # ---- partitioning (per-node shards) ----

    def partition(
        self,
        sub_id: int,
        n_parts: int,
        strategy: str = "iid",
        alpha: float = 0.5,
        seed: int = 0,
        test_strategy: Optional[str] = None,
    ) -> "FederatedDataset":
        """Extract shard ``sub_id`` of ``n_parts``.

        - ``iid``: contiguous equal slices (reference :105-125),
        - ``sorted``: sort-by-label then slice → each node sees few classes
          (reference ``iid=False``, :86-100),
        - ``dirichlet``: label-skew with concentration ``alpha``.

        ``test_strategy`` defaults to ``"iid"`` (reference parity: every
        node judges against the global distribution); pass the train
        strategy instead when each node's deployment distribution matches
        its local data — the personalization (FedPer) setting.
        """
        tr = _partition_indices(self.y_train, sub_id, n_parts, strategy, alpha, seed)
        te = _partition_indices(
            self.y_test, sub_id, n_parts, test_strategy or "iid", alpha, seed
        )
        return FederatedDataset(
            self.x_train[tr], self.y_train[tr], self.x_test[te], self.y_test[te],
            self.num_classes, source=self.source,
        )

    # ---- access ----

    @property
    def num_samples(self) -> int:
        return len(self.y_train)

    def epoch_batches(self, batch_size: int, rng: np.random.Generator) -> tuple[np.ndarray, np.ndarray]:
        """One shuffled epoch as ``[nb, bs, ...]`` arrays (remainder dropped)."""
        n = len(self.y_train)
        nb = max(n // batch_size, 1)
        take = min(nb * batch_size, n)
        perm = rng.permutation(n)[:take]
        xs = self.x_train[perm].reshape(nb, -1, *self.x_train.shape[1:])
        ys = self.y_train[perm].reshape(nb, -1, *self.y_train.shape[1:])
        return xs, ys

    def test_arrays(self) -> tuple[np.ndarray, np.ndarray]:
        return self.x_test, self.y_test


def _parse_idx(data: bytes) -> np.ndarray:
    zeros, dtype_code, ndim = struct.unpack(">HBB", data[:4])
    if zeros != 0:
        raise ValueError("not an IDX file")
    dims = struct.unpack(f">{ndim}I", data[4 : 4 + 4 * ndim])
    dtype = {8: np.uint8, 9: np.int8, 11: np.int16, 12: np.int32, 13: np.float32, 14: np.float64}[dtype_code]
    return np.frombuffer(data, dtype=np.dtype(dtype).newbyteorder(">"), offset=4 + 4 * ndim).reshape(dims)


def _partition_indices(
    y: np.ndarray, sub_id: int, n_parts: int, strategy: str, alpha: float, seed: int
) -> np.ndarray:
    n = len(y)
    if not 0 <= sub_id < n_parts:
        raise ValueError(f"sub_id {sub_id} out of range for {n_parts} parts")
    if strategy == "iid":
        size = n // n_parts
        return np.arange(sub_id * size, (sub_id + 1) * size if sub_id < n_parts - 1 else n)
    if strategy == "sorted":
        order = np.argsort(y, kind="stable")
        size = n // n_parts
        lo = sub_id * size
        hi = (sub_id + 1) * size if sub_id < n_parts - 1 else n
        return order[lo:hi]
    if strategy == "dirichlet":
        rng = np.random.default_rng(seed)
        classes = np.unique(y)
        # same proportions matrix on every node (shared seed) → consistent split
        props = rng.dirichlet([alpha] * n_parts, size=len(classes))  # [C, parts]
        own: list[np.ndarray] = []
        for ci, c in enumerate(classes):
            idx = np.flatnonzero(y == c)
            rng_c = np.random.default_rng(seed + 1000 + int(c))
            idx = rng_c.permutation(idx)
            bounds = (np.cumsum(props[ci]) * len(idx)).astype(np.int64)
            lo = 0 if sub_id == 0 else bounds[sub_id - 1]
            own.append(idx[lo : bounds[sub_id]])
        out = np.concatenate(own) if own else np.empty(0, dtype=np.int64)
        return np.sort(out)
    raise ValueError(f"unknown partition strategy: {strategy}")
