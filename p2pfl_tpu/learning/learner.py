"""Learners: the per-node training engine.

``NodeLearner`` mirrors the reference template
(``p2pfl/learning/learner.py:36-150``); :class:`JaxLearner` replaces the
PyTorch-Lightning learner (``lightning_learner.py``) with a TPU-first design:

- one jitted, donated **epoch** step — the whole epoch is a ``lax.scan`` over
  statically-shaped ``[num_batches, batch, ...]`` arrays, so there is exactly
  one device dispatch per epoch (the reference dispatches per batch through
  the Lightning loop);
- compute in bfloat16 on the MXU, params + optimizer state in float32;
- all learners of the same architecture share one compilation: the flax
  module and the (cached) optax transform are static args with structural
  equality, so N simulated nodes compile once, not N times.

The jit cache note matters: the reference's per-node Lightning ``Trainer`` is
rebuilt every round (``lightning_learner.py:180-198``); here compilation
happens once per architecture per process.
"""

from __future__ import annotations

import threading
from abc import ABC, abstractmethod
from functools import lru_cache, partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.weights import ModelUpdate, decode_params, restore_like
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.models.base import FlaxModel, apply_with_aux

Pytree = Any


class NodeLearner(ABC):
    """Template for node learners (reference ``learner.py:36-150``)."""

    @abstractmethod
    def set_parameters(self, params: Pytree) -> None: ...

    @abstractmethod
    def get_parameters(self) -> Pytree: ...

    @abstractmethod
    def set_epochs(self, epochs: int) -> None: ...

    @abstractmethod
    def fit(self) -> None: ...

    @abstractmethod
    def interrupt_fit(self) -> None: ...

    @abstractmethod
    def evaluate(self) -> dict[str, float]: ...

    @abstractmethod
    def get_num_samples(self) -> int: ...

    # ---- shared plumbing ----

    addr: str = ""

    def fused_round(self):
        """Whole train-stage compute as one donated dispatch, or None.

        The fused overlay round (``Settings.ROUND_FUSED``): evaluate the
        incoming model, run all local epochs and fold the node's own
        weighted fp32 partial-aggregation contribution in a SINGLE jit
        dispatch, returning the node's own
        :class:`~p2pfl_tpu.learning.weights.ModelUpdate` with device-
        resident ``params`` and ``partial_acc`` — nothing on the model
        plane syncs to host. Metrics come back as device scalars, stashed
        for :meth:`pop_round_metrics` (one batched D2H flush per round).

        Returning None means this learner cannot fuse (the base default):
        ``TrainStage`` falls back to the staged ``evaluate()`` + ``fit()``
        sequence, which stays the bit-parity baseline.
        """
        return None

    def pop_round_metrics(self) -> dict:
        """Take (and clear) the metrics stashed by :meth:`fused_round`.

        ``{"train_loss_series": ([E] dev vector, [E] step numbers)
        [, "test_loss", "test_acc"]}`` — values are device arrays;
        converting them is the round's ONE metric host sync, done by the
        stage flush after aggregation already forced the program.
        """
        out = getattr(self, "_round_metrics", None) or {}
        self._round_metrics = {}
        return out

    def set_addr(self, addr: str) -> None:
        self.addr = addr

    @property
    def model_version(self) -> int:
        """Monotone counter identifying the current parameter content.

        Bumped by :meth:`bump_model_version` on every ``set_parameters`` /
        ``fit`` (and by anything that mutates the error-feedback residual
        outside :func:`~p2pfl_tpu.learning.weights.encode_params`). The
        payload cache keys encoded gossip bytes on it — see the
        ``learning/weights.py`` module docstring.
        """
        return getattr(self, "_model_version", 0)

    def bump_model_version(self) -> None:
        self._model_version = self.model_version + 1

    def payload_cache(self):
        """The learner's shared encode-once cache (created on first use)."""
        from p2pfl_tpu.learning.weights import PayloadCache

        cache = getattr(self, "_payload_cache", None)
        if cache is None:
            cache = PayloadCache(owner=self.addr)
            self._payload_cache = cache
        cache.owner = self.addr  # addr may be set after first use
        return cache

    def get_model_update(self) -> ModelUpdate:
        update = ModelUpdate(self.get_parameters(), [self.addr], self.get_num_samples())
        anchor = getattr(self, "_wire_anchor", None)
        if anchor is not None:
            update.anchor = anchor
            update.anchor_tag = getattr(self, "_wire_anchor_tag", None)
        # encode-once: every update handed out shares the learner's payload
        # cache, keyed on the version of the params it was built from
        update.payload_cache = self.payload_cache()
        update.cache_version = self.model_version
        return update

    def set_wire_anchor(self, params, tag: str) -> None:
        """Pin the round-start global model as the delta-coding anchor.

        Called by the stages at the two points where every node holds the
        round's shared model (after init-weights sync, and at each round
        boundary) — see ``learning/weights.py`` topk8. ``tag`` is the round
        identity (``"experiment_epoch:round"``) that both ends of a
        delta-coded transfer must agree on.
        """
        from p2pfl_tpu.settings import Settings

        if Settings.WIRE_COMPRESSION != "topk8":
            self._wire_anchor = None
            return
        self._wire_anchor = params
        self._wire_anchor_tag = tag

    def ef_residual_store(self) -> dict:
        """The node's error-feedback residual ({path: dropped delta mass}).

        Attached by TrainStage to the node's OWN contribution only — it
        must accumulate exactly one encode per round (the payload cache
        enforces this: repeat sends of the contribution hit the cached
        bytes instead of re-folding). Code that mutates the returned dict
        directly must call :meth:`bump_model_version` so cached payloads
        built from the old residual are never replayed.

        Residual lifecycle: under ``Settings.WIRE_COMPRESSION_DEVICE`` the
        entries are DEVICE arrays — the fused encode donates them into the
        next dispatch and writes the new carry back without a host
        round-trip, so the residual never crosses D2H between rounds. The
        host encoder normalizes device entries with ``np.asarray`` (and
        vice versa), so flipping the producer mid-experiment degrades to
        one transfer, never a wrong delta; entries whose tensor changed
        shape or left the topk path are dropped at encode time
        (``weights._validate_residual``) instead of surfacing as a
        broadcast error deep inside the codec.
        """
        if not hasattr(self, "_ef_residual"):
            self._ef_residual = {}
        return self._ef_residual

    def materialize(self, update: ModelUpdate) -> ModelUpdate:
        """Decode a wire payload against this learner's parameter structure."""
        if update.params is not None:
            return update
        anchor = getattr(self, "_wire_anchor", None)
        tag = getattr(self, "_wire_anchor_tag", None)
        # a streamed transfer's leaves were decoded (and possibly
        # device_put) as their chunks arrived — the unary frame never
        # existed on this side, so prefer the eager result over re-decoding
        if update.decoded_flat is not None:
            flat = update.decoded_flat
        else:
            flat = decode_params(update.encoded, anchor=anchor, anchor_tag=tag)
        params = restore_like(self.get_parameters(), flat)
        out = ModelUpdate(params, update.contributors, update.num_samples)
        # relays re-encode fresh aggregates against the same shared anchor
        out.anchor = anchor
        out.anchor_tag = tag
        # the async version triple travels with the payload it describes
        out.version = update.version
        return out


# ---- pure jitted steps (module-level => shared jit cache) ----


@lru_cache(maxsize=None)
def adam(lr: float = 1e-3) -> optax.GradientTransformation:
    """Cached so every learner with the same lr shares one jit cache entry."""
    return optax.adam(lr)


@lru_cache(maxsize=None)
def sgd(lr: float = 1e-3) -> optax.GradientTransformation:
    """Cached like :func:`adam`. SCAFFOLD's variate update assumes SGD."""
    return optax.sgd(lr)


def _loss(params, module, x, y):
    """Training loss: CE + any sown auxiliary losses (MoE router balance)."""
    logits, aux = apply_with_aux(module, params, x)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
    return ce + aux, logits


def _prox_term(params, anchor, mu: float):
    """FedProx penalty μ/2·‖w − anchor‖² — shared by node and SPMD modes so
    their local-step math cannot desynchronize."""
    sq = sum(
        jnp.sum((a.astype(jnp.float32) - b.astype(jnp.float32)) ** 2)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(anchor))
    )
    return 0.5 * mu * sq


@partial(jax.jit, static_argnames=("module", "tx", "prox_mu"), donate_argnums=(1,))
def train_epoch(params, opt_state, xs, ys, module, tx, prox_mu: float = 0.0, anchor=None):
    """One full epoch: scan of SGD steps over [nb, bs, ...] batches.

    ``params`` is NOT donated: with the zero-copy in-memory transport other
    nodes' aggregators may hold references to these exact buffers.

    ``prox_mu > 0`` adds the FedProx proximal term μ/2·‖w − anchor‖²
    (Li et al. 2020) pulling local steps toward the round's global model
    (``anchor``; defaults to the params this epoch starts from).
    """
    if prox_mu > 0.0 and anchor is None:
        anchor = params

    def step(carry, batch):
        p, o = carry
        x, y = batch

        def full_loss(p_):
            loss, logits = _loss(p_, module, x, y)
            if prox_mu > 0.0:
                loss = loss + _prox_term(p_, anchor, prox_mu)
            return loss, logits

        (loss, _), grads = jax.value_and_grad(full_loss, has_aux=True)(p)
        updates, o = tx.update(grads, o, p)
        p = optax.apply_updates(p, updates)
        return (p, o), loss

    (params, opt_state), losses = jax.lax.scan(step, (params, opt_state), (xs, ys))
    return params, opt_state, jnp.mean(losses)


def ce_eval(params, module, x, y):
    """Pure-CE eval loss + logits — NO sown aux regularizers, so reported
    test_loss stays comparable across MoE/dense models and across
    node/SPMD/LoRA modes. Every eval path funnels through this."""
    logits = module.apply({"params": params}, x)
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean(), logits


@partial(jax.jit, static_argnames=("module",))
def eval_step(params, x, y, module):
    loss, logits = ce_eval(params, module, x, y)
    acc = jnp.mean((jnp.argmax(logits, axis=-1) == y).astype(jnp.float32))
    return loss, acc


class JaxLearner(NodeLearner):
    """JAX/flax learner: jitted epoch scan + jitted eval.

    One chip by default. Passing ``mesh`` (usually a node's ``(data,
    model)`` submesh — :func:`~p2pfl_tpu.parallel.mesh.node_slices`)
    places params AND optimizer state by partition rules
    (``parallel/sharding.py``; ``partition_rules=None`` uses the
    transformer defaults) and every jitted step — ``train_epoch``,
    ``eval_step``, the fused round — compiles as a GSPMD program over
    that mesh: computation follows the placed arguments, no learner code
    changes. The rule set is linted against the model and mesh here, at
    construction, so a typo'd regex fails at node startup rather than
    silently replicating the model.
    """

    def __init__(
        self,
        model: FlaxModel,
        data: FederatedDataset,
        addr: str = "",
        epochs: int = 1,
        batch_size: int = 128,
        learning_rate: float = 1e-3,
        seed: int = 0,
        keep_opt_state: bool = False,
        prox_mu: float = 0.0,
        dp_clip: float = 0.0,
        dp_noise: float = 0.0,
        mesh=None,
        partition_rules=None,
    ) -> None:
        self.model = model
        self.data = data
        self.addr = addr
        self.epochs = epochs
        self.batch_size = batch_size
        self.tx = adam(learning_rate)
        self.keep_opt_state = keep_opt_state
        # FedProx (Li et al. 2020): μ > 0 adds a proximal pull toward the
        # round's incoming global model during local steps
        self.prox_mu = float(prox_mu)
        # DP-SGD (Abadi et al. 2016): per-example clipped grads + Gaussian
        # noise; dp_clip > 0 enables, dp_noise is the noise multiplier σ.
        # An accountant tracks (ε, δ) across fit() calls.
        self.dp_clip = float(dp_clip)
        self.dp_noise = float(dp_noise)
        if self.dp_noise > 0.0 and self.dp_clip <= 0.0:
            # noise without a clip bound has no privacy semantics — and the
            # dp path is gated on dp_clip, so it would silently be ignored
            raise ValueError("dp_noise > 0 requires dp_clip > 0")
        self.accountant = None
        if self.dp_clip > 0.0:
            from p2pfl_tpu.learning.privacy import PrivacyAccountant

            if self.dp_noise > 0.0:
                q = min(1.0, batch_size / max(1, data.num_samples))
                self.accountant = PrivacyAccountant(self.dp_noise, q)
        self.mesh = mesh
        self._param_placement = None
        self._opt_init = self.tx.init
        if mesh is not None:
            from p2pfl_tpu.parallel.sharding import (
                DEFAULT_TRANSFORMER_RULES,
                check_partition_rules,
                tree_shardings,
            )

            rules = (
                tuple(partition_rules)
                if partition_rules is not None
                else DEFAULT_TRANSFORMER_RULES
            )
            # loud at construction: unmatched paths / dead rules / unknown
            # axes are a startup error, not an hour of silent replication
            check_partition_rules(
                rules, model.params, mesh, allow_dead=partition_rules is None
            )
            self._param_placement = tree_shardings(mesh, model.params, rules)
            opt_struct = jax.eval_shape(self.tx.init, model.params)
            # the same rules place the optimizer state (optax paths embed
            # the param path); init runs jitted so the fresh state lands
            # directly in its mesh layout
            self._opt_init = jax.jit(
                self.tx.init, out_shardings=tree_shardings(mesh, opt_struct, rules)
            )
        self.params: Pytree = self._place(model.params)
        self.opt_state = self._opt_init(self.params)
        self._rng = np.random.default_rng(seed)
        self._interrupt = threading.Event()
        self._steps_done = 0

    def _place(self, params: Pytree) -> Pytree:
        """Incoming params → the learner's mesh layout (no-op unplaced)."""
        if self._param_placement is None:
            return params
        return jax.device_put(params, self._param_placement)

    # ---- params ----

    def set_parameters(self, params: Pytree) -> None:
        # structural check — architecture mismatch raises instead of hanging
        if jax.tree.structure(params) != jax.tree.structure(self.params):
            from p2pfl_tpu.exceptions import ModelNotMatchingError

            raise ModelNotMatchingError("incoming params do not match model structure")
        self.params = self._place(params)
        self.bump_model_version()
        if not self.keep_opt_state:
            # reference behavior: a fresh Trainer (and optimizer) per round
            # (lightning_learner.py:180-198). keep_opt_state=True carries the
            # Adam moments across rounds instead — the same documented
            # improvement knob as SpmdFederation(keep_opt_state=True)
            self.opt_state = self._opt_init(self.params)

    def get_parameters(self) -> Pytree:
        return self.params

    def set_epochs(self, epochs: int) -> None:
        self.epochs = epochs

    # ---- training ----

    def fit(self) -> None:
        self._interrupt.clear()
        if self.epochs == 0:
            return  # test mode, like the reference's epochs=0 CI runs
        self.bump_model_version()
        # round's global model (FedProx anchor — used by both DP and plain paths)
        anchor = self.params if self.prox_mu > 0.0 else None
        for _ in range(self.epochs):
            if self._interrupt.is_set():
                logger.info(self.addr, "Training interrupted")
                return
            xs, ys = self.data.epoch_batches(self.batch_size, self._rng)
            from p2pfl_tpu.management.profiling import dispatch_span

            if self.dp_clip > 0.0:
                from p2pfl_tpu.learning.privacy import dp_train_epoch

                key = jax.random.PRNGKey(int(self._rng.integers(2**31)))
                with dispatch_span("train_epoch", self.addr, dp=True):
                    self.params, self.opt_state, loss = dp_train_epoch(
                        self.params, self.opt_state, jnp.asarray(xs), jnp.asarray(ys),
                        key, self.model.module, self.tx, self.dp_clip, self.dp_noise,
                        prox_mu=self.prox_mu, anchor=anchor,
                    )
                if self.accountant is not None:
                    self.accountant.step(xs.shape[0])
            else:
                with dispatch_span("train_epoch", self.addr):
                    self.params, self.opt_state, loss = train_epoch(
                        self.params, self.opt_state, jnp.asarray(xs), jnp.asarray(ys),
                        self.model.module, self.tx, prox_mu=self.prox_mu, anchor=anchor,
                    )
            self._steps_done += xs.shape[0]
            logger.log_metric(self.addr, "train_loss", float(loss), step=self._steps_done)

    def fused_round(self):
        """Eval + all local epochs + own partial fold: ONE donated dispatch.

        Calls :func:`p2pfl_tpu.parallel.spmd.fused_node_round` (the shared
        fused-step builder — same ``_local_epoch`` math as the SPMD round)
        on this node's params/opt state and the round's pre-drawn epoch
        batches, so ``TrainStage`` crosses the host↔device boundary once
        per round instead of ``1 + epochs`` times with a blocking
        ``float(loss)`` after every epoch. Metrics stay device scalars
        (stashed for :meth:`pop_round_metrics`); the returned own update
        carries device-resident ``params`` and the fp32 ``partial_acc``
        the aggregator folds peers into.

        Returns None — caller falls back to the staged path — for the
        variants the single program does not cover: DP-SGD (its per-epoch
        rng derivation is owned by ``fit``) and ``epochs == 0`` test mode.
        A FAILED dispatch also returns None after restoring the batch-rng
        stream and rebuilding the donated opt state, so one bad dispatch
        degrades to the staged path instead of poisoning the learner
        (the PR-4 encode lesson, applied to the round program).
        """
        if self.epochs == 0 or self.dp_clip > 0.0:
            return None
        from p2pfl_tpu.management.profiling import dispatch_span
        from p2pfl_tpu.parallel.spmd import fused_node_round, tree_has_deleted
        from p2pfl_tpu.settings import Settings

        self._interrupt.clear()
        rng_state = self._rng.bit_generator.state
        xs_eps, ys_eps = [], []
        for _ in range(self.epochs):
            xs, ys = self.data.epoch_batches(self.batch_size, self._rng)
            xs_eps.append(xs)
            ys_eps.append(ys)
        if self._interrupt.is_set():
            # interrupt_fit() landed while batches were being drawn: honor
            # it before committing the (uninterruptible) whole-round
            # dispatch; the rng rewind makes the abort side-effect-free
            self._rng.bit_generator.state = rng_state
            logger.info(self.addr, "Training interrupted")
            return None
        x_test, y_test = self.data.test_arrays()
        has_eval = len(y_test) > 0
        # under secure aggregation the own contribution gets masked before
        # it enters the aggregator — a pre-folded unmasked accumulator
        # would bypass the mask, so the fold is compiled out
        with_acc = not Settings.SECURE_AGGREGATION
        try:
            with dispatch_span("fused_round", self.addr, epochs=self.epochs):
                out = fused_node_round(
                    self.params,
                    self.opt_state,
                    jnp.asarray(np.stack(xs_eps)),
                    jnp.asarray(np.stack(ys_eps)),
                    jnp.float32(float(self.get_num_samples())),
                    jnp.asarray(x_test) if has_eval else None,
                    jnp.asarray(y_test) if has_eval else None,
                    module=self.model.module,
                    tx=self.tx,
                    prox_mu=self.prox_mu,
                    with_acc=with_acc,
                    agg_dtype=Settings.AGG_DTYPE,
                )
        except Exception as exc:  # noqa: BLE001 — degrade to staged, never poison
            self._rng.bit_generator.state = rng_state
            if tree_has_deleted(self.opt_state):
                # the dispatch consumed the donated opt state before dying:
                # rebuild instead of leaving deleted arrays in the store
                # (via the placed init so a submesh learner keeps its layout)
                self.opt_state = self._opt_init(self.params)
            logger.error(
                self.addr,
                f"Fused round dispatch failed ({exc!r}) — opt state "
                "rebuilt, falling back to the staged path",
            )
            return None
        self.params = out["params"]
        self.opt_state = out["opt_state"]
        self.bump_model_version()
        nb = xs_eps[0].shape[0]
        base = self._steps_done
        self._steps_done += self.epochs * nb
        # per-epoch loss points at the same step numbers fit() logs —
        # the flush replays the staged path's exact train_loss series
        metrics = {
            "train_loss_series": (
                out["train_losses"],
                [base + (e + 1) * nb for e in range(self.epochs)],
            )
        }
        if has_eval:
            metrics["test_loss"] = out["eval_loss"]
            metrics["test_acc"] = out["eval_acc"]
        self._round_metrics = metrics
        update = self.get_model_update()
        if with_acc:
            update.partial_acc = (out["psum"], out["wsum"])
        return update

    def interrupt_fit(self) -> None:
        self._interrupt.set()

    def evaluate(self) -> dict[str, float]:
        x, y = self.data.test_arrays()
        if len(y) == 0:
            return {}
        from p2pfl_tpu.management.profiling import dispatch_span

        with dispatch_span("eval_step", self.addr):
            loss, acc = eval_step(self.params, jnp.asarray(x), jnp.asarray(y), self.model.module)
        return {"test_loss": float(loss), "test_acc": float(acc)}

    def get_num_samples(self) -> int:
        return self.data.num_samples


class DummyLearner(NodeLearner):
    """No-ML learner for FSM/communication tests: params is a tiny pytree."""

    def __init__(self, model=None, data=None, value: float = 0.0) -> None:
        self.params = {"w": jnp.full((4,), value)}
        self.epochs = 1
        self._num_samples = 10

    def set_parameters(self, params):
        if jax.tree.structure(params) != jax.tree.structure(self.params):
            from p2pfl_tpu.exceptions import ModelNotMatchingError

            raise ModelNotMatchingError("structure mismatch")
        self.params = params
        self.bump_model_version()

    def get_parameters(self):
        return self.params

    def set_epochs(self, epochs):
        self.epochs = epochs

    def fit(self):
        self.params = jax.tree.map(lambda x: x + 1.0, self.params)
        self.bump_model_version()

    def interrupt_fit(self):
        pass

    def evaluate(self):
        return {"dummy_metric": float(np.asarray(jax.tree.leaves(self.params)[0]).mean())}

    def get_num_samples(self):
        return self._num_samples
