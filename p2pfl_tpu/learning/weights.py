"""Weights containers and the wire codec.

The reference ships weights as a pickle of a list of numpy arrays in
state-dict order and zips them back positionally
(``p2pfl/learning/pytorch/lightning_learner.py:113-138``). Here the payload
is a self-describing binary format: a JSON header with named paths, shapes
and dtypes, followed by raw little-endian buffers. This gives

- name-aware (not positional) matching → architecture mismatch is detected
  structurally, raising :class:`ModelNotMatchingError` instead of silently
  loading wrong layers,
- zero pickle (no arbitrary code execution from the wire),
- native bfloat16 support via ml_dtypes.

On transports that stay in-process (memory, mesh-collective) the pytree is
passed by reference and never hits this codec — weights stay device-resident
(``Settings.MEMORY_WIRE_CODEC`` opts the memory transport into the byte path
for benching/testing the codec without sockets).

Encode-once, send-many
----------------------
Gossip pushes the SAME model to many peers over many ticks, so the encode
pipeline (flatten → quantize → CRC32C → frame) must run once per *model
version*, not once per send. :class:`PayloadCache` is the content-addressed
store behind that: the learner attaches it (plus its monotone model-version
counter) to every :meth:`ModelUpdate.encode`-able update it hands out, and
``encode()`` keys the bytes on

``(model version, round, wire compression, anchor_tag, error-feedback?)``

The version bumps on ``set_parameters`` / ``fit`` / external residual
mutation, so a stale encode can never be replayed; ``anchor_tag`` is in the
key because topk8 bytes are deltas against a specific round's anchor — the
same params delta-coded against a different anchor are different bytes. The
error-feedback flag isolates the one encode per round that folds (and
mutates) the residual store from residual-free encodes of the same version:
a cache hit on the ``ef`` entry is exactly the "residual folded once per
round" contract (Seide et al. 2014) — repeat sends reuse the bytes instead
of double-folding.
"""

from __future__ import annotations

import json
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import numpy as np

from p2pfl_tpu.exceptions import AnchorMismatchError, DecodingParamsError, ModelNotMatchingError

Pytree = Any

# process-wide encode accounting (bench_gossip reads this): every real run
# of the encode pipeline counts, cache hits don't
_encode_lock = threading.Lock()
_encode_calls = 0
# wire-byte accounting (bench_gossip compression split + per-node comm
# metrics): raw model bytes in, payload bytes out, bytes that actually
# crossed device→host, and which producer ran
_wire_stats = {
    "raw_bytes": 0,
    "payload_bytes": 0,
    "d2h_bytes": 0,
    "host_encodes": 0,
    "device_encodes": 0,
    "stream_encodes": 0,
    # high-water mark (max, not sum) of any StreamDecoder's buffered
    # payload bytes — the MEASURED receiver-side bounded-memory claim:
    # stays ~O(chunk + largest leaf) for dense streams no matter how big
    # the model is (bench_gossip `stream` row asserts it)
    "stream_peak_scratch_bytes": 0,
}


def encode_call_count() -> int:
    """Total :func:`encode_params` invocations in this process."""
    with _encode_lock:
        return _encode_calls


def wire_stats() -> dict:
    """Process-wide wire-byte counters (see :func:`encode_params`)."""
    with _encode_lock:
        return dict(_wire_stats)


def reset_wire_stats() -> None:
    with _encode_lock:
        for k in _wire_stats:
            _wire_stats[k] = 0


class PayloadCache:
    """Content-addressed cache of encoded weight payloads (encode-once).

    A small FIFO-bounded map — keys are monotone (the model version only
    grows), so old entries die naturally; the bound only guards against a
    pathological interleave. Hit/miss counters feed the logger's
    communication metrics (``logger.get_comm_metrics``) so the cache's
    effect is observable per node.
    """

    MAX_ENTRIES = 4

    def __init__(self, owner: str = "") -> None:
        self.owner = owner
        self._lock = threading.Lock()
        # payloads keyed by content: unary entries hold the framed bytes,
        # chunk-aware entries (keys prefixed "chunks") hold the tuple of
        # stream chunk frames — encode-once/send-many fans the SAME cached
        # chunk list out to K peers without re-framing
        self._entries: "dict[tuple, object]" = {}
        # error-feedback fold ownership per payload content (see
        # ef_fold_once) — separate from _entries so markers can never
        # evict cached payloads
        self._ef_marks: "dict[tuple, None]" = {}
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple) -> Optional[Any]:
        from p2pfl_tpu.management.logger import logger

        with self._lock:
            cached = self._entries.get(key)
            if cached is not None:
                self.hits += 1
            else:
                self.misses += 1
        logger.log_comm_metric(
            self.owner, "encode_cache_hit" if cached is not None else "encode_cache_miss"
        )
        return cached

    def peek(self, key: tuple) -> Optional[Any]:
        """`get` without hit/miss accounting — for CROSS-flavor probes
        (a unary encode checking for a cached chunk list and vice versa):
        the probe must not inflate the encode_cache_miss metric that the
        encode-once contract tests pin to exactly one per content."""
        with self._lock:
            return self._entries.get(key)

    def put(self, key: tuple, payload: Any) -> None:
        with self._lock:
            self._entries[key] = payload
            while len(self._entries) > self.MAX_ENTRIES:
                self._entries.pop(next(iter(self._entries)))

    def ef_fold_once(self, key: tuple) -> bool:
        """True exactly once per content key — the caller that gets True
        OWNS the error-feedback fold for that payload content; every
        later encoder of the same content (a cache miss on a *different*
        plane's key — the ICI shard encode and the byte encode cache
        under different keys) must encode residual-free instead of
        re-folding the just-written carry as if it were last round's.
        Keys are monotone like payload keys, so the FIFO bound only
        guards a pathological interleave."""
        with self._lock:
            if key in self._ef_marks:
                return False
            self._ef_marks[key] = None
            while len(self._ef_marks) > self.MAX_ENTRIES * 2:
                self._ef_marks.pop(next(iter(self._ef_marks)))
            return True

_MAGIC = b"P2TW"  # p2pfl-tpu weights
_VERSION = 1

_SEP = "/"


def anchor_digest(tree: Pytree) -> int:
    """CRC32C over a pytree's canonical buffer order (sorted paths)."""
    from p2pfl_tpu import native

    flat = _flatten_named(tree)
    crc = 0
    for key in sorted(flat):
        crc = native.crc32c(np.ascontiguousarray(flat[key]).tobytes(), crc)
    return crc


def named_leaves(tree: Pytree):
    """``(treedef, [(canonical path key, leaf), ...])`` in flatten order.

    The single source of the path-key scheme shared by the wire codec and
    secagg masking/recovery — keys built anywhere else would silently stop
    matching if the scheme ever changed.
    """
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return treedef, [
        (_SEP.join(_path_part(p) for p in path), leaf) for path, leaf in leaves_with_path
    ]


def _flatten_named(tree: Pytree) -> dict[str, np.ndarray]:
    """Flatten a pytree (nested dicts / dataclass pytrees) to path->array."""
    return {key: np.asarray(leaf) for key, leaf in named_leaves(tree)[1]}


def _path_part(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    if hasattr(p, "name"):
        return str(p.name)
    return str(p)


def _validate_residual(residual: Optional[dict], eligible_sizes: dict) -> None:
    """Drop stale error-feedback entries IN PLACE before an encode.

    Two staleness modes bit us in production shapes: (a) a tensor changed
    shape between rounds (architecture hot-swap, LoRA merge) — the stored
    flat residual no longer broadcasts against the new delta and the
    encode dies deep inside with a shape error; (b) a key left the topk
    path (compression-mode flip, anchor loss, tensor shrank under the
    size floor) — its residual would sit in the store forever, and worse,
    re-enter stale if the key ever came back. Validation is at use time:
    keep a key only if it is eligible THIS encode and its stored size
    matches the tensor's current size.
    """
    if residual is None:
        return
    for key in list(residual):
        size = eligible_sizes.get(key)
        if size is None or getattr(residual[key], "size", None) != size:
            del residual[key]


def _as_u8(arr: np.ndarray) -> memoryview:
    """Zero-copy uint8 memoryview over a contiguous array's bytes.

    ``reshape(-1).view(np.uint8)`` reinterprets rather than copies, so the
    returned view keeps ``arr``'s buffer alive — the framing/chunking
    writers downstream make the ONE copy into the outgoing frame (the old
    per-leaf ``.tobytes()`` made a second, payload-sized one)."""
    return memoryview(np.ascontiguousarray(arr).reshape(-1).view(np.uint8))


def _encode_host(
    named: dict,
    compression: Optional[str],
    anchor_named: Optional[dict],
    topk_plan: dict,
    residual: Optional[dict],
) -> tuple[list, int]:
    """Host (numpy) producer — the bit-format-reference baseline.

    Walks tensors serially: full D2H pull per leaf, ``argpartition`` top-k,
    native C++ quantize. ``topk_plan`` (``{path: budget}``, computed once
    in :func:`encode_params`) is the single source of which tensors are
    delta-coded and at what k. Returns ``(plans, d2h_bytes)`` exactly like
    :func:`p2pfl_tpu.ops.compression.encode_device`; the byte layout per
    tensor is the format contract both producers implement. Buffers are
    zero-copy :func:`_as_u8` views — the frame/chunk writer makes the only
    payload copy.
    """
    from p2pfl_tpu import native

    plans = []
    d2h = 0
    for key in sorted(named):
        arr = np.asarray(named[key])
        d2h += arr.nbytes
        entry = {"k": key, "shape": list(arr.shape), "dtype": arr.dtype.name}
        if key in topk_plan:
            anchor_arr = np.asarray(anchor_named[key], dtype=np.float32)
            d2h += anchor_arr.nbytes
            delta = np.asarray(arr, np.float32).ravel() - anchor_arr.ravel()
            if residual is not None and key in residual:
                # np.asarray: the store may hold a device-resident carry
                # from a WIRE_COMPRESSION_DEVICE flip — normalize host-side
                delta = delta + np.asarray(residual[key], dtype=np.float32)
            k = topk_plan[key]
            idx = np.argpartition(np.abs(delta), -k)[-k:].astype(np.uint32)
            idx.sort()
            vals = delta[idx]
            q, scale = native.quantize(vals)
            if residual is not None:
                # error feedback: what this payload fails to carry (dropped
                # coordinates + quantization error) feeds the next round
                sent = np.zeros_like(delta)
                sent[idx] = native.dequantize(q, scale)
                residual[key] = delta - sent
            # two pieces, no concat copy: CRC chains across them and the
            # framing loop below writes them back to back
            bufs = (_as_u8(idx), _as_u8(q))
            entry["enc"] = "tk8"
            entry["scale"] = scale
            entry["nnz"] = int(k)
        elif compression in ("int8", "topk8") and arr.dtype.kind == "f":
            q, scale = native.quantize(np.asarray(arr, dtype=np.float32))
            bufs = (_as_u8(q),)
            entry["enc"] = "i8"
            entry["scale"] = scale
        else:
            bufs = (_as_u8(arr),)
        plans.append((entry, bufs))
    return plans, d2h


def _frame_parts(plans: list, anchor_tag: Optional[str]) -> tuple[bytes, list]:
    """``(prefix, buffers)`` of the framed payload: ``prefix`` is the
    unary frame's ``magic + header-length + JSON header`` and ``buffers``
    the per-tensor byte views in entry order. Shared by the unary framer
    and the chunker — concatenating ``prefix`` with every buffer IS the
    unary payload, which is what makes chunk streams byte-compatible with
    unary frames by construction."""
    from p2pfl_tpu import native

    entries = []
    buffers = []
    crc = 0
    for entry, bufs in plans:
        entry["n"] = sum(len(b) for b in bufs)
        for b in bufs:
            crc = native.crc32c(b, crc)
            buffers.append(b)
        entries.append(entry)
    head = {"v": _VERSION, "t": entries, "crc": crc}
    if any(e.get("enc") == "tk8" for e in entries):
        head["anchor_tag"] = anchor_tag if anchor_tag is not None else ""
    header = json.dumps(head).encode("utf-8")
    prefix = bytearray(8 + len(header))
    prefix[0:4] = _MAGIC
    struct.pack_into("<I", prefix, 4, len(header))
    prefix[8:] = header
    return bytes(prefix), buffers


def _frame(plans: list, anchor_tag: Optional[str]) -> bytes:
    """Assemble per-tensor plans into the framed payload (shared by both
    producers — one frame layout, one decoder)."""
    prefix, buffers = _frame_parts(plans, anchor_tag)
    # single preallocated frame: sizes are all known here and the plans
    # hold zero-copy views, so the payload bytes are written exactly once
    total = len(prefix) + sum(len(b) for b in buffers)
    out = bytearray(total)
    out[0 : len(prefix)] = prefix
    off = len(prefix)
    for b in buffers:
        out[off : off + len(b)] = b
        off += len(b)
    return bytes(out)


# ---- chunk stream framing (the streaming byte plane) ----

_CHUNK_MAGIC = b"P2TC"  # p2pfl-tpu chunk
#: chunk types: the header chunk carries the unary frame's prefix (magic +
#: header length + JSON header), data chunks carry consecutive payload
#: slabs, the end chunk closes the stream with the expected chunk count
CHUNK_HEADER, CHUNK_DATA, CHUNK_END = 0, 1, 2
_CHUNK_OVERHEAD = 17  # magic(4) + type(1) + seq(4) + body length(4) + crc32c(4)
_MIN_CHUNK_BYTES = 64 * 1024


def _chunk_bytes_setting() -> int:
    from p2pfl_tpu.settings import Settings

    return max(int(Settings.WIRE_CHUNK_MB * 1024 * 1024), _MIN_CHUNK_BYTES)


def _chunk(ctype: int, seq: int, body) -> bytes:
    from p2pfl_tpu import native

    n = len(body)
    out = bytearray(_CHUNK_OVERHEAD + n)
    out[0:4] = _CHUNK_MAGIC
    out[4] = ctype
    struct.pack_into("<III", out, 5, seq, n, native.crc32c(body, 0))
    out[_CHUNK_OVERHEAD:] = body
    return bytes(out)


def parse_stream_chunk(frame) -> tuple[int, int, memoryview, int]:
    """``(type, seq, body, body_crc)`` of one self-delimiting stream chunk.

    Every chunk is independently verifiable: magic, framed body length and
    a per-chunk CRC32C — a corrupted chunk is rejected the moment it
    arrives instead of poisoning a whole reassembled payload. Raises
    :class:`DecodingParamsError` on any violation. The verified body CRC
    is returned so the decoder can fold it into the running whole-payload
    CRC via :func:`native.crc32c_combine` without a second byte pass.
    """
    from p2pfl_tpu import native

    mv = memoryview(frame)
    if len(mv) < _CHUNK_OVERHEAD or bytes(mv[:4]) != _CHUNK_MAGIC:
        raise DecodingParamsError("bad chunk magic — not a p2pfl_tpu stream chunk")
    ctype = mv[4]
    seq, n, crc = struct.unpack_from("<III", mv, 5)
    body = mv[_CHUNK_OVERHEAD:]
    if len(body) != n:
        raise DecodingParamsError(f"chunk {seq}: body {len(body)} bytes != framed {n}")
    if native.crc32c(body, 0) != crc:
        raise DecodingParamsError(f"chunk {seq}: CRC mismatch — corrupted in flight")
    if ctype not in (CHUNK_HEADER, CHUNK_DATA, CHUNK_END):
        raise DecodingParamsError(f"chunk {seq}: unknown chunk type {ctype}")
    return ctype, seq, body, crc


def _gen_chunks(prefix: bytes, buffers, chunk_bytes: int):
    """Yield ``(prefix, buffers)`` framed as stream chunks, one at a time.

    Invariant (tested): the header + data chunk bodies concatenate to
    exactly ``prefix + b"".join(buffers)`` — the unary payload. Cuts are
    leaf-aligned whenever the next buffer fits in a fresh slab (the
    receiver then completes whole leaves per chunk); buffers larger than a
    slab are split across chunks.

    A generator so the transport can pull frames as the wire drains them:
    the per-chunk copy + CRC pass overlaps with the send of earlier
    chunks and the receiver's incremental decode, instead of running as a
    serial prefix before the first byte moves.
    """
    yield _chunk(CHUNK_HEADER, 0, prefix)
    seq = 1
    pending: list = []
    pending_n = 0

    def _flush() -> bytes:
        nonlocal pending, pending_n, seq
        # write the pieces straight into the framed chunk (one copy per
        # byte — no intermediate body buffer) and CRC the assembled slab
        from p2pfl_tpu import native

        frame = bytearray(_CHUNK_OVERHEAD + pending_n)
        frame[0:4] = _CHUNK_MAGIC
        frame[4] = CHUNK_DATA
        off = _CHUNK_OVERHEAD
        for piece in pending:
            frame[off : off + len(piece)] = piece
            off += len(piece)
        crc = native.crc32c(memoryview(frame)[_CHUNK_OVERHEAD:], 0)
        struct.pack_into("<III", frame, 5, seq, pending_n, crc)
        seq += 1
        pending, pending_n = [], 0
        return bytes(frame)

    for b in buffers:
        mv = b if isinstance(b, memoryview) else memoryview(b)
        # leaf-aligned cut: close the open slab rather than straddle a
        # leaf boundary when the whole leaf fits in the next slab
        if pending_n and pending_n + len(mv) > chunk_bytes and len(mv) <= chunk_bytes:
            yield _flush()
        while len(mv) > 0:
            take = min(len(mv), chunk_bytes - pending_n)
            pending.append(mv[:take])
            pending_n += take
            mv = mv[take:]
            if pending_n >= chunk_bytes:
                yield _flush()
    if pending_n:
        yield _flush()
    yield _chunk(CHUNK_END, seq, json.dumps({"n": seq}).encode("utf-8"))


def _assemble_chunks(prefix: bytes, buffers: list, chunk_bytes: int) -> list[bytes]:
    return list(_gen_chunks(prefix, buffers, chunk_bytes))


def iter_chunked_payload(payload: bytes, chunk_bytes: Optional[int] = None):
    """Lazily cut an already-framed unary payload into stream chunks.

    The cache fan-out path: when the encode-once cache already holds the
    unary bytes, streaming to K peers re-frames those bytes (leaf-aligned
    via the header's entry sizes) instead of re-running the encode
    pipeline. Frame validation happens eagerly (before the first yield)
    so a malformed payload raises at call time, not mid-stream."""
    if chunk_bytes is None:
        chunk_bytes = _chunk_bytes_setting()
    mv = memoryview(payload)
    if bytes(mv[:4]) != _MAGIC:
        raise DecodingParamsError("bad magic — not a p2pfl_tpu weights payload")
    (hlen,) = struct.unpack("<I", mv[4:8])
    header = json.loads(bytes(mv[8 : 8 + hlen]).decode("utf-8"))
    prefix = bytes(mv[: 8 + hlen])
    buffers = []
    off = 8 + hlen
    for e in header["t"]:
        n = int(e["n"])
        if off + n > len(payload):
            raise DecodingParamsError(f"truncated payload at {e['k']}")
        buffers.append(mv[off : off + n])
        off += n
    if off != len(payload):
        raise DecodingParamsError("payload longer than its header declares")
    return _gen_chunks(prefix, buffers, chunk_bytes)


def chunk_encoded_payload(payload: bytes, chunk_bytes: Optional[int] = None) -> list[bytes]:
    """Materialized :func:`iter_chunked_payload` (the cache stores lists)."""
    return list(iter_chunked_payload(payload, chunk_bytes))


def payload_from_chunks(chunks) -> bytes:
    """Rebuild the unary frame from a P2TC chunk list (the inverse of
    :func:`chunk_encoded_payload` — header + data bodies concatenate to
    exactly the unary payload)."""
    out = bytearray()
    for frame in chunks:
        ctype, _, body, _ = parse_stream_chunk(frame)
        if ctype != CHUNK_END:
            out += body
    return bytes(out)


def encode_params(
    tree: Pytree,
    compression: Optional[str] = None,
    anchor: Optional[Pytree] = None,
    anchor_tag: Optional[str] = None,
    residual: Optional[dict] = None,
    owner: Optional[str] = None,
) -> bytes:
    """Serialize a params pytree to the self-describing wire format.

    ``compression="int8"`` quantizes float tensors symmetrically per-tensor
    (4x smaller payloads; native C++ hot loop in ``p2pfl_tpu/native`` when
    built). Every payload carries a CRC32C over the tensor bytes; decoding
    verifies it.

    ``compression="topk8"`` delta-codes against ``anchor`` (the round-start
    global model): per float tensor, keep the top
    ``Settings.TOPK_FRACTION`` coordinates of ``params − anchor`` by
    magnitude, int8-quantized, shipped as (uint32 index, int8 value) pairs
    — ~``0.05 × 5/4`` of the dense float32 bytes at the default fraction.
    ``anchor_tag`` (the round identity ``"epoch:round"``, pinned by the
    stages) rides in the header: the receiver accepts the delta only when
    its own anchor carries the same tag. Anchors of the same round are NOT
    bit-identical across nodes — each node folds its OWN params losslessly
    but its peers' through the lossy wire — so reconstruction tolerates a
    small anchor divergence (same order as the int8 quantization error);
    the tag catches the catastrophic case, delta-coding against a
    different round's model. With no anchor (e.g. the round-0 init model)
    the tensor falls back to dense int8. ``residual`` (a mutable
    {path: array} dict) enables error feedback: the coordinates a round
    drops are added back into the next round's delta instead of being lost
    (Seide et al. 2014; Karimireddy et al. 2019).

    Producer selection: with ``Settings.WIRE_COMPRESSION_DEVICE`` on and
    device-resident params, the delta/EF/top-k/int8 math runs as ONE fused
    jit dispatch (``ops/compression.py``) and only the compressed buffers
    cross device→host — the residual store then carries device arrays
    between rounds. The host numpy path remains the bit-format-compatible
    baseline: both producers emit the same frame layout, and the one
    decoder (:func:`decode_params`) decodes either. Stale residual entries
    (shape changes, keys off the topk path after a mode flip) are dropped
    before every encode. ``owner`` (the node address, threaded through
    :meth:`ModelUpdate.encode`) routes per-node wire-byte counters into
    ``logger.get_comm_metrics``; process-wide totals are always kept
    (:func:`wire_stats`).
    """
    plans, named, d2h, producer = _encode_plans(tree, compression, anchor, residual)
    payload = _frame(plans, anchor_tag)
    _account_encode(named, len(payload), d2h, producer, owner)
    return payload


def encode_params_chunked(
    tree: Pytree,
    compression: Optional[str] = None,
    anchor: Optional[Pytree] = None,
    anchor_tag: Optional[str] = None,
    residual: Optional[dict] = None,
    owner: Optional[str] = None,
    chunk_bytes: Optional[int] = None,
) -> list[bytes]:
    """:func:`encode_params`, emitted as a list of stream chunk frames.

    Same pipeline, same producers, same accounting — but the unary frame
    is never materialized: the per-tensor buffer views are cut straight
    into ``~Settings.WIRE_CHUNK_MB`` slabs (leaf-aligned where possible,
    per-chunk CRC32C, header chunk first, end chunk last), so the sender
    holds one copy of the payload as chunks instead of chunks + frame.
    The chunk bodies concatenate to exactly the unary payload —
    :class:`StreamDecoder` and :func:`decode_params` share one decoder
    core over identical bytes.
    """
    if chunk_bytes is None:
        chunk_bytes = _chunk_bytes_setting()
    plans, named, d2h, producer = _encode_plans(tree, compression, anchor, residual)
    prefix, buffers = _frame_parts(plans, anchor_tag)
    chunks = _assemble_chunks(prefix, buffers, chunk_bytes)
    payload_len = len(prefix) + sum(len(b) for b in buffers)
    _account_encode(named, payload_len, d2h, producer, owner, streamed=True)
    return chunks


def _encode_plans(
    tree: Pytree,
    compression: Optional[str],
    anchor: Optional[Pytree],
    residual: Optional[dict],
) -> tuple[list, dict, int, str]:
    """The shared encode pipeline behind both the unary and the chunked
    entry points: producer selection + per-tensor plans. Returns
    ``(plans, named, d2h_bytes, producer)``."""
    from p2pfl_tpu.settings import Settings

    global _encode_calls
    with _encode_lock:
        _encode_calls += 1

    if compression is None:
        compression = Settings.WIRE_COMPRESSION
    topk_frac = Settings.TOPK_FRACTION if compression == "topk8" else 0.0

    def _named(t: Pytree) -> dict:
        # leaves keep their device residency, but non-array leaves (Python
        # scalars in a params pytree) are normalized exactly like the old
        # _flatten_named did — every leaf downstream has .dtype/.shape
        return {
            key: leaf if hasattr(leaf, "dtype") else np.asarray(leaf)
            for key, leaf in named_leaves(t)[1]
        }

    named = _named(tree)
    anchor_named = _named(anchor) if anchor is not None else None

    # the ONE topk-eligibility predicate + budget AND the one sizing
    # helper, shared by both byte producers and the shard-plane codec
    # (ops/compression.py — drift here would silently wipe valid
    # error-feedback carries or diverge nnz)
    from p2pfl_tpu.ops.compression import build_topk_plan, leaf_size as _size

    topk_plan = build_topk_plan(named, anchor_named, topk_frac)
    _validate_residual(residual, {key: _size(named[key]) for key in topk_plan})

    from p2pfl_tpu.settings import wire_compression_device

    use_device = (
        wire_compression_device()
        and compression in ("int8", "topk8")
        and any(isinstance(leaf, jax.Array) for leaf in named.values())
    )
    if use_device:
        from p2pfl_tpu.ops import compression as device_codec

        plans, d2h = device_codec.encode_device(named, anchor_named, topk_plan, residual)
        producer = "device"
    else:
        plans, d2h = _encode_host(named, compression, anchor_named, topk_plan, residual)
        producer = "host"
    return plans, named, d2h, producer


def _account_encode(
    named: dict,
    payload_len: int,
    d2h: int,
    producer: str,
    owner: Optional[str],
    streamed: bool = False,
) -> None:
    from p2pfl_tpu.ops.compression import leaf_size as _size

    raw_bytes = sum(_size(leaf) * np.dtype(leaf.dtype).itemsize for leaf in named.values())
    with _encode_lock:
        _wire_stats["raw_bytes"] += raw_bytes
        _wire_stats["payload_bytes"] += payload_len
        _wire_stats["d2h_bytes"] += d2h
        _wire_stats[f"{producer}_encodes"] += 1
        if streamed:
            _wire_stats["stream_encodes"] += 1
    if owner:
        from p2pfl_tpu.management.logger import logger

        logger.log_comm_metric(owner, "wire_raw_bytes", raw_bytes)
        logger.log_comm_metric(owner, "wire_payload_bytes", payload_len)
        logger.log_comm_metric(owner, "wire_d2h_bytes", d2h)
        logger.log_comm_metric(owner, f"wire_encode_{producer}")


def _leaf_meta(e: dict) -> tuple[np.dtype, int]:
    """Validate one header entry and return ``(dtype, element_count)``.

    Shared by the unary decoder and the streaming decoder so both enforce
    the same header/byte-length consistency rules (one decoder core).
    """
    dtype = _resolve_dtype(e["dtype"])
    count = int(np.prod(e["shape"], dtype=np.int64)) if e["shape"] else 1
    if e.get("enc") == "tk8":
        expect = int(e["nnz"]) * 5  # uint32 index + int8 value per coordinate
    elif e.get("enc") == "i8":
        expect = count
    else:
        expect = count * dtype.itemsize
    if e["n"] != expect:
        raise DecodingParamsError(
            f"inconsistent header for {e['k']}: n={e['n']} vs shape {e['shape']}"
        )
    return dtype, count


def _decode_dense_leaf(e: dict, buf) -> np.ndarray:
    """Decode one dense (raw or int8) leaf from its exact byte slice.

    ``buf`` must be exactly ``e['n']`` bytes (a memoryview slice of the
    unary frame, or a completed per-leaf buffer in the streaming decoder).
    tk8 leaves never come through here — they need the anchor.
    """
    from p2pfl_tpu import native

    dtype, count = _leaf_meta(e)
    if e.get("enc") == "i8":
        q = np.frombuffer(buf, dtype=np.int8, count=count)
        return native.dequantize(q, float(e["scale"])).astype(dtype).reshape(e["shape"])
    return np.frombuffer(buf, dtype=dtype, count=count).reshape(e["shape"])


def decode_params(
    payload: bytes,
    anchor: Optional[Pytree] = None,
    anchor_tag: Optional[str] = None,
) -> dict[str, np.ndarray]:
    """Decode the wire format to a flat ``{path: array}`` dict.

    Delta-coded (``tk8``) payloads require an ``anchor`` whose round
    identity matches the header's ``anchor_tag``; a mismatch raises
    :class:`AnchorMismatchError` — reconstructing against a different
    round's model would yield silently wrong parameters. Same-round
    anchors may differ slightly across nodes (see :func:`encode_params`);
    that divergence is part of the codec's loss budget.

    One decoder decodes BOTH producers (host and device frames are
    layout-identical). ``tk8`` indices are validated strictly ascending
    and in range — both producers emit them sorted, so a duplicate or
    unsorted index stream is a malformed payload, not a dialect. When
    ``Settings.WIRE_COMPRESSION_DEVICE`` is on and the anchor is
    device-resident, reconstruction runs as one fused scatter-add on
    device (``ops/compression.py``) AFTER the CRC verifies, instead of
    pulling every anchor tensor host-side into a ``.ravel().copy()``.
    """
    try:
        # memoryview slicing: header parse + per-tensor CRC walk the frame
        # without copying tensor bytes (np.frombuffer below is zero-copy too)
        mv = memoryview(payload)
        if bytes(mv[:4]) != _MAGIC:
            raise DecodingParamsError("bad magic — not a p2pfl_tpu weights payload")
        (hlen,) = struct.unpack("<I", mv[4:8])
        header = json.loads(bytes(mv[8 : 8 + hlen]).decode("utf-8"))
        if header["v"] != _VERSION:
            raise DecodingParamsError(f"unsupported weights version {header['v']}")
        from p2pfl_tpu import native

        anchor_flat = None
        if "anchor_tag" in header:
            if anchor is None:
                raise AnchorMismatchError(
                    "payload is delta-coded (topk8) but no anchor is available"
                )
            if (anchor_tag or "") != header["anchor_tag"]:
                raise AnchorMismatchError(
                    f"anchor round mismatch (local {anchor_tag!r} != payload "
                    f"{header['anchor_tag']!r}) — sender delta-coded against a "
                    "different round's model"
                )
            # raw leaves (no np.asarray): a device-resident anchor must
            # reach the device consumer without a host round-trip
            anchor_flat = dict(named_leaves(anchor)[1])

        from p2pfl_tpu.settings import wire_compression_device

        device_consume = wire_compression_device()
        flat = {}
        deferred: list = []  # tk8 entries reconstructed on device post-CRC
        off = 8 + hlen
        crc = 0
        for e in header["t"]:
            dtype, count = _leaf_meta(e)
            if off + e["n"] > len(payload):
                raise DecodingParamsError(f"truncated payload at {e['k']}")
            crc = native.crc32c(mv[off : off + e["n"]], crc)
            if e.get("enc") == "tk8":
                nnz = int(e["nnz"])
                if anchor_flat is None or e["k"] not in anchor_flat:
                    raise AnchorMismatchError(f"no anchor tensor for delta-coded {e['k']}")
                idx = np.frombuffer(payload, dtype=np.uint32, count=nnz, offset=off)
                q = np.frombuffer(payload, dtype=np.int8, count=nnz, offset=off + nnz * 4)
                if nnz and int(idx.max()) >= count:
                    raise DecodingParamsError(f"index out of range in {e['k']}")
                # both producers emit strictly ascending indices per tensor;
                # anything else (duplicates, unsorted, nnz > count) is a
                # malformed payload — and the device scatter-ADD relies on
                # uniqueness to match the host reconstruction
                if nnz > 1 and np.any(np.diff(idx.astype(np.int64)) <= 0):
                    raise DecodingParamsError(
                        f"duplicate or unsorted indices in {e['k']}"
                    )
                anchor_leaf = anchor_flat[e["k"]]
                # the device scatter indexes per tensor in int32; a tensor
                # beyond int32 index space (>2^31−1 elements) falls back to
                # the host consumer's uint32 path
                if (
                    device_consume
                    and isinstance(anchor_leaf, jax.Array)
                    and count <= np.iinfo(np.int32).max
                ):
                    deferred.append(
                        (
                            e["k"],
                            anchor_leaf,
                            idx,
                            native.dequantize(q, float(e["scale"])),
                            tuple(e["shape"]),
                            dtype,
                        )
                    )
                    off += e["n"]
                    continue
                dense = np.asarray(anchor_leaf, np.float32).ravel().copy()
                dense[idx] = dense[idx] + native.dequantize(q, float(e["scale"]))
                arr = dense.astype(dtype).reshape(e["shape"])
            else:
                arr = _decode_dense_leaf(e, mv[off : off + e["n"]])
            flat[e["k"]] = arr
            off += e["n"]
        if "crc" in header and header["crc"] != crc:
            raise DecodingParamsError(f"CRC mismatch: payload corrupted ({crc} != {header['crc']})")
        if deferred:
            from p2pfl_tpu.ops import compression as device_codec

            flat.update(device_codec.decode_tk8_device(deferred))
        return flat
    except (DecodingParamsError, AnchorMismatchError):
        raise
    except Exception as exc:  # noqa: BLE001 — any malformed payload is a decode error
        raise DecodingParamsError(str(exc)) from exc


class StreamDecoder:
    """Incremental decoder for a ``P2TC`` chunk stream (one model transfer).

    Feed frames in order via :meth:`feed`. Dense (raw / ``i8``) leaves are
    decoded the moment their bytes complete — optionally ``device_put`` —
    so receiver-side peak payload memory is O(chunk + largest leaf in
    flight) instead of O(model). Delta-coded (``tk8``) streams need the
    receiver's round anchor, which the transport layer doesn't have, so
    the decoder switches to REASSEMBLE mode (header carries
    ``anchor_tag``): it accumulates the byte-identical unary frame and
    hands it to the normal :func:`decode_params` path at materialize
    time. tk8 payloads are ~0.25 byte/param, so reassembly stays small.

    Every chunk's own CRC32C is checked by :func:`parse_stream_chunk`;
    the header's whole-payload CRC is reconstructed by FOLDING those
    already-verified per-chunk CRCs with :func:`native.crc32c_combine`
    (CRC32C composes over arbitrary split points — O(1) matrix math per
    chunk, so the payload bytes are hashed exactly once) and verified at
    the end chunk together with the declared chunk count and the per-leaf
    byte totals. Any violation raises :class:`DecodingParamsError` — the
    caller drops the stream as ONE failed transfer.
    """

    def __init__(self, device_put: bool = False):
        self._device_put = device_put
        self._expect_seq = 0
        self.header: Optional[dict] = None
        self._entries: list = []
        self._entry_idx = 0
        self._leaf_buf: Optional[bytearray] = None
        self._leaf_fill = 0
        self._crc = 0
        self._flat: dict = {}
        self._reassemble: Optional[bytearray] = None
        self._done = False
        self.chunks = 0
        self.payload_bytes = 0
        #: high-water mark of bytes this decoder held buffered at once
        #: (in-flight chunk frame + open leaf buffer / reassembly buffer) —
        #: the measured half of the bounded-memory contract: for dense
        #: streams it never scales with the model, only with
        #: chunk size + the largest single leaf
        self.peak_scratch_bytes = 0

    @property
    def complete(self) -> bool:
        return self._done

    @property
    def reassembled(self) -> bool:
        return self._reassemble is not None

    def feed(self, frame) -> None:
        ctype, seq, body, crc = parse_stream_chunk(frame)
        if self._done:
            raise DecodingParamsError("chunk after end-of-stream")
        if seq != self._expect_seq:
            raise DecodingParamsError(
                f"out-of-order chunk: seq {seq}, expected {self._expect_seq}"
            )
        self._expect_seq += 1
        self.chunks += 1
        if ctype == CHUNK_HEADER:
            self._start(body)
        elif ctype == CHUNK_DATA:
            self._data(body, crc)
        else:  # parse_stream_chunk admits only the three known types
            self._finish(body)
        scratch = len(frame) + (
            len(self._reassemble)
            if self._reassemble is not None
            else (len(self._leaf_buf) if self._leaf_buf is not None else 0)
        )
        if scratch > self.peak_scratch_bytes:
            self.peak_scratch_bytes = scratch

    def _start(self, body) -> None:
        if self.header is not None:
            raise DecodingParamsError("duplicate stream header chunk")
        if bytes(body[:4]) != _MAGIC:
            raise DecodingParamsError("bad magic in stream header chunk")
        (hlen,) = struct.unpack("<I", body[4:8])
        if len(body) != 8 + hlen:
            raise DecodingParamsError("stream header chunk length mismatch")
        header = json.loads(bytes(body[8:]).decode("utf-8"))
        if header["v"] != _VERSION:
            raise DecodingParamsError(f"unsupported weights version {header['v']}")
        self.header = header
        self._entries = header["t"]
        for e in self._entries:
            _leaf_meta(e)  # validate every entry before any bytes land
        if "anchor_tag" in header or any(e.get("enc") == "tk8" for e in self._entries):
            # delta decode needs the receiver's anchor at materialize time
            self._reassemble = bytearray(body)
        else:
            self._advance_leaf()

    def _advance_leaf(self) -> None:
        # zero-size leaves (a 0-dim in the shape) carry no payload bytes —
        # complete them eagerly rather than waiting on an empty slice
        while self._entry_idx < len(self._entries):
            e = self._entries[self._entry_idx]
            if e["n"] == 0:
                self._finish_leaf(e, b"")
                self._entry_idx += 1
                continue
            self._leaf_buf = bytearray(e["n"])
            self._leaf_fill = 0
            return
        self._leaf_buf = None

    def _finish_leaf(self, e: dict, buf) -> None:
        arr = _decode_dense_leaf(e, buf)
        if self._device_put:
            arr = jax.device_put(arr)
        self._flat[e["k"]] = arr

    def _data(self, body, crc: int) -> None:
        if self.header is None:
            raise DecodingParamsError("data chunk before stream header")
        from p2pfl_tpu import native

        # fold the chunk's already-verified CRC into the running whole-
        # payload CRC — O(1) matrix math, not a second pass over the bytes
        self._crc = native.crc32c_combine(self._crc, crc, len(body))
        self.payload_bytes += len(body)
        if self._reassemble is not None:
            self._reassemble += body
            return
        off, n = 0, len(body)
        while off < n:
            if self._leaf_buf is None:
                raise DecodingParamsError("payload bytes past the last leaf")
            e = self._entries[self._entry_idx]
            take = min(n - off, e["n"] - self._leaf_fill)
            self._leaf_buf[self._leaf_fill : self._leaf_fill + take] = body[off : off + take]
            self._leaf_fill += take
            off += take
            if self._leaf_fill == e["n"]:
                self._finish_leaf(e, self._leaf_buf)
                self._entry_idx += 1
                self._advance_leaf()

    def _finish(self, body) -> None:
        if self.header is None:
            raise DecodingParamsError("end chunk before stream header")
        try:
            declared = json.loads(bytes(body).decode("utf-8"))["n"]
        except (ValueError, KeyError, UnicodeDecodeError) as exc:
            raise DecodingParamsError(f"malformed end chunk: {exc}") from exc
        if declared != self._expect_seq - 1:
            raise DecodingParamsError(
                f"chunk count mismatch: end declares {declared}, saw {self._expect_seq - 1}"
            )
        expect_bytes = sum(int(e["n"]) for e in self._entries)
        if self.payload_bytes != expect_bytes:
            raise DecodingParamsError(
                f"stream truncated: {self.payload_bytes} payload bytes, "
                f"header declares {expect_bytes}"
            )
        if "crc" in self.header and self.header["crc"] != self._crc:
            raise DecodingParamsError(
                f"CRC mismatch: stream corrupted ({self._crc} != {self.header['crc']})"
            )
        self._done = True
        with _encode_lock:
            if self.peak_scratch_bytes > _wire_stats["stream_peak_scratch_bytes"]:
                _wire_stats["stream_peak_scratch_bytes"] = self.peak_scratch_bytes

    def result_flat(self) -> dict:
        """The leaf-decoded ``{path: array}`` dict (dense streams only)."""
        if not self._done:
            raise DecodingParamsError("stream incomplete")
        if self._reassemble is not None:
            raise DecodingParamsError(
                "delta-coded stream has no eager flat result — use result_payload()"
            )
        return self._flat

    def result_payload(self) -> bytes:
        """The byte-identical unary frame (REASSEMBLE mode only)."""
        if not self._done:
            raise DecodingParamsError("stream incomplete")
        if self._reassemble is None:
            raise DecodingParamsError(
                "dense stream was leaf-decoded on arrival — use result_flat()"
            )
        return bytes(self._reassemble)


def estimate_payload_bytes(update) -> Optional[int]:
    """Cheap estimate of an update's encoded payload size, WITHOUT encoding.

    Transports use this to pick unary vs streaming before paying for the
    encode. Exact when the payload bytes already exist; otherwise derived
    from raw leaf sizes scaled by the wire-compression mode (``int8``
    ships one byte per element; ``topk8`` ~0.33 byte/element at its 1/16
    density ceiling, call it /12 to stay conservative). Returns ``None``
    when nothing is known (no params, no bytes) — treat as "small".
    """
    if update.encoded is not None:
        return len(update.encoded)
    if update.params is None:
        return None
    from p2pfl_tpu.settings import Settings

    raw = 0
    for _, leaf in named_leaves(update.params)[1]:
        shape = np.shape(leaf)
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        itemsize = np.dtype(leaf.dtype).itemsize if hasattr(leaf, "dtype") else 8
        raw += count * itemsize
    comp = Settings.WIRE_COMPRESSION
    if comp == "int8":
        est = raw // 4
    elif comp == "topk8" and update.anchor is not None:
        est = raw // 12
    else:
        est = raw
    return est + 4096  # header slack


def _resolve_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def restore_like(template: Pytree, flat: dict[str, np.ndarray]) -> Pytree:
    """Rebuild a pytree with ``template``'s structure from a flat path dict.

    Raises :class:`ModelNotMatchingError` on any structural mismatch — this is
    the check that makes the reference's ``test_wrong_model`` scenario
    (``test/node_test.py:155-176``) fail fast instead of hanging.
    """
    tmpl_flat = _flatten_named(template)
    if set(tmpl_flat) != set(flat):
        missing = set(tmpl_flat) ^ set(flat)
        raise ModelNotMatchingError(f"param paths differ (symmetric diff: {sorted(missing)[:5]}...)")
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves_with_path:
        key = _SEP.join(_path_part(p) for p in path)
        arr = flat[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ModelNotMatchingError(f"shape mismatch at {key}: {arr.shape} vs {np.shape(leaf)}")
        new_leaves.append(jax.numpy.asarray(arr).astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


@dataclass
class ModelUpdate:
    """A model (or partial aggregation of models) moving through the network.

    ``contributors`` is the set of node addresses whose local training is
    already folded into ``params`` — the unit of the reference's
    partial-aggregation algebra (``p2pfl/learning/aggregators/aggregator.py``).
    ``num_samples`` is the total sample weight of those contributors.
    """

    params: Pytree
    contributors: list[str] = field(default_factory=list)
    num_samples: int = 1
    encoded: Optional[bytes] = None  # populated lazily for byte transports
    #: receiver-side streaming decode result (``{path: array}``, possibly
    #: already device-resident): set by the transport's
    #: :class:`StreamDecoder` when a dense stream was leaf-decoded on
    #: arrival — the unary frame never existed on this side, so
    #: ``materialize`` consumes this instead of decoding ``encoded``.
    #: Never serialized.
    decoded_flat: Optional[dict] = None
    #: True when this "aggregate" is really the round-start global kept by
    #: a failed secagg recovery (a no-op round) — receivers of a diffusion
    #: must never mistake it for the round's authoritative aggregate, so
    #: GossipModelStage skips outward diffusion when set. Never serialized.
    noop_round: bool = False
    #: True when this update is a FINALIZED (self-mask-free) aggregate a
    #: peer diffused under Bonawitz double masking — set by AddModelCommand
    #: when it strips the ``secagg.CLEAN_MARKER`` pseudo-contributor. A
    #: full-coverage aggregate ASSEMBLED from masked partials is bit-
    #: different from the clean diffusion (the self-mask sum still rides on
    #: it), so the finalize step must know which kind it holds. Travels on
    #: the wire only as the marker, never as a field.
    secagg_clean: bool = False
    #: round-start global model for delta (topk8) wire coding — never
    #: serialized; attached by the learner, inherited through aggregation
    anchor: Optional[Pytree] = None
    anchor_tag: Optional[str] = None  # round identity, e.g. "1:3"
    #: mutable error-feedback store ({path: residual}) — set only on a
    #: node's OWN train-stage contribution (TrainStage attaches it; letting
    #: every diffusion encode write it would clobber the store with
    #: aggregate-encode error) so dropped delta coordinates re-enter the
    #: next round
    ef_residual: Optional[dict] = None
    #: device-resident partial-aggregation accumulator ``(psum, wsum)``:
    #: ``psum`` is the fp32 pytree ``num_samples × params`` already folded
    #: INSIDE the fused round dispatch (``parallel/spmd.py``
    #: ``fused_node_round``), ``wsum`` the matching fp32 sample weight.
    #: Set only on a node's OWN fused train-stage contribution; FedAvg's
    #: aggregate starts its weighted fold from it instead of re-casting and
    #: re-weighting the trained params, so the Train→Aggregate seam carries
    #: device arrays end to end. Never serialized, never set on wire
    #: updates, dropped by aggregation results and secagg masking.
    partial_acc: Optional[tuple] = None
    #: async-federation version triple ``(origin, seq, base_version)``
    #: (``federation/staleness.py`` UpdateVersion): ``origin`` is the
    #: producing node, ``seq`` its monotone per-node update counter (the
    #: receiver-side version vector dedups on it — duplicate/reordered
    #: delivery, e.g. FaultPlan duplicates, can never double-merge), and
    #: ``base_version`` the global model version the update was trained
    #: FROM (the aggregator computes staleness τ = current − base with no
    #: global clock). OPTIONAL wire field, same backward-compat pattern
    #: as the telemetry ``trace_ctx``: serialized as ``"vv"`` in the gRPC
    #: envelope header only when set, absent frames decode unchanged, and
    #: the protobuf interop schema never carries it. Unused (None) by the
    #: sync round FSM.
    version: Optional[tuple] = None
    #: experiment identity (the fleet-wide id minted by the start_learning
    #: initiator): OPTIONAL wire field serialized as ``"xp"`` in the gRPC
    #: envelope header, same backward-compat pattern as ``"vv"``/``"tc"``
    #: (absent frames decode unchanged; the protobuf interop schema never
    #: carries it). Receivers filter cross-experiment stash/drain
    #: stragglers on it EXACTLY — ``Node.take_async_stash`` /
    #: ``take_early_init`` fall back to the TTL + epoch heuristics only
    #: for frames from old senders that lack it.
    xp: Optional[str] = None
    #: shard-plane handshake triple ``(slice_shape, slice_index, codec)``
    #: (``communication/ici.py``): the sender's slice topology — the
    #: devices-array shape of its submesh (or ``(1,)`` for a single-chip
    #: node), its slot on the global mesh's nodes axis (-1 when unknown)
    #: and the codec tag its shard payloads use. OPTIONAL wire field
    #: serialized as ``"sp"`` in the gRPC envelope header, same
    #: backward-compat pattern as ``"vv"``/``"xp"`` (absent frames decode
    #: unchanged; the protobuf interop schema never carries it). Stamped
    #: by ``protocol.build_weights`` whenever the sending node has a
    #: registered shard-plane endpoint — including on BYTE-path fallback
    #: frames to non-colocated peers, which is what makes it a handshake:
    #: the receiver learns the sender's slice topology from ordinary
    #: frames and can validate co-location before any shard transfer.
    sp: Optional[tuple] = None
    #: encode-once plumbing (module docstring) — the learner's shared
    #: :class:`PayloadCache` plus its model-version counter at the time
    #: this update was handed out; ``cache_round`` is stamped by
    #: ``protocol.build_weights``. None ⇒ encode() bypasses the cache.
    #: Never serialized.
    payload_cache: Optional["PayloadCache"] = None
    cache_version: Optional[int] = None
    cache_round: Optional[int] = None
    #: serializes encode(): the concurrent send fan-out may encode the same
    #: instance from several worker threads, and an error-feedback encode
    #: mutates the residual store — exactly once, under this lock
    _encode_lock: threading.Lock = field(default_factory=threading.Lock, repr=False, compare=False)

    def ef_fold_key(self, compression: str) -> tuple:
        """The ONE cross-plane error-feedback fold-ownership key.

        Both encoders of this update's content — the byte path
        (:meth:`encode`) and the ICI shard plane
        (``communication/ici.py``) — claim the fold through
        ``PayloadCache.ef_fold_once`` with exactly this tuple; building
        it anywhere else risks the keys drifting apart, which would
        silently re-arm the fold on the second plane (the double-apply
        bug the mechanism exists to prevent).
        """
        return (self.cache_version, self.cache_round, compression, self.anchor_tag)

    def encode(self) -> bytes:
        with self._encode_lock:
            return self._encode_locked()

    def _encode_locked(self) -> bytes:
        if self.encoded is not None:
            return self.encoded
        from p2pfl_tpu.settings import Settings, wire_compression_device

        cache = self.payload_cache
        key = None
        if (
            cache is not None
            and self.cache_version is not None
            and Settings.GOSSIP_PAYLOAD_CACHE
        ):
            key = (
                self.cache_version,
                self.cache_round,
                Settings.WIRE_COMPRESSION,
                # RESOLVED producer flag: device and host bytes decode
                # identically but differ at quantization-tie level — never
                # mix them in one key
                wire_compression_device(),
                self.anchor_tag,
                self.ef_residual is not None,
            )
            cached = cache.get(key)
            if cached is not None:
                self.encoded = cached
                return cached
            chunked = cache.peek(("chunks", *key, _chunk_bytes_setting()))
            if chunked is not None:
                # a streamed send already encoded this content — the chunk
                # bodies concatenate to the byte-identical unary frame, so
                # rebuild it instead of re-running the encode pipeline
                self.encoded = payload_from_chunks(chunked)
                cache.put(key, self.encoded)
                return self.encoded
        residual = self.ef_residual
        if residual is not None and cache is not None and self.cache_version is not None:
            # cross-PLANE fold ownership: the ICI shard encode and the
            # byte encode cache under different keys, so a cache miss
            # here does not mean the residual is unfolded — whichever
            # plane encoded this content first owns the fold, and the
            # other encodes residual-free (re-folding the just-written
            # carry would double-apply it)
            if not cache.ef_fold_once(self.ef_fold_key(Settings.WIRE_COMPRESSION)):
                residual = None
        self.encoded = encode_params(
            self.params,
            anchor=self.anchor,
            anchor_tag=self.anchor_tag,
            residual=residual,
            owner=cache.owner if cache is not None else None,
        )
        if key is not None:
            cache.put(key, self.encoded)
        return self.encoded

    def encode_chunks(self, chunk_bytes: Optional[int] = None) -> list:
        """Encode as a P2TC chunk list for the streaming byte plane.

        Same encode-once discipline as :meth:`encode`: the chunk list is
        cached per content under a chunk-flavored key, already-encoded
        unary bytes are re-sliced instead of re-encoded (and vice versa —
        see ``_encode_locked``), and the error-feedback fold is claimed
        through the SAME :meth:`ef_fold_key` as the unary and ICI
        encoders, so the residual folds exactly once no matter which
        plane encodes this content first.
        """
        with self._encode_lock:
            return self._encode_chunks_locked(chunk_bytes)

    def _chunk_cache_key(self, cbytes: int) -> Optional[tuple]:
        """("chunks", <unary key fields>, chunk size), or None when this
        update isn't cacheable — ``_encode_locked`` strips the first and
        last elements to cross-reuse the entry from the unary flavor."""
        from p2pfl_tpu.settings import Settings, wire_compression_device

        if (
            self.payload_cache is None
            or self.cache_version is None
            or not Settings.GOSSIP_PAYLOAD_CACHE
        ):
            return None
        return (
            "chunks",
            self.cache_version,
            self.cache_round,
            Settings.WIRE_COMPRESSION,
            wire_compression_device(),
            self.anchor_tag,
            self.ef_residual is not None,
            cbytes,
        )

    def iter_chunks(self, chunk_bytes: Optional[int] = None):
        """Chunk frames for ONE streamed send, framed lazily.

        Same encode-once discipline as :meth:`encode_chunks`, but only the
        encode pipeline (or cache lookup) runs before this returns — the
        P2TC framing pass (per-chunk copy + CRC) happens as the transport
        pulls each frame, overlapping with the wire and the receiver's
        incremental decode instead of running as a serial prefix before
        the first byte moves. The completed list is installed under the
        chunk cache key at exhaustion, so fan-out sends of the same
        content skip the pipeline AND the framing.
        """
        from p2pfl_tpu.settings import Settings

        cbytes = chunk_bytes if chunk_bytes is not None else _chunk_bytes_setting()
        with self._encode_lock:
            cache = self.payload_cache
            key = self._chunk_cache_key(cbytes)
            if key is not None:
                cached = cache.get(key)
                if cached is not None:
                    return iter(cached)
            payload = self.encoded
            if payload is None and key is not None:
                payload = cache.peek(key[1:-1])
            if payload is None:
                # the chunk-key miss above is this content's one accounted
                # miss — run the pipeline directly (same ef-fold ownership
                # contract as _encode_locked / _encode_chunks_locked)
                residual = self.ef_residual
                if residual is not None and cache is not None and self.cache_version is not None:
                    if not cache.ef_fold_once(self.ef_fold_key(Settings.WIRE_COMPRESSION)):
                        residual = None
                payload = encode_params(
                    self.params,
                    anchor=self.anchor,
                    anchor_tag=self.anchor_tag,
                    residual=residual,
                    owner=cache.owner if cache is not None else None,
                )
                self.encoded = payload
                if key is not None:
                    cache.put(key[1:-1], payload)

        def _frames():
            collected = []
            for frame in iter_chunked_payload(payload, cbytes):
                collected.append(frame)
                yield frame
            if key is not None:
                cache.put(key, collected)

        return _frames()

    def _encode_chunks_locked(self, chunk_bytes: Optional[int]) -> list:
        from p2pfl_tpu.settings import Settings

        cbytes = chunk_bytes if chunk_bytes is not None else _chunk_bytes_setting()
        if self.encoded is not None:
            return chunk_encoded_payload(self.encoded, cbytes)
        cache = self.payload_cache
        key = self._chunk_cache_key(cbytes)
        if key is not None:
            cached = cache.get(key)
            if cached is not None:
                return cached
            unary = cache.peek(key[1:-1])
            if unary is not None:
                chunks = chunk_encoded_payload(unary, cbytes)
                cache.put(key, chunks)
                return chunks
        residual = self.ef_residual
        if residual is not None and cache is not None and self.cache_version is not None:
            # cross-plane fold ownership — same contract as _encode_locked
            if not cache.ef_fold_once(self.ef_fold_key(Settings.WIRE_COMPRESSION)):
                residual = None
        chunks = encode_params_chunked(
            self.params,
            anchor=self.anchor,
            anchor_tag=self.anchor_tag,
            residual=residual,
            owner=cache.owner if cache is not None else None,
            chunk_bytes=cbytes,
        )
        if key is not None:
            cache.put(key, chunks)
        return chunks

    @staticmethod
    def decode(payload: bytes, template: Pytree, contributors: list[str], num_samples: int) -> "ModelUpdate":
        flat = decode_params(payload)
        return ModelUpdate(restore_like(template, flat), list(contributors), num_samples)
