"""Weights containers and the wire codec.

The reference ships weights as a pickle of a list of numpy arrays in
state-dict order and zips them back positionally
(``p2pfl/learning/pytorch/lightning_learner.py:113-138``). Here the payload
is a self-describing binary format: a JSON header with named paths, shapes
and dtypes, followed by raw little-endian buffers. This gives

- name-aware (not positional) matching → architecture mismatch is detected
  structurally, raising :class:`ModelNotMatchingError` instead of silently
  loading wrong layers,
- zero pickle (no arbitrary code execution from the wire),
- native bfloat16 support via ml_dtypes.

On transports that stay in-process (memory, mesh-collective) the pytree is
passed by reference and never hits this codec — weights stay device-resident.
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import numpy as np

from p2pfl_tpu.exceptions import AnchorMismatchError, DecodingParamsError, ModelNotMatchingError

Pytree = Any

_MAGIC = b"P2TW"  # p2pfl-tpu weights
_VERSION = 1

_SEP = "/"


def anchor_digest(tree: Pytree) -> int:
    """CRC32C over a pytree's canonical buffer order (sorted paths)."""
    from p2pfl_tpu import native

    flat = _flatten_named(tree)
    crc = 0
    for key in sorted(flat):
        crc = native.crc32c(np.ascontiguousarray(flat[key]).tobytes(), crc)
    return crc


def named_leaves(tree: Pytree):
    """``(treedef, [(canonical path key, leaf), ...])`` in flatten order.

    The single source of the path-key scheme shared by the wire codec and
    secagg masking/recovery — keys built anywhere else would silently stop
    matching if the scheme ever changed.
    """
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return treedef, [
        (_SEP.join(_path_part(p) for p in path), leaf) for path, leaf in leaves_with_path
    ]


def _flatten_named(tree: Pytree) -> dict[str, np.ndarray]:
    """Flatten a pytree (nested dicts / dataclass pytrees) to path->array."""
    return {key: np.asarray(leaf) for key, leaf in named_leaves(tree)[1]}


def _path_part(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    if hasattr(p, "name"):
        return str(p.name)
    return str(p)


def encode_params(
    tree: Pytree,
    compression: Optional[str] = None,
    anchor: Optional[Pytree] = None,
    anchor_tag: Optional[str] = None,
    residual: Optional[dict] = None,
) -> bytes:
    """Serialize a params pytree to the self-describing wire format.

    ``compression="int8"`` quantizes float tensors symmetrically per-tensor
    (4x smaller payloads; native C++ hot loop in ``p2pfl_tpu/native`` when
    built). Every payload carries a CRC32C over the tensor bytes; decoding
    verifies it.

    ``compression="topk8"`` delta-codes against ``anchor`` (the round-start
    global model): per float tensor, keep the top
    ``Settings.TOPK_FRACTION`` coordinates of ``params − anchor`` by
    magnitude, int8-quantized, shipped as (uint32 index, int8 value) pairs
    — ~``0.05 × 5/4`` of the dense float32 bytes at the default fraction.
    ``anchor_tag`` (the round identity ``"epoch:round"``, pinned by the
    stages) rides in the header: the receiver accepts the delta only when
    its own anchor carries the same tag. Anchors of the same round are NOT
    bit-identical across nodes — each node folds its OWN params losslessly
    but its peers' through the lossy wire — so reconstruction tolerates a
    small anchor divergence (same order as the int8 quantization error);
    the tag catches the catastrophic case, delta-coding against a
    different round's model. With no anchor (e.g. the round-0 init model)
    the tensor falls back to dense int8. ``residual`` (a mutable
    {path: np.ndarray} dict) enables error feedback: the coordinates a
    round drops are added back into the next round's delta instead of
    being lost (Seide et al. 2014; Karimireddy et al. 2019).
    """
    from p2pfl_tpu import native

    if compression is None:
        from p2pfl_tpu.settings import Settings

        compression = Settings.WIRE_COMPRESSION
    if compression == "topk8":
        from p2pfl_tpu.settings import Settings as _S

        topk_frac = _S.TOPK_FRACTION
    anchor_flat = _flatten_named(anchor) if anchor is not None else None
    flat = _flatten_named(tree)
    entries = []
    buffers = []
    crc = 0
    for key in sorted(flat):
        arr = flat[key]
        entry = {"k": key, "shape": list(arr.shape), "dtype": arr.dtype.name}
        use_topk = (
            compression == "topk8"
            and arr.dtype.kind == "f"
            and anchor_flat is not None
            and key in anchor_flat
            and arr.size > 16  # tiny tensors: index overhead beats the savings
        )
        if use_topk:
            delta = np.asarray(arr, np.float32).ravel() - np.asarray(
                anchor_flat[key], np.float32
            ).ravel()
            if residual is not None and key in residual:
                delta = delta + residual[key]
            k = max(1, int(np.ceil(arr.size * topk_frac)))
            idx = np.argpartition(np.abs(delta), -k)[-k:].astype(np.uint32)
            idx.sort()
            vals = delta[idx]
            q, scale = native.quantize(vals)
            if residual is not None:
                # error feedback: what this payload fails to carry (dropped
                # coordinates + quantization error) feeds the next round
                sent = np.zeros_like(delta)
                sent[idx] = native.dequantize(q, scale)
                residual[key] = delta - sent
            buf = idx.tobytes() + q.tobytes()
            entry["enc"] = "tk8"
            entry["scale"] = scale
            entry["nnz"] = int(k)
        elif compression in ("int8", "topk8") and arr.dtype.kind == "f":
            q, scale = native.quantize(np.asarray(arr, dtype=np.float32))
            buf = q.tobytes()
            entry["enc"] = "i8"
            entry["scale"] = scale
        else:
            buf = np.ascontiguousarray(arr).tobytes()
        entry["n"] = len(buf)
        crc = native.crc32c(buf, crc)
        entries.append(entry)
        buffers.append(buf)
    head = {"v": _VERSION, "t": entries, "crc": crc}
    if any(e.get("enc") == "tk8" for e in entries):
        head["anchor_tag"] = anchor_tag if anchor_tag is not None else ""
    header = json.dumps(head).encode("utf-8")
    out = bytearray()
    out += _MAGIC
    out += struct.pack("<I", len(header))
    out += header
    for buf in buffers:
        out += buf
    return bytes(out)


def decode_params(
    payload: bytes,
    anchor: Optional[Pytree] = None,
    anchor_tag: Optional[str] = None,
) -> dict[str, np.ndarray]:
    """Decode the wire format to a flat ``{path: array}`` dict.

    Delta-coded (``tk8``) payloads require an ``anchor`` whose round
    identity matches the header's ``anchor_tag``; a mismatch raises
    :class:`AnchorMismatchError` — reconstructing against a different
    round's model would yield silently wrong parameters. Same-round
    anchors may differ slightly across nodes (see :func:`encode_params`);
    that divergence is part of the codec's loss budget.
    """
    try:
        if payload[:4] != _MAGIC:
            raise DecodingParamsError("bad magic — not a p2pfl_tpu weights payload")
        (hlen,) = struct.unpack("<I", payload[4:8])
        header = json.loads(payload[8 : 8 + hlen].decode("utf-8"))
        if header["v"] != _VERSION:
            raise DecodingParamsError(f"unsupported weights version {header['v']}")
        from p2pfl_tpu import native

        anchor_flat = None
        if "anchor_tag" in header:
            if anchor is None:
                raise AnchorMismatchError(
                    "payload is delta-coded (topk8) but no anchor is available"
                )
            if (anchor_tag or "") != header["anchor_tag"]:
                raise AnchorMismatchError(
                    f"anchor round mismatch (local {anchor_tag!r} != payload "
                    f"{header['anchor_tag']!r}) — sender delta-coded against a "
                    "different round's model"
                )
            anchor_flat = _flatten_named(anchor)

        flat = {}
        off = 8 + hlen
        crc = 0
        for e in header["t"]:
            dtype = _resolve_dtype(e["dtype"])
            count = int(np.prod(e["shape"], dtype=np.int64)) if e["shape"] else 1
            if e.get("enc") == "tk8":
                nnz = int(e["nnz"])
                expect = nnz * 5  # uint32 index + int8 value per coordinate
            elif e.get("enc") == "i8":
                expect = count
            else:
                expect = count * dtype.itemsize
            if e["n"] != expect:
                raise DecodingParamsError(f"inconsistent header for {e['k']}: n={e['n']} vs shape {e['shape']}")
            if off + e["n"] > len(payload):
                raise DecodingParamsError(f"truncated payload at {e['k']}")
            crc = native.crc32c(payload[off : off + e["n"]], crc)
            if e.get("enc") == "tk8":
                nnz = int(e["nnz"])
                if anchor_flat is None or e["k"] not in anchor_flat:
                    raise AnchorMismatchError(f"no anchor tensor for delta-coded {e['k']}")
                idx = np.frombuffer(payload, dtype=np.uint32, count=nnz, offset=off)
                q = np.frombuffer(payload, dtype=np.int8, count=nnz, offset=off + nnz * 4)
                if nnz and int(idx.max()) >= count:
                    raise DecodingParamsError(f"index out of range in {e['k']}")
                dense = np.asarray(anchor_flat[e["k"]], np.float32).ravel().copy()
                dense[idx] = dense[idx] + native.dequantize(q, float(e["scale"]))
                arr = dense.astype(dtype)
            elif e.get("enc") == "i8":
                q = np.frombuffer(payload, dtype=np.int8, count=count, offset=off)
                arr = native.dequantize(q, float(e["scale"])).astype(dtype)
            else:
                arr = np.frombuffer(payload, dtype=dtype, count=count, offset=off)
            flat[e["k"]] = arr.reshape(e["shape"])
            off += e["n"]
        if "crc" in header and header["crc"] != crc:
            raise DecodingParamsError(f"CRC mismatch: payload corrupted ({crc} != {header['crc']})")
        return flat
    except (DecodingParamsError, AnchorMismatchError):
        raise
    except Exception as exc:  # noqa: BLE001 — any malformed payload is a decode error
        raise DecodingParamsError(str(exc)) from exc


def _resolve_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def restore_like(template: Pytree, flat: dict[str, np.ndarray]) -> Pytree:
    """Rebuild a pytree with ``template``'s structure from a flat path dict.

    Raises :class:`ModelNotMatchingError` on any structural mismatch — this is
    the check that makes the reference's ``test_wrong_model`` scenario
    (``test/node_test.py:155-176``) fail fast instead of hanging.
    """
    tmpl_flat = _flatten_named(template)
    if set(tmpl_flat) != set(flat):
        missing = set(tmpl_flat) ^ set(flat)
        raise ModelNotMatchingError(f"param paths differ (symmetric diff: {sorted(missing)[:5]}...)")
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves_with_path:
        key = _SEP.join(_path_part(p) for p in path)
        arr = flat[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ModelNotMatchingError(f"shape mismatch at {key}: {arr.shape} vs {np.shape(leaf)}")
        new_leaves.append(jax.numpy.asarray(arr).astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


@dataclass
class ModelUpdate:
    """A model (or partial aggregation of models) moving through the network.

    ``contributors`` is the set of node addresses whose local training is
    already folded into ``params`` — the unit of the reference's
    partial-aggregation algebra (``p2pfl/learning/aggregators/aggregator.py``).
    ``num_samples`` is the total sample weight of those contributors.
    """

    params: Pytree
    contributors: list[str] = field(default_factory=list)
    num_samples: int = 1
    encoded: Optional[bytes] = None  # populated lazily for byte transports
    #: True when this "aggregate" is really the round-start global kept by
    #: a failed secagg recovery (a no-op round) — receivers of a diffusion
    #: must never mistake it for the round's authoritative aggregate, so
    #: GossipModelStage skips outward diffusion when set. Never serialized.
    noop_round: bool = False
    #: True when this update is a FINALIZED (self-mask-free) aggregate a
    #: peer diffused under Bonawitz double masking — set by AddModelCommand
    #: when it strips the ``secagg.CLEAN_MARKER`` pseudo-contributor. A
    #: full-coverage aggregate ASSEMBLED from masked partials is bit-
    #: different from the clean diffusion (the self-mask sum still rides on
    #: it), so the finalize step must know which kind it holds. Travels on
    #: the wire only as the marker, never as a field.
    secagg_clean: bool = False
    #: round-start global model for delta (topk8) wire coding — never
    #: serialized; attached by the learner, inherited through aggregation
    anchor: Optional[Pytree] = None
    anchor_tag: Optional[str] = None  # round identity, e.g. "1:3"
    #: mutable error-feedback store ({path: residual}) — set only on a
    #: node's OWN train-stage contribution (TrainStage attaches it; letting
    #: every diffusion encode write it would clobber the store with
    #: aggregate-encode error) so dropped delta coordinates re-enter the
    #: next round
    ef_residual: Optional[dict] = None

    def encode(self) -> bytes:
        if self.encoded is None:
            self.encoded = encode_params(
                self.params,
                anchor=self.anchor,
                anchor_tag=self.anchor_tag,
                residual=self.ef_residual,
            )
        return self.encoded

    @staticmethod
    def decode(payload: bytes, template: Pytree, contributors: list[str], num_samples: int) -> "ModelUpdate":
        flat = decode_params(payload)
        return ModelUpdate(restore_like(template, flat), list(contributors), num_samples)
