"""Weights containers and the wire codec.

The reference ships weights as a pickle of a list of numpy arrays in
state-dict order and zips them back positionally
(``p2pfl/learning/pytorch/lightning_learner.py:113-138``). Here the payload
is a self-describing binary format: a JSON header with named paths, shapes
and dtypes, followed by raw little-endian buffers. This gives

- name-aware (not positional) matching → architecture mismatch is detected
  structurally, raising :class:`ModelNotMatchingError` instead of silently
  loading wrong layers,
- zero pickle (no arbitrary code execution from the wire),
- native bfloat16 support via ml_dtypes.

On transports that stay in-process (memory, mesh-collective) the pytree is
passed by reference and never hits this codec — weights stay device-resident.
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import numpy as np

from p2pfl_tpu.exceptions import DecodingParamsError, ModelNotMatchingError

Pytree = Any

_MAGIC = b"P2TW"  # p2pfl-tpu weights
_VERSION = 1

_SEP = "/"


def _flatten_named(tree: Pytree) -> dict[str, np.ndarray]:
    """Flatten a pytree (nested dicts / dataclass pytrees) to path->array."""
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = _SEP.join(_path_part(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _path_part(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    if hasattr(p, "name"):
        return str(p.name)
    return str(p)


def encode_params(tree: Pytree, compression: Optional[str] = None) -> bytes:
    """Serialize a params pytree to the self-describing wire format.

    ``compression="int8"`` quantizes float tensors symmetrically per-tensor
    (4x smaller payloads; native C++ hot loop in ``p2pfl_tpu/native`` when
    built). Every payload carries a CRC32C over the tensor bytes; decoding
    verifies it.
    """
    from p2pfl_tpu import native

    if compression is None:
        from p2pfl_tpu.settings import Settings

        compression = Settings.WIRE_COMPRESSION
    flat = _flatten_named(tree)
    entries = []
    buffers = []
    crc = 0
    for key in sorted(flat):
        arr = flat[key]
        entry = {"k": key, "shape": list(arr.shape), "dtype": arr.dtype.name}
        if compression == "int8" and arr.dtype.kind == "f":
            q, scale = native.quantize(np.asarray(arr, dtype=np.float32))
            buf = q.tobytes()
            entry["enc"] = "i8"
            entry["scale"] = scale
        else:
            buf = np.ascontiguousarray(arr).tobytes()
        entry["n"] = len(buf)
        crc = native.crc32c(buf, crc)
        entries.append(entry)
        buffers.append(buf)
    header = json.dumps({"v": _VERSION, "t": entries, "crc": crc}).encode("utf-8")
    out = bytearray()
    out += _MAGIC
    out += struct.pack("<I", len(header))
    out += header
    for buf in buffers:
        out += buf
    return bytes(out)


def decode_params(payload: bytes) -> dict[str, np.ndarray]:
    """Decode the wire format to a flat ``{path: array}`` dict."""
    try:
        if payload[:4] != _MAGIC:
            raise DecodingParamsError("bad magic — not a p2pfl_tpu weights payload")
        (hlen,) = struct.unpack("<I", payload[4:8])
        header = json.loads(payload[8 : 8 + hlen].decode("utf-8"))
        if header["v"] != _VERSION:
            raise DecodingParamsError(f"unsupported weights version {header['v']}")
        from p2pfl_tpu import native

        flat = {}
        off = 8 + hlen
        crc = 0
        for e in header["t"]:
            dtype = _resolve_dtype(e["dtype"])
            count = int(np.prod(e["shape"], dtype=np.int64)) if e["shape"] else 1
            stored_itemsize = 1 if e.get("enc") == "i8" else dtype.itemsize
            if e["n"] != count * stored_itemsize:
                raise DecodingParamsError(f"inconsistent header for {e['k']}: n={e['n']} vs shape {e['shape']}")
            if off + e["n"] > len(payload):
                raise DecodingParamsError(f"truncated payload at {e['k']}")
            crc = native.crc32c(payload[off : off + e["n"]], crc)
            if e.get("enc") == "i8":
                q = np.frombuffer(payload, dtype=np.int8, count=count, offset=off)
                arr = native.dequantize(q, float(e["scale"])).astype(dtype)
            else:
                arr = np.frombuffer(payload, dtype=dtype, count=count, offset=off)
            flat[e["k"]] = arr.reshape(e["shape"])
            off += e["n"]
        if "crc" in header and header["crc"] != crc:
            raise DecodingParamsError(f"CRC mismatch: payload corrupted ({crc} != {header['crc']})")
        return flat
    except DecodingParamsError:
        raise
    except Exception as exc:  # noqa: BLE001 — any malformed payload is a decode error
        raise DecodingParamsError(str(exc)) from exc


def _resolve_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def restore_like(template: Pytree, flat: dict[str, np.ndarray]) -> Pytree:
    """Rebuild a pytree with ``template``'s structure from a flat path dict.

    Raises :class:`ModelNotMatchingError` on any structural mismatch — this is
    the check that makes the reference's ``test_wrong_model`` scenario
    (``test/node_test.py:155-176``) fail fast instead of hanging.
    """
    tmpl_flat = _flatten_named(template)
    if set(tmpl_flat) != set(flat):
        missing = set(tmpl_flat) ^ set(flat)
        raise ModelNotMatchingError(f"param paths differ (symmetric diff: {sorted(missing)[:5]}...)")
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves_with_path:
        key = _SEP.join(_path_part(p) for p in path)
        arr = flat[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ModelNotMatchingError(f"shape mismatch at {key}: {arr.shape} vs {np.shape(leaf)}")
        new_leaves.append(jax.numpy.asarray(arr).astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


@dataclass
class ModelUpdate:
    """A model (or partial aggregation of models) moving through the network.

    ``contributors`` is the set of node addresses whose local training is
    already folded into ``params`` — the unit of the reference's
    partial-aggregation algebra (``p2pfl/learning/aggregators/aggregator.py``).
    ``num_samples`` is the total sample weight of those contributors.
    """

    params: Pytree
    contributors: list[str] = field(default_factory=list)
    num_samples: int = 1
    encoded: Optional[bytes] = None  # populated lazily for byte transports

    def encode(self) -> bytes:
        if self.encoded is None:
            self.encoded = encode_params(self.params)
        return self.encoded

    @staticmethod
    def decode(payload: bytes, template: Pytree, contributors: list[str], num_samples: int) -> "ModelUpdate":
        flat = decode_params(payload)
        return ModelUpdate(restore_like(template, flat), list(contributors), num_samples)
