"""Weights containers and the wire codec.

The reference ships weights as a pickle of a list of numpy arrays in
state-dict order and zips them back positionally
(``p2pfl/learning/pytorch/lightning_learner.py:113-138``). Here the payload
is a self-describing binary format: a JSON header with named paths, shapes
and dtypes, followed by raw little-endian buffers. This gives

- name-aware (not positional) matching → architecture mismatch is detected
  structurally, raising :class:`ModelNotMatchingError` instead of silently
  loading wrong layers,
- zero pickle (no arbitrary code execution from the wire),
- native bfloat16 support via ml_dtypes.

On transports that stay in-process (memory, mesh-collective) the pytree is
passed by reference and never hits this codec — weights stay device-resident
(``Settings.MEMORY_WIRE_CODEC`` opts the memory transport into the byte path
for benching/testing the codec without sockets).

Encode-once, send-many
----------------------
Gossip pushes the SAME model to many peers over many ticks, so the encode
pipeline (flatten → quantize → CRC32C → frame) must run once per *model
version*, not once per send. :class:`PayloadCache` is the content-addressed
store behind that: the learner attaches it (plus its monotone model-version
counter) to every :meth:`ModelUpdate.encode`-able update it hands out, and
``encode()`` keys the bytes on

``(model version, round, wire compression, anchor_tag, error-feedback?)``

The version bumps on ``set_parameters`` / ``fit`` / external residual
mutation, so a stale encode can never be replayed; ``anchor_tag`` is in the
key because topk8 bytes are deltas against a specific round's anchor — the
same params delta-coded against a different anchor are different bytes. The
error-feedback flag isolates the one encode per round that folds (and
mutates) the residual store from residual-free encodes of the same version:
a cache hit on the ``ef`` entry is exactly the "residual folded once per
round" contract (Seide et al. 2014) — repeat sends reuse the bytes instead
of double-folding.
"""

from __future__ import annotations

import json
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import numpy as np

from p2pfl_tpu.exceptions import AnchorMismatchError, DecodingParamsError, ModelNotMatchingError

Pytree = Any

# process-wide encode accounting (bench_gossip reads this): every real run
# of the encode pipeline counts, cache hits don't
_encode_lock = threading.Lock()
_encode_calls = 0
# wire-byte accounting (bench_gossip compression split + per-node comm
# metrics): raw model bytes in, payload bytes out, bytes that actually
# crossed device→host, and which producer ran
_wire_stats = {
    "raw_bytes": 0,
    "payload_bytes": 0,
    "d2h_bytes": 0,
    "host_encodes": 0,
    "device_encodes": 0,
}


def encode_call_count() -> int:
    """Total :func:`encode_params` invocations in this process."""
    with _encode_lock:
        return _encode_calls


def wire_stats() -> dict:
    """Process-wide wire-byte counters (see :func:`encode_params`)."""
    with _encode_lock:
        return dict(_wire_stats)


def reset_wire_stats() -> None:
    with _encode_lock:
        for k in _wire_stats:
            _wire_stats[k] = 0


class PayloadCache:
    """Content-addressed cache of encoded weight payloads (encode-once).

    A small FIFO-bounded map — keys are monotone (the model version only
    grows), so old entries die naturally; the bound only guards against a
    pathological interleave. Hit/miss counters feed the logger's
    communication metrics (``logger.get_comm_metrics``) so the cache's
    effect is observable per node.
    """

    MAX_ENTRIES = 4

    def __init__(self, owner: str = "") -> None:
        self.owner = owner
        self._lock = threading.Lock()
        self._entries: "dict[tuple, bytes]" = {}
        # error-feedback fold ownership per payload content (see
        # ef_fold_once) — separate from _entries so markers can never
        # evict cached payloads
        self._ef_marks: "dict[tuple, None]" = {}
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple) -> Optional[bytes]:
        from p2pfl_tpu.management.logger import logger

        with self._lock:
            cached = self._entries.get(key)
            if cached is not None:
                self.hits += 1
            else:
                self.misses += 1
        logger.log_comm_metric(
            self.owner, "encode_cache_hit" if cached is not None else "encode_cache_miss"
        )
        return cached

    def put(self, key: tuple, payload: bytes) -> None:
        with self._lock:
            self._entries[key] = payload
            while len(self._entries) > self.MAX_ENTRIES:
                self._entries.pop(next(iter(self._entries)))

    def ef_fold_once(self, key: tuple) -> bool:
        """True exactly once per content key — the caller that gets True
        OWNS the error-feedback fold for that payload content; every
        later encoder of the same content (a cache miss on a *different*
        plane's key — the ICI shard encode and the byte encode cache
        under different keys) must encode residual-free instead of
        re-folding the just-written carry as if it were last round's.
        Keys are monotone like payload keys, so the FIFO bound only
        guards a pathological interleave."""
        with self._lock:
            if key in self._ef_marks:
                return False
            self._ef_marks[key] = None
            while len(self._ef_marks) > self.MAX_ENTRIES * 2:
                self._ef_marks.pop(next(iter(self._ef_marks)))
            return True

_MAGIC = b"P2TW"  # p2pfl-tpu weights
_VERSION = 1

_SEP = "/"


def anchor_digest(tree: Pytree) -> int:
    """CRC32C over a pytree's canonical buffer order (sorted paths)."""
    from p2pfl_tpu import native

    flat = _flatten_named(tree)
    crc = 0
    for key in sorted(flat):
        crc = native.crc32c(np.ascontiguousarray(flat[key]).tobytes(), crc)
    return crc


def named_leaves(tree: Pytree):
    """``(treedef, [(canonical path key, leaf), ...])`` in flatten order.

    The single source of the path-key scheme shared by the wire codec and
    secagg masking/recovery — keys built anywhere else would silently stop
    matching if the scheme ever changed.
    """
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return treedef, [
        (_SEP.join(_path_part(p) for p in path), leaf) for path, leaf in leaves_with_path
    ]


def _flatten_named(tree: Pytree) -> dict[str, np.ndarray]:
    """Flatten a pytree (nested dicts / dataclass pytrees) to path->array."""
    return {key: np.asarray(leaf) for key, leaf in named_leaves(tree)[1]}


def _path_part(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    if hasattr(p, "name"):
        return str(p.name)
    return str(p)


def _validate_residual(residual: Optional[dict], eligible_sizes: dict) -> None:
    """Drop stale error-feedback entries IN PLACE before an encode.

    Two staleness modes bit us in production shapes: (a) a tensor changed
    shape between rounds (architecture hot-swap, LoRA merge) — the stored
    flat residual no longer broadcasts against the new delta and the
    encode dies deep inside with a shape error; (b) a key left the topk
    path (compression-mode flip, anchor loss, tensor shrank under the
    size floor) — its residual would sit in the store forever, and worse,
    re-enter stale if the key ever came back. Validation is at use time:
    keep a key only if it is eligible THIS encode and its stored size
    matches the tensor's current size.
    """
    if residual is None:
        return
    for key in list(residual):
        size = eligible_sizes.get(key)
        if size is None or getattr(residual[key], "size", None) != size:
            del residual[key]


def _encode_host(
    named: dict,
    compression: Optional[str],
    anchor_named: Optional[dict],
    topk_plan: dict,
    residual: Optional[dict],
) -> tuple[list, int]:
    """Host (numpy) producer — the bit-format-reference baseline.

    Walks tensors serially: full D2H pull per leaf, ``argpartition`` top-k,
    native C++ quantize. ``topk_plan`` (``{path: budget}``, computed once
    in :func:`encode_params`) is the single source of which tensors are
    delta-coded and at what k. Returns ``(plans, d2h_bytes)`` exactly like
    :func:`p2pfl_tpu.ops.compression.encode_device`; the byte layout per
    tensor is the format contract both producers implement.
    """
    from p2pfl_tpu import native

    plans = []
    d2h = 0
    for key in sorted(named):
        arr = np.asarray(named[key])
        d2h += arr.nbytes
        entry = {"k": key, "shape": list(arr.shape), "dtype": arr.dtype.name}
        if key in topk_plan:
            anchor_arr = np.asarray(anchor_named[key], dtype=np.float32)
            d2h += anchor_arr.nbytes
            delta = np.asarray(arr, np.float32).ravel() - anchor_arr.ravel()
            if residual is not None and key in residual:
                # np.asarray: the store may hold a device-resident carry
                # from a WIRE_COMPRESSION_DEVICE flip — normalize host-side
                delta = delta + np.asarray(residual[key], dtype=np.float32)
            k = topk_plan[key]
            idx = np.argpartition(np.abs(delta), -k)[-k:].astype(np.uint32)
            idx.sort()
            vals = delta[idx]
            q, scale = native.quantize(vals)
            if residual is not None:
                # error feedback: what this payload fails to carry (dropped
                # coordinates + quantization error) feeds the next round
                sent = np.zeros_like(delta)
                sent[idx] = native.dequantize(q, scale)
                residual[key] = delta - sent
            # two pieces, no concat copy: CRC chains across them and the
            # framing loop below writes them back to back
            bufs = (idx.tobytes(), q.tobytes())
            entry["enc"] = "tk8"
            entry["scale"] = scale
            entry["nnz"] = int(k)
        elif compression in ("int8", "topk8") and arr.dtype.kind == "f":
            q, scale = native.quantize(np.asarray(arr, dtype=np.float32))
            bufs = (q.tobytes(),)
            entry["enc"] = "i8"
            entry["scale"] = scale
        else:
            bufs = (np.ascontiguousarray(arr).tobytes(),)
        plans.append((entry, bufs))
    return plans, d2h


def _frame(plans: list, anchor_tag: Optional[str]) -> bytes:
    """Assemble per-tensor plans into the framed payload (shared by both
    producers — one frame layout, one decoder)."""
    from p2pfl_tpu import native

    entries = []
    buffers = []
    crc = 0
    for entry, bufs in plans:
        entry["n"] = sum(len(b) for b in bufs)
        for b in bufs:
            crc = native.crc32c(b, crc)
            buffers.append(b)
        entries.append(entry)
    head = {"v": _VERSION, "t": entries, "crc": crc}
    if any(e.get("enc") == "tk8" for e in entries):
        head["anchor_tag"] = anchor_tag if anchor_tag is not None else ""
    header = json.dumps(head).encode("utf-8")
    # single preallocated frame: sizes are all known here, so the payload is
    # written exactly once instead of growing a bytearray per tensor
    total = 8 + len(header) + sum(len(b) for b in buffers)
    out = bytearray(total)
    out[0:4] = _MAGIC
    struct.pack_into("<I", out, 4, len(header))
    off = 8
    out[off : off + len(header)] = header
    off += len(header)
    for b in buffers:
        out[off : off + len(b)] = b
        off += len(b)
    return bytes(out)


def encode_params(
    tree: Pytree,
    compression: Optional[str] = None,
    anchor: Optional[Pytree] = None,
    anchor_tag: Optional[str] = None,
    residual: Optional[dict] = None,
    owner: Optional[str] = None,
) -> bytes:
    """Serialize a params pytree to the self-describing wire format.

    ``compression="int8"`` quantizes float tensors symmetrically per-tensor
    (4x smaller payloads; native C++ hot loop in ``p2pfl_tpu/native`` when
    built). Every payload carries a CRC32C over the tensor bytes; decoding
    verifies it.

    ``compression="topk8"`` delta-codes against ``anchor`` (the round-start
    global model): per float tensor, keep the top
    ``Settings.TOPK_FRACTION`` coordinates of ``params − anchor`` by
    magnitude, int8-quantized, shipped as (uint32 index, int8 value) pairs
    — ~``0.05 × 5/4`` of the dense float32 bytes at the default fraction.
    ``anchor_tag`` (the round identity ``"epoch:round"``, pinned by the
    stages) rides in the header: the receiver accepts the delta only when
    its own anchor carries the same tag. Anchors of the same round are NOT
    bit-identical across nodes — each node folds its OWN params losslessly
    but its peers' through the lossy wire — so reconstruction tolerates a
    small anchor divergence (same order as the int8 quantization error);
    the tag catches the catastrophic case, delta-coding against a
    different round's model. With no anchor (e.g. the round-0 init model)
    the tensor falls back to dense int8. ``residual`` (a mutable
    {path: array} dict) enables error feedback: the coordinates a round
    drops are added back into the next round's delta instead of being lost
    (Seide et al. 2014; Karimireddy et al. 2019).

    Producer selection: with ``Settings.WIRE_COMPRESSION_DEVICE`` on and
    device-resident params, the delta/EF/top-k/int8 math runs as ONE fused
    jit dispatch (``ops/compression.py``) and only the compressed buffers
    cross device→host — the residual store then carries device arrays
    between rounds. The host numpy path remains the bit-format-compatible
    baseline: both producers emit the same frame layout, and the one
    decoder (:func:`decode_params`) decodes either. Stale residual entries
    (shape changes, keys off the topk path after a mode flip) are dropped
    before every encode. ``owner`` (the node address, threaded through
    :meth:`ModelUpdate.encode`) routes per-node wire-byte counters into
    ``logger.get_comm_metrics``; process-wide totals are always kept
    (:func:`wire_stats`).
    """
    from p2pfl_tpu.settings import Settings

    global _encode_calls
    with _encode_lock:
        _encode_calls += 1

    if compression is None:
        compression = Settings.WIRE_COMPRESSION
    topk_frac = Settings.TOPK_FRACTION if compression == "topk8" else 0.0

    def _named(t: Pytree) -> dict:
        # leaves keep their device residency, but non-array leaves (Python
        # scalars in a params pytree) are normalized exactly like the old
        # _flatten_named did — every leaf downstream has .dtype/.shape
        return {
            key: leaf if hasattr(leaf, "dtype") else np.asarray(leaf)
            for key, leaf in named_leaves(t)[1]
        }

    named = _named(tree)
    anchor_named = _named(anchor) if anchor is not None else None

    # the ONE topk-eligibility predicate + budget AND the one sizing
    # helper, shared by both byte producers and the shard-plane codec
    # (ops/compression.py — drift here would silently wipe valid
    # error-feedback carries or diverge nnz)
    from p2pfl_tpu.ops.compression import build_topk_plan, leaf_size as _size

    topk_plan = build_topk_plan(named, anchor_named, topk_frac)
    _validate_residual(residual, {key: _size(named[key]) for key in topk_plan})

    from p2pfl_tpu.settings import wire_compression_device

    use_device = (
        wire_compression_device()
        and compression in ("int8", "topk8")
        and any(isinstance(leaf, jax.Array) for leaf in named.values())
    )
    if use_device:
        from p2pfl_tpu.ops import compression as device_codec

        plans, d2h = device_codec.encode_device(named, anchor_named, topk_plan, residual)
        producer = "device"
    else:
        plans, d2h = _encode_host(named, compression, anchor_named, topk_plan, residual)
        producer = "host"
    payload = _frame(plans, anchor_tag)

    raw_bytes = sum(_size(leaf) * np.dtype(leaf.dtype).itemsize for leaf in named.values())
    with _encode_lock:
        _wire_stats["raw_bytes"] += raw_bytes
        _wire_stats["payload_bytes"] += len(payload)
        _wire_stats["d2h_bytes"] += d2h
        _wire_stats[f"{producer}_encodes"] += 1
    if owner:
        from p2pfl_tpu.management.logger import logger

        logger.log_comm_metric(owner, "wire_raw_bytes", raw_bytes)
        logger.log_comm_metric(owner, "wire_payload_bytes", len(payload))
        logger.log_comm_metric(owner, "wire_d2h_bytes", d2h)
        logger.log_comm_metric(owner, f"wire_encode_{producer}")
    return payload


def decode_params(
    payload: bytes,
    anchor: Optional[Pytree] = None,
    anchor_tag: Optional[str] = None,
) -> dict[str, np.ndarray]:
    """Decode the wire format to a flat ``{path: array}`` dict.

    Delta-coded (``tk8``) payloads require an ``anchor`` whose round
    identity matches the header's ``anchor_tag``; a mismatch raises
    :class:`AnchorMismatchError` — reconstructing against a different
    round's model would yield silently wrong parameters. Same-round
    anchors may differ slightly across nodes (see :func:`encode_params`);
    that divergence is part of the codec's loss budget.

    One decoder decodes BOTH producers (host and device frames are
    layout-identical). ``tk8`` indices are validated strictly ascending
    and in range — both producers emit them sorted, so a duplicate or
    unsorted index stream is a malformed payload, not a dialect. When
    ``Settings.WIRE_COMPRESSION_DEVICE`` is on and the anchor is
    device-resident, reconstruction runs as one fused scatter-add on
    device (``ops/compression.py``) AFTER the CRC verifies, instead of
    pulling every anchor tensor host-side into a ``.ravel().copy()``.
    """
    try:
        # memoryview slicing: header parse + per-tensor CRC walk the frame
        # without copying tensor bytes (np.frombuffer below is zero-copy too)
        mv = memoryview(payload)
        if bytes(mv[:4]) != _MAGIC:
            raise DecodingParamsError("bad magic — not a p2pfl_tpu weights payload")
        (hlen,) = struct.unpack("<I", mv[4:8])
        header = json.loads(bytes(mv[8 : 8 + hlen]).decode("utf-8"))
        if header["v"] != _VERSION:
            raise DecodingParamsError(f"unsupported weights version {header['v']}")
        from p2pfl_tpu import native

        anchor_flat = None
        if "anchor_tag" in header:
            if anchor is None:
                raise AnchorMismatchError(
                    "payload is delta-coded (topk8) but no anchor is available"
                )
            if (anchor_tag or "") != header["anchor_tag"]:
                raise AnchorMismatchError(
                    f"anchor round mismatch (local {anchor_tag!r} != payload "
                    f"{header['anchor_tag']!r}) — sender delta-coded against a "
                    "different round's model"
                )
            # raw leaves (no np.asarray): a device-resident anchor must
            # reach the device consumer without a host round-trip
            anchor_flat = dict(named_leaves(anchor)[1])

        from p2pfl_tpu.settings import wire_compression_device

        device_consume = wire_compression_device()
        flat = {}
        deferred: list = []  # tk8 entries reconstructed on device post-CRC
        off = 8 + hlen
        crc = 0
        for e in header["t"]:
            dtype = _resolve_dtype(e["dtype"])
            count = int(np.prod(e["shape"], dtype=np.int64)) if e["shape"] else 1
            if e.get("enc") == "tk8":
                nnz = int(e["nnz"])
                expect = nnz * 5  # uint32 index + int8 value per coordinate
            elif e.get("enc") == "i8":
                expect = count
            else:
                expect = count * dtype.itemsize
            if e["n"] != expect:
                raise DecodingParamsError(f"inconsistent header for {e['k']}: n={e['n']} vs shape {e['shape']}")
            if off + e["n"] > len(payload):
                raise DecodingParamsError(f"truncated payload at {e['k']}")
            crc = native.crc32c(mv[off : off + e["n"]], crc)
            if e.get("enc") == "tk8":
                nnz = int(e["nnz"])
                if anchor_flat is None or e["k"] not in anchor_flat:
                    raise AnchorMismatchError(f"no anchor tensor for delta-coded {e['k']}")
                idx = np.frombuffer(payload, dtype=np.uint32, count=nnz, offset=off)
                q = np.frombuffer(payload, dtype=np.int8, count=nnz, offset=off + nnz * 4)
                if nnz and int(idx.max()) >= count:
                    raise DecodingParamsError(f"index out of range in {e['k']}")
                # both producers emit strictly ascending indices per tensor;
                # anything else (duplicates, unsorted, nnz > count) is a
                # malformed payload — and the device scatter-ADD relies on
                # uniqueness to match the host reconstruction
                if nnz > 1 and np.any(np.diff(idx.astype(np.int64)) <= 0):
                    raise DecodingParamsError(
                        f"duplicate or unsorted indices in {e['k']}"
                    )
                anchor_leaf = anchor_flat[e["k"]]
                # the device scatter indexes per tensor in int32; a tensor
                # beyond int32 index space (>2^31−1 elements) falls back to
                # the host consumer's uint32 path
                if (
                    device_consume
                    and isinstance(anchor_leaf, jax.Array)
                    and count <= np.iinfo(np.int32).max
                ):
                    deferred.append(
                        (
                            e["k"],
                            anchor_leaf,
                            idx,
                            native.dequantize(q, float(e["scale"])),
                            tuple(e["shape"]),
                            dtype,
                        )
                    )
                    off += e["n"]
                    continue
                dense = np.asarray(anchor_leaf, np.float32).ravel().copy()
                dense[idx] = dense[idx] + native.dequantize(q, float(e["scale"]))
                arr = dense.astype(dtype)
            elif e.get("enc") == "i8":
                q = np.frombuffer(payload, dtype=np.int8, count=count, offset=off)
                arr = native.dequantize(q, float(e["scale"])).astype(dtype)
            else:
                arr = np.frombuffer(payload, dtype=dtype, count=count, offset=off)
            flat[e["k"]] = arr.reshape(e["shape"])
            off += e["n"]
        if "crc" in header and header["crc"] != crc:
            raise DecodingParamsError(f"CRC mismatch: payload corrupted ({crc} != {header['crc']})")
        if deferred:
            from p2pfl_tpu.ops import compression as device_codec

            flat.update(device_codec.decode_tk8_device(deferred))
        return flat
    except (DecodingParamsError, AnchorMismatchError):
        raise
    except Exception as exc:  # noqa: BLE001 — any malformed payload is a decode error
        raise DecodingParamsError(str(exc)) from exc


def _resolve_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def restore_like(template: Pytree, flat: dict[str, np.ndarray]) -> Pytree:
    """Rebuild a pytree with ``template``'s structure from a flat path dict.

    Raises :class:`ModelNotMatchingError` on any structural mismatch — this is
    the check that makes the reference's ``test_wrong_model`` scenario
    (``test/node_test.py:155-176``) fail fast instead of hanging.
    """
    tmpl_flat = _flatten_named(template)
    if set(tmpl_flat) != set(flat):
        missing = set(tmpl_flat) ^ set(flat)
        raise ModelNotMatchingError(f"param paths differ (symmetric diff: {sorted(missing)[:5]}...)")
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves_with_path:
        key = _SEP.join(_path_part(p) for p in path)
        arr = flat[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ModelNotMatchingError(f"shape mismatch at {key}: {arr.shape} vs {np.shape(leaf)}")
        new_leaves.append(jax.numpy.asarray(arr).astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


@dataclass
class ModelUpdate:
    """A model (or partial aggregation of models) moving through the network.

    ``contributors`` is the set of node addresses whose local training is
    already folded into ``params`` — the unit of the reference's
    partial-aggregation algebra (``p2pfl/learning/aggregators/aggregator.py``).
    ``num_samples`` is the total sample weight of those contributors.
    """

    params: Pytree
    contributors: list[str] = field(default_factory=list)
    num_samples: int = 1
    encoded: Optional[bytes] = None  # populated lazily for byte transports
    #: True when this "aggregate" is really the round-start global kept by
    #: a failed secagg recovery (a no-op round) — receivers of a diffusion
    #: must never mistake it for the round's authoritative aggregate, so
    #: GossipModelStage skips outward diffusion when set. Never serialized.
    noop_round: bool = False
    #: True when this update is a FINALIZED (self-mask-free) aggregate a
    #: peer diffused under Bonawitz double masking — set by AddModelCommand
    #: when it strips the ``secagg.CLEAN_MARKER`` pseudo-contributor. A
    #: full-coverage aggregate ASSEMBLED from masked partials is bit-
    #: different from the clean diffusion (the self-mask sum still rides on
    #: it), so the finalize step must know which kind it holds. Travels on
    #: the wire only as the marker, never as a field.
    secagg_clean: bool = False
    #: round-start global model for delta (topk8) wire coding — never
    #: serialized; attached by the learner, inherited through aggregation
    anchor: Optional[Pytree] = None
    anchor_tag: Optional[str] = None  # round identity, e.g. "1:3"
    #: mutable error-feedback store ({path: residual}) — set only on a
    #: node's OWN train-stage contribution (TrainStage attaches it; letting
    #: every diffusion encode write it would clobber the store with
    #: aggregate-encode error) so dropped delta coordinates re-enter the
    #: next round
    ef_residual: Optional[dict] = None
    #: device-resident partial-aggregation accumulator ``(psum, wsum)``:
    #: ``psum`` is the fp32 pytree ``num_samples × params`` already folded
    #: INSIDE the fused round dispatch (``parallel/spmd.py``
    #: ``fused_node_round``), ``wsum`` the matching fp32 sample weight.
    #: Set only on a node's OWN fused train-stage contribution; FedAvg's
    #: aggregate starts its weighted fold from it instead of re-casting and
    #: re-weighting the trained params, so the Train→Aggregate seam carries
    #: device arrays end to end. Never serialized, never set on wire
    #: updates, dropped by aggregation results and secagg masking.
    partial_acc: Optional[tuple] = None
    #: async-federation version triple ``(origin, seq, base_version)``
    #: (``federation/staleness.py`` UpdateVersion): ``origin`` is the
    #: producing node, ``seq`` its monotone per-node update counter (the
    #: receiver-side version vector dedups on it — duplicate/reordered
    #: delivery, e.g. FaultPlan duplicates, can never double-merge), and
    #: ``base_version`` the global model version the update was trained
    #: FROM (the aggregator computes staleness τ = current − base with no
    #: global clock). OPTIONAL wire field, same backward-compat pattern
    #: as the telemetry ``trace_ctx``: serialized as ``"vv"`` in the gRPC
    #: envelope header only when set, absent frames decode unchanged, and
    #: the protobuf interop schema never carries it. Unused (None) by the
    #: sync round FSM.
    version: Optional[tuple] = None
    #: experiment identity (the fleet-wide id minted by the start_learning
    #: initiator): OPTIONAL wire field serialized as ``"xp"`` in the gRPC
    #: envelope header, same backward-compat pattern as ``"vv"``/``"tc"``
    #: (absent frames decode unchanged; the protobuf interop schema never
    #: carries it). Receivers filter cross-experiment stash/drain
    #: stragglers on it EXACTLY — ``Node.take_async_stash`` /
    #: ``take_early_init`` fall back to the TTL + epoch heuristics only
    #: for frames from old senders that lack it.
    xp: Optional[str] = None
    #: shard-plane handshake triple ``(slice_shape, slice_index, codec)``
    #: (``communication/ici.py``): the sender's slice topology — the
    #: devices-array shape of its submesh (or ``(1,)`` for a single-chip
    #: node), its slot on the global mesh's nodes axis (-1 when unknown)
    #: and the codec tag its shard payloads use. OPTIONAL wire field
    #: serialized as ``"sp"`` in the gRPC envelope header, same
    #: backward-compat pattern as ``"vv"``/``"xp"`` (absent frames decode
    #: unchanged; the protobuf interop schema never carries it). Stamped
    #: by ``protocol.build_weights`` whenever the sending node has a
    #: registered shard-plane endpoint — including on BYTE-path fallback
    #: frames to non-colocated peers, which is what makes it a handshake:
    #: the receiver learns the sender's slice topology from ordinary
    #: frames and can validate co-location before any shard transfer.
    sp: Optional[tuple] = None
    #: encode-once plumbing (module docstring) — the learner's shared
    #: :class:`PayloadCache` plus its model-version counter at the time
    #: this update was handed out; ``cache_round`` is stamped by
    #: ``protocol.build_weights``. None ⇒ encode() bypasses the cache.
    #: Never serialized.
    payload_cache: Optional["PayloadCache"] = None
    cache_version: Optional[int] = None
    cache_round: Optional[int] = None
    #: serializes encode(): the concurrent send fan-out may encode the same
    #: instance from several worker threads, and an error-feedback encode
    #: mutates the residual store — exactly once, under this lock
    _encode_lock: threading.Lock = field(default_factory=threading.Lock, repr=False, compare=False)

    def ef_fold_key(self, compression: str) -> tuple:
        """The ONE cross-plane error-feedback fold-ownership key.

        Both encoders of this update's content — the byte path
        (:meth:`encode`) and the ICI shard plane
        (``communication/ici.py``) — claim the fold through
        ``PayloadCache.ef_fold_once`` with exactly this tuple; building
        it anywhere else risks the keys drifting apart, which would
        silently re-arm the fold on the second plane (the double-apply
        bug the mechanism exists to prevent).
        """
        return (self.cache_version, self.cache_round, compression, self.anchor_tag)

    def encode(self) -> bytes:
        with self._encode_lock:
            return self._encode_locked()

    def _encode_locked(self) -> bytes:
        if self.encoded is not None:
            return self.encoded
        from p2pfl_tpu.settings import Settings, wire_compression_device

        cache = self.payload_cache
        key = None
        if (
            cache is not None
            and self.cache_version is not None
            and Settings.GOSSIP_PAYLOAD_CACHE
        ):
            key = (
                self.cache_version,
                self.cache_round,
                Settings.WIRE_COMPRESSION,
                # RESOLVED producer flag: device and host bytes decode
                # identically but differ at quantization-tie level — never
                # mix them in one key
                wire_compression_device(),
                self.anchor_tag,
                self.ef_residual is not None,
            )
            cached = cache.get(key)
            if cached is not None:
                self.encoded = cached
                return cached
        residual = self.ef_residual
        if residual is not None and cache is not None and self.cache_version is not None:
            # cross-PLANE fold ownership: the ICI shard encode and the
            # byte encode cache under different keys, so a cache miss
            # here does not mean the residual is unfolded — whichever
            # plane encoded this content first owns the fold, and the
            # other encodes residual-free (re-folding the just-written
            # carry would double-apply it)
            if not cache.ef_fold_once(self.ef_fold_key(Settings.WIRE_COMPRESSION)):
                residual = None
        self.encoded = encode_params(
            self.params,
            anchor=self.anchor,
            anchor_tag=self.anchor_tag,
            residual=residual,
            owner=cache.owner if cache is not None else None,
        )
        if key is not None:
            cache.put(key, self.encoded)
        return self.encoded

    @staticmethod
    def decode(payload: bytes, template: Pytree, contributors: list[str], num_samples: int) -> "ModelUpdate":
        flat = decode_params(payload)
        return ModelUpdate(restore_like(template, flat), list(contributors), num_samples)
