"""Aggregator base: partial-aggregation bookkeeping around a pure kernel.

Re-implements the semantics of the reference's state machine
(``p2pfl/learning/aggregators/aggregator.py:37-281``) with the lock-as-event
pattern replaced by a real :class:`threading.Event`:

- ``set_nodes_to_aggregate(train_set)`` opens the round's collection window.
- ``add_model(update)`` accepts a model or partial aggregation:
  * a full-coverage update replaces everything collected so far
    (reference 156-168),
  * a contributor-disjoint update accumulates (170-185),
  * overlapping / foreign / duplicate contributors are rejected (187-198),
  * in *waiting* mode (non-train-set nodes) the first update IS the result
    (139-146).
- ``wait_and_get_aggregation()`` blocks until coverage is complete or
  ``Settings.AGGREGATION_TIMEOUT``, then aggregates whatever arrived.
- ``get_partial_aggregation(except_nodes)`` pre-aggregates everything a peer
  has not seen (249-281) — the payload of train-set gossip.
- ``discard_member(addr)`` — mid-round train-set repair (no reference
  equivalent): an evicted member that never contributed is dropped from the
  round's coverage target, so the window closes on the survivors instead of
  waiting out the full timeout for a model that is never coming.

Subclasses implement one pure function, :meth:`aggregate`, over a list of
:class:`ModelUpdate` — typically a single jitted op from ``ops/aggregation``.
"""

from __future__ import annotations

import threading
from typing import Optional

from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.settings import Settings


class Aggregator:
    """Base aggregation strategy + round collection state."""

    #: False for strategies (Krum, median, ...) that need the individual
    #: models and therefore must not be fed pre-averaged partials.
    SUPPORTS_PARTIALS: bool = True
    #: True for stateful strategies (FedOpt) whose :meth:`aggregate` must run
    #: exactly once per round even when a single update covers the train set
    #: (the single-model shortcut would skip the server step).
    ALWAYS_AGGREGATE: bool = False
    #: True only for strategies that are linear in the contributions, so
    #: secure-aggregation pairwise masks cancel through them
    #: (``learning/secagg.py``). Robust strategies inspect individual
    #: models and would operate on masked noise.
    MASK_COMPATIBLE: bool = False

    def __init__(self, node_name: str = "unknown") -> None:
        self.node_name = node_name
        #: Byzantine admission screen (federation/defense.py) — attached
        #: by the owning Node; inert while None / Settings.BYZ_SCREEN off
        self.defense = None
        #: what contributions are screened AGAINST: the round-start
        #: params the stage pins via :meth:`set_screen_reference`
        self._screen_ref = None
        self._lock = threading.Lock()
        self._complete = threading.Event()
        self._complete.set()  # no aggregation in progress
        self._train_set: list[str] = []
        self._waiting: bool = False
        #: mid-round train-set repair (``discard_member``): members evicted
        #: from the overlay before contributing. The coverage TARGET is
        #: ``train_set - removed`` while the foreign-contributor check stays
        #: against the full original train set (a removed member's update
        #: that did reach a peer remains aggregatable).
        self._removed: set[str] = set()
        self._models: dict[frozenset, ModelUpdate] = {}
        # gossip ships the same partial to several peers per tick: memoize
        # the combined update per exact set of source groups, so the
        # (stateless) aggregate — and, downstream, its wire encode via the
        # returned instance's byte cache — runs once, not once per
        # candidate. Invalidated whenever the collected model set changes;
        # the generation counter keeps an aggregate computed against a
        # superseded model set from being inserted after an invalidation.
        self._partial_memo: dict[frozenset, ModelUpdate] = {}
        self._memo_gen = 0

    # ---- round lifecycle ----

    def set_nodes_to_aggregate(self, nodes: list[str]) -> None:
        if not self._complete.is_set():
            raise Exception(f"({self.node_name}) aggregation already in progress")
        with self._lock:
            self._train_set = list(nodes)
            self._waiting = False
            self._removed = set()
            self._models = {}
            self._partial_memo = {}
            self._memo_gen += 1
            self._complete.clear()

    def set_screen_reference(self, params) -> None:
        """Pin the round-start global the admission screen compares
        contributions against (``federation/defense.py``) — set by
        TrainStage before the collection window opens; by-reference, no
        copy. The async plane's buffers screen against their own current
        params instead."""
        self._screen_ref = params

    def set_waiting_aggregated_model(self, nodes: list[str]) -> None:
        """Non-train-set path: accept the first incoming update as the result.

        Reference: ``aggregator.py`` waiting path + ``wait_agg_models_stage.py:48``.
        """
        with self._lock:
            self._train_set = list(nodes)
            self._waiting = True
            # waiting mode accepts only the full aggregate — no screen
            # (and a stale reference from a previous round this node DID
            # train must not reject the real result)
            self._screen_ref = None
            self._removed = set()
            self._models = {}
            self._partial_memo = {}
            self._memo_gen += 1
            self._complete.clear()

    def clear(self) -> None:
        with self._lock:
            self._train_set = []
            self._waiting = False
            self._screen_ref = None
            self._removed = set()
            self._models = {}
            self._partial_memo = {}
            self._memo_gen += 1
            self._complete.set()

    def reset_experiment(self) -> None:
        """Experiment boundary: drop cross-ROUND strategy state.

        The per-round :meth:`clear` deliberately keeps state that persists
        across rounds (FedOpt moments, CenteredClip's center); a new
        experiment must not inherit it — round 0 would otherwise be
        server-stepped/clipped against the PREVIOUS experiment's final
        model. Called at experiment START (StartLearningStage — the
        authoritative reset) and on stop-learning (``node.py``); a
        naturally-finished experiment does NOT reset, so the final strategy
        state stays inspectable after the run.
        """

    # ---- collection ----

    def get_aggregated_models(self) -> list[str]:
        """Names of all contributors currently folded into collected models."""
        with self._lock:
            return sorted({c for key in self._models for c in key})

    def add_model(self, update: ModelUpdate, source: Optional[str] = None) -> list[str]:
        """Add a model/partial. Returns the updated contributor coverage list.

        An empty return means the update was rejected (duplicate, overlapping,
        foreign contributor, screened out, or no collection window open).

        ``source`` is the DELIVERING peer (the wire envelope's sender) —
        used only for Byzantine screen attribution: gossip relays other
        nodes' models verbatim, so a corrupted payload indicts the link
        that delivered it, not the contributor named inside it (a lying
        sender could otherwise frame an honest origin). Screen-enabled
        receivers never store a rejected payload, so honest nodes never
        relay poison and the attribution converges on the attacker.

        Accepts fully DEVICE-RESIDENT contributions: ``update.params`` may
        be uncommitted jax arrays (futures of an in-flight dispatch) and
        the node's own fused-round contribution additionally carries
        ``update.partial_acc`` — the fp32 accumulator the train dispatch
        already folded. Nothing here forces a host sync; collection is
        pure bookkeeping, and the fold happens in the aggregate kernels.
        """
        contributors = frozenset(update.contributors)
        if not contributors:
            logger.debug(self.node_name, "Rejecting model with no contributors")
            return []
        if not self.SUPPORTS_PARTIALS and update.partial_acc is not None:
            # the fused round's (psum, wsum) accumulator is pre-averaged
            # state: silently folding it would hand a robust aggregate
            # exactly the poisoned-mean input its SUPPORTS_PARTIALS=False
            # contract exists to refuse — fail LOUDLY instead (the stages
            # strip partial_acc for robust strategies before this seam,
            # so reaching here is a caller bug, not a runtime condition)
            raise ValueError(
                f"({self.node_name}) {type(self).__name__} declares "
                "SUPPORTS_PARTIALS=False but was handed a partial_acc-folded "
                "contribution — robust aggregation needs the individual "
                "model, not the fused-round accumulator; strip partial_acc "
                "or use the staged path"
            )
        if (
            self.defense is not None
            and self._screen_ref is not None
            and update.params is not None
            and not Settings.SECURE_AGGREGATION  # masked updates are
            # DESIGNED to look like noise; only their sum is meaningful
        ):
            origin = source if source is not None else next(iter(contributors))
            if not self.defense.admit(origin, update.params, self._screen_ref):
                # screened out (federation/defense.py counts screen_reject
                # / byz_quarantined_drop); rejection, not an error — the
                # suspicion EWMA decides whether this origin is evicted
                return []
        with self._lock:
            if self._waiting:
                # only a FULL-train-set aggregate is acceptable while waiting
                # (reference aggregator.py:139-146 requires
                # set(contributors) == set(train_set)); accepting a stray
                # partial would make one node's single model this node's
                # "aggregated model" — a poisoning hole. With mid-round
                # repair the target interval widens: after members died
                # (``_removed``), a survivors-only aggregate counts as full
                # — but anything below the repaired target, or naming
                # foreign contributors, stays rejected.
                target = frozenset(self._train_set) - self._removed
                if not target:
                    # mid-round repair evicted EVERY member: an empty target
                    # would accept any subset — a lone survivor's partial
                    # must not become this node's "aggregated model". Fall
                    # back to the strict full-coverage requirement (a
                    # post-partition-heal full aggregate still passes).
                    target = frozenset(self._train_set)
                if not (target <= contributors <= frozenset(self._train_set)):
                    logger.debug(
                        self.node_name,
                        f"Rejecting model while waiting: coverage {sorted(contributors)} "
                        f"outside [{sorted(target)}, {sorted(self._train_set)}]",
                    )
                    return []
                if self._models:  # first full update wins
                    logger.debug(self.node_name, "Rejecting model: already received while waiting")
                    return []
                self._models = {contributors: update}
                self._partial_memo = {}
                self._memo_gen += 1
                self._complete.set()
                return list(update.contributors)

            if self._complete.is_set():
                logger.debug(self.node_name, "Rejecting model: no aggregation in progress")
                return []

            train = set(self._train_set)
            if not contributors <= train:
                logger.debug(
                    self.node_name,
                    f"Rejecting model with foreign contributors {sorted(contributors - train)}",
                )
                return []

            if not self.SUPPORTS_PARTIALS and len(contributors) > 1 and contributors != train:
                # a pre-averaged partial would poison a robust aggregate
                logger.debug(
                    self.node_name,
                    f"Rejecting partial aggregation {sorted(contributors)}: "
                    f"{type(self).__name__} needs individual models",
                )
                return []

            if contributors == train:
                # full-coverage update replaces everything (reference 156-168)
                self._models = {contributors: update}
                self._partial_memo = {}
                self._memo_gen += 1
                self._complete.set()
                return sorted(train)

            covered = {c for key in self._models for c in key}
            if contributors & covered:
                logger.debug(
                    self.node_name,
                    f"Rejecting overlapping model {sorted(contributors)} (covered: {sorted(covered)})",
                )
                return []

            self._models[contributors] = update
            self._partial_memo = {}
            self._memo_gen += 1
            covered |= contributors
            if covered >= train - self._removed:
                # the target excludes members repaired out mid-round —
                # survivors' coverage closes the window without them
                self._complete.set()
            return sorted(covered)

    def discard_member(self, addr: str) -> Optional[list[str]]:
        """Mid-round train-set repair: ``addr`` was evicted from the overlay.

        If its contribution has not arrived, shrink the round's coverage
        TARGET to the surviving members so :meth:`wait_and_get_aggregation`
        resolves to the survivors' partial as soon as they are all in,
        instead of burning the remaining ``AGGREGATION_TIMEOUT`` on a model
        that is never coming (the reference's graceful-degradation path,
        made proactive). A contribution that already arrived is KEPT — the
        member's training happened; only its absence is repaired.

        Returns the current coverage list when the caller should
        re-broadcast ``models_aggregated`` (collection target changed on a
        collecting node), else None. Never called under
        ``SECURE_AGGREGATION`` (see ``Settings.TRAIN_SET_REPAIR``): there
        the aggregate still carries the dead member's uncancelled pair
        masks and secagg's seed-recovery machinery owns the dropout.
        """
        with self._lock:
            if addr not in self._train_set or addr in self._removed:
                return None
            if self._complete.is_set() and not self._waiting:
                return None  # no collection window open — nothing to repair
            covered = {c for key in self._models for c in key}
            if addr in covered:
                logger.debug(
                    self.node_name,
                    f"Train-set member {addr} evicted but already contributed — keeping",
                )
                return None
            self._removed.add(addr)
            target = set(self._train_set) - self._removed
            logger.log_comm_metric(self.node_name, "train_set_repair")
            logger.warning(
                self.node_name,
                f"Train-set repair: {addr} evicted before contributing — "
                f"coverage target shrunk to {sorted(target)}",
            )
            if self._waiting:
                return None  # acceptance interval widened; nothing to announce
            if covered and covered >= target:
                self._complete.set()
            return sorted(covered)

    # ---- results ----

    def wait_and_get_aggregation(self, timeout: Optional[float] = None) -> ModelUpdate:
        """Block until coverage completes (or timeout), then aggregate."""
        timeout = Settings.AGGREGATION_TIMEOUT if timeout is None else timeout
        finished = self._complete.wait(timeout=timeout)
        with self._lock:
            models = list(self._models.values())
            train = set(self._train_set)
            waiting = self._waiting
            # close the collection window: late updates for this round are
            # rejected and the next set_nodes_to_aggregate() will not raise
            self._complete.set()
        if not models:
            raise Exception(f"({self.node_name}) aggregation produced no models (timeout={not finished})")
        if not finished:
            covered = {c for m in models for c in m.contributors}
            logger.info(
                self.node_name,
                f"Aggregation timeout — proceeding with partial coverage {sorted(covered)} of {sorted(train)}",
            )
            if Settings.SECURE_AGGREGATION and covered != train:
                # pairwise masks only cancel over the FULL train set; the
                # missing members' masks still ride on this aggregate. The
                # stage must run seed-disclosure recovery before applying it
                # (GossipModelStage._secagg_finalize, learning/secagg.py).
                logger.warning(
                    self.node_name,
                    "SecAgg: partial coverage — unresolved pairwise masks; "
                    "attempting dropout recovery",
                )
        # a single model is returned as-is when (a) this node is waiting,
        # (b) the strategy is stateless, or (c) it is a full multi-node
        # aggregate a faster train-set peer diffused (already
        # server-stepped — re-aggregating would double-step); on_result
        # lets stateful strategies resync to the consensus model
        if len(models) == 1 and (
            waiting or not self.ALWAYS_AGGREGATE or len(models[0].contributors) > 1
        ):
            return self.on_result(models[0])
        from p2pfl_tpu.management.profiling import dispatch_span

        with dispatch_span("aggregate", self.node_name, n_models=len(models)):
            result = self.aggregate(models)
        return self._inherit_anchor(result, models)

    @staticmethod
    def _inherit_anchor(result: ModelUpdate, models: list[ModelUpdate]) -> ModelUpdate:
        """Carry the delta-coding anchor through aggregation.

        All of a round's updates share one anchor (the round-start global,
        ``learning/weights.py`` topk8), so a fresh aggregate re-encodes
        against the same anchor when it goes back on the wire.
        """
        if result.anchor is None and models and models[0].anchor is not None:
            result.anchor = models[0].anchor
            result.anchor_tag = models[0].anchor_tag
        return result

    def on_result(self, update: ModelUpdate) -> ModelUpdate:
        """Hook: the round resolved to ``update`` WITHOUT this node running
        :meth:`aggregate` (waiting mode, or a peer's finished aggregate
        arrived first). Stateful strategies resync their server state here."""
        return update

    def get_partial_aggregation(self, except_nodes: list[str]) -> Optional[ModelUpdate]:
        """Aggregate collected models not already covered by ``except_nodes``.

        For strategies without partial support this returns None when more
        than one model would need combining — use :meth:`get_models_to_send`.
        """
        todo = self._models_not_covered(except_nodes)
        if not todo:
            return None
        if len(todo) == 1:
            return todo[0]
        if not self.SUPPORTS_PARTIALS:
            return None
        return self._memoized_aggregate(todo)

    def get_models_to_send(self, except_nodes: list[str]) -> list[ModelUpdate]:
        """Payloads to gossip to a peer that already covers ``except_nodes``.

        Partial-supporting strategies send one pre-aggregated update; robust
        strategies send the individual models so the receiver can aggregate
        them itself.
        """
        todo = self._models_not_covered(except_nodes)
        if not todo:
            return []
        if self.SUPPORTS_PARTIALS and len(todo) > 1:
            return [self._memoized_aggregate(todo)]
        return todo

    def _memoized_aggregate(self, todo: list[ModelUpdate]) -> ModelUpdate:
        """One combined update per distinct set of source groups.

        Only reached from the partial-gossip getters, whose strategies are
        stateless partial-supporting ones (``SUPPORTS_PARTIALS=False``
        families never get here), so re-using the combined result is pure
        memoization — and returning the SAME instance lets its encoded
        bytes be reused across every candidate it is sent to.
        """
        memo_key = frozenset(frozenset(m.contributors) for m in todo)
        with self._lock:
            hit = self._partial_memo.get(memo_key)
            gen = self._memo_gen
        if hit is not None:
            return hit
        from p2pfl_tpu.management.profiling import dispatch_span

        with dispatch_span("aggregate", self.node_name, n_models=len(todo)):
            aggregated = self.aggregate(todo)
        result = self._inherit_anchor(aggregated, todo)
        with self._lock:
            if self._memo_gen == gen:  # collected set unchanged since read
                self._partial_memo[memo_key] = result
        return result

    def _models_not_covered(self, except_nodes: list[str]) -> list[ModelUpdate]:
        skip = set(except_nodes)
        with self._lock:
            return [m for key, m in self._models.items() if not (key & skip)]

    # ---- strategy ----

    def aggregate(self, models: list[ModelUpdate]) -> ModelUpdate:
        raise NotImplementedError
