"""FedAvg: sample-weighted mean (McMahan et al. 2017).

Reference: ``p2pfl/learning/aggregators/fedavg.py:28-60`` (a Python loop over
state-dict layers). Here: one jitted weighted-mean over the stacked pytree —
and when the round ran fused (``Settings.ROUND_FUSED``), the node's own
contribution arrives as a device-resident fp32 accumulator
(:attr:`~p2pfl_tpu.learning.weights.ModelUpdate.partial_acc`, folded inside
the train dispatch), so aggregation starts from it and only folds the peers:
the Train→Aggregate seam never re-casts or re-weights the own params.
"""

from __future__ import annotations

import jax.numpy as jnp

from p2pfl_tpu.learning.aggregators.aggregator import Aggregator
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.ops.aggregation import fedavg, fedavg_fold_acc
from p2pfl_tpu.ops.tree import tree_align_copy_count, tree_align_devices, tree_stack
from p2pfl_tpu.settings import Settings


class FedAvg(Aggregator):
    SUPPORTS_PARTIALS = True
    MASK_COMPATIBLE = True  # linear: secagg pairwise masks cancel through it

    def aggregate(self, models: list[ModelUpdate]) -> ModelUpdate:
        align_before = tree_align_copy_count()
        try:
            return self._aggregate(models)
        finally:
            # per-node visibility of D2D fix-up copies: the ICI weights
            # plane's deliveries must contribute ZERO here (they arrive
            # already on this node's shardings) while the zero-copy
            # memory transport's cross-slice contributions still count
            # theirs — the bench reads exactly this metric
            copies = tree_align_copy_count() - align_before
            if copies:
                logger.log_comm_metric(self.node_name, "tree_align_copies", copies)

    def _aggregate(self, models: list[ModelUpdate]) -> ModelUpdate:
        contributors = sorted({c for m in models for c in m.contributors})
        total = sum(m.num_samples for m in models)
        own = next((m for m in models if m.partial_acc is not None), None)
        if own is not None:
            # fused-round seam: continue the in-dispatch fp32 fold instead
            # of restacking; the accumulator is read, never donated — the
            # memoized partial getters reuse it across peer-coverage sets
            others = [m for m in models if m is not own]
            psum, wsum = own.partial_acc
            params = fedavg_fold_acc(
                psum,
                wsum,
                # zero-copy in-memory peers may sit on ANOTHER submesh
                # learner's device slice — align to the own accumulator's
                # placement before the fold jit sees them
                tuple(tree_align_devices(m.params, own.params) for m in others),
                jnp.asarray([float(m.num_samples) for m in others], jnp.float32),
                own.params,
                Settings.AGG_DTYPE,
            )
            return ModelUpdate(params, contributors, total)
        stacked = tree_stack(
            [tree_align_devices(m.params, models[0].params) for m in models]
        )
        weights = jnp.asarray([float(m.num_samples) for m in models])
        params = fedavg(stacked, weights, Settings.AGG_DTYPE)
        return ModelUpdate(params, contributors, total)
