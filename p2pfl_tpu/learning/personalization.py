"""Personalized federated learning: federate the body, keep the head local.

FedPer (Arivazhagan et al. 2019): each node trains the FULL model locally,
but only the shared *body* parameters enter aggregation; the *personal*
subtrees (typically the classification head) never leave the node. Under
heterogeneous (non-IID) shards this lets every node keep a head fitted to
its own label distribution while still pooling feature learning.

The reference has no personalization (FedAvg over whole state dicts only,
``p2pfl/learning/aggregators/fedavg.py``). Here it rides the existing
seams: :meth:`get_model_update` ships the body subtree,
:meth:`set_parameters` merges an incoming body with the local personal
leaves, and :meth:`materialize` decodes wire payloads against the body
template — so every transport, codec (int8/topk8), aggregator, and the
whole round FSM work unchanged.
"""

from __future__ import annotations

from typing import Any, Iterable

import jax

from p2pfl_tpu.exceptions import ModelNotMatchingError
from p2pfl_tpu.learning.learner import JaxLearner
from p2pfl_tpu.learning.weights import (
    ModelUpdate,
    _SEP,
    _flatten_named,
    _path_part,
    decode_params,
    restore_like,
)

Pytree = Any


def _split(params: Pytree, personal: tuple[str, ...]):
    """(body, personal) leaf masks by flattened-path prefix match."""
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(params)
    body_flags = []
    for path, _leaf in leaves_with_path:
        key = _SEP.join(_path_part(p) for p in path)
        body_flags.append(not any(key == p or key.startswith(p + _SEP) for p in personal))
    return leaves_with_path, treedef, body_flags


class PersonalizedLearner(JaxLearner):
    """``JaxLearner`` whose ``personal`` path prefixes stay node-local.

    ``personal`` entries are flattened param paths (e.g. ``"Dense_2"`` for
    the MLP head, or ``"layer_3/ffn"``) — everything under a prefix is
    excluded from every outgoing update and preserved through every
    incoming one.

    Every training member of a federation must agree on the federated
    subtree (same ``personal`` prefixes), exactly as they must agree on
    the architecture: a plain learner mixed in cannot consume body-only
    updates and stops itself via the model-mismatch path (the reference's
    wrong-model semantics, ``test/node_test.py:155-176``).
    """

    def __init__(self, *args, personal: Iterable[str] = (), **kwargs) -> None:
        super().__init__(*args, **kwargs)
        self.personal = tuple(personal)
        if not self.personal:
            raise ValueError("PersonalizedLearner needs at least one personal path prefix")
        # EVERY prefix must match something: a typo'd prefix among valid
        # ones would otherwise silently federate the layer the user marked
        # as never-leave-the-node
        keys = list(_flatten_named(self.params))
        for prefix in self.personal:
            if not any(k == prefix or k.startswith(prefix + _SEP) for k in keys):
                raise ValueError(f"personal prefix {prefix!r} matches no parameters")
        _lwp, _td, flags = _split(self.params, self.personal)
        if not any(flags):
            raise ValueError("every parameter is personal — nothing left to federate")

    # ---- outgoing: body only ----

    def _body_tree(self, params: Pytree) -> dict:
        """Nested dict holding ONLY the body leaves (personal paths absent).

        A plain nested dict keeps the wire payload self-describing: the
        receiver rebuilds against its own body template by path name.
        """
        leaves_with_path, _td, flags = _split(params, self.personal)
        out: dict = {}
        for (path, leaf), is_body in zip(leaves_with_path, flags):
            if not is_body:
                continue
            parts = [_path_part(p) for p in path]
            cur = out
            for part in parts[:-1]:
                cur = cur.setdefault(part, {})
            cur[parts[-1]] = leaf
        return out

    def get_model_update(self) -> ModelUpdate:
        update = super().get_model_update()  # anchor fields attach there
        update.params = self._body_tree(update.params)
        return update

    def fused_round(self):
        """Staged path only: the fused program's partial accumulator folds
        the FULL parameter tree, but this learner federates body-only
        updates — a full-tree fold would leak the personal subtree into
        the aggregate. Returning None routes ``TrainStage`` to the staged
        ``evaluate()`` + ``fit()`` sequence, whose outgoing update already
        strips the personal paths."""
        return None

    def set_wire_anchor(self, params, tag: str) -> None:
        # delta-code against the BODY anchor (the only thing on the wire)
        super().set_wire_anchor(self._body_tree(params), tag)

    # ---- incoming: merge body, keep personal ----

    def set_parameters(self, params: Pytree) -> None:
        """Accept a full tree (init) or a body-only tree (aggregates)."""
        incoming = {
            _SEP.join(_path_part(p) for p in path): leaf
            for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
        }
        leaves_with_path, treedef, flags = _split(self.params, self.personal)
        merged = []
        for (path, leaf), is_body in zip(leaves_with_path, flags):
            key = _SEP.join(_path_part(p) for p in path)
            if is_body:
                if key not in incoming:
                    raise ModelNotMatchingError(f"incoming update misses body param {key}")
                arr = incoming[key]
                if tuple(jax.numpy.shape(arr)) != tuple(jax.numpy.shape(leaf)):
                    raise ModelNotMatchingError(f"shape mismatch at {key}")
                merged.append(jax.numpy.asarray(arr).astype(leaf.dtype))
            else:
                merged.append(leaf)  # personal: never overwritten
        self.params = jax.tree_util.tree_unflatten(treedef, merged)
        if not self.keep_opt_state:
            self.opt_state = self.tx.init(self.params)
        # this override bypasses JaxLearner.set_parameters: bump here too,
        # or the payload cache would replay pre-merge bytes as the round's
        # aggregated diffusion
        self.bump_model_version()

    def materialize(self, update: ModelUpdate) -> ModelUpdate:
        if update.params is not None:
            return update
        anchor = getattr(self, "_wire_anchor", None)
        tag = getattr(self, "_wire_anchor_tag", None)
        # streamed transfers arrive leaf-decoded (see JaxLearner.materialize)
        if update.decoded_flat is not None:
            flat = update.decoded_flat
        else:
            flat = decode_params(update.encoded, anchor=anchor, anchor_tag=tag)
        body_template = self._body_tree(self.params)
        if set(flat) == set(_flatten_named(self.params)):
            # a FULL-model payload (e.g. the init model from a
            # non-personalized initiator over a byte transport):
            # reconstruct the whole tree; set_parameters still keeps the
            # local head when applying it
            template = self.params
        else:
            template = body_template
        out = ModelUpdate(
            restore_like(template, flat), update.contributors, update.num_samples
        )
        out.anchor = anchor
        out.anchor_tag = tag
        return out
