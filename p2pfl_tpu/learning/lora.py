"""Federated LoRA: train and exchange only the adapters.

BASELINE config 5. The full model stays frozen and node-resident; the round
payload (and the aggregator's algebra) sees only the ``lora_*`` subtree —
for the default tiny config that's <1% of the parameters, and for a
TinyLlama-scale model it turns a ~2 GB gossip payload into a few MB.

Works with any module whose adapter params carry the ``lora_`` name prefix
(:class:`~p2pfl_tpu.models.transformer.LoRADense`).
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import NodeLearner, adam, ce_eval
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.models.base import FlaxModel, apply_with_aux

Pytree = Any


def split_lora(params: Pytree) -> tuple[dict, dict]:
    """Split a nested-dict param tree into (lora_subtree, base_subtree)."""

    def walk(node):
        lora, base = {}, {}
        for key, val in node.items():
            if isinstance(val, dict):
                sub_l, sub_b = walk(val)
                if sub_l:
                    lora[key] = sub_l
                if sub_b:
                    base[key] = sub_b
            elif key.startswith("lora_"):
                lora[key] = val
            else:
                base[key] = val
        return lora, base

    return walk(params)


def merge_params(base: dict, overlay: dict) -> dict:
    """Recursively overlay one nested dict onto another (pure, trace-safe)."""
    out = dict(base)
    for key, val in overlay.items():
        if key in out and isinstance(out[key], dict) and isinstance(val, dict):
            out[key] = merge_params(out[key], val)
        else:
            out[key] = val
    return out


def _lm_loss(lora, base, module, x, y):
    """Training loss: CE + any sown auxiliary losses (MoE router balance)."""
    params = merge_params(base, lora)
    logits, aux = apply_with_aux(module, params, x)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
    return ce + aux, logits


@partial(jax.jit, static_argnames=("module", "tx"), donate_argnums=(1,))
def lora_train_epoch(lora, opt_state, base, xs, ys, module, tx):
    """Epoch scan updating only the adapter subtree (frozen base is an input)."""

    def step(carry, batch):
        lo, o = carry
        x, y = batch
        (loss, _), grads = jax.value_and_grad(_lm_loss, has_aux=True)(lo, base, module, x, y)
        updates, o = tx.update(grads, o, lo)
        lo = optax.apply_updates(lo, updates)
        return (lo, o), loss

    (lora, opt_state), losses = jax.lax.scan(step, (lora, opt_state), (xs, ys))
    return lora, opt_state, jnp.mean(losses)


@partial(jax.jit, static_argnames=("module",))
def lora_eval(lora, base, x, y, module):
    loss, logits = ce_eval(merge_params(base, lora), module, x, y)
    acc = jnp.mean((jnp.argmax(logits, axis=-1) == y).astype(jnp.float32))
    return loss, acc


class LoRALearner(NodeLearner):
    """JaxLearner variant whose exchanged parameters are the LoRA subtree only.

    ``get_parameters`` / ``set_parameters`` / ``get_model_update`` all speak
    the adapter subtree — aggregators, the weights codec, and both gossip and
    SPMD modes work unchanged on the smaller tree.
    """

    def __init__(
        self,
        model: FlaxModel,
        data: FederatedDataset,
        addr: str = "",
        epochs: int = 1,
        batch_size: int = 16,
        learning_rate: float = 1e-3,
        seed: int = 0,
    ) -> None:
        self.model = model
        self.data = data
        self.addr = addr
        self.epochs = epochs
        self.batch_size = batch_size
        self.tx = adam(learning_rate)
        self.lora, self.base = split_lora(model.params)
        if not jax.tree.leaves(self.lora):
            raise ValueError("model has no lora_* params — use JaxLearner instead")
        self.opt_state = self.tx.init(self.lora)
        self._rng = np.random.default_rng(seed)
        self._interrupt = threading.Event()
        self._steps_done = 0

    # ---- exchanged params = adapters only ----

    def set_parameters(self, params: Pytree) -> None:
        if jax.tree.structure(params) != jax.tree.structure(self.lora):
            from p2pfl_tpu.exceptions import ModelNotMatchingError

            raise ModelNotMatchingError("incoming params do not match LoRA structure")
        self.lora = params
        self.opt_state = self.tx.init(params)
        # the payload cache keys encoded bytes on model_version: skipping
        # the bump would replay the PREVIOUS adapters' bytes for these
        self.bump_model_version()

    def get_parameters(self) -> Pytree:
        return self.lora

    def full_parameters(self) -> Pytree:
        return merge_params(self.base, self.lora)

    def set_epochs(self, epochs: int) -> None:
        self.epochs = epochs

    # ---- training ----

    def fit(self) -> None:
        self._interrupt.clear()
        for _ in range(self.epochs):
            if self._interrupt.is_set():
                logger.info(self.addr, "Training interrupted")
                return
            xs, ys = self.data.epoch_batches(self.batch_size, self._rng)
            self.lora, self.opt_state, loss = lora_train_epoch(
                self.lora,
                self.opt_state,
                self.base,
                jnp.asarray(xs),
                jnp.asarray(ys),
                self.model.module,
                self.tx,
            )
            self._steps_done += xs.shape[0]
            logger.log_metric(self.addr, "train_loss", float(loss), step=self._steps_done)
        # trained adapters are new payload content (encode-once cache key)
        self.bump_model_version()

    def interrupt_fit(self) -> None:
        self._interrupt.set()

    def evaluate(self) -> dict[str, float]:
        x, y = self.data.test_arrays()
        if len(y) == 0:
            return {}
        loss, acc = lora_eval(
            self.lora, self.base, jnp.asarray(x), jnp.asarray(y), self.model.module
        )
        return {"test_loss": float(loss), "test_acc": float(acc)}

    def get_num_samples(self) -> int:
        return self.data.num_samples
