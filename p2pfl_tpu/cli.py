"""Command-line interface.

Reference: Typer app with ``experiment list`` / ``experiment run``
(``p2pfl/cli.py:65-203``), a Rich logo banner and Rich tables. argparse
here (typer/rich aren't in this image); same surface — examples are
discovered from ``p2pfl_tpu/examples/`` and run in-process with their own
argv — with a dependency-free equivalent of the Rich UX: an ANSI banner
and box-drawing tables on a UTF-8 interactive terminal; pipes and
ASCII-only stdouts keep the plain machine-parseable two-column listing.
"""

from __future__ import annotations

import argparse
import importlib
import pkgutil
import sys

_BANNER = r"""
  ___ ___ ___ ___ _      _____ ___ _   _
 | _ \_  ) _ \ __| |    |_   _| _ \ | | |
 |  _// /|  _/ _|| |__    | | |  _/ |_| |
 |_| /___|_| |_| |____|   |_| |_|  \___/
"""


def _fancy() -> bool:
    """Decorate only for a UTF-8-capable interactive terminal — a pipe or
    an ASCII-only stdout keeps the plain machine-parseable two-column form
    (the pre-round-5 output)."""
    if not sys.stdout.isatty():
        return False
    try:
        "┌".encode(getattr(sys.stdout, "encoding", "") or "ascii")
    except (UnicodeEncodeError, LookupError):
        return False
    return True


def _color(s: str, code: str) -> str:
    return f"\033[{code}m{s}\033[0m"


def _banner() -> str:
    return _color(_BANNER, "34") + _color(
        "  peer-to-peer federated learning, TPU-native\n", "2"
    )


def _table(headers: list[str], rows: list[list[str]]) -> str:
    """Box-drawing table (no ANSI — pure glyphs) — the in-image stand-in
    for Rich's Table (reference ``cli.py:112-125``)."""
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]

    def line(left: str, mid: str, right: str, fill: str = "─") -> str:
        return left + mid.join(fill * (w + 2) for w in widths) + right

    def row(cells: list[str]) -> str:
        return "│" + "│".join(f" {c:<{w}} " for w, c in zip(widths, cells)) + "│"

    parts = [line("┌", "┬", "┐"), row(headers), line("├", "┼", "┤")]
    parts += [row(r) for r in rows]
    parts.append(line("└", "┴", "┘"))
    return "\n".join(parts)


def _discover() -> dict[str, str]:
    """Example name → first docstring line."""
    import p2pfl_tpu.examples as ex

    out = {}
    for info in pkgutil.iter_modules(ex.__path__):
        mod = importlib.import_module(f"p2pfl_tpu.examples.{info.name}")
        doc = (mod.__doc__ or "").strip().splitlines()
        out[info.name] = doc[0] if doc else ""
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="p2pfl_tpu", description="TPU-native federated learning")
    sub = parser.add_subparsers(dest="command")

    exp = sub.add_parser("experiment", help="list or run bundled experiments")
    exp_sub = exp.add_subparsers(dest="action")
    exp_sub.add_parser("list", help="list available experiments")
    run = exp_sub.add_parser("run", help="run an experiment by name")
    run.add_argument("name")
    run.add_argument("extra", nargs=argparse.REMAINDER, help="arguments passed to the experiment")

    sub.add_parser("bench", help="run the headline benchmark")
    # remote-management verbs are stubs in the reference too (cli.py:71-95)
    for stub in ("login", "remote", "launch"):
        sub.add_parser(stub, help="(coming soon)")

    args = parser.parse_args(argv)
    if args.command in ("login", "remote", "launch"):
        print(f"{args.command}: coming soon (stub — reference parity, cli.py:71-95)")
        return 0
    if args.command == "experiment":
        if args.action == "list":
            entries = sorted(_discover().items())
            if _fancy():
                print(_banner())
                print(_table(["experiment", "description"], [[n, d] for n, d in entries]))
            else:
                for name, doc in entries:
                    print(f"{name:20s} {doc}")
            return 0
        if args.action == "run":
            examples = _discover()
            if args.name not in examples:
                print(f"unknown experiment {args.name!r}; try: {', '.join(sorted(examples))}")
                return 1
            mod = importlib.import_module(f"p2pfl_tpu.examples.{args.name}")
            mod.main(args.extra)
            return 0
        exp.print_help()
        return 1
    if args.command == "bench":
        import runpy

        runpy.run_path("bench.py", run_name="__main__")
        return 0
    parser.print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
