"""One-call federation builder for simulations and scripts.

The reference's examples hand-assemble N nodes, topology, learning kick-off
and result collection (``p2pfl/examples/mnist.py:96-161``); this wraps that
recipe behind one object. Gossip mode only — for the mesh fast path use
:class:`p2pfl_tpu.parallel.SpmdFederation`, which shares the semantics.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from p2pfl_tpu.federation.simfleet import FleetResult, SimulatedAsyncFleet  # noqa: F401 — re-export: the 1k-node simulated fleet driver
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.node import Node
from p2pfl_tpu.utils import connect_line, full_connection, wait_convergence, wait_to_finish


class Simulation:
    """N in-process nodes on a chosen topology, ready to learn.

    ``learner_fn(i, shard) -> learner`` builds each node's learner;
    ``topology`` is ``"line" | "ring" | "full" | "star"``.
    """

    def __init__(
        self,
        n_nodes: int,
        learner_fn: Callable[[int, FederatedDataset], Any],
        dataset: FederatedDataset,
        topology: str = "line",
        partition: str = "iid",
        alpha: float = 0.5,
        aggregator_fn: Optional[Callable[[], Any]] = None,
        protocol_fn: Optional[Callable[[], Any]] = None,
    ) -> None:
        self.nodes: list[Node] = []
        for i in range(n_nodes):
            shard = dataset.partition(i, n_nodes, partition, alpha)
            protocol = protocol_fn() if protocol_fn else _default_protocol()
            self.nodes.append(
                Node(
                    learner=learner_fn(i, shard),
                    aggregator=aggregator_fn() if aggregator_fn else None,
                    protocol=protocol,
                )
            )
        self.topology = topology

    def start(self, wait: float = 10.0) -> "Simulation":
        for node in self.nodes:
            node.start()
        n = len(self.nodes)
        if self.topology == "line":
            connect_line(self.nodes)
        elif self.topology == "ring":
            connect_line(self.nodes)
            if n > 2:
                self.nodes[-1].connect(self.nodes[0].addr)
        elif self.topology == "full":
            for node in self.nodes:
                full_connection(node, self.nodes)
        elif self.topology == "star":
            for leaf in self.nodes[1:]:
                leaf.connect(self.nodes[0].addr)
        else:
            raise ValueError(f"unknown topology {self.topology!r}")
        wait_convergence(self.nodes, n - 1, only_direct=False, wait=wait)
        return self

    def learn(self, rounds: int = 1, epochs: int = 1, timeout: float = 600.0) -> "Simulation":
        """Run one experiment. Under ``Settings.FEDERATION_MODE="async"``
        the same call drives the async control plane (``rounds`` is then
        each node's local update budget — there are no global rounds);
        for 1k+-node *virtual* fleets use :class:`SimulatedAsyncFleet`
        instead of instantiating real nodes."""
        self.nodes[0].set_start_learning(rounds=rounds, epochs=epochs)
        wait_to_finish(self.nodes, timeout=timeout)
        return self

    def evaluate(self) -> dict[str, dict[str, float]]:
        return {n.addr: n.learner.evaluate() for n in self.nodes}

    def metrics(self):
        """Global (per-round) metric store contents for this process."""
        return logger.get_global_logs()

    def stop(self) -> None:
        for node in self.nodes:
            node.stop()


def _default_protocol():
    from p2pfl_tpu.communication.memory import InMemoryProtocol

    return InMemoryProtocol()
