"""Attention ops: fused causal attention + ring attention for long context.

The reference has no attention anywhere (SURVEY §2.9) — this exists for the
BASELINE config-5 model family (TinyLlama LoRA) and makes long-context
first-class: sequences longer than one chip's HBM are sharded over a mesh
axis and attended with **ring attention** (Liu et al. 2023): K/V blocks
rotate around the ring via ``ppermute`` while each device keeps an online
(flash-style) softmax accumulator — full attention, O(T_local) memory per
device, communication overlapped by XLA with the per-block matmuls.

All softmax statistics accumulate in float32; inputs stay bfloat16 on the
MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def causal_attention(q, k, v, scale: Optional[float] = None) -> jax.Array:
    """Plain fused causal attention. q,k,v: [B, T, H, D] (k/v may have fewer
    heads — GQA — already repeated by the caller). Returns [B, T, H, D]."""
    b, t, h, d = q.shape
    scale = (d ** -0.5) if scale is None else scale
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    mask = jnp.tril(jnp.ones((t, t), dtype=bool))
    logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _block_attend(q, k, v, q_off, k_off, scale, causal):
    """One flash block: returns (numerator [B,Tq,H,D] f32, denom [B,H,Tq] f32,
    running max [B,H,Tq] f32) for q against one K/V block with global offsets."""
    tq, tk = q.shape[1], k.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_off + jnp.arange(tq)
        k_pos = k_off + jnp.arange(tk)
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)  # [B,H,Tq]
    p = jnp.exp(s - m[..., None])
    # fully-masked rows: exp(NEG_INF - NEG_INF) = 1 would pollute the denom
    p = jnp.where(m[..., None] <= NEG_INF / 2, 0.0, p)
    num = jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v).astype(jnp.float32)
    den = jnp.sum(p, axis=-1)  # [B,H,Tq]
    return num, den, m


@partial(jax.jit, static_argnames=("axis_name", "causal"))
def _ring_attention_sharded(q, k, v, *, axis_name: str, causal: bool = True):
    """Per-device body; call under ``shard_map`` with T sharded on ``axis_name``.

    q,k,v local blocks: [B, T_local, H, D]. K/V rotate ``ring_size`` hops;
    accumulators merge with the standard online-softmax rescaling.
    """
    ring = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, tl, h, d = q.shape
    scale = d ** -0.5

    acc = jnp.zeros((b, tl, h, d), jnp.float32)
    den = jnp.zeros((b, h, tl), jnp.float32)
    m = jnp.full((b, h, tl), NEG_INF, jnp.float32)
    # mark accumulators device-varying so the loop carry types line up with
    # the sharded K/V blocks (jax>=0.8 shard_map vma typing; identity on
    # older jax — parallel/compat.py)
    from p2pfl_tpu.parallel.compat import device_varying

    acc, den, m = device_varying((acc, den, m), axis_name)
    perm = [(j, (j + 1) % ring) for j in range(ring)]

    def body(i, carry):
        acc, den, m, kb, vb = carry
        src = (my - i) % ring  # which shard this K/V block came from
        num_i, den_i, m_i = _block_attend(
            q, kb, vb, q_off=my * tl, k_off=src * tl, scale=scale, causal=causal
        )
        m_new = jnp.maximum(m, m_i)
        # guard: rows where nothing is visible yet keep NEG_INF stats
        alpha = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        beta = jnp.where(m_i <= NEG_INF / 2, 0.0, jnp.exp(m_i - m_new))
        acc = acc * alpha.transpose(0, 2, 1)[..., None] + num_i * beta.transpose(0, 2, 1)[..., None]
        den = den * alpha + den_i * beta
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return acc, den, m_new, kb, vb

    acc, den, m, _, _ = lax.fori_loop(0, ring, body, (acc, den, m, k, v))
    out = acc / jnp.maximum(den, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _ring_flash_sharded(q, k, v, *, axis_name: str, config, interpret: bool):
    """Per-device ring body with Pallas flash blocks: each hop runs the
    offset-aware flash kernel on the local Q against the incoming K/V shard
    (O(T_local·D) memory instead of the dense body's O(T_local²) logits),
    then merges via log-sum-exp — the differentiable ring-flash composition.
    ``config`` is the static :class:`~p2pfl_tpu.ops.flash_attention.FlashConfig`
    kernel schedule for every hop's kernel.
    """
    from p2pfl_tpu.ops.flash_attention import flash_attention_block

    ring = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, tl, h, d = q.shape
    perm = [(j, (j + 1) % ring) for j in range(ring)]

    out = jnp.zeros((b, tl, h, d), jnp.float32)
    # lse rides the kernels' block-size-independent [B, H, 1, T_local] row
    # layout, so hop merges never depend on the configured block shapes
    lse = jnp.full((b, h, 1, tl), NEG_INF, jnp.float32)
    from p2pfl_tpu.parallel.compat import device_varying

    out, lse = device_varying((out, lse), axis_name)

    kb, vb = k, v
    for i in range(ring):  # ring size is static: plain python loop
        src = (my - i) % ring  # which shard this K/V block came from
        ob, lb = flash_attention_block(
            q, kb, vb, my * tl, src * tl, config, interpret
        )
        new = jnp.logaddexp(lse, lb)
        # NEG_INF is a large finite sentinel (-1e30), so test against the
        # same <= NEG_INF/2 convention the kernels use — not isfinite
        wo = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(lse - new))
        wn = jnp.where(lb <= NEG_INF / 2, 0.0, jnp.exp(lb - new))

        def as_bthd(w):  # [B,H,1,T] -> [B,T,H,1]
            return w.reshape(b, h, tl).transpose(0, 2, 1)[..., None]

        out = out * as_bthd(wo) + ob.astype(jnp.float32) * as_bthd(wn)
        lse = new
        if i + 1 < ring:
            kb = lax.ppermute(kb, axis_name, perm)
            vb = lax.ppermute(vb, axis_name, perm)
    return out.astype(q.dtype)


def ring_attention(
    q, k, v, mesh, axis_name: str, causal: bool = True, impl: str = "dense",
    block: int = 128, flash_config=None,
) -> jax.Array:
    """Full-sequence attention with T sharded over ``axis_name`` of ``mesh``.

    q,k,v: [B, T, H, D] global arrays (T divisible by the axis size).
    ``impl="flash"`` runs each ring hop through the offset-aware Pallas
    flash kernel — O(T_local·D) memory per device instead of the dense
    body's O(T_local²) logits matrix (causal only). ``flash_config`` pins
    the hops' full static kernel schedule
    (:class:`~p2pfl_tpu.ops.flash_attention.FlashConfig`); ``block`` is the
    square-block shorthand used when no config is given.
    """
    from jax.sharding import PartitionSpec as P

    from p2pfl_tpu.parallel.compat import shard_map_compat, shard_map_unchecked

    spec = P(None, axis_name, None, None)
    if impl == "flash":
        if not causal:
            raise ValueError("impl='flash' supports causal attention only")
        from p2pfl_tpu.ops.flash_attention import FlashConfig

        interpret = jax.default_backend() != "tpu"
        tl = q.shape[1] // mesh.shape[axis_name]
        config = flash_config or FlashConfig(
            block_q=min(block, tl), block_k=min(block, tl)
        )
        body = partial(
            _ring_flash_sharded,
            axis_name=axis_name,
            config=config,
            interpret=interpret,
        )
        # pallas_call's out_shape carries no vma typing — disable the check
        # for the flash body (the collectives are still the same ring)
        fn = shard_map_unchecked(
            body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
        )
        return fn(q, k, v)
    body = partial(_ring_attention_sharded.__wrapped__, axis_name=axis_name, causal=causal)
    fn = shard_map_compat(body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
