"""Device-side wire compression: fused delta / error-feedback / top-k / int8.

The host codec (``learning/weights.py``) walks tensors serially through
numpy: it pulls the FULL fp32 model device-to-host, argpartitions each
tensor, and quantizes in a scalar loop — for topk8 that is ~16× more bytes
over D2H than the payload ultimately carries, on the critical path of every
gossip send. The math itself (params − anchor delta, residual add, top-k by
magnitude, symmetric int8 — Seide et al. 2014; Karimireddy et al. 2019) is
an embarrassingly parallel reduction a TPU finishes in microseconds.

This module is the **device producer** behind
``Settings.WIRE_COMPRESSION_DEVICE``: one jit-compiled program per model
spec that

- treats the model as a sequence of flat fp32 segments with *static*
  sizes and budgets (the spec — leaf paths, sizes, per-tensor k — is a
  static jit argument, so one compilation serves every round),
- fuses ``(params − anchor) + residual`` into the same dispatch,
- serves per-tensor budgets by segment-local selection: one
  :func:`jax.lax.top_k` per segment, all inside the single fused program
  (a padded ``[segments, max_len]`` batched top_k was tried first and
  lost ~4× to padding waste — every row pays the largest tensor's length
  and budget; per-segment selection costs exactly ``Σ topk(n_i, k_i)``),
- quantizes symmetrically per segment (``scale = absmax/127``, absmax is
  simply element 0 of the descending top-k magnitudes),
- scatters the dequantized payload back onto the delta to produce the new
  error-feedback residual, which **stays resident on device** as the carry
  for the next round (the residual buffers are donated, so XLA can update
  them in place),
- concatenates exactly the coordinates the wire carries into ``[Σk_i]``
  outputs — the ONLY device→host transfer is the compressed ``(int32 idx,
  int8 q, fp32 scale)`` buffers, byte-for-byte what the frame ships.

Dense-int8 segments (``compression="int8"``, or topk-ineligible float
tensors under topk8) ride the same dispatch via a segment-max absmax.
Non-float leaves (including bfloat16, which the wire ships raw — numpy
dtype kind ``V``) fall back to host bytes, exactly like the host producer.

The emitted per-tensor plans feed the SAME framing as the host producer,
so payloads from either producer decode with the one shared decoder
(wire-format invariance — asserted by tests/test_device_compression.py).
:func:`decode_tk8_device` is the matching consumer: dequantized deltas are
scatter-added onto the device-resident anchor in one fused program instead
of pulling the anchor host-side and mutating a ``.ravel().copy()``.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any

def topk_budget(size: int, topk_frac: float) -> int:
    """Per-tensor top-k budget — MUST match the host producer's formula."""
    return max(1, int(np.ceil(size * topk_frac)))


def leaf_size(leaf) -> int:
    """Element count of a leaf (1 for scalars) — shared sizing helper."""
    return int(np.prod(np.shape(leaf), dtype=np.int64)) if np.shape(leaf) else 1


def build_topk_plan(named: dict, anchor_named: Optional[dict], topk_frac: float) -> dict:
    """The ONE topk-eligibility predicate + per-tensor budget.

    Shared by the host producer, the device producer AND the shard-plane
    codec (``communication/ici.py``) — drift here would silently wipe
    valid error-feedback carries or diverge the producers' nnz. A tensor
    is delta-coded iff: topk is active (``topk_frac > 0``), the leaf is
    float, the anchor holds a matching path, and the tensor is big enough
    (> 16 elements) for sparsification to pay.
    """
    if topk_frac <= 0.0 or anchor_named is None:
        return {}
    return {
        key: topk_budget(leaf_size(leaf), topk_frac)
        for key, leaf in named.items()
        if np.dtype(leaf.dtype).kind == "f"
        and key in anchor_named
        and leaf_size(leaf) > 16
    }


def split_codec_specs(named: dict, topk_plan: dict) -> tuple[list, tuple, tuple]:
    """Sorted keys + the static (tk, dense) segment specs both device
    entry points compile against: ``tk_spec`` is ``(key, size, budget)``
    per delta-coded tensor, ``dense_spec`` ``(key, size)`` per dense-int8
    float tensor; non-float leaves belong to neither (raw passthrough)."""
    keys = sorted(named)
    tk_spec: list[tuple[str, int, int]] = []
    dense_spec: list[tuple[str, int]] = []
    for key in keys:
        leaf = named[key]
        if np.dtype(leaf.dtype).kind != "f":
            continue  # raw passthrough, handled by the caller
        if key in topk_plan:
            tk_spec.append((key, leaf_size(leaf), topk_plan[key]))
        else:
            dense_spec.append((key, leaf_size(leaf)))
    return keys, tuple(tk_spec), tuple(dense_spec)


# ---- the fused encode program ----


def _quantize_seg(vals):
    """Symmetric int8 of one segment — same formula as ``native.quantize``
    (``scale = absmax/127``, 1.0 when the segment is all-zero)."""
    absmax = jnp.max(jnp.abs(vals))
    scale = jnp.where(absmax > 0, absmax / jnp.float32(127.0), jnp.float32(1.0))
    q = jnp.clip(jnp.rint(vals / scale), -127, 127).astype(jnp.int8)
    return q, scale


@partial(jax.jit, static_argnums=(4, 5, 6, 7, 8), donate_argnums=(2,))
def _encode_jit(
    tk_leaves, anchor_leaves, res_leaves, dense_leaves, tk_spec, dense_spec, res_mask,
    want_res, barrier=True,
):
    """One dispatch: delta + residual + per-segment top-k + int8.

    Every op here is segment-local on a static slice — XLA fuses the lot
    into one program, and selection costs ``Σ topk(n_i, k_i)`` with no
    padding waste. ``res_leaves`` (the error-feedback carry) is donated —
    the new residual can reuse its buffers and never visits the host.

    ``barrier`` (static) pins the top_k/sort results as materialized
    values — load-bearing on single-device XLA:CPU (below). It MUST be
    False when the inputs are committed across a multi-device mesh:
    ``optimization_barrier`` under the SPMD partitioner hard-crashes
    XLA:CPU (a fatal ``hlo_casting_utils`` check, observed on jax
    0.4.37), and the fusion-duplication pathology it works around is a
    single-device CPU artifact anyway.
    """
    out = {}
    if tk_spec:
        idx_parts, q_parts, scales, new_res = [], [], [], []
        ri = 0
        for i, (_key, _size, budget) in enumerate(tk_spec):
            d = tk_leaves[i].astype(jnp.float32).reshape(-1) - anchor_leaves[i].astype(
                jnp.float32
            ).reshape(-1)
            if res_mask[i]:
                d = d + res_leaves[ri]
                ri += 1
            mags, pos = jax.lax.top_k(jnp.abs(d), budget)  # descending
            # without the barriers XLA:CPU duplicates the top_k/sort into
            # every consumer fusion (q, residual, idx outputs) — measured
            # ~10× wall-clock on the bench MLP; pinning the sorted results
            # as materialized values keeps selection cost at Σ topk(n_i,k_i)
            if barrier:
                mags, pos = jax.lax.optimization_barrier((mags, pos))
            scale = jnp.where(mags[0] > 0, mags[0] / jnp.float32(127.0), jnp.float32(1.0))
            pos = jnp.sort(pos)  # wire ships ascending
            if barrier:
                pos = jax.lax.optimization_barrier(pos)
            vals = d[pos]
            q = jnp.clip(jnp.rint(vals / scale), -127, 127).astype(jnp.int8)
            if want_res:
                # error feedback: residual = delta − dequantized(sent) at the
                # selected coordinates, untouched delta everywhere else
                new_res.append(d.at[pos].set(vals - q.astype(jnp.float32) * scale))
            idx_parts.append(pos.astype(jnp.int32))
            q_parts.append(q)
            scales.append(scale)
        out["tk"] = (
            jnp.concatenate(idx_parts) if len(idx_parts) > 1 else idx_parts[0],
            jnp.concatenate(q_parts) if len(q_parts) > 1 else q_parts[0],
            jnp.stack(scales),
            tuple(new_res),  # per-segment carries — stay on device
        )
    if dense_spec:
        dq_parts, dscales = [], []
        for i in range(len(dense_spec)):
            q, scale = _quantize_seg(dense_leaves[i].astype(jnp.float32).reshape(-1))
            dq_parts.append(q)
            dscales.append(scale)
        out["dense"] = (
            jnp.concatenate(dq_parts) if len(dq_parts) > 1 else dq_parts[0],
            jnp.stack(dscales),
        )
    return out


def _run_encode_jit(
    named: dict,
    anchor_named: Optional[dict],
    tk_spec: tuple,
    dense_spec: tuple,
    residual: Optional[dict],
    barrier: bool = True,
) -> dict:
    """Stage leaves, run :func:`_encode_jit`, write back the EF carries.

    Shared by the D2H-materializing producer (:func:`encode_device`) and
    the shard-resident producer (:func:`encode_shard_device`) so the two
    can never diverge on residual-donation failure handling or carry
    write-back order.
    """
    tk_leaves = tuple(jnp.asarray(named[k]) for k, _s, _b in tk_spec)
    anchor_leaves = tuple(jnp.asarray(anchor_named[k]) for k, _s, _b in tk_spec)
    res_mask = tuple(
        residual is not None and k in residual for k, _s, _b in tk_spec
    )
    res_leaves = tuple(
        jnp.asarray(residual[k], jnp.float32).reshape(-1)
        for (k, _s, _b), present in zip(tk_spec, res_mask)
        if present
    )
    dense_leaves = tuple(jnp.asarray(named[k]) for k, _s in dense_spec)

    try:
        outs = _encode_jit(
            tk_leaves,
            anchor_leaves,
            res_leaves,
            dense_leaves,
            tk_spec,
            dense_spec,
            res_mask,
            residual is not None,
            barrier,
        )
    except Exception:
        # res_leaves were DONATED: a dispatch that fails after handing
        # buffers to the runtime (transient OOM) leaves the store's arrays
        # deleted while still referenced — and .size metadata survives
        # deletion, so _validate_residual would never notice. Drop the
        # entries we donated: the next encode restarts their carry from
        # zero instead of dying on 'Array has been deleted' forever.
        if residual is not None:
            for (key, _size, _b), present in zip(tk_spec, res_mask):
                if present:
                    residual.pop(key, None)
        raise
    if tk_spec and residual is not None:
        for (key, _size, _b), carry in zip(tk_spec, outs["tk"][3]):
            residual[key] = carry
    return outs


def encode_device(
    named: dict,
    anchor_named: Optional[dict],
    topk_plan: dict,
    residual: Optional[dict],
) -> tuple[list, int]:
    """Device producer: per-tensor wire plans from one fused dispatch.

    Only invoked for the ``int8``/``topk8`` modes: every float leaf off
    the topk plan is dense-int8, never raw.

    ``named``/``anchor_named`` map canonical leaf paths to leaves (device
    arrays stay on device; stray numpy leaves are uploaded once);
    ``topk_plan`` (``{path: budget}``) is the caller-computed single
    source of which tensors are delta-coded and at what k — the same dict
    the host producer consumes. Returns ``(plans, d2h_bytes)`` where
    ``plans`` is ``[(entry_dict, buffers)]`` in sorted-key order, ready
    for the shared framing in ``learning/weights.py`` — the entry/byte
    layout is identical to the host producer's, so either side's decoder
    accepts it. ``residual`` (when given) is updated IN PLACE with
    device-resident slices of the new error-feedback carry; the caller
    owns validation/pruning of stale entries. ``d2h_bytes`` counts every
    byte materialized host-side — the compressed buffers plus any raw
    (non-float) passthrough leaves.
    """
    keys, tk_spec, dense_spec = split_codec_specs(named, topk_plan)
    outs = _run_encode_jit(named, anchor_named, tk_spec, dense_spec, residual)

    d2h = 0
    idx_np = q_np = scales_np = None
    if tk_spec:
        idx_dev, q_dev, scales_dev, _new_res = outs["tk"]
        # the ONLY model-sized D2H is these compressed buffers (the EF
        # carries were written back device-resident by _run_encode_jit)
        idx_np = np.asarray(idx_dev)
        q_np = np.asarray(q_dev)
        scales_np = np.asarray(scales_dev)
        d2h += idx_np.nbytes + q_np.nbytes + scales_np.nbytes
    qd_np = scales_d_np = None
    if dense_spec:
        qd_dev, scales_d_dev = outs["dense"]
        qd_np = np.asarray(qd_dev)
        scales_d_np = np.asarray(scales_d_dev)
        d2h += qd_np.nbytes + scales_d_np.nbytes

    plans = []
    tk_i = dense_i = 0
    tk_off = dense_off = 0
    tk_lookup = {k: i for i, (k, _s, _b) in enumerate(tk_spec)}
    for key in keys:
        leaf = named[key]
        entry = {
            "k": key,
            "shape": list(leaf.shape),
            "dtype": np.dtype(leaf.dtype).name,
        }
        if key in tk_lookup and tk_i < len(tk_spec) and tk_spec[tk_i][0] == key:
            _key, size, budget = tk_spec[tk_i]
            idx = idx_np[tk_off : tk_off + budget].view(np.uint32)
            q = q_np[tk_off : tk_off + budget]
            entry["enc"] = "tk8"
            entry["scale"] = float(scales_np[tk_i])
            entry["nnz"] = int(budget)
            plans.append((entry, (idx.tobytes(), q.tobytes())))
            tk_off += budget
            tk_i += 1
        elif dense_i < len(dense_spec) and dense_spec[dense_i][0] == key:
            _key, size = dense_spec[dense_i]
            q = qd_np[dense_off : dense_off + size]
            entry["enc"] = "i8"
            entry["scale"] = float(scales_d_np[dense_i])
            plans.append((entry, (q.tobytes(),)))
            dense_off += size
            dense_i += 1
        else:
            raw = np.ascontiguousarray(np.asarray(leaf)).tobytes()
            d2h += len(raw)
            plans.append((entry, (raw,)))
    return plans, d2h


# ---- the fused decode (consumer) program ----


@jax.jit
def _scatter_jit(anchor_leaves, idx_leaves, val_leaves):
    """Dequantized deltas scatter-added onto each device anchor leaf.

    Per-leaf scatters (not one concatenated flat buffer): indices stay
    per-tensor — never summed into global offsets that could overflow
    int32 on multi-billion-parameter models — and no transient full-model
    fp32 copy is allocated for the concat. Still one fused dispatch.
    """
    return tuple(
        leaf.astype(jnp.float32).reshape(-1).at[idx].add(vals)
        for leaf, idx, vals in zip(anchor_leaves, idx_leaves, val_leaves)
    )


def decode_tk8_device(items: list) -> dict:
    """Device consumer for the ``tk8`` entries of one payload.

    ``items`` is ``[(key, anchor_leaf, idx_u32, vals_f32, shape, dtype)]``
    in wire order, where ``vals`` are the already-dequantized delta values
    (dequantization is ``q * scale`` — negligible host work on payload-
    sized data) and ``anchor_leaf`` is the device-resident anchor tensor.
    Reconstructs ``anchor + scatter(delta)`` in ONE fused dispatch instead
    of pulling each anchor tensor host-side and mutating a ravel-copy; the
    returned leaves are device arrays ready for ``restore_like``.

    The caller has already validated indices (strictly ascending, in
    range, tensor size inside int32 index space), so each scatter-add
    touches each coordinate at most once.
    """
    anchor_leaves = tuple(leaf for _k, leaf, _i, _v, _sh, _dt in items)
    idx_leaves = tuple(
        jnp.asarray(idx.astype(np.int32)) for _k, _l, idx, _v, _sh, _dt in items
    )
    val_leaves = tuple(
        jnp.asarray(np.asarray(v, np.float32)) for _k, _l, _i, v, _sh, _dt in items
    )
    dense = _scatter_jit(anchor_leaves, idx_leaves, val_leaves)
    return {
        key: flat.reshape(shape).astype(dtype)
        for (key, _leaf, _idx, _vals, shape, dtype), flat in zip(items, dense)
    }


# ---- shard-resident entry points (the ICI weights plane's codec) ----
#
# The producers above exist to shrink the D2H pull to ~payload size; the
# shard-native ICI weights plane (communication/ici.py) goes one further
# and never crosses D2H at all: the compressed (idx, q, scale) buffers
# stay DEVICE arrays, move to the peer's slice over the interconnect
# (parallel/ici_plane.py), and are consumed by a fused scatter against the
# receiver's device-resident anchor. Same math, same _encode_jit program,
# same segment specs (split_codec_specs / build_topk_plan) — only the
# materialization step is gone, so bytes-over-host is exactly zero.


def encode_shard_device(
    named: dict,
    anchor_named: Optional[dict],
    topk_plan: dict,
    residual: Optional[dict],
    barrier: bool = True,
) -> tuple[tuple, tuple, dict]:
    """Device-resident encode: one fused dispatch, NOTHING materialized.

    Returns ``(tk_spec, dense_spec, payload)`` where ``payload`` maps
    buffer names (``"idx"``/``"q"``/``"scales"`` for the delta-coded
    segments, ``"dq"``/``"dscales"`` for dense-int8) to DEVICE arrays —
    the exact tensors :func:`decode_shard_device` consumes on the far
    slice. Non-float leaves belong to neither spec; the caller ships them
    raw (they are already device-resident). ``residual`` follows the same
    donated error-feedback contract as :func:`encode_device` (the carry
    is written back device-resident; a failed dispatch drops the donated
    entries) via the shared :func:`_run_encode_jit`.
    """
    _keys, tk_spec, dense_spec = split_codec_specs(named, topk_plan)
    outs = _run_encode_jit(named, anchor_named, tk_spec, dense_spec, residual, barrier)
    payload: dict = {}
    if tk_spec:
        idx_dev, q_dev, scales_dev, _new_res = outs["tk"]
        payload["idx"] = idx_dev
        payload["q"] = q_dev
        payload["scales"] = scales_dev
    if dense_spec:
        dq_dev, dscales_dev = outs["dense"]
        payload["dq"] = dq_dev
        payload["dscales"] = dscales_dev
    return tk_spec, dense_spec, payload


@partial(jax.jit, static_argnums=(4, 5))
def _shard_scatter_jit(anchor_leaves, idx, q, scales, tk_spec, out_meta):
    """Delta segments → reconstructed tensors, one fused dispatch.

    ``idx``/``q`` are the concatenated per-segment buffers in spec order
    (per-tensor LOCAL indices — never global offsets, same int32 contract
    as :func:`_scatter_jit`); the static ``out_meta`` carries each
    segment's (shape, dtype name) so reshape + cast stay inside the one
    program. Indices are strictly ascending per segment by construction
    (the encoder sorts), so the scatter-add touches each coordinate once.
    """
    outs = []
    off = 0
    for i, (_key, _size, budget) in enumerate(tk_spec):
        shape, dtype = out_meta[i]
        seg = idx[off : off + budget]
        vals = q[off : off + budget].astype(jnp.float32) * scales[i]
        flat = anchor_leaves[i].astype(jnp.float32).reshape(-1).at[seg].add(vals)
        outs.append(flat.reshape(shape).astype(dtype))
        off += budget
    return tuple(outs)


@partial(jax.jit, static_argnums=(2, 3))
def _shard_dense_jit(dq, dscales, dense_spec, out_meta):
    """Dense-int8 segments → dequantized tensors, one fused dispatch."""
    outs = []
    off = 0
    for i, (_key, size) in enumerate(dense_spec):
        shape, dtype = out_meta[i]
        seg = dq[off : off + size].astype(jnp.float32) * dscales[i]
        outs.append(seg.reshape(shape).astype(dtype))
        off += size
    return tuple(outs)


def decode_shard_device(
    payload: dict,
    tk_spec: tuple,
    dense_spec: tuple,
    anchor_named: Optional[dict],
    template_named: dict,
) -> dict:
    """Consume a shard-resident payload against the RECEIVER's anchors.

    The mirror of :func:`encode_shard_device`: delta segments scatter-add
    onto the receiver's device-resident anchor tensors (same divergence
    budget as the byte decoder — same-round anchors differ across nodes
    by at most the codec's loss), dense segments dequantize, and every
    output takes the matching ``template_named`` leaf's shape/dtype. Two
    fused dispatches at most; nothing crosses the host.
    """
    out: dict = {}
    if tk_spec:
        anchors = tuple(jnp.asarray(anchor_named[k]) for k, _s, _b in tk_spec)
        meta = tuple(
            (tuple(np.shape(template_named[k])), np.dtype(template_named[k].dtype).name)
            for k, _s, _b in tk_spec
        )
        recon = _shard_scatter_jit(
            anchors, payload["idx"], payload["q"], payload["scales"], tk_spec, meta
        )
        for (key, _s, _b), leaf in zip(tk_spec, recon):
            out[key] = leaf
    if dense_spec:
        meta = tuple(
            (tuple(np.shape(template_named[k])), np.dtype(template_named[k].dtype).name)
            for k, _s in dense_spec
        )
        recon = _shard_dense_jit(payload["dq"], payload["dscales"], dense_spec, meta)
        for (key, _s), leaf in zip(dense_spec, recon):
            out[key] = leaf
    return out
