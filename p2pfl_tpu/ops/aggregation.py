"""Pure aggregation kernels over stacked pytrees.

Each function takes a pytree whose leaves have a leading node axis
``[N, ...]`` plus per-node scalars, and returns the aggregated pytree.
All are jit-compatible pure functions — the strategy classes in
``learning/aggregators`` wrap them with the partial-aggregation bookkeeping.

The reference ships only FedAvg (``p2pfl/learning/aggregators/fedavg.py``);
the robust family (median / trimmed mean / Krum) covers BASELINE config 4.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

Pytree = Any


@partial(jax.jit, static_argnames=("agg_dtype",))
def fedavg(stacked: Pytree, weights: jax.Array, agg_dtype: str = "float32") -> Pytree:
    """Sample-weighted mean. weights: [N] (unnormalized sample counts)."""
    w = weights.astype(agg_dtype)
    w = w / jnp.sum(w)

    def avg(x):
        return jnp.tensordot(w, x.astype(agg_dtype), axes=(0, 0)).astype(x.dtype)

    return jax.tree.map(avg, stacked)


@partial(jax.jit, static_argnames=("agg_dtype",))
def fedavg_fold_acc(
    psum: Pytree,
    wsum: jax.Array,
    others: tuple,
    weights: jax.Array,
    ref: Pytree,
    agg_dtype: str = "float32",
) -> Pytree:
    """Finish a FedAvg whose first term is a pre-folded accumulator.

    ``(psum, wsum)`` is a node's own device-resident partial-aggregation
    accumulator (``weight × params`` folded INSIDE the fused round
    dispatch, ``parallel/spmd.py fused_node_round``); ``others`` is a
    tuple of the remaining contributions' pytrees with ``weights`` their
    ``[k]`` sample counts (k may be 0). One dispatch: the peers stack
    into one ``[k, ...]`` tensordot (the same reduction shape
    :func:`fedavg` compiles, one executable per k like every stacked
    kernel), the running sum and the final divide all fuse; ``ref``
    gives the output dtypes.

    Numerics note: this accumulates-then-divides where :func:`fedavg`
    normalizes-then-tensordots — equivalent algebra, summed in a
    different order, so results agree to summation-order ulp level in
    ``agg_dtype`` (the fold-vs-restack parity test's tolerance), NOT bit
    for bit. The bit-exact fused-vs-staged contract is on the train
    program's outputs (params / opt state / accumulator), not on this
    fold's ordering.
    """
    if others:
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *others)
        w = weights.astype(agg_dtype)
        psum = jax.tree.map(
            lambda s, x: s + jnp.tensordot(w, x.astype(agg_dtype), axes=(0, 0)),
            psum,
            stacked,
        )
        wsum = wsum + jnp.sum(w)
    return jax.tree.map(lambda s, r: (s / wsum).astype(r.dtype), psum, ref)


def fedavg_fold_stacked(stacked_psum: Pytree, stacked_wsum: jax.Array, ref: Pytree) -> Pytree:
    """Finish a FedAvg from node-stacked partial accumulators.

    ``stacked_psum`` leaves are ``[N, ...]`` stacks of per-node
    ``weight × params`` terms (each node's :func:`~p2pfl_tpu.parallel.
    spmd.fused_node_round` ``psum`` output, already in ``AGG_DTYPE``);
    ``stacked_wsum`` is the matching ``[N]`` weight vector. Reduces the
    node axis then divides — the :func:`fedavg_fold_acc` algebra with the
    peer fold expressed as an axis reduction, so under ``jit`` with the
    node axis SHARDED over a mesh the reduction lowers to one per-shard
    partial sum + all-reduce and no device ever holds more than its own
    shard of the aggregate (the submesh federation's cross-slice fold,
    ``parallel/submesh.py``).

    Numerics: accumulate-then-divide, like :func:`fedavg_fold_acc` —
    agrees with :func:`fedavg`'s normalize-then-tensordot to
    summation-order ulp in the accumulate dtype, and bit-for-bit when the
    node weights are equal (scaling by the common factor commutes with
    every rounding step). ``ref`` gives the output dtypes. Deliberately
    NOT jitted here: callers wrap it with their own ``out_shardings``
    (zero masked-out contributions enter as explicit zero stacks, keeping
    the reduction shape static per N).
    """
    wtot = jnp.sum(stacked_wsum)
    return jax.tree.map(
        lambda s, r: (jnp.sum(s, axis=0) / wtot).astype(r.dtype), stacked_psum, ref
    )


@partial(jax.jit, static_argnames=("f",))
def krum_screen_merge(stacked: Pytree, weights: jax.Array, f: int) -> Pytree:
    """Krum SCREENING + weighted mean: drop the ``f`` most outlying
    contributions (Multi-Krum selection with ``multi = N − f``), then fold
    the survivors with the caller's weights — for the async buffer those
    are the staleness weights ``num_samples × w(τ)``, so the FedBuff
    weighting survives the screen (unlike the rank-based kernels, which
    have no weighted analogue). One dispatch: selection indices feed a
    gathered tensordot.
    """
    idx = krum_select(stacked, n_byzantine=f, multi=stacked_n(stacked) - f)
    w = jnp.take(weights.astype("float32"), idx)
    w = w / jnp.sum(w)

    def pick(x):
        sel = jnp.take(x, idx, axis=0).astype("float32")
        return jnp.tensordot(w, sel, axes=(0, 0)).astype(x.dtype)

    return jax.tree.map(pick, stacked)


def stacked_n(stacked: Pytree) -> int:
    """Node-axis length of a stacked pytree (static under jit)."""
    return jax.tree.leaves(stacked)[0].shape[0]


def buffered_robust_merge(
    stacked: Pytree,
    weights: jax.Array,
    kind: str,
    *,
    trim: int = 1,
    f: int = 1,
    agg_dtype: str = "float32",
) -> Pytree:
    """The async buffer's flush kernel, selected by
    ``Settings.ASYNC_ROBUST_AGG`` (``federation/buffer.py``).

    Every branch folds the same ``(origin, seq)``-sorted stack, so the
    buffer's arrival-order-independence determinism contract holds for
    all of them; every branch is a jitted device-resident fold (shape-
    keyed executables, one per K like the sync kernels). Weighting
    semantics per kind:

    - ``"fedavg"`` — the FedBuff staleness-weighted mean (pre-robust
      behavior, bit-identical to the old hardcoded fold);
    - ``"trimmed-mean"`` / ``"median"`` — per-coordinate rank statistics;
      they IGNORE the staleness weights by construction (a weighted rank
      rule forfeits the breakdown-point guarantee that makes it robust);
      τ still bounds admission (over-stale updates were already dropped);
    - ``"krum-screen"`` — Krum drops the ``f`` most outlying
      contributions, the staleness-weighted mean folds the survivors
      (weights kept).

    ``trim``/``f`` are clamped so at least one contribution survives —
    a buffer smaller than the configured robustness degrades to the mean
    of what it has rather than refusing to flush.
    """
    n = stacked_n(stacked)
    if kind == "fedavg" or n == 1:
        return fedavg(stacked, weights, agg_dtype=agg_dtype)
    if kind == "trimmed-mean":
        t = min(int(trim), (n - 1) // 2)
        if t <= 0:
            return fedavg(stacked, weights, agg_dtype=agg_dtype)
        return trimmed_mean(stacked, t)
    if kind == "median":
        return fedmedian(stacked)
    if kind == "krum-screen":
        fc = min(int(f), n - 1)
        # krum_select scores against N − f − 2 nearest neighbors; below
        # that population the screen cannot rank and the mean is all
        # there is
        if fc <= 0 or n - fc - 2 < 1:
            return fedavg(stacked, weights, agg_dtype=agg_dtype)
        return krum_screen_merge(stacked, weights, fc)
    raise ValueError(
        f"unknown ASYNC_ROBUST_AGG {kind!r} "
        "(expected fedavg | trimmed-mean | median | krum-screen)"
    )


def robust_fold_stacked(stacked: Pytree, ref: Pytree, kind: str, *, trim: int = 1) -> Pytree:
    """Robust per-coordinate fold over a NODE-STACKED sharded layout —
    the robust twin of :func:`fedavg_fold_stacked`.

    ``stacked`` leaves are ``[N, ...]`` stacks of per-node PARAMS (raw
    models, not ``weight × params`` accumulators: a median of scaled
    terms is not a median of models), node axis sharded over the mesh's
    nodes axis. Per-coordinate rank statistics reduce the node axis;
    under ``jit`` with model-sharded ``out_shardings`` the partitioner
    re-shards node-stacks to coordinate-shards, so each device only ever
    holds the N values of ITS OWN model shard — N × (1/m) of the model,
    never a full copy (the PR-10 contract; callers assert the sharding
    metadata like ``ShardedNodeFederation._assert_fold_shardings``).

    Deliberately NOT jitted here: callers wrap it with their own
    ``out_shardings`` (``parallel/submesh.py`` robust aggregation).
    ``ref`` gives the output dtypes.
    """
    n = stacked_n(stacked)
    if kind == "median":
        return jax.tree.map(
            lambda x, r: jnp.median(x.astype("float32"), axis=0).astype(r.dtype),
            stacked,
            ref,
        )
    if kind == "trimmed-mean":
        t = min(int(trim), (n - 1) // 2)

        def tm(x, r):
            xs = jnp.sort(x.astype("float32"), axis=0)
            kept = jax.lax.slice_in_dim(xs, t, n - t, axis=0)
            return jnp.mean(kept, axis=0).astype(r.dtype)

        if t <= 0:
            return jax.tree.map(
                lambda x, r: jnp.mean(x.astype("float32"), axis=0).astype(r.dtype),
                stacked,
                ref,
            )
        return jax.tree.map(tm, stacked, ref)
    raise ValueError(f"unknown robust fold kind {kind!r} (expected median | trimmed-mean)")


@jax.jit
def screen_stats(params: Pytree, ref: Pytree) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Admission-screen statistics for one contribution vs the current
    global: ``(‖params‖₂, ‖ref‖₂, cos(params, ref))`` — one fused
    device reduction (``federation/defense.py`` reads the three scalars).
    """
    dot = jnp.float32(0.0)
    p2 = jnp.float32(0.0)
    r2 = jnp.float32(0.0)
    for x, y in zip(jax.tree.leaves(params), jax.tree.leaves(ref)):
        xf = x.astype("float32").ravel()
        yf = y.astype("float32").ravel()
        dot = dot + jnp.dot(xf, yf)
        p2 = p2 + jnp.dot(xf, xf)
        r2 = r2 + jnp.dot(yf, yf)
    pn = jnp.sqrt(jnp.maximum(p2, 1e-24))
    rn = jnp.sqrt(jnp.maximum(r2, 1e-24))
    return pn, rn, dot / (pn * rn)


@partial(jax.jit, static_argnames=("lr", "agg_dtype"))
def server_merge(prev: Pytree, avg: Pytree, lr: float = 1.0, agg_dtype: str = "float32") -> Pytree:
    """FedBuff server step: ``new = (1−η)·prev + η·avg`` in ``agg_dtype``.

    ``avg`` is the buffer's staleness-weighted average (:func:`fedavg`
    over effective weights ``num_samples × w(τ)`` — the weighting lives
    in the weights vector, so the reduction kernel is shared with the
    sync path). ``lr`` (η) is the server mixing rate; at 1.0 the merge
    degenerates to adopting the average. One fused elementwise program;
    output dtypes follow ``prev``.
    """

    def mix(p, a):
        out = (1.0 - lr) * p.astype(agg_dtype) + lr * a.astype(agg_dtype)
        return out.astype(p.dtype)

    return jax.tree.map(mix, prev, avg)


@jax.jit
def fedmedian(stacked: Pytree) -> Pytree:
    """Coordinate-wise median across the node axis."""

    def med(x):
        return jnp.median(x.astype("float32"), axis=0).astype(x.dtype)

    return jax.tree.map(med, stacked)


@partial(jax.jit, static_argnames=("trim",))
def trimmed_mean(stacked: Pytree, trim: int) -> Pytree:
    """Coordinate-wise trimmed mean: drop ``trim`` lowest and highest per coord.

    ``trim`` must satisfy ``2 * trim < N``. Robust to ``trim`` Byzantine nodes.
    """

    def tm(x):
        n = x.shape[0]
        xs = jnp.sort(x.astype("float32"), axis=0)
        kept = jax.lax.slice_in_dim(xs, trim, n - trim, axis=0)
        return jnp.mean(kept, axis=0).astype(x.dtype)

    return jax.tree.map(tm, stacked)


def _flatten_nodes(stacked: Pytree) -> jax.Array:
    """[N, ...] pytree -> [N, P] matrix of all params per node (fp32)."""
    leaves = [x.astype("float32").reshape(x.shape[0], -1) for x in jax.tree.leaves(stacked)]
    return jnp.concatenate(leaves, axis=1)


@partial(jax.jit, static_argnames=("n_byzantine", "multi"))
def krum_select(stacked: Pytree, n_byzantine: int, multi: int = 1) -> jax.Array:
    """Krum / Multi-Krum selection scores.

    Returns the indices of the ``multi`` nodes with the lowest Krum score
    (sum of squared distances to their ``N - f - 2`` nearest neighbors).
    The [N, P] distance matrix is one MXU matmul: ``|a-b|^2 = |a|^2 + |b|^2 - 2ab``.
    """
    flat = _flatten_nodes(stacked)  # [N, P]
    n = flat.shape[0]
    sq = jnp.sum(flat * flat, axis=1)  # [N]
    d2 = sq[:, None] + sq[None, :] - 2.0 * (flat @ flat.T)  # [N, N]
    d2 = jnp.maximum(d2, 0.0)
    d2 = d2.at[jnp.arange(n), jnp.arange(n)].set(jnp.inf)
    k = max(n - n_byzantine - 2, 1)
    nearest = jax.lax.top_k(-d2, k)[0]  # [N, k] negated distances
    scores = -jnp.sum(nearest, axis=1)  # [N]
    return jax.lax.top_k(-scores, multi)[1]  # indices of lowest scores


def krum(stacked: Pytree, n_byzantine: int, multi: int = 1) -> Pytree:
    """(Multi-)Krum aggregate: mean of the ``multi`` selected node models."""
    idx = krum_select(stacked, n_byzantine, multi)

    def pick(x):
        sel = jnp.take(x, idx, axis=0).astype("float32")
        return jnp.mean(sel, axis=0).astype(x.dtype)

    return jax.tree.map(pick, stacked)


@partial(jax.jit, static_argnames=("iters",))
def centered_clip(stacked: Pytree, center: Pytree, tau: float, iters: int = 3) -> Pytree:
    """Centered clipping (Karimireddy, He, Jaggi 2021). Robust aggregator.

    ``v ← v + mean_i clip_tau(x_i − v)`` iterated from ``v = center`` (the
    previous round's global model), where ``clip_tau`` rescales each node's
    whole-model deviation to norm ≤ τ. History-aware: a Byzantine node can
    pull the aggregate at most τ per round regardless of its magnitude —
    unlike coordinate-wise rules it needs no ``f`` estimate, and unlike
    Krum it uses information from every honest node. The per-node
    deviation norms are one ``[N, P]`` reduction; everything stays fp32 on
    device.
    """
    flat_leaves = [x.astype("float32") for x in jax.tree.leaves(stacked)]
    treedef = jax.tree.structure(stacked)
    c_leaves = [x.astype("float32") for x in jax.tree.leaves(center)]

    def norms(v_leaves):
        # [N] L2 norm of each node's deviation from the current center
        sq = sum(
            jnp.sum((x - v[None]) ** 2, axis=tuple(range(1, x.ndim)))
            for x, v in zip(flat_leaves, v_leaves)
        )
        return jnp.sqrt(jnp.maximum(sq, 1e-24))

    def body(_, v_leaves):
        s = jnp.minimum(1.0, tau / norms(v_leaves))  # [N] clip factors
        return [
            v + jnp.mean(s.reshape((-1,) + (1,) * (x.ndim - 1)) * (x - v[None]), axis=0)
            for x, v in zip(flat_leaves, v_leaves)
        ]

    v_leaves = jax.lax.fori_loop(0, iters, body, c_leaves)
    out = jax.tree.unflatten(treedef, v_leaves)
    return jax.tree.map(lambda o, x: o.astype(x.dtype), out, stacked)


@partial(jax.jit, static_argnames=("opt", "lr", "b1", "b2", "tau"))
def fedopt_update(
    prev: Pytree,
    avg: Pytree,
    m: Pytree,
    v: Pytree,
    t: jax.Array,
    opt: str = "adam",
    lr: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.99,
    tau: float = 1e-3,
) -> tuple[Pytree, Pytree, Pytree]:
    """FedOpt server step (Reddi et al. 2021): treat ``prev - avg`` as a
    pseudo-gradient and apply a server-side adaptive optimizer to it.

    ``opt``: ``"adam"`` (FedAdam), ``"yogi"`` (FedYogi) or ``"adagrad"``
    (FedAdagrad). ``m``/``v`` are the server's first/second-moment pytrees;
    ``t`` is the 1-based server step for Adam bias correction. Returns
    ``(new_params, new_m, new_v)`` — one fused elementwise XLA program.
    """

    def one(p, a, mi, vi):
        g = p.astype("float32") - a.astype("float32")  # pseudo-grad
        mn = b1 * mi + (1.0 - b1) * g
        g2 = g * g
        if opt == "adam":
            vn = b2 * vi + (1.0 - b2) * g2
        elif opt == "yogi":
            vn = vi - (1.0 - b2) * g2 * jnp.sign(vi - g2)
        elif opt == "adagrad":
            vn = vi + g2
        else:
            raise ValueError(f"unknown server opt {opt!r}")
        if opt == "adam":
            mhat = mn / (1.0 - b1 ** t)
            vhat = vn / (1.0 - b2 ** t)
        else:
            mhat, vhat = mn, vn
        new = p.astype("float32") - lr * mhat / (jnp.sqrt(vhat) + tau)
        return new.astype(p.dtype), mn, vn

    flat_p, tdef = jax.tree.flatten(prev)
    flat_a = jax.tree.leaves(avg)
    flat_m = jax.tree.leaves(m)
    flat_v = jax.tree.leaves(v)
    out = [one(p, a, mi, vi) for p, a, mi, vi in zip(flat_p, flat_a, flat_m, flat_v)]
    news, ms, vs = zip(*out)
    return tdef.unflatten(news), tdef.unflatten(ms), tdef.unflatten(vs)


def bulyan(stacked: Pytree, n_byzantine: int) -> Pytree:
    """Bulyan (El Mhamdi et al. 2018): iterated Krum selection then
    coordinate-wise trimmed mean — tolerates f Byzantine among N ≥ 4f + 3.

    θ = N − 2f models are selected one at a time (each round re-runs Krum on
    the remaining stack, the true iterative variant), then aggregated with a
    β = f trimmed mean per coordinate. Each iteration is a jitted
    shape-keyed call, so repeated rounds at the same N reuse executables.
    """
    import numpy as np

    n = jax.tree.leaves(stacked)[0].shape[0]
    f = n_byzantine
    if n < 4 * f + 3:
        raise ValueError(f"Bulyan needs N >= 4f + 3 (N={n}, f={f})")
    theta = n - 2 * f

    remaining = list(range(n))
    chosen: list[int] = []
    cur = stacked
    for _ in range(theta):
        idx = int(np.asarray(krum_select(cur, n_byzantine=f, multi=1))[0])
        chosen.append(remaining.pop(idx))
        keep = jnp.asarray([i for i in range(len(remaining) + 1) if i != idx], dtype=jnp.int32)
        cur = jax.tree.map(lambda x: jnp.take(x, keep, axis=0), cur)

    sel = jax.tree.map(lambda x: jnp.take(x, jnp.asarray(chosen, dtype=jnp.int32), axis=0), stacked)
    return trimmed_mean(sel, trim=f)
