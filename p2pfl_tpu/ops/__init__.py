"""Pure JAX ops: pytree math, aggregation kernels, codecs, Pallas attention
kernels and their autotuner."""

from p2pfl_tpu.ops.tree import (
    tree_add,
    tree_scale,
    tree_stack,
    tree_sub,
    tree_unstack,
    tree_weighted_mean,
    tree_zeros_like,
)

def __getattr__(name):
    # FlashConfig is exported lazily: an eager re-export would drag the
    # jax.experimental.pallas import chain into every `p2pfl_tpu.ops`
    # import (gossip/codec-only processes use only ops.tree). Exporting
    # the flash_attention FUNCTION here is deliberately avoided entirely —
    # it would shadow the p2pfl_tpu.ops.flash_attention SUBMODULE.
    if name == "FlashConfig":
        from p2pfl_tpu.ops.flash_attention import FlashConfig

        return FlashConfig
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "FlashConfig",
    "tree_add",
    "tree_scale",
    "tree_stack",
    "tree_sub",
    "tree_unstack",
    "tree_weighted_mean",
    "tree_zeros_like",
]
