"""Megafleet kernels: the async fleet as ONE jitted array program.

:mod:`~p2pfl_tpu.federation.simfleet` drives the async plane as a Python
event heap — exact, but ~10⁴ heap pops/sec caps it three orders of
magnitude short of "heavy traffic from millions of users". This module
re-expresses the same run as a single ``lax.scan`` over the
chronologically sorted contribution arrivals, with the whole edge
population held as dense per-client arrays. The scan body reuses the
REAL aggregation math — :func:`~p2pfl_tpu.ops.aggregation.fedavg` over
effective weights ``num_samples · w(τ)`` and
:func:`~p2pfl_tpu.ops.aggregation.server_merge`, the exact kernels
:class:`~p2pfl_tpu.federation.buffer.BufferedAggregator` folds with
(inlined when traced inside the scan), and
:func:`staleness_weight_arr`, the elementwise twin of
:func:`~p2pfl_tpu.federation.staleness.staleness_weight` — so a
vectorized run is the same algorithm, not a lookalike.

Why a scan over sorted arrivals is EXACT for the flat topology: every
quantity the heap driver derives from event interleaving is a function
of *time* —

- a client's adoption base at a train completion ``t`` is the number of
  global versions whose push had ARRIVED by then, i.e.
  ``searchsorted(mint_times, t − adopt_delay)`` (one binary search
  against the carry's mint-time array replaces the heap's
  ``model_arrive`` events entirely);
- the buffer window an arrival joins is determined by processing
  arrivals in ``t_arr`` order — exactly the heap's pop order;
- and every mint time is the ``K``-th accepted arrival's time, which the
  scan knows at the step that fires the flush.

Because the scan is sorted by arrival time and an update's training time
precedes its arrival, every ``searchsorted`` read only ever sees mint
times that are already final — causality is the sort order. The
hierarchical program extends the same carry with vectorized per-regional
windows (one scatter row per arrival); its one deliberate approximation
is that a regional flush's aggregate is *processed* at the flush step
while its ``link_delay`` shows up only in the recorded mint time and the
adoption bookkeeping — aggregates from different regionals that would
interleave inside one in-flight window can order differently than the
heap's, which is the documented tolerance of the hierarchical parity
anchor (``docs/design.md`` "megafleet").

**Branch-free by design.** The body contains no ``lax.cond``: XLA
double-buffers carry arrays that cross a conditional boundary, and a
per-step copy of the ``[R, K, dim]`` regional windows turns a 4M-event
scan into terabytes of memcpy (measured: 5× the per-event cost at 1M
clients vs 100k before this layout). Instead every step executes the
same straight-line program — predicated scatters into the big carries
(in-place under ``scan``) and an unconditionally computed window fold
whose result is ``where``-masked by the flush predicate. A not-yet-full
window's fold is garbage (even ``0/0`` when empty) that the mask
discards; the extra fold per event is ~100 flops on a ``[K, dim]``
window — noise next to the copies it replaces.

**The cross-buffer copy law** (measured on XLA:CPU, jax 0.4.37; every
rule below is worth ~3 orders of magnitude at 1M clients):

- writing carry ``A`` with a value that reads carry ``B``'s *pre-update*
  state while ``B`` is also written in the same step makes XLA preserve
  ``B`` with a full copy per step — a read→write pair it cannot
  linearize. Copies of an ``[N, …]`` buffer per event are catastrophic.
- Fix 1 — *re-gather*: when the dependent write wants the POST-update
  value, read it back from the already-updated carry (``w_cur``,
  ``agg_params`` below) instead of reusing the temporary that also fed
  the first write. The dataflow becomes linear and everything updates in
  place.
- Fix 2 — *pack coupled state into one buffer*: the adoption bookkeeping
  (``base_seen``) is read to pick the train branch and written every
  step; as a separate ``[N]`` carry it pairs with the ``w`` write and
  re-copies itself per event. It rides as column ``dim`` of the ``w``
  rows instead (f32 — exact for versions < 2²⁴), making adopt+train a
  single-buffer read-modify-write.
- Residual pairs are left where ``B`` is small and R-bounded (``rcount``
  / ``radopt`` / ``mint`` / ``G``): their per-step copies are KB-scale
  in the hierarchical shape. This is also why the FLAT program is the
  1k-parity anchor rather than the fleet-scale engine — its ``G``/
  ``mint`` histories grow with total merges, and the copy law would
  re-copy them per event at 1M clients; the hierarchical shape (the
  production topology) keeps them at the global-version count.

The jit-staleness contract: nothing in a scan body reads ``Settings`` or
mutable module state — every knob (α, η, K, staleness bound, rate gaps)
arrives through the static :class:`FleetConfig`, so a config change
provably re-traces.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import optax

from p2pfl_tpu.ops.aggregation import fedavg, server_merge

Pytree = Any

#: sort key for empty window slots — pads order last and carry weight 0,
#: so they add exact +0.0 terms to the fold (see fold_window)
PAD_KEY = jnp.iinfo(jnp.int32).max


class FleetConfig(NamedTuple):
    """Static shape/knob tuple baked into one compiled fleet program.

    Everything here participates in the trace (a changed value compiles a
    new program — the jit-staleness rule's explicit-argument contract).
    ``rate_gap_*`` are the Bonawitz per-tier rate limits in virtual
    seconds between *accepted* offers (0 disables the gate and compiles
    it out); ``hist_bins`` sizes the staleness histograms (the last bin
    absorbs the tail).
    """

    hier: bool  #: two-tier (regional windows + global) vs flat
    n_clients: int
    dim: int  #: consensus-task parameter dimension
    n_regionals: int  #: R (1 in flat mode; regional 0 is the global root)
    k_global: int  #: global window size (flat: the only window)
    k_reg_max: int  #: widest regional window (per-regional K in reg["k"])
    v_cap: int  #: global version capacity (host-computed upper bound)
    alpha: float  #: FedBuff staleness exponent
    server_lr: float  #: η of the server merge
    local_lr: float  #: consensus-task pull rate toward the private target
    max_staleness: int
    rate_gap_reg: float
    rate_gap_glob: float
    hist_bins: int
    agg_key_stride: int  #: grid column count for (regional, up_seq) lookups
    unroll: int  #: lax.scan unroll factor
    # ---- chunked-engine extensions (defaults keep the per-event
    # construction sites working; see run_fleet_program_chunked) ----
    chunk: int = 1  #: events per scan step (1 = per-event reference engine)
    gf_cap: int = 0  #: max global mints per chunk (host bound: chunk//k+2)
    fold_kind: str = "fedavg"  #: window fold family (Settings.ASYNC_ROBUST_AGG)
    trim: int = 1  #: trimmed-mean clamp (Settings.ASYNC_TRIM)
    task: str = "consensus"  #: "consensus" | "linear" | "mlp" train kernel
    t_din: int = 0  #: gradient-task input dim
    t_nout: int = 0  #: gradient-task class count
    t_hidden: int = 0  #: MLP hidden width (0 for linear)
    t_bs: int = 0  #: per-step batch size
    t_steps: int = 0  #: SGD steps per local round
    data_seed: int = 0  #: PRNG root of the per-(client, round) data streams
    byz: bool = False  #: byzantine payload columns present in events
    dup: bool = False  #: duplicate verdict grids present


def staleness_weight_arr(tau: jax.Array, alpha: float) -> jax.Array:
    """Elementwise FedBuff weight ``w(τ) = 1/(1+τ)^α`` — the array twin
    of :func:`p2pfl_tpu.federation.staleness.staleness_weight` (same
    clamp, same formula, f32; pointwise parity pinned by test). ``alpha``
    is static: 0 compiles to ones like the scalar's early-out."""
    t = jnp.maximum(tau.astype(jnp.float32), 0.0)
    if float(alpha) == 0.0:
        return jnp.ones_like(t)
    return 1.0 / (1.0 + t) ** jnp.float32(alpha)


def grad_param_dim(kind: str, din: int, nout: int, hidden: int = 0) -> int:
    """Flat parameter count of the vmapped tiny learner (``linear``:
    one dense layer; ``mlp``: dense→relu→dense)."""
    if kind == "linear":
        return din * nout + nout
    if kind == "mlp":
        return din * hidden + hidden + hidden * nout + nout
    raise ValueError(f"unknown gradient task kind {kind!r}")


def grad_logits(
    kind: str, din: int, nout: int, hidden: int, flat: jax.Array, x: jax.Array
) -> jax.Array:
    """Forward pass from a FLAT parameter vector — the same dense math a
    flax ``Dense`` stack computes, unflattened by index arithmetic so the
    whole model rides as one ``[dim]`` row of the fleet carry."""
    if kind == "linear":
        w = flat[: din * nout].reshape(din, nout)
        b = flat[din * nout :]
        return x @ w + b
    o = din * hidden
    w1 = flat[:o].reshape(din, hidden)
    b1 = flat[o : o + hidden]
    o += hidden
    w2 = flat[o : o + hidden * nout].reshape(hidden, nout)
    b2 = flat[o + hidden * nout :]
    h = jax.nn.relu(x @ w1 + b1)
    return h @ w2 + b2


def make_grad_fns(
    kind: str,
    din: int,
    nout: int,
    hidden: int,
    bs: int,
    steps: int,
    lr: float,
    data_seed: int,
):
    """Build the gradient-task kernels shared by every consumer that must
    agree on the SAME local round: the chunked fleet engine, the heap
    driver's vectorized-twin ``train_fn`` (1k parity pin) and the
    :class:`~p2pfl_tpu.learning.learner.JaxLearner` parity test.

    Returns ``(gen_batch, train_one, train_vec)``:

    - ``gen_batch(i, m, mu_row, tw, tb)`` → ``(xs [steps, bs, din],
      ys [steps, bs] int32)`` — the i-th client's m-th local round drawn
      from the counter-keyed stream ``fold_in(fold_in(key(data_seed), i),
      m)``: a Gaussian cloud around the client's ``mu`` (the non-IID
      knob) labeled by a fixed teacher — order-independent, so heap and
      scan derive identical batches from (client, seq) alone;
    - ``train_one(flat, xs, ys)`` — ``steps`` plain-SGD steps on
      softmax cross-entropy, arranged as ``p + g·(−lr)`` which is
      bit-identical to ``optax.sgd`` + ``apply_updates`` (the exact
      update :meth:`JaxLearner.train_epoch` applies);
    - ``train_vec`` — ``train_one∘gen_batch`` vmapped over
      ``(flat, i, m, mu)`` with the teacher broadcast.
    """
    root = jax.random.PRNGKey(data_seed)

    def gen_batch(i, m, mu_row, tw, tb):
        key = jax.random.fold_in(jax.random.fold_in(root, i), m)
        x = mu_row[None, None, :] + jax.random.normal(key, (steps, bs, din), jnp.float32)
        y = jnp.argmax(x @ tw + tb, axis=-1).astype(jnp.int32)
        return x, y

    neg_lr = jnp.float32(-lr)

    def train_one(flat, xs, ys):
        def step(p, xy):
            x, y = xy

            def loss_fn(q):
                logits = grad_logits(kind, din, nout, hidden, q, x)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, y
                ).mean()

            g = jax.grad(loss_fn)(p)
            return p + g * neg_lr, None

        out, _ = jax.lax.scan(step, flat, (xs, ys))
        return out

    def train_vec(flats, his, los, mus, tw, tb):
        def one(flat, i, m, mu):
            xs, ys = gen_batch(i, m, mu, tw, tb)
            return train_one(flat, xs, ys)

        return jax.vmap(one)(flats, his, los, mus)

    return gen_batch, train_one, train_vec


def fold_window(
    rows: jax.Array,
    weights: jax.Array,
    keys: jax.Array,
    prev: jax.Array,
    server_lr: float,
    kind: str = "fedavg",
    trim: int = 1,
    keys_hi: jax.Array | None = None,
) -> jax.Array:
    """One buffer flush on a dense window — exactly the live
    :meth:`BufferedAggregator._merge_locked` math: sort the window by its
    ``(origin, seq)`` fold keys, fold (:func:`fedavg`, or the robust
    family of :func:`~p2pfl_tpu.ops.aggregation.buffered_robust_merge`)
    over the effective weights, :func:`server_merge` into ``prev``.
    Empty pad slots (``weights == 0``, ``keys == PAD_KEY``) sort last and
    contribute exact ``+0.0`` terms to the fedavg path, so a clamped-K
    regional window folds bit-identically to a dense K-length fold. (An
    ALL-empty window divides 0/0 — callers inside the scan mask the
    result with the flush predicate, which is False exactly then.)

    ``keys_hi`` is the high word of the two-word ``(origin, seq)`` fold
    key (``lexsort((keys, keys_hi))`` == the heap's tuple sort over
    zero-padded origin addresses); when ``None`` the single int32 ``keys``
    carries the whole order — the pre-two-word calling convention.

    ``kind``/``trim`` are static and select the flush family exactly as
    ``Settings.ASYNC_ROBUST_AGG``/``ASYNC_TRIM`` select the heap
    buffer's. The robust kinds are pad-AWARE twins of ``trimmed_mean`` /
    ``fedmedian``: rank statistics over the ``weights > 0`` slots only
    (same clamp ``trim ≤ (n-1)//2``, degrade-to-mean at ``n`` too small,
    weights ignored by construction), computed branch-free over a
    possibly-padded window so a clamped-K regional flush matches the
    heap's dense n-row fold to fp tolerance. ``"krum-screen"`` needs the
    pairwise-distance screen and stays heap-only (host raises upstream).

    ``rows [K, dim]``, ``weights [K]``, ``prev [dim]``; ``server_lr`` is
    static. Reuses the SAME jitted kernels the live buffer calls — under
    an outer trace they inline, standalone they dispatch once each.
    """
    if keys_hi is None:
        order = jnp.argsort(keys)
    else:
        order = jnp.lexsort((keys, keys_hi))
    sorted_rows = jnp.take(rows, order, axis=0)
    sorted_w = jnp.take(weights, order)
    if kind == "fedavg":
        avg = fedavg({"p": sorted_rows}, sorted_w, agg_dtype="float32")["p"]
    elif kind in ("trimmed-mean", "median"):
        # pads (weight 0) sort to +inf per coordinate; n live rows occupy
        # ranks [0, n) after the sort, so rank selection is index math
        live = sorted_w > 0.0
        n = jnp.sum(live.astype(jnp.int32))
        vals = jnp.where(live[:, None], sorted_rows.astype(jnp.float32), jnp.inf)
        svals = jnp.sort(vals, axis=0)
        k = rows.shape[0]
        ranks = jnp.arange(k, dtype=jnp.int32)
        if kind == "median":
            lo = svals[jnp.clip((n - 1) // 2, 0, k - 1)]
            hi = svals[jnp.clip(n // 2, 0, k - 1)]
            avg = 0.5 * (lo + hi)
        else:
            t = jnp.minimum(jnp.int32(trim), (n - 1) // 2)
            keep = (ranks[:, None] >= t) & (ranks[:, None] < n - t)
            kept = jnp.where(keep, svals, 0.0)
            avg = jnp.sum(kept, axis=0) / jnp.maximum(n - 2 * t, 1).astype(jnp.float32)
        # single-row window: rank stats degrade to that row (heap: n==1
        # short-circuits to fedavg of one)
        avg = jnp.where(n >= 1, avg, jnp.zeros_like(avg))
    else:  # pragma: no cover - host guards reject krum-screen upstream
        raise ValueError(f"fold kind {kind!r} has no vectorized window fold")
    return server_merge({"p": prev}, {"p": avg}, lr=server_lr, agg_dtype="float32")["p"]


def _init_carry(cfg: FleetConfig, init_params) -> Dict[str, jax.Array]:
    n, dim, r = cfg.n_clients, cfg.dim, cfg.n_regionals
    row0 = jnp.concatenate(
        [jnp.asarray(init_params, jnp.float32), jnp.zeros((1,), jnp.float32)]
    )
    carry = {
        # per-client lazy state: current params, with the highest adopted
        # version packed as column `dim` (the cross-buffer copy law — a
        # separate [N] base_seen carry would be re-copied per event)
        "w": jnp.broadcast_to(row0, (n, dim + 1)).astype(jnp.float32),
        # global model history: G[v] = params of version v (G[0] = init);
        # mint[v-1] = virtual time version v was minted (+inf = unminted)
        "G": jnp.zeros((cfg.v_cap + 1, dim), jnp.float32).at[0].set(init_params),
        "mint": jnp.full((cfg.v_cap,), jnp.inf, jnp.float32),
        "last_mint": jnp.float32(-jnp.inf),
        "version": jnp.int32(0),
        # global window; fold keys are two int32 words (hi = origin
        # index, lo = sequence) — the heap's (origin, seq) tuple order
        # without int64, so 1M clients × long runs never overflow a key
        "gbuf": jnp.zeros((cfg.k_global, dim), jnp.float32),
        "gwt": jnp.zeros((cfg.k_global,), jnp.float32),
        "gkey_hi": jnp.full((cfg.k_global,), PAD_KEY, jnp.int32),
        "gkey_lo": jnp.full((cfg.k_global,), PAD_KEY, jnp.int32),
        "gcount": jnp.int32(0),
        "last_acc_g": jnp.float32(-jnp.inf),
        # counters + staleness histograms, split by seam: "edge" = where
        # client updates enter a window (the regional tier, or the global
        # window in flat mode), "agg" = where regional aggregates enter
        # the global window (hier only)
        "merges": jnp.int32(0),
        "stale_edge": jnp.int32(0),
        "rate_edge": jnp.int32(0),
        "stale_agg": jnp.int32(0),
        "rate_agg": jnp.int32(0),
        "hist_edge": jnp.zeros((cfg.hist_bins,), jnp.int32),
        "hist_glob": jnp.zeros((cfg.hist_bins,), jnp.int32),
    }
    if cfg.hier:
        carry.update(
            {
                # vectorized regional tier: one window + lazily-adopted
                # params per regional, all scatter-addressed by r
                "rbuf": jnp.zeros((r, cfg.k_reg_max, dim), jnp.float32),
                "rwt": jnp.zeros((r, cfg.k_reg_max), jnp.float32),
                "rsamp": jnp.zeros((r, cfg.k_reg_max), jnp.float32),
                "rkey_hi": jnp.full((r, cfg.k_reg_max), PAD_KEY, jnp.int32),
                "rkey_lo": jnp.full((r, cfg.k_reg_max), PAD_KEY, jnp.int32),
                "rcount": jnp.zeros((r,), jnp.int32),
                "rparams": jnp.broadcast_to(init_params, (r, dim)).astype(jnp.float32),
                "radopt": jnp.zeros((r,), jnp.int32),
                "up_seq": jnp.zeros((r,), jnp.int32),
                "last_acc_r": jnp.full((r,), -jnp.inf, jnp.float32),
                "rmerges": jnp.int32(0),
                "agg_drop": jnp.int32(0),
            }
        )
    return carry


def run_fleet_program(
    cfg: FleetConfig,
    events: Dict[str, jax.Array],
    clients: Dict[str, jax.Array],
    reg: Dict[str, jax.Array],
    init_params: jax.Array,
) -> Dict[str, Any]:
    """Compile and run the fleet scan. ``events`` are the pre-sorted
    arrival rows (``client/key/t_train/t_arr/send_ok``, each ``[E]``);
    ``clients`` holds ``targets [N, dim]``, ``samples [N]``,
    ``adopt_delay [N]`` and (hier) ``regional_of [N]``; ``reg`` holds the
    per-regional ``k``, ``adopt_delay`` and ``agg_delay`` arrays. Returns
    the final carry (host-side consumers slice ``G``/``mint`` by
    ``version``). One compile per :class:`FleetConfig`.
    """

    def offer_global(c, accept, params, wgt, key_hi, key_lo, tau, t_evt, seam):
        """Predicated offer into the global window + masked flush.
        ``seam`` ("edge" | "agg") is a trace-time label selecting which
        counter/histogram family the admission feeds."""
        fresh = tau <= cfg.max_staleness
        if cfg.rate_gap_glob > 0.0:
            rate_ok = (t_evt - c["last_acc_g"]) >= cfg.rate_gap_glob
        else:
            rate_ok = jnp.bool_(True)
        ins = accept & fresh & rate_ok
        hist = "hist_edge" if seam == "edge" else "hist_glob"
        c[f"stale_{seam}"] = c[f"stale_{seam}"] + (accept & ~fresh).astype(jnp.int32)
        c[f"rate_{seam}"] = c[f"rate_{seam}"] + (
            accept & fresh & ~rate_ok
        ).astype(jnp.int32)

        slot = c["gcount"]
        c["gbuf"] = c["gbuf"].at[slot].set(jnp.where(ins, params, c["gbuf"][slot]))
        c["gwt"] = c["gwt"].at[slot].set(jnp.where(ins, wgt, c["gwt"][slot]))
        c["gkey_hi"] = c["gkey_hi"].at[slot].set(
            jnp.where(ins, key_hi, c["gkey_hi"][slot])
        )
        c["gkey_lo"] = c["gkey_lo"].at[slot].set(
            jnp.where(ins, key_lo, c["gkey_lo"][slot])
        )
        c["last_acc_g"] = jnp.where(ins, t_evt, c["last_acc_g"])
        c[hist] = c[hist].at[jnp.clip(tau, 0, cfg.hist_bins - 1)].add(
            ins.astype(jnp.int32)
        )
        count = c["gcount"] + ins.astype(jnp.int32)
        flush = ins & (count == cfg.k_global)
        c["gcount"] = jnp.where(flush, 0, count)

        # the fold runs every step (garbage when not flushing, masked
        # below) — cheaper than letting the window cross a cond boundary
        new_g = fold_window(
            c["gbuf"],
            c["gwt"],
            c["gkey_lo"],
            c["G"][c["version"]],
            cfg.server_lr,
            kind=cfg.fold_kind,
            trim=cfg.trim,
            keys_hi=c["gkey_hi"],
        )
        v = c["version"] + flush.astype(jnp.int32)
        c["G"] = c["G"].at[v].set(jnp.where(flush, new_g, c["G"][v]))
        # the recorded mint time is clamped monotone: out-of-order
        # aggregate arrival times (the hier ordering tolerance) must not
        # make the searchsorted axis non-ascending
        t_mint = jnp.maximum(t_evt, c["last_mint"])
        mi = jnp.where(flush, v - 1, 0)
        c["mint"] = c["mint"].at[mi].set(jnp.where(flush, t_mint, c["mint"][mi]))
        c["last_mint"] = jnp.where(flush, t_mint, c["last_mint"])
        c["version"] = v
        c["merges"] = c["merges"] + flush.astype(jnp.int32)
        empty_w = jnp.zeros((cfg.k_global,), jnp.float32)
        empty_k = jnp.full((cfg.k_global,), PAD_KEY, jnp.int32)
        c["gwt"] = jnp.where(flush, empty_w, c["gwt"])
        c["gkey_hi"] = jnp.where(flush, empty_k, c["gkey_hi"])
        c["gkey_lo"] = jnp.where(flush, empty_k, c["gkey_lo"])
        return c

    def offer_regional(
        c, accept, r, params, raw_samples, wgt, key_hi, key_lo, tau, rv, t_arr
    ):
        """Predicated offer into regional ``r``; a full window flushes
        into the regional params and sends the aggregate up."""
        fresh = tau <= cfg.max_staleness
        if cfg.rate_gap_reg > 0.0:
            rate_ok = (t_arr - c["last_acc_r"][r]) >= cfg.rate_gap_reg
        else:
            rate_ok = jnp.bool_(True)
        ins = accept & fresh & rate_ok
        c["stale_edge"] = c["stale_edge"] + (accept & ~fresh).astype(jnp.int32)
        c["rate_edge"] = c["rate_edge"] + (accept & fresh & ~rate_ok).astype(jnp.int32)

        slot = c["rcount"][r]
        c["rbuf"] = c["rbuf"].at[r, slot].set(jnp.where(ins, params, c["rbuf"][r, slot]))
        c["rwt"] = c["rwt"].at[r, slot].set(jnp.where(ins, wgt, c["rwt"][r, slot]))
        c["rsamp"] = c["rsamp"].at[r, slot].set(
            jnp.where(ins, raw_samples, c["rsamp"][r, slot])
        )
        c["rkey_hi"] = c["rkey_hi"].at[r, slot].set(
            jnp.where(ins, key_hi, c["rkey_hi"][r, slot])
        )
        c["rkey_lo"] = c["rkey_lo"].at[r, slot].set(
            jnp.where(ins, key_lo, c["rkey_lo"][r, slot])
        )
        c["last_acc_r"] = c["last_acc_r"].at[r].set(
            jnp.where(ins, t_arr, c["last_acc_r"][r])
        )
        c["hist_edge"] = c["hist_edge"].at[jnp.clip(tau, 0, cfg.hist_bins - 1)].add(
            ins.astype(jnp.int32)
        )
        count = c["rcount"][r] + ins.astype(jnp.int32)
        flush = ins & (count == reg["k"][r])
        c["rcount"] = c["rcount"].at[r].set(jnp.where(flush, 0, count))

        # regional flush (masked): current params = lazily-adopted
        # freshest arrived global (set_global semantics — only the last
        # adoption before the flush matters), fold, push the aggregate up
        cur = jnp.where(rv > c["radopt"][r], c["G"][rv], c["rparams"][r])
        merged = fold_window(
            c["rbuf"][r],
            c["rwt"][r],
            c["rkey_lo"][r],
            cur,
            cfg.server_lr,
            kind=cfg.fold_kind,
            trim=cfg.trim,
            keys_hi=c["rkey_hi"][r],
        )
        raw = jnp.sum(c["rsamp"][r])
        c["rparams"] = c["rparams"].at[r].set(jnp.where(flush, merged, c["rparams"][r]))
        # same re-gather trick as w_cur: the aggregate pushed upward reads
        # the updated rparams row (== merged whenever flush, the only
        # predicate under which offer_global consumes it) so `merged`
        # never feeds two carry buffers
        agg_params = c["rparams"][r]
        c["radopt"] = c["radopt"].at[r].set(
            jnp.where(flush, jnp.maximum(c["radopt"][r], rv), c["radopt"][r])
        )
        c["rmerges"] = c["rmerges"] + flush.astype(jnp.int32)
        up = c["up_seq"][r] + flush.astype(jnp.int32)
        c["up_seq"] = c["up_seq"].at[r].set(up)
        empty_w = jnp.zeros((cfg.k_reg_max,), jnp.float32)
        empty_k = jnp.full((cfg.k_reg_max,), PAD_KEY, jnp.int32)
        c["rwt"] = c["rwt"].at[r].set(jnp.where(flush, empty_w, c["rwt"][r]))
        c["rsamp"] = c["rsamp"].at[r].set(jnp.where(flush, empty_w, c["rsamp"][r]))
        c["rkey_hi"] = c["rkey_hi"].at[r].set(jnp.where(flush, empty_k, c["rkey_hi"][r]))
        c["rkey_lo"] = c["rkey_lo"].at[r].set(jnp.where(flush, empty_k, c["rkey_lo"][r]))

        # the upward aggregate: version triple (r, up, rv) with effective
        # weight raw_samples · w(τ_g) — processed now, arrival-time
        # bookkeeping via the regional's agg_delay (0 for the root's own
        # cluster: a direct offer). The regional→root hop is a real wire
        # in the heap driver, so it sees the fault plan too: per-send
        # drop verdicts and jitter from the host-precomputed
        # (regional, up_seq) grids (all-pass / zero when no plan).
        sidx = jnp.clip(up - 1, 0, reg["send_ok"].shape[1] - 1)
        agg_ok = reg["send_ok"][r, sidx]
        t_agg = t_arr + reg["agg_delay"][r] + reg["jit"][r, sidx]
        c["agg_drop"] = c["agg_drop"] + (flush & ~agg_ok).astype(jnp.int32)
        tau_g = jnp.maximum(c["version"] - rv, 0)
        gwgt = raw * staleness_weight_arr(tau_g, cfg.alpha)
        return offer_global(
            c, flush & agg_ok, agg_params, gwgt, r, up, tau_g, t_agg, "agg"
        )

    def body(c, e):
        i = e["client"]
        # ---- adopt + train (always: a wire drop loses the SEND, not the
        # local step — heap semantics). The train step is distributed
        # into the two adoption branches with the heap's exact arithmetic
        # order (x + lr·(t − x)) so each branch is bit-identical to the
        # event driver's numpy step.
        base = jnp.searchsorted(
            c["mint"], e["t_train"] - clients["adopt_delay"][i]
        ).astype(jnp.int32)
        row = c["w"][i]
        wvec, prev = row[: cfg.dim], row[cfg.dim]
        base_f = base.astype(jnp.float32)
        adopt = base_f > prev
        g = c["G"][base]
        ti = clients["targets"][i]
        lr = jnp.float32(cfg.local_lr)
        new_vec = jnp.where(adopt, g + lr * (ti - g), wvec + lr * (ti - wvec))
        new_base = jnp.maximum(base_f, prev)
        c["w"] = c["w"].at[i].set(jnp.concatenate([new_vec, new_base[None]]))
        # re-gather from the UPDATED carry instead of reusing the new_vec
        # temporary: one value feeding two carry buffers (the w scatter
        # above + a window scatter below) defeats XLA's in-place buffer
        # reuse and re-copies the whole [N, dim] state per step —
        # measured 1000× the per-event cost at 100k clients
        row_cur = c["w"][i]
        w_cur = row_cur[: cfg.dim]
        base_eff = row_cur[cfg.dim].astype(jnp.int32)

        ok = e["send_ok"]
        samples = clients["samples"][i]
        if cfg.hier:
            r = clients["regional_of"][i]
            rv = jnp.searchsorted(
                c["mint"], e["t_arr"] - reg["adopt_delay"][r]
            ).astype(jnp.int32)
            tau = jnp.maximum(rv - base_eff, 0)
            wgt = samples * staleness_weight_arr(tau, cfg.alpha)
            c = offer_regional(
                c, ok, r, w_cur, samples, wgt, e["key_hi"], e["key_lo"], tau, rv,
                e["t_arr"],
            )
        else:
            tau = jnp.maximum(c["version"] - base_eff, 0)
            wgt = samples * staleness_weight_arr(tau, cfg.alpha)
            c = offer_global(
                c, ok, w_cur, wgt, e["key_hi"], e["key_lo"], tau, e["t_arr"], "edge"
            )
        return c, None

    @jax.jit
    def program(events, carry):
        carry, _ = jax.lax.scan(body, carry, events, unroll=cfg.unroll)
        return carry

    carry = _init_carry(cfg, init_params)
    return program(events, carry)


# ---------------------------------------------------------------------------
# chunked-event engine
# ---------------------------------------------------------------------------


def _init_carry_chunked(
    cfg: FleetConfig, init_params, n_w_rows: int | None = None
) -> Dict[str, jax.Array]:
    """The per-event carry plus one TRASH row per scatter target (client
    ``N``, regional ``R``, version ``v_cap+1``, mint ``v_cap``): masked
    scatters route their dead lanes there instead of predicating every
    write, which keeps the chunk body one straight-line program.

    ``n_w_rows`` overrides the client-row count of ``w`` for the sharded
    engine, whose layout is ``shards × (ncap + local trash)`` — every
    row is the same init row, so the shape is the only difference."""
    n, dim, r = cfg.n_clients, cfg.dim, cfg.n_regionals
    rows = (n + 1) if n_w_rows is None else n_w_rows
    row0 = jnp.concatenate(
        [jnp.asarray(init_params, jnp.float32), jnp.zeros((1,), jnp.float32)]
    )
    carry = {
        "w": jnp.broadcast_to(row0, (rows, dim + 1)).astype(jnp.float32),
        "G": jnp.zeros((cfg.v_cap + 2, dim), jnp.float32).at[0].set(init_params),
        "mint": jnp.full((cfg.v_cap + 1,), jnp.inf, jnp.float32),
        "last_mint": jnp.float32(-jnp.inf),
        "version": jnp.int32(0),
        "gbuf": jnp.zeros((cfg.k_global + 1, dim), jnp.float32),
        "gwt": jnp.zeros((cfg.k_global + 1,), jnp.float32),
        "gkey_hi": jnp.full((cfg.k_global + 1,), PAD_KEY, jnp.int32),
        "gkey_lo": jnp.full((cfg.k_global + 1,), PAD_KEY, jnp.int32),
        "gcount": jnp.int32(0),
        "last_acc_g": jnp.float32(-jnp.inf),
        "merges": jnp.int32(0),
        "stale_edge": jnp.int32(0),
        "rate_edge": jnp.int32(0),
        "stale_agg": jnp.int32(0),
        "rate_agg": jnp.int32(0),
        "dup_agg": jnp.int32(0),
        "byz_agg": jnp.int32(0),
        "hist_edge": jnp.zeros((cfg.hist_bins,), jnp.int32),
        "hist_glob": jnp.zeros((cfg.hist_bins,), jnp.int32),
    }
    if cfg.hier:
        carry.update(
            {
                "rbuf": jnp.zeros((r + 1, cfg.k_reg_max, dim), jnp.float32),
                "rwt": jnp.zeros((r + 1, cfg.k_reg_max), jnp.float32),
                "rsamp": jnp.zeros((r + 1, cfg.k_reg_max), jnp.float32),
                "rkey_hi": jnp.full((r + 1, cfg.k_reg_max), PAD_KEY, jnp.int32),
                "rkey_lo": jnp.full((r + 1, cfg.k_reg_max), PAD_KEY, jnp.int32),
                "rcount": jnp.zeros((r + 1,), jnp.int32),
                "rparams": jnp.broadcast_to(init_params, (r + 1, dim)).astype(
                    jnp.float32
                ),
                "radopt": jnp.zeros((r + 1,), jnp.int32),
                "up_seq": jnp.zeros((r + 1,), jnp.int32),
                "last_acc_r": jnp.full((r + 1,), -jnp.inf, jnp.float32),
                "rmerges": jnp.int32(0),
                "agg_drop": jnp.int32(0),
            }
        )
    return carry


def _make_train_vec(cfg: FleetConfig, clients: Dict[str, jax.Array]):
    """The chunk engines' batched local round: ``train_vec(starts, idx,
    e)`` trains every lane of a ``[C, dim]`` start matrix as client
    ``idx``'s next local round (consensus pull toward the private
    target, or the :func:`make_grad_fns` SGD round keyed by the lane's
    ``(key_hi, key_lo)`` fold key — which IS (client, seq), so the
    drawn batch is order-independent)."""
    if cfg.task == "consensus":

        def train_vec(starts, idx, e):
            ti = clients["targets"][idx]
            lr = jnp.float32(cfg.local_lr)
            return starts + lr * (ti - starts)

    else:
        _, _, tv = make_grad_fns(
            cfg.task,
            cfg.t_din,
            cfg.t_nout,
            cfg.t_hidden,
            cfg.t_bs,
            cfg.t_steps,
            cfg.local_lr,
            cfg.data_seed,
        )

        def train_vec(starts, idx, e):
            mu = clients["mu"][idx]
            return tv(starts, e["key_hi"], e["key_lo"], mu, clients["tw"], clients["tb"])

    return train_vec


def _make_apply_byz(cfg: FleetConfig, clients: Dict[str, jax.Array]):
    """Vectorized ByzantineSpec payload transforms at the send seam
    (sign_flip / scale / noise by per-event kind code); the noise
    rows are host-drawn per attacker send (counter stream 47) and
    pre-scaled by ``noise_std``."""

    def apply_byz(p, e):
        if not cfg.byz:
            return p
        k = e["bkind"][:, None]
        p = jnp.where(k == 1, -p, p)
        p = jnp.where(k == 2, e["blam"][:, None] * p, p)
        if "bnoise" in e:
            p = jnp.where(k == 3, p + clients["noise"][e["bnoise"]], p)
        return p

    return apply_byz


def _make_chunk_body(
    cfg: FleetConfig,
    clients: Dict[str, jax.Array],
    reg: Dict[str, jax.Array],
    train_vec,
    apply_byz,
    adopt_train,
    writeback_w,
):
    """The shared chunk step of the chunked AND sharded engines — the
    admission scan (pass B), the flush loop (pass C) and the replicated
    writebacks (pass D) are one implementation; only the two touches of
    the fleet-scale ``w`` buffer differ by layout and arrive as hooks:

    - ``adopt_train(c, e) -> (c, wcur, prev0i, base0)`` — pass A: gather
      the chunk's client rows, adopt against the PRE-chunk mint history,
      run one vmapped local round, scatter the rows back, and return the
      CHRONOLOGICAL ``[C, dim]`` trained payloads plus each lane's
      pre-chunk adopted version. The sharded hook trains only the lanes
      its shard owns and reassembles the chronological view with one
      ``all_gather`` (pure concatenation — no cross-shard arithmetic, so
      nothing reassociates).
    - ``writeback_w(c, e, ys, fresh_g, v0) -> c`` — the corrected-adopter
      re-scatter at the end of pass D (lanes whose adoption base moved
      by an in-chunk mint retrain from the fresh global).

    Everything the hooks feed back is ``[C]``-chronological, so the
    verdict math in between is layout-blind — the sharded engine's
    bit-parity with the chunked engine is this function being shared.
    """
    C = cfg.chunk
    GF = cfg.gf_cap
    dim = cfg.dim
    v_cap = cfg.v_cap
    k_max = cfg.k_reg_max
    k_glob = cfg.k_global
    stride = cfg.agg_key_stride
    r_trash = cfg.n_regionals
    v_trash = cfg.v_cap + 1
    m_trash = cfg.v_cap

    def chunk_body(c, e):
        idx = e["client"]
        live = e["live"]

        # ---- pass A (layout hook): adopt + train against the PRE-chunk
        # mint history, returning the chronological trained payloads
        c, wcur, prev0i, base0 = adopt_train(c, e)
        mint_hist = c["mint"][:v_cap]

        payload0 = apply_byz(wcur, e)
        samples = clients["samples"][idx]
        v0 = c["version"]
        ok0 = e["send_ok"] & live
        nm0 = jnp.full((GF,), jnp.inf, jnp.float32)

        # ---- pass B: scalar admission scan (window bookkeeping only)
        if cfg.hier:
            rr = e["r"]
            rv0 = jnp.searchsorted(mint_hist, e["t_radopt"]).astype(jnp.int32)
            rcnt0 = c["rcount"][rr]
            up0 = c["up_seq"][rr]
            lacc0 = c["last_acc_r"][rr]

            def adm(s, x):
                (ver, gcnt, gwin, nmn, lastm, laccg, nm, cnt_sc, win_sc, up_sc,
                 lacc_sc, j) = s
                adj = jnp.sum((nm < x["t_adopt"]).astype(jnp.int32))
                radj = jnp.sum((nm < x["t_radopt"]).astype(jnp.int32))
                v_a = jnp.maximum(x["base0"] + adj, x["prev0"])
                rv = x["rv0"] + radj
                tau = jnp.maximum(rv - v_a, 0)
                fresh = tau <= cfg.max_staleness
                p = x["prev_r"]
                has_p = p >= 0
                pc = jnp.clip(p, 0, C - 1)
                cnt_in = jnp.where(has_p, cnt_sc[pc], x["rcnt0"])
                win_in = jnp.where(has_p, win_sc[pc], 0)
                up_in = jnp.where(has_p, up_sc[pc], x["up0"])
                lacc_in = jnp.where(has_p, lacc_sc[pc], x["lacc0"])
                if cfg.rate_gap_reg > 0.0:
                    rate_ok = (x["t_arr"] - lacc_in) >= cfg.rate_gap_reg
                else:
                    rate_ok = jnp.bool_(True)
                acc = x["ok"]
                ins = acc & fresh & rate_ok
                cnt_new = cnt_in + ins.astype(jnp.int32)
                # >= not ==: a churn epoch can shrink k below an already
                # part-filled window; the next insertion still flushes
                flush_r = ins & (cnt_new >= x["k_r"])
                cnt_out = jnp.where(flush_r, 0, cnt_new)
                win_out = win_in + flush_r.astype(jnp.int32)
                up_new = up_in + flush_r.astype(jnp.int32)
                lacc_out = jnp.where(ins, x["t_arr"], lacc_in)

                # inline aggregate admission — the heap's order: the
                # flush's upward send crosses the wire grids, then the
                # global window, at this same position in the chunk
                sidx = jnp.clip(up_new - 1, 0, stride - 1)
                rrj = x["rr"]
                agg_ok = reg["send_ok"][rrj, sidx]
                t_agg = x["t_arr"] + reg["agg_delay"][rrj] + reg["jit"][rrj, sidx]
                if cfg.dup:
                    dup = flush_r & agg_ok & reg["dup"][rrj, sidx]
                else:
                    dup = jnp.bool_(False)
                tau_g = jnp.maximum(ver - rv, 0)
                fresh_g = tau_g <= cfg.max_staleness
                if cfg.rate_gap_glob > 0.0:
                    rate_g_ok = (t_agg - laccg) >= cfg.rate_gap_glob
                else:
                    rate_g_ok = jnp.bool_(True)
                acc_g = flush_r & agg_ok
                gins = acc_g & fresh_g & rate_g_ok
                gslot = gcnt
                gcnt_new = gcnt + gins.astype(jnp.int32)
                gflush = gins & (gcnt_new >= k_glob)
                gcnt_out = jnp.where(gflush, 0, gcnt_new)
                gwin_ins = gwin
                gwin_out = gwin + gflush.astype(jnp.int32)
                laccg_out = jnp.where(gins, t_agg, laccg)
                tm = jnp.maximum(t_agg, lastm)
                nmi = jnp.clip(nmn, 0, GF - 1)
                nm_out = nm.at[nmi].set(jnp.where(gflush, tm, nm[nmi]))
                nmn_out = nmn + gflush.astype(jnp.int32)
                lastm_out = jnp.where(gflush, tm, lastm)
                ver_out = ver + gflush.astype(jnp.int32)

                cnt_sc = cnt_sc.at[j].set(cnt_out)
                win_sc = win_sc.at[j].set(win_out)
                up_sc = up_sc.at[j].set(up_new)
                lacc_sc = lacc_sc.at[j].set(lacc_out)
                ys = {
                    "ins": ins,
                    "slot": cnt_in,
                    "win": win_in,
                    "cnt_out": cnt_out,
                    "win_out": win_out,
                    "up": up_new,
                    "tau": tau,
                    "adj": adj,
                    "flush_r": flush_r,
                    "lacc": lacc_out,
                    "stale_e": acc & ~fresh,
                    "rate_e": acc & fresh & ~rate_ok,
                    "rv": rv,
                    "gins": gins,
                    "gslot": gslot,
                    "gwin": gwin_ins,
                    "taug": tau_g,
                    "gflush": gflush,
                    "aggdrop": flush_r & ~agg_ok,
                    "dup": dup,
                    "stale_g": acc_g & ~fresh_g,
                    "rate_g": acc_g & fresh_g & ~rate_g_ok,
                }
                return (
                    ver_out, gcnt_out, gwin_out, nmn_out, lastm_out, laccg_out,
                    nm_out, cnt_sc, win_sc, up_sc, lacc_sc, j + 1,
                ), ys

            xs = {
                "t_adopt": e["t_adopt"],
                "t_radopt": e["t_radopt"],
                "t_arr": e["t_arr"],
                "base0": base0,
                "prev0": prev0i,
                "rv0": rv0,
                "rr": rr,
                "k_r": e["k_r"],
                "prev_r": e["prev_r"],
                "ok": ok0,
                "rcnt0": rcnt0,
                "up0": up0,
                "lacc0": lacc0,
            }
            s0 = (
                v0, c["gcount"], jnp.int32(0), jnp.int32(0), c["last_mint"],
                c["last_acc_g"], nm0,
                jnp.zeros((C,), jnp.int32), jnp.zeros((C,), jnp.int32),
                jnp.zeros((C,), jnp.int32), jnp.zeros((C,), jnp.float32),
                jnp.int32(0),
            )
            sf, ys = jax.lax.scan(adm, s0, xs)
            (ver_f, gcnt_f, gwin_f, nmn_f, lastm_f, laccg_f, nm_f) = sf[:7]
            valid = ys["flush_r"]
        else:

            def adm(s, x):
                (ver, gcnt, gwin, nmn, lastm, laccg, nm, j) = s
                adj = jnp.sum((nm < x["t_adopt"]).astype(jnp.int32))
                v_a = jnp.maximum(x["base0"] + adj, x["prev0"])
                tau = jnp.maximum(ver - v_a, 0)
                fresh = tau <= cfg.max_staleness
                if cfg.rate_gap_glob > 0.0:
                    rate_ok = (x["t_arr"] - laccg) >= cfg.rate_gap_glob
                else:
                    rate_ok = jnp.bool_(True)
                acc = x["ok"]
                ins = acc & fresh & rate_ok
                gslot = gcnt
                gcnt_new = gcnt + ins.astype(jnp.int32)
                gflush = ins & (gcnt_new >= k_glob)
                gcnt_out = jnp.where(gflush, 0, gcnt_new)
                gwin_ins = gwin
                gwin_out = gwin + gflush.astype(jnp.int32)
                laccg_out = jnp.where(ins, x["t_arr"], laccg)
                tm = jnp.maximum(x["t_arr"], lastm)
                nmi = jnp.clip(nmn, 0, GF - 1)
                nm_out = nm.at[nmi].set(jnp.where(gflush, tm, nm[nmi]))
                nmn_out = nmn + gflush.astype(jnp.int32)
                lastm_out = jnp.where(gflush, tm, lastm)
                ver_out = ver + gflush.astype(jnp.int32)
                ys = {
                    "ins": ins,
                    "tau": tau,
                    "adj": adj,
                    "gslot": gslot,
                    "gwin": gwin_ins,
                    "gflush": gflush,
                    "stale_e": acc & ~fresh,
                    "rate_e": acc & fresh & ~rate_ok,
                }
                return (
                    ver_out, gcnt_out, gwin_out, nmn_out, lastm_out, laccg_out,
                    nm_out, j + 1,
                ), ys

            xs = {
                "t_adopt": e["t_adopt"],
                "t_arr": e["t_arr"],
                "base0": base0,
                "prev0": prev0i,
                "ok": ok0,
            }
            s0 = (
                v0, c["gcount"], jnp.int32(0), jnp.int32(0), c["last_mint"],
                c["last_acc_g"], nm0, jnp.int32(0),
            )
            sf, ys = jax.lax.scan(adm, s0, xs)
            (ver_f, gcnt_f, gwin_f, nmn_f, lastm_f, laccg_f, nm_f) = sf[:7]
            valid = ys["gflush"]

        wgt_all = samples * staleness_weight_arr(ys["tau"], cfg.alpha)
        n_ent = jnp.sum(valid.astype(jnp.int32))
        pos = jnp.arange(C, dtype=jnp.int32)
        perm = jnp.argsort(jnp.where(valid, pos, C + pos))

        # ---- pass C: the actual flushes over compacted entry records
        if cfg.hier:
            ent = {
                "valid": valid[perm],
                "r": rr[perm],
                "win": ys["win"][perm],
                "rv": ys["rv"][perm],
                "up": ys["up"][perm],
                "gslot": ys["gslot"][perm],
                "gwin": ys["gwin"][perm],
                "gins": ys["gins"][perm],
                "taug": ys["taug"][perm],
                "gflush": ys["gflush"][perm],
            }
            wg_ent = staleness_weight_arr(ent["taug"], cfg.alpha)
            if cfg.byz:
                akind = reg["akind"][ent["r"]]
                alam = reg["alam"][ent["r"]]
                anrow = reg["agg_noise_idx"][
                    ent["r"], jnp.clip(ent["up"] - 1, 0, stride - 1)
                ]

            def ent_body(q, st):
                (prev_g, fresh_g, mcount, payload, aggout, aggw, rparams_c,
                 radopt_c) = st
                r_q = ent["r"][q]
                win_q = ent["win"][q]
                rv_q = ent["rv"][q]
                # one-hot window reconstruction (exact: ≤1 event per slot)
                mt = ys["ins"] & (rr == r_q) & (ys["win"] == win_q)
                sl = jnp.where(mt, ys["slot"], k_max)
                onehot = sl[None, :] == jnp.arange(k_max, dtype=jnp.int32)[:, None]
                any_s = jnp.any(onehot, axis=1)
                of = onehot.astype(jnp.float32)
                oi = onehot.astype(jnp.int32)
                first = win_q == 0
                base_wt = jnp.where(first, c["rwt"][r_q], 0.0)
                base_samp = jnp.where(first, c["rsamp"][r_q], 0.0)
                base_hi = jnp.where(first, c["rkey_hi"][r_q], PAD_KEY)
                base_lo = jnp.where(first, c["rkey_lo"][r_q], PAD_KEY)
                rows = jnp.where(any_s[:, None], of @ payload, c["rbuf"][r_q])
                wts = jnp.where(any_s, of @ wgt_all, base_wt)
                samp = jnp.where(any_s, of @ samples, base_samp)
                khi = jnp.where(any_s, (oi * e["key_hi"][None, :]).sum(1), base_hi)
                klo = jnp.where(any_s, (oi * e["key_lo"][None, :]).sum(1), base_lo)
                g_rv = jnp.where(
                    rv_q > v0,
                    fresh_g[jnp.clip(rv_q - v0 - 1, 0, GF - 1)],
                    c["G"][jnp.clip(rv_q, 0, v_cap)],
                )
                cur = jnp.where(rv_q > radopt_c[r_q], g_rv, rparams_c[r_q])
                merged = fold_window(
                    rows, wts, klo, cur, cfg.server_lr,
                    kind=cfg.fold_kind, trim=cfg.trim, keys_hi=khi,
                )
                rparams_c = rparams_c.at[r_q].set(merged)
                radopt_c = radopt_c.at[r_q].set(jnp.maximum(radopt_c[r_q], rv_q))
                aggp = merged
                if cfg.byz:
                    ak = akind[q]
                    aggp = jnp.where(ak == 1, -aggp, aggp)
                    aggp = jnp.where(ak == 2, alam[q] * aggp, aggp)
                    aggp = jnp.where(
                        ak == 3, aggp + reg["agg_noise"][anrow[q]], aggp
                    )
                aggout = aggout.at[q].set(aggp)
                aggw = aggw.at[q].set(jnp.sum(samp) * wg_ent[q])

                # masked global flush (the fold runs every entry — the
                # branch-free contract at entry granularity)
                gw_q = ent["gwin"][q]
                gmt = ent["gins"] & (ent["gwin"] == gw_q)
                gsl = jnp.where(gmt, ent["gslot"], k_glob)
                goh = gsl[None, :] == jnp.arange(k_glob, dtype=jnp.int32)[:, None]
                gany = jnp.any(goh, axis=1)
                gof = goh.astype(jnp.float32)
                goi = goh.astype(jnp.int32)
                gfirst = gw_q == 0
                gb_wt = jnp.where(gfirst, c["gwt"][:k_glob], 0.0)
                gb_hi = jnp.where(gfirst, c["gkey_hi"][:k_glob], PAD_KEY)
                gb_lo = jnp.where(gfirst, c["gkey_lo"][:k_glob], PAD_KEY)
                rows_g = jnp.where(gany[:, None], gof @ aggout, c["gbuf"][:k_glob])
                wts_g = jnp.where(gany, gof @ aggw, gb_wt)
                ghi = jnp.where(gany, (goi * ent["r"][None, :]).sum(1), gb_hi)
                glo = jnp.where(gany, (goi * ent["up"][None, :]).sum(1), gb_lo)
                newg = fold_window(
                    rows_g, wts_g, glo, prev_g, cfg.server_lr,
                    kind=cfg.fold_kind, trim=cfg.trim, keys_hi=ghi,
                )
                gfl = ent["gflush"][q]
                mcount_new = mcount + gfl.astype(jnp.int32)
                mi = jnp.clip(mcount, 0, GF - 1)
                fresh_g = fresh_g.at[mi].set(jnp.where(gfl, newg, fresh_g[mi]))
                prev_g = jnp.where(gfl, newg, prev_g)
                # correction sweep: adopters of this mint retrain from it
                # and their staged payloads are re-corrupted
                cm = (ys["adj"] == mcount_new) & gfl & live
                couts = train_vec(jnp.broadcast_to(newg, (C, dim)), idx, e)
                payload = jnp.where(cm[:, None], apply_byz(couts, e), payload)
                return (
                    prev_g, fresh_g, mcount_new, payload, aggout, aggw,
                    rparams_c, radopt_c,
                )

            st0 = (
                c["G"][v0],
                jnp.zeros((GF, dim), jnp.float32),
                jnp.int32(0),
                payload0,
                jnp.zeros((C, dim), jnp.float32),
                jnp.zeros((C,), jnp.float32),
                c["rparams"],
                c["radopt"],
            )
            (_, fresh_g, _, payload, aggout, aggw, rparams_c, radopt_c) = (
                jax.lax.fori_loop(0, n_ent, ent_body, st0)
            )
        else:
            ent = {"gwin": ys["gwin"][perm]}

            def ent_body(q, st):
                prev_g, fresh_g, mcount, payload = st
                gw_q = ent["gwin"][q]
                gmt = ys["ins"] & (ys["gwin"] == gw_q)
                gsl = jnp.where(gmt, ys["gslot"], k_glob)
                goh = gsl[None, :] == jnp.arange(k_glob, dtype=jnp.int32)[:, None]
                gany = jnp.any(goh, axis=1)
                gof = goh.astype(jnp.float32)
                goi = goh.astype(jnp.int32)
                gfirst = gw_q == 0
                gb_wt = jnp.where(gfirst, c["gwt"][:k_glob], 0.0)
                gb_hi = jnp.where(gfirst, c["gkey_hi"][:k_glob], PAD_KEY)
                gb_lo = jnp.where(gfirst, c["gkey_lo"][:k_glob], PAD_KEY)
                rows_g = jnp.where(gany[:, None], gof @ payload, c["gbuf"][:k_glob])
                wts_g = jnp.where(gany, gof @ wgt_all, gb_wt)
                ghi = jnp.where(gany, (goi * e["key_hi"][None, :]).sum(1), gb_hi)
                glo = jnp.where(gany, (goi * e["key_lo"][None, :]).sum(1), gb_lo)
                newg = fold_window(
                    rows_g, wts_g, glo, prev_g, cfg.server_lr,
                    kind=cfg.fold_kind, trim=cfg.trim, keys_hi=ghi,
                )
                # every flat entry IS a flush (valid == gflush)
                mcount_new = mcount + 1
                fresh_g = fresh_g.at[jnp.clip(mcount, 0, GF - 1)].set(newg)
                cm = (ys["adj"] == mcount_new) & live
                couts = train_vec(jnp.broadcast_to(newg, (C, dim)), idx, e)
                payload = jnp.where(cm[:, None], apply_byz(couts, e), payload)
                return newg, fresh_g, mcount_new, payload

            st0 = (
                c["G"][v0],
                jnp.zeros((GF, dim), jnp.float32),
                jnp.int32(0),
                payload0,
            )
            _, fresh_g, _, payload = jax.lax.fori_loop(0, n_ent, ent_body, st0)

        # ---- pass D: vectorized writebacks (one predicated scatter per
        # carry; dead lanes route to the trash rows)
        ar_gf = jnp.arange(GF, dtype=jnp.int32)
        mmask = ar_gf < nmn_f
        c["G"] = c["G"].at[jnp.where(mmask, v0 + 1 + ar_gf, v_trash)].set(fresh_g)
        c["mint"] = c["mint"].at[jnp.where(mmask, v0 + ar_gf, m_trash)].set(nm_f)
        c["version"] = ver_f
        c["last_mint"] = lastm_f
        c["gcount"] = gcnt_f
        c["last_acc_g"] = laccg_f
        c["merges"] = c["merges"] + nmn_f
        c["stale_edge"] = c["stale_edge"] + jnp.sum(ys["stale_e"].astype(jnp.int32))
        c["rate_edge"] = c["rate_edge"] + jnp.sum(ys["rate_e"].astype(jnp.int32))
        c["hist_edge"] = c["hist_edge"].at[jnp.clip(ys["tau"], 0, cfg.hist_bins - 1)].add(
            ys["ins"].astype(jnp.int32)
        )

        # global window: reset if it turned over, then fill staged slots
        greset = gwin_f > 0
        c["gwt"] = jnp.where(greset, jnp.zeros_like(c["gwt"]), c["gwt"])
        pad_g = jnp.full_like(c["gkey_hi"], PAD_KEY)
        c["gkey_hi"] = jnp.where(greset, pad_g, c["gkey_hi"])
        c["gkey_lo"] = jnp.where(greset, pad_g, c["gkey_lo"])
        if cfg.hier:
            gfill = ent["gins"] & (ent["gwin"] == gwin_f)
            gs_f = jnp.where(gfill, ent["gslot"], k_glob)
            c["gbuf"] = c["gbuf"].at[gs_f].set(aggout)
            c["gwt"] = c["gwt"].at[gs_f].set(aggw)
            c["gkey_hi"] = c["gkey_hi"].at[gs_f].set(ent["r"])
            c["gkey_lo"] = c["gkey_lo"].at[gs_f].set(ent["up"])
        else:
            gfill = ys["ins"] & (ys["gwin"] == gwin_f)
            gs_f = jnp.where(gfill, ys["gslot"], k_glob)
            c["gbuf"] = c["gbuf"].at[gs_f].set(payload)
            c["gwt"] = c["gwt"].at[gs_f].set(wgt_all)
            c["gkey_hi"] = c["gkey_hi"].at[gs_f].set(e["key_hi"])
            c["gkey_lo"] = c["gkey_lo"].at[gs_f].set(e["key_lo"])

        if cfg.hier:
            c["stale_agg"] = c["stale_agg"] + jnp.sum(ys["stale_g"].astype(jnp.int32))
            c["rate_agg"] = c["rate_agg"] + jnp.sum(ys["rate_g"].astype(jnp.int32))
            c["agg_drop"] = c["agg_drop"] + jnp.sum(ys["aggdrop"].astype(jnp.int32))
            c["dup_agg"] = c["dup_agg"] + jnp.sum(ys["dup"].astype(jnp.int32))
            c["rmerges"] = c["rmerges"] + n_ent
            c["hist_glob"] = c["hist_glob"].at[
                jnp.clip(ys["taug"], 0, cfg.hist_bins - 1)
            ].add(ys["gins"].astype(jnp.int32))
            if cfg.byz:
                c["byz_agg"] = c["byz_agg"] + jnp.sum(
                    (ent["valid"] & (akind > 0)).astype(jnp.int32)
                )
            rr_t = jnp.where(e["last_r"], rr, r_trash)
            c["rcount"] = c["rcount"].at[rr_t].set(ys["cnt_out"])
            c["up_seq"] = c["up_seq"].at[rr_t].set(ys["up"])
            c["last_acc_r"] = c["last_acc_r"].at[rr_t].set(ys["lacc"])
            c["rparams"] = rparams_c
            c["radopt"] = radopt_c
            # regional windows: reset every regional whose window turned
            # over, then fill the final window's staged slots
            rr_rst = jnp.where(e["last_r"] & (ys["win_out"] > 0), rr, r_trash)
            c["rwt"] = c["rwt"].at[rr_rst].set(jnp.zeros((C, k_max), jnp.float32))
            c["rsamp"] = c["rsamp"].at[rr_rst].set(jnp.zeros((C, k_max), jnp.float32))
            pad_r = jnp.full((C, k_max), PAD_KEY, jnp.int32)
            c["rkey_hi"] = c["rkey_hi"].at[rr_rst].set(pad_r)
            c["rkey_lo"] = c["rkey_lo"].at[rr_rst].set(pad_r)
            winfin = jnp.zeros((r_trash + 1,), jnp.int32).at[rr_t].set(ys["win_out"])
            fill = ys["ins"] & (ys["win"] == winfin[rr])
            rr_f = jnp.where(fill, rr, r_trash)
            sl_f = jnp.where(fill, ys["slot"], 0)
            c["rbuf"] = c["rbuf"].at[rr_f, sl_f].set(payload)
            c["rwt"] = c["rwt"].at[rr_f, sl_f].set(wgt_all)
            c["rsamp"] = c["rsamp"].at[rr_f, sl_f].set(samples)
            c["rkey_hi"] = c["rkey_hi"].at[rr_f, sl_f].set(e["key_hi"])
            c["rkey_lo"] = c["rkey_lo"].at[rr_f, sl_f].set(e["key_lo"])

        # corrected adopters (layout hook): retrain from the fresh global
        # they actually saw (honest weights — corruption only touches the
        # SENT copy)
        c = writeback_w(c, e, ys, fresh_g, v0)
        return c, None

    return chunk_body


def _strip_chunk_out(cfg: FleetConfig, out: Dict[str, Any]) -> Dict[str, Any]:
    """Strip the trash rows so consumers see the per-event carry shapes
    (``w`` is layout-specific and already stripped by the caller)."""
    out["G"] = out["G"][: cfg.v_cap + 1]
    out["mint"] = out["mint"][: cfg.v_cap]
    for k in ("gbuf", "gwt", "gkey_hi", "gkey_lo"):
        out[k] = out[k][: cfg.k_global]
    if cfg.hier:
        for k in (
            "rbuf", "rwt", "rsamp", "rkey_hi", "rkey_lo", "rcount", "rparams",
            "radopt", "up_seq", "last_acc_r",
        ):
            out[k] = out[k][: cfg.n_regionals]
    return out


def run_fleet_program_chunked(
    cfg: FleetConfig,
    events: Dict[str, jax.Array],
    clients: Dict[str, jax.Array],
    reg: Dict[str, jax.Array],
    init_params: jax.Array,
) -> Dict[str, Any]:
    """The fleet scan with ``cfg.chunk`` events per step — same algorithm
    as :func:`run_fleet_program`, amortizing XLA:CPU's per-op dispatch
    (the per-event engine's actual bottleneck: ~200 tiny HLO ops per
    29µs event) over a whole chunk. Flat-topology results are
    bit-identical to the per-event scan (the parity test's contract);
    the hierarchical engine inherits the per-event engine's documented
    aggregate-ordering tolerance unchanged.

    The decomposition (see docs/design.md "chunked-event scan"):

    1. **Pass A** — batched gather + one vmapped train for all ``C``
       events against the PRE-chunk mint history, one scatter into
       ``w``. Sound because the host pads chunks so no client appears
       twice per chunk, and any event whose adoption base is moved by an
       IN-chunk mint is provably an adopter (a new mint time sits below
       its threshold ⟹ every earlier mint does too ⟹ ``base0`` was
       already the pre-chunk version), so its row is recomputed from the
       fresh global in pass C and re-scattered.
    2. **Admission scan** — the sequential window bookkeeping reduced to
       SCALAR ops: one inner ``lax.scan`` over the chunk carrying only
       counters, the in-chunk mint times (for the ``adj``/``radj``
       base corrections) and tiny per-chunk chain scratches (per-regional
       counts threaded through ``prev_r`` links precomputed by the
       host). Big-array state is never touched here — per-event outputs
       ride out as stacked ``ys``.
    3. **Pass C** — the few actual flushes (``n_ent ≤ C``, typically
       ``C/k``) run in a ``fori_loop`` over COMPACTED entry records;
       each reconstructs its window by an exact one-hot gather over the
       chunk's staged payloads (masked-tail rule: slots not staged
       in-chunk fall back to the pre-chunk window for window 0 and to
       empty pads — weight 0, PAD key, an exact ``+0.0`` in the fold —
       for later windows), folds it with :func:`fold_window`, and
       applies byzantine transforms at the aggregate seam.
    4. **Writebacks** — one predicated scatter per carry buffer: fresh
       globals/mints via trash-masked index vectors, window resets then
       final-window fills, and the corrected-adopter ``w`` rows. The
       cross-buffer copy law survives because every value that feeds two
       buffers is re-gathered from an already-updated carry (pass A's
       ``w`` re-gather) or materialized per-chunk (``[C]``-sized
       temporaries), exactly the per-event engine's two fixes at chunk
       granularity.

    Passes 2–4 live in :func:`_make_chunk_body`, shared verbatim with
    :func:`run_fleet_program_sharded`; this function supplies the
    single-device pass-A / corrected-adopter hooks.
    """
    dim = cfg.dim
    v_cap = cfg.v_cap
    GF = cfg.gf_cap
    n_trash = cfg.n_clients
    train_vec = _make_train_vec(cfg, clients)
    apply_byz = _make_apply_byz(cfg, clients)

    def adopt_train(c, e):
        idx = e["client"]
        # ---- pass A: adopt + train against the PRE-chunk mint history
        mint_hist = c["mint"][:v_cap]
        base0 = jnp.searchsorted(mint_hist, e["t_adopt"]).astype(jnp.int32)
        rows0 = c["w"][idx]
        wvec0 = rows0[:, :dim]
        prev0 = rows0[:, dim]
        base0_f = base0.astype(jnp.float32)
        adopt0 = base0_f > prev0
        g0 = c["G"][base0]
        starts0 = jnp.where(adopt0[:, None], g0, wvec0)
        outs0 = train_vec(starts0, idx, e)
        newver0 = jnp.maximum(base0_f, prev0)
        c["w"] = c["w"].at[idx].set(jnp.concatenate([outs0, newver0[:, None]], axis=1))
        # re-gather from the UPDATED carry (copy law, fix 1): the staged
        # payloads must not be the same temporary that fed the w scatter
        rows_cur = c["w"][idx]
        wcur = rows_cur[:, :dim]
        return c, wcur, prev0.astype(jnp.int32), base0

    def writeback_w(c, e, ys, fresh_g, v0):
        idx = e["client"]
        cmask = (ys["adj"] > 0) & e["live"]
        starts2 = fresh_g[jnp.clip(ys["adj"] - 1, 0, GF - 1)]
        couts2 = train_vec(starts2, idx, e)
        newver2 = (v0 + ys["adj"]).astype(jnp.float32)
        wt2 = jnp.where(cmask, idx, n_trash)
        c["w"] = c["w"].at[wt2].set(
            jnp.concatenate([couts2, newver2[:, None]], axis=1)
        )
        return c

    chunk_body = _make_chunk_body(
        cfg, clients, reg, train_vec, apply_byz, adopt_train, writeback_w
    )

    @jax.jit
    def program(events, carry):
        carry, _ = jax.lax.scan(chunk_body, carry, events, unroll=cfg.unroll)
        return carry

    carry = _init_carry_chunked(cfg, init_params)
    out = dict(program(events, carry))
    out["w"] = out["w"][: cfg.n_clients]
    return _strip_chunk_out(cfg, out)


def run_fleet_program_sharded(
    cfg: FleetConfig,
    events: Dict[str, jax.Array],
    clients: Dict[str, jax.Array],
    reg: Dict[str, jax.Array],
    init_params: jax.Array,
    mesh,
) -> Dict[str, Any]:
    """The chunked fleet scan partitioned over a 1-D ``(clients,)`` device
    mesh (:func:`p2pfl_tpu.parallel.fleet_mesh.fleet_clients_mesh`) via
    ``shard_map`` — bit-identical to :func:`run_fleet_program_chunked`
    by construction (see docs/design.md "sharded scan semantics"):

    - **Sharded:** only the fleet-scale state — the ``w [N, dim+1]``
      client rows, laid out ``[P, ncap+1, dim+1]`` with client ``i`` on
      shard ``i // ncap`` and one LOCAL trash row per shard — plus the
      per-chunk segment grids (``seg_fwd``/``seg_loc``/``seg_live``,
      each shard's ≤ ``Cp`` lanes of the chunk in chronological order).
      Pass A runs on the owner shard only: local gather, the vmapped
      local round over ``Cp`` instead of ``C`` lanes (the FLOPs win),
      local scatter.
    - **Replicated:** everything version-count-sized — global history,
      windows, counters, the admission scan, the flush loop. Admission
      is a scalar recurrence over the chunk in arrival order; running
      it per-shard over only local events would need the OTHER shards'
      accept/flush verdicts mid-recurrence, so replicating it is what
      keeps verdicts (and therefore every fold) bit-identical.
    - **One collective per chunk:** after the local train, each shard
      contributes its ``[Cp, dim+2]`` packed rows (trained row +
      pre-chunk adopted version) to a tiled ``all_gather``, and the
      replicated ``invperm`` grid unpermutes the ``[P·Cp]`` segment
      layout back to chronological ``[C]``. The gather is pure
      concatenation — fold keys, weights and sums are computed AFTER it
      on the replicated side, so no floating-point sum ever
      reassociates across shards (the cross-shard fold-key rule).

    ``events`` must carry the segment grids + ``invperm`` built by
    :meth:`MegaFleet._shard_layout` alongside the chronological grids.
    """
    from jax.sharding import PartitionSpec

    from p2pfl_tpu.parallel.compat import shard_map_compat
    from p2pfl_tpu.parallel.fleet_mesh import shard_capacity

    axis = mesh.axis_names[0]
    n_dev = mesh.size
    ncap = shard_capacity(cfg.n_clients, n_dev)
    nloc = ncap + 1  # owned rows + the shard-local trash row
    dim = cfg.dim
    v_cap = cfg.v_cap
    GF = cfg.gf_cap
    train_vec = _make_train_vec(cfg, clients)
    apply_byz = _make_apply_byz(cfg, clients)

    def adopt_train(c, e):
        # pass A on the shard's own lanes: e["seg_fwd"] maps each local
        # segment lane to its chronological chunk position (dead lanes
        # → lane 0, trained then discarded via the local trash row)
        fwd = e["seg_fwd"]
        loc = e["seg_loc"]
        idx_l = e["client"][fwd]
        mint_hist = c["mint"][:v_cap]
        base0 = jnp.searchsorted(mint_hist, e["t_adopt"]).astype(jnp.int32)
        base0_l = base0[fwd]
        rows0 = c["w"][loc]
        wvec0 = rows0[:, :dim]
        prev0 = rows0[:, dim]
        base0_f = base0_l.astype(jnp.float32)
        adopt0 = base0_f > prev0
        g0 = c["G"][base0_l]
        starts0 = jnp.where(adopt0[:, None], g0, wvec0)
        e_l = {"key_hi": e["key_hi"][fwd], "key_lo": e["key_lo"][fwd]}
        outs0 = train_vec(starts0, idx_l, e_l)
        newver0 = jnp.maximum(base0_f, prev0)
        c["w"] = c["w"].at[loc].set(jnp.concatenate([outs0, newver0[:, None]], axis=1))
        # re-gather (copy law, fix 1), then ONE tiled all_gather: packed
        # [Cp, dim+2] = trained row ⊕ pre-chunk adopted version, and the
        # replicated invperm undoes the segment permutation so every
        # shard sees the same chronological [C] view the chunked engine
        # computes — concatenation only, nothing reassociates
        rows_cur = c["w"][loc]
        packed = jnp.concatenate([rows_cur, prev0[:, None]], axis=1)
        full = jax.lax.all_gather(packed, axis, tiled=True)
        chron = full[e["invperm"]]
        wcur = chron[:, :dim]
        prev0i = chron[:, dim + 1].astype(jnp.int32)
        return c, wcur, prev0i, base0

    def writeback_w(c, e, ys, fresh_g, v0):
        # corrected adopters, owner-shard only: gather the replicated
        # [C] verdicts at the shard's lanes — no collective needed
        fwd = e["seg_fwd"]
        loc = e["seg_loc"]
        loc_trash = nloc - 1
        cmask = (ys["adj"] > 0) & e["live"]
        adj_l = ys["adj"][fwd]
        starts2 = fresh_g[jnp.clip(adj_l - 1, 0, GF - 1)]
        e_l = {"key_hi": e["key_hi"][fwd], "key_lo": e["key_lo"][fwd]}
        couts2 = train_vec(starts2, e["client"][fwd], e_l)
        newver2 = (v0 + adj_l).astype(jnp.float32)
        cm_l = cmask[fwd] & e["seg_live"]
        wt2 = jnp.where(cm_l, loc, loc_trash)
        c["w"] = c["w"].at[wt2].set(
            jnp.concatenate([couts2, newver2[:, None]], axis=1)
        )
        return c

    chunk_body = _make_chunk_body(
        cfg, clients, reg, train_vec, apply_byz, adopt_train, writeback_w
    )

    seg_keys = ("seg_fwd", "seg_loc", "seg_live")
    ev_seg = {k: events[k] for k in seg_keys}
    ev_repl = {k: v for k, v in events.items() if k not in seg_keys}

    def body_fn(w, rest, er, es):
        carry = dict(rest)
        carry["w"] = w

        def step(c, xs):
            e = dict(xs[0])
            e.update(xs[1])
            return chunk_body(c, e)

        carry, _ = jax.lax.scan(step, carry, (er, es), unroll=cfg.unroll)
        w_out = carry.pop("w")
        return w_out, carry

    shard = PartitionSpec(axis)
    seg = PartitionSpec(None, axis)
    repl = PartitionSpec()
    program = jax.jit(
        shard_map_compat(
            body_fn,
            mesh=mesh,
            in_specs=(shard, repl, repl, seg),
            out_specs=(shard, repl),
        )
    )
    carry = _init_carry_chunked(cfg, init_params, n_w_rows=n_dev * nloc)
    w0 = carry.pop("w")
    w_out, out = program(w0, carry, ev_repl, ev_seg)
    out = dict(out)
    # un-map the block-sharded rows (drop each shard's trash row and the
    # last shard's padding) back to the chunked engine's [N, dim+1]
    w_full = jnp.reshape(w_out, (n_dev, nloc, dim + 1))[:, :ncap]
    out["w"] = jnp.reshape(w_full, (n_dev * ncap, dim + 1))[: cfg.n_clients]
    return _strip_chunk_out(cfg, out)
